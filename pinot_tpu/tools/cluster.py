"""Embedded cluster: controller + broker + N servers in one process.

Re-design of the reference's embedded-cluster test/quickstart harness
(``pinot-integration-test-base/.../ClusterTest.java:81`` — real
controller/broker/server instances in one JVM — and
``pinot-tools/.../Quickstart.java``): every role runs against the shared
cluster state store; transport is in-process method calls with the same
interfaces the gRPC services expose.
"""

from __future__ import annotations

import glob
import os
import time

from typing import Dict, List, Optional

from pinot_tpu.broker.broker import BrokerRequestHandler
from pinot_tpu.common.response import BrokerResponse
from pinot_tpu.controller.controller import Controller
from pinot_tpu.controller.state import ClusterStateStore
from pinot_tpu.segment.creator import SegmentBuilder
from pinot_tpu.segment.immutable import load_segment
from pinot_tpu.server.server import ServerInstance
from pinot_tpu.spi.data import Schema
from pinot_tpu.spi.table import TableConfig


class EmbeddedCluster:
    """Ref: ClusterTest.java:81 (startBrokers:107 / startServers:198)."""

    def __init__(self, num_servers: int = 1, data_dir: str = "/tmp/pinot_tpu_cluster",
                 snapshot: bool = False, llc_seed: Optional[str] = None,
                 query_timeout_s: float = 120.0,
                 device_reduce: Optional[bool] = None):
        os.makedirs(data_dir, exist_ok=True)
        snap = os.path.join(data_dir, "cluster_state.json") if snapshot else None
        self.data_dir = data_dir
        self.store = ClusterStateStore(snapshot_path=snap)
        self.controller = Controller(self.store, llc_seed=llc_seed)
        self.servers: Dict[str, ServerInstance] = {}
        self.minions: Dict[str, object] = {}
        # device_reduce: servers and broker share this process, so the
        # broker may merge group-by partials on device (PR-16 route)
        self.broker = BrokerRequestHandler(self.store,
                                           query_timeout_s=query_timeout_s,
                                           device_reduce=device_reduce)
        for i in range(num_servers):
            self.add_server(f"server_{i}")

    # -- roles ---------------------------------------------------------------
    def add_server(self, instance_id: str) -> ServerInstance:
        server = ServerInstance(
            instance_id, self.store,
            completion_protocol=self.controller.completion,
            segment_dir=os.path.join(self.data_dir, "server_segments"))
        server.start()
        self.servers[instance_id] = server
        self.broker.register_server(instance_id, server)
        return server

    def stop_server(self, instance_id: str) -> None:
        server = self.servers.pop(instance_id, None)
        if server is not None:
            server.shutdown()

    def add_minion(self, instance_id: str = "minion_0", start: bool = True):
        """Ref: ClusterTest startMinion — a MINION worker over the shared
        state store, executing controller-generated tasks."""
        from pinot_tpu.minion import MinionInstance

        minion = MinionInstance(
            instance_id, self.controller,
            work_dir=os.path.join(self.data_dir, "minion_work"))
        if start:
            minion.start()
        self.minions[instance_id] = minion
        return minion

    # -- table/data operations (controller API) ------------------------------
    def create_table(self, table_config: TableConfig, schema: Schema) -> None:
        self.controller.add_schema(schema)
        self.controller.add_table(table_config)

    def upload_segment_dir(self, table_with_type: str, segment_dir: str) -> None:
        md = load_segment(segment_dir).metadata
        self.controller.add_segment(table_with_type, md,
                                    f"file://{os.path.abspath(segment_dir)}")

    def ingest_rows(self, table_with_type: str, schema: Schema,
                    rows_columnar: Dict[str, list],
                    segment_name: Optional[str] = None) -> str:
        """Offline batch ingest: build a segment from columnar data and push
        it (the SegmentGenerationJobRunner + upload path in one call)."""
        name = segment_name or f"{schema.schema_name}_{int(time.time() * 1e3)}"
        out = os.path.join(self.data_dir, "built_segments")
        os.makedirs(out, exist_ok=True)
        cfg = self.store.get_table_config(table_with_type)
        b = SegmentBuilder(schema, name,
                           indexing_config=cfg.indexing_config if cfg else None)
        b.build(rows_columnar, out)
        seg_dir = os.path.join(out, name)
        self.upload_segment_dir(table_with_type, seg_dir)
        return name

    # -- query front door ----------------------------------------------------
    def query(self, sql: str) -> BrokerResponse:
        return self.broker.handle_sql(sql)

    def query_rows(self, sql: str) -> List[list]:
        resp = self.query(sql)
        if resp.has_exceptions:
            raise RuntimeError(f"query failed: {resp.exceptions}")
        return resp.result_table.rows if resp.result_table else []

    def hosting_servers(self, table: str) -> List[str]:
        """Instances serving >=1 segment of ``table`` per the ExternalView
        — the denominator of the bench's scatter prune ratio (a query that
        was going to skip a data-free server anyway proves nothing)."""
        ev = self.store.get_external_view(table)
        return sorted({inst for m in ev.values() for inst in m})

    # -- convergence helpers (tests) -----------------------------------------
    def wait_for_ev_converged(self, table: str, timeout_s: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            ideal = self.store.get_ideal_state(table)
            ev = self.store.get_external_view(table)
            if all(ev.get(seg, {}).get(inst) == st
                   for seg, m in ideal.items() for inst, st in m.items()):
                return True
            time.sleep(0.02)
        return False

    def wait_for_docs(self, table_raw: str, expected: int,
                      timeout_s: float = 20.0) -> bool:
        """Realtime assert helper: total queryable docs reach ``expected``."""
        deadline = time.monotonic() + timeout_s
        sql = f"SELECT count(*) FROM {table_raw}"
        while time.monotonic() < deadline:
            try:
                rows = self.query_rows(sql)
                if rows and rows[0][0] >= expected:
                    return True
            except RuntimeError:
                pass
            time.sleep(0.05)
        return False

    def shutdown(self) -> None:
        self.broker.shutdown()
        for s in list(self.servers.values()):
            s.shutdown()
        self.servers.clear()
        self.controller.stop()
