"""User-event table generator — the index-rung's user-facing workload.

Pinot's signature deployment shape is *user-facing analytics*: a wide
per-user event table answering huge volumes of tiny point-filter
group-bys ("this user's last-30-days spend by category") at strict
latency SLOs. Those queries touch a vanishing fraction of rows, so the
reference serves them off ``BitmapInvertedIndexReader`` /
``RangeIndexReader`` postings, never a scan. This module generates that
table with the distributions that make the shape real:

- ``user_id`` — Zipf-distributed (a few whales, a long tail), inverted
  index: the point-filter column. A tail user's postings are a handful
  of docIds; the index rung ships exactly those to the device.
- ``tags`` — multi-value dimension, inverted index (the MV postings
  union path).
- ``latency_ms`` — raw (no-dictionary) metric with a RANGE index: the
  ``BETWEEN``-predicate column.
- ``revenue`` — **dictionary-encoded** numeric metric: aggregating it
  exercises the gather kernel's dictvals passthrough (the dictId->value
  LUT must NOT be gathered by docId).
- ``country`` / ``device`` / ``event_type`` — low-cardinality dims for
  the GROUP BY side (country carries an inverted index too).

``build_segments`` mirrors :mod:`pinot_tpu.tools.ssb`'s per-segment
independent generation so builds parallelize without cross-segment
data movement.
"""

from __future__ import annotations

import os
from typing import Dict, List

import numpy as np

from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema

# tail users hold a handful of rows each; whales hold thousands —
# rng.zipf(ZIPF_A) clipped to NUM_USERS gives both in one draw
NUM_USERS = 100_000
ZIPF_A = 1.3

COUNTRIES = ["US", "IN", "BR", "DE", "JP", "GB", "FR", "CA", "AU", "MX"]
DEVICES = ["ios", "android", "web", "tv"]
EVENT_TYPES = ["view", "click", "cart", "purchase", "refund"]
TAGS = [f"tag{i}" for i in range(32)]


def user_schema() -> Schema:
    D, M = FieldType.DIMENSION, FieldType.METRIC
    I, S = DataType.INT, DataType.STRING
    return Schema("user_events", [
        FieldSpec("user_id", I, D),
        FieldSpec("country", S, D),
        FieldSpec("device", S, D),
        FieldSpec("event_type", S, D),
        FieldSpec("tags", S, D, single_value=False),
        FieldSpec("latency_ms", I, M),
        FieldSpec("revenue", I, M),
        FieldSpec("num_items", I, M),
    ])


def user_indexing_config():
    """Inverted on the point-filter dims (user_id/country/event_type/tags),
    RANGE on the raw latency column; revenue/num_items stay
    dictionary-encoded on purpose (the dictvals-passthrough aggregation
    path), latency_ms raw (range index wants raw sorted values)."""
    from pinot_tpu.spi.table import IndexingConfig

    return IndexingConfig(
        inverted_index_columns=["user_id", "country", "event_type", "tags"],
        range_index_columns=["latency_ms"],
        no_dictionary_columns=["latency_ms"],
    )


def generate_frame(i: int, num_segments: int, n: int,
                   seed: int = 7) -> Dict[str, np.ndarray]:
    """Segment ``i``'s rows — independently seeded, like the SSB builder."""
    rng = np.random.default_rng(seed * 1_000_003 + i)
    user = rng.zipf(ZIPF_A, n).clip(1, NUM_USERS).astype(np.int64)
    n_tags = rng.integers(1, 4, n)
    tag_pool = np.array(TAGS)
    # MV columns ride the frame as plain python list-of-lists
    tags = [tag_pool[rng.integers(0, len(TAGS), k)].tolist()
            for k in n_tags]
    return {
        "user_id": user,
        "country": np.array(COUNTRIES)[rng.integers(0, len(COUNTRIES), n)],
        "device": np.array(DEVICES)[rng.integers(0, len(DEVICES), n)],
        "event_type": np.array(EVENT_TYPES)[
            rng.integers(0, len(EVENT_TYPES), n)],
        "tags": tags,
        # long-tailed latency, integer ms (range-index predicates)
        "latency_ms": (rng.gamma(2.0, 40.0, n) + 1).astype(np.int64),
        # small value domain -> dictionary-encodes tightly
        "revenue": rng.integers(0, 500, n).astype(np.int64),
        "num_items": rng.integers(1, 10, n).astype(np.int64),
    }


def _build_one(i: int, num_segments: int, n: int, seed: int,
               out_dir: str) -> str:
    from pinot_tpu.segment import SegmentBuilder

    frame = generate_frame(i, num_segments, n, seed)
    name = f"user_{i}"
    SegmentBuilder(user_schema(), name,
                   indexing_config=user_indexing_config()).build(frame,
                                                                 out_dir)
    return name


def build_segments(out_dir: str, num_segments: int = 4, rows: int = 1_000_000,
                   seed: int = 7, workers: int = 0) -> List:
    """Build + load ``num_segments`` user-event segments (spawn pool when
    ``workers`` allows, same rationale as :func:`ssb.build_segments`)."""
    from pinot_tpu.segment import load_segment

    per = -(-rows // num_segments)
    jobs = []
    left = rows
    for i in range(num_segments):
        take = min(per, left)
        if take <= 0:
            break
        jobs.append((i, num_segments, take, seed, out_dir))
        left -= take
    if not workers:
        workers = min(len(jobs), os.cpu_count() or 1)
    if workers > 1 and len(jobs) > 1:
        import multiprocessing as mp

        with mp.get_context("spawn").Pool(workers) as pool:
            names = pool.starmap(_build_one, jobs)
    else:
        names = [_build_one(*j) for j in jobs]
    return [load_segment(os.path.join(out_dir, nm)) for nm in names]


def tail_users(rows: int, num_segments: int = 4, seed: int = 7,
               count: int = 64, max_rows_frac: float = 0.001) -> List[int]:
    """Deterministic sample of user_ids whose TOTAL row count stays under
    ``max_rows_frac`` of the table — the selective point-filter targets
    the userfacing suite cycles through (tail users, not whales)."""
    per = -(-rows // num_segments)
    counts: Dict[int, int] = {}
    left = rows
    for i in range(num_segments):
        take = min(per, left)
        if take <= 0:
            break
        rng = np.random.default_rng(seed * 1_000_003 + i)
        user = rng.zipf(ZIPF_A, take).clip(1, NUM_USERS).astype(np.int64)
        uniq, cnt = np.unique(user, return_counts=True)
        for u, c in zip(uniq.tolist(), cnt.tolist()):
            counts[u] = counts.get(u, 0) + c
        left -= take
    cap = max(1, int(rows * max_rows_frac))
    pool = sorted(u for u, c in counts.items() if 0 < c <= cap)
    if not pool:
        return []
    pick = np.random.default_rng(seed).choice(
        len(pool), size=min(count, len(pool)), replace=False)
    return [pool[int(j)] for j in sorted(pick)]


def point_queries(users: List[int]) -> List[str]:
    """The user-facing query mix: per-user point-filter group-bys and
    range-augmented aggregations, one query per sampled user (cycled by
    the closed-loop workers). Every one is <1%-selective, so each MUST
    serve from the index rung."""
    out = []
    for k, u in enumerate(users):
        shape = k % 3
        if shape == 0:
            out.append(
                f"SELECT event_type, count(*), sum(revenue) "
                f"FROM user_events WHERE user_id = {u} "
                f"GROUP BY event_type")
        elif shape == 1:
            out.append(
                f"SELECT country, count(*), sum(num_items) "
                f"FROM user_events WHERE user_id = {u} "
                f"AND event_type IN ('click', 'purchase') "
                f"GROUP BY country")
        else:
            out.append(
                f"SELECT count(*), sum(revenue) FROM user_events "
                f"WHERE user_id = {u} AND latency_ms BETWEEN 10 AND 200")
    return out
