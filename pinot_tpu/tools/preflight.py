"""Kernel preflight: static shape/dtype/memory verification of every
Pallas plan before it touches the chip.

The ROADMAP's remaining TPU risk is a runtime-discovery loop: ship a
round, watch Mosaic reject shapes, read ``pallas_exec_failed`` ledger
entries, fix, repeat — on scarce chip time. The lowering constraints that
loop discovers are PUBLISHED (tile alignment by dtype, ~16 MB VMEM per
core, small SMEM, supported dtypes — PAPERS.md: Jouppi et al. ISCA'23,
the JAX/Pallas references), so this module verifies them ahead of time:

- :class:`LoweringModel` — a pure-Python TPU lowering model: VMEM/SMEM
  budgets, lane/sublane tiling, the supported packed bit-widths, limb
  bounds. Numbers are deliberately conservative (utilization headroom for
  compiler scratch and double buffering).
- :func:`preflight_spec` — one concrete :class:`PallasSpec` against the
  model: mirrors ``build_kernel``'s exact BlockSpec/accumulator layout
  (via ``_row_layout``) and sizes every VMEM block, the matmul row stack
  and one-hot temporaries, and the SMEM param vector. Emits a verdict
  row with the first violated rule's ``pallas_preflight_<rule>`` code
  (registered in ``tracing.PALLAS_PREFLIGHT_REASONS``).
- :func:`extract_query_spec` — a SegmentPlan to its concrete kernel spec
  the same way ``run_segment`` would (group-range probe narrowing
  included, probe runs in interpret mode), WITHOUT launching the real
  kernel.
- :func:`run_preflight` — the plan space: every SSB flight's extracted
  spec plus a fuzzed shape grid (limb counts, ivs run counts, remainder
  tiles, narrowed group ranges, packed widths) -> a per-shape verdict
  table.
- :func:`seed_blocklist` / :func:`attach_verdicts` — predicted-fail SSB
  shapes land in the executor's per-shape blocklist with their rule code,
  so the engine declines them loudly (``pallas_preflight_<rule>`` on the
  ledger) instead of dying inside Mosaic; the verdict table rides
  ``GET /debug/pallas`` and the bench round JSON.

``python -m pinot_tpu.tools.preflight`` builds a small SSB fixture and
prints the table (``--json`` for machines).
"""

from __future__ import annotations

import json

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from pinot_tpu.engine.staging import LIMB_BITS, PALLAS_TILE

# lane width / one-hot chunk (pallas_kernels._G_CHUNK)
_LANE = 128


@dataclass(frozen=True)
class _Rule:
    code: str        # the ledger reason (pallas_preflight_*)
    title: str       # one-line README/verdict-table description


# rule order is severity order: the FIRST violated rule is the verdict's
# primary code (a shape failing groups_bound usually fails vmem too — the
# cause, not the symptom, should reach the ledger)
RULES: Tuple[_Rule, ...] = (
    _Rule("pallas_preflight_groups_bound",
          "padded group count is lane-aligned (%128) and within "
          "MAX_PALLAS_GROUPS"),
    _Rule("pallas_preflight_tile_align",
          "packed bit-widths are word-aligned powers of two; every VMEM "
          "block is (sublane, 128k)-tiled for its dtype"),
    _Rule("pallas_preflight_dtype_unsupported",
          "ref dtypes stay in {u32, i32, f32}; limb planes only on "
          "integer inputs; plane counts consistent with value inputs"),
    _Rule("pallas_preflight_limb_planes",
          "limb counts cover <= i64 sums (L <= 6) and every per-tile "
          "limb partial is f32-exact"),
    _Rule("pallas_preflight_grid_bound",
          "grid dims positive and the step count bounded"),
    _Rule("pallas_preflight_smem_budget",
          "SMEM scalar params (interval slots + per-segment doc counts) "
          "fit the scalar-memory budget"),
    _Rule("pallas_preflight_vmem_budget",
          "per-step VMEM working set (blocks + matmul row stack + "
          "one-hot temporaries) fits the ~16 MB/core budget"),
)


@dataclass(frozen=True)
class LoweringModel:
    """Conservative TPU lowering model (pallas guide: ~16 MB VMEM/core,
    small SMEM, (8, 128) min tile for 32-bit dtypes, MXU 128x128)."""

    vmem_bytes: int = 16 * 2 ** 20
    # headroom for compiler scratch, double buffering, and spills the
    # model cannot see — the budget the working set must fit
    vmem_utilization: float = 0.75
    # modeled SMEM capacity in i32 scalar slots for the params vector
    smem_slots: int = 1024
    lane: int = _LANE
    sublane_f32: int = 8
    # planar unpack requires word-aligned widths (staging.pack_bits)
    packed_bits_ok: Tuple[int, ...] = (1, 2, 4, 8, 16, 32)
    max_groups: int = 8192            # pallas_kernels.MAX_PALLAS_GROUPS
    max_limbs: int = 6                # ceil(62 bits / 12-bit limbs)
    max_grid_steps: int = 1 << 24

    @property
    def vmem_budget(self) -> int:
        return int(self.vmem_bytes * self.vmem_utilization)


@dataclass
class Verdict:
    """One shape's preflight outcome."""

    shape: str                        # human label (qid or fuzz label)
    source: str                       # "ssb" | "fuzz"
    ok: bool
    rule: Optional[str] = None        # first violated rule's code
    detail: str = ""
    vmem_bytes: int = 0
    smem_slots: int = 0
    failures: List[Tuple[str, str]] = field(default_factory=list)

    def row(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "shape": self.shape, "source": self.source,
            "verdict": "pass" if self.ok else "fail",
            "vmem_bytes": self.vmem_bytes, "smem_slots": self.smem_slots,
        }
        if not self.ok:
            out["rule"] = self.rule
            out["detail"] = self.detail
            if len(self.failures) > 1:
                out["also"] = [r for r, _ in self.failures[1:]]
        return out


# --------------------------------------------------------------------------
# the lowering model applied to one concrete PallasSpec
# --------------------------------------------------------------------------

def _vmem_estimate(spec, model: LoweringModel) -> int:
    """Per-grid-step VMEM bytes: every BlockSpec block build_kernel binds
    plus the kernel's large intermediates (matmul row stack, one-hot /
    iota / min-max select buffers). Mirrors pallas_kernels.build_kernel's
    layout via the same ``_row_layout``."""
    from pinot_tpu.engine.pallas_kernels import _row_layout

    T = PALLAS_TILE
    _fsum, isum, mm_row, Mf, Mi, Mm = _row_layout(spec)
    G = spec.num_groups_padded
    n_values = len(spec.value_is_int)
    vlimbs = spec.value_limbs or (0,) * n_values
    n_value_refs = sum(l if l else 1 for l in vlimbs)

    total = 0
    # packed input blocks: (1, 1, W/128, 128) u32
    for bits in spec.packed_bits:
        vpw = 32 // max(1, bits)
        total += (T // max(1, vpw)) * 4
    # value ref blocks: (1, 1, RT, 128) i32/f32
    total += n_value_refs * T * 4
    # unpacked dictId planes [RT, 128] i32 per packed column
    total += len(spec.packed_bits) * T * 4
    # output accumulators (whole arrays resident across the grid)
    total += (Mf + Mi + Mm) * G * 4
    total += model.lane * 4  # out_seg block (1, 128)
    # matmul row stack R [M_mat, RT, 128] f32
    n_limb_rows = sum(L for (_s, L) in isum.values())
    m_mat = (Mf // 2) + 1 + n_limb_rows
    total += m_mat * T * 4
    # one-hot chunk buffers: g_iota + oh [RT, 128, 128] f32
    total += 2 * T * model.lane * 4
    # min/max select buffers (eq + v3) when mm rows exist
    if mm_row:
        total += 2 * T * model.lane * 4
    return total


def preflight_spec(spec, model: Optional[LoweringModel] = None,
                   shape: str = "", source: str = "fuzz") -> Verdict:
    """Verify one concrete PallasSpec against the lowering model."""
    model = model or LoweringModel()
    failures: List[Tuple[str, str]] = []

    def fail(code: str, detail: str) -> None:
        failures.append((code, detail))

    G = spec.num_groups_padded
    if G <= 0 or G % model.lane or G > model.max_groups:
        fail("pallas_preflight_groups_bound",
             f"padded groups {G} (lane {model.lane}, "
             f"max {model.max_groups})")

    for bits in spec.packed_bits:
        if bits not in model.packed_bits_ok:
            fail("pallas_preflight_tile_align",
                 f"packed width {bits} is not word-aligned "
                 f"({model.packed_bits_ok}); unpack planes would not "
                 f"tile to (sublane, {model.lane})")
            break

    n_values = len(spec.value_is_int)
    vlimbs = spec.value_limbs or (0,) * n_values
    if len(vlimbs) != n_values:
        fail("pallas_preflight_dtype_unsupported",
             f"value_limbs has {len(vlimbs)} entries for "
             f"{n_values} value inputs")
    else:
        for i, (l, is_int) in enumerate(zip(vlimbs, spec.value_is_int)):
            if l and not is_int:
                fail("pallas_preflight_dtype_unsupported",
                     f"value input {i} carries {l} limb planes but is "
                     f"not integral (planes are i32 slices of i64)")
                break

    agg_limbs = [limbs for (_b, _v, limbs) in spec.aggs
                 if limbs is not None]
    all_limbs = list(agg_limbs) + [l for l in vlimbs if l]
    if any(l <= 0 or l > model.max_limbs for l in all_limbs):
        fail("pallas_preflight_limb_planes",
             f"limb counts {sorted(set(all_limbs))} outside "
             f"[1, {model.max_limbs}] — i64 reassembly would shift past "
             f"the exactness bound")
    elif ((1 << LIMB_BITS) - 1) * PALLAS_TILE >= (1 << 24):
        fail("pallas_preflight_limb_planes",
             "per-tile limb partial not f32-exact")

    S, TPS = spec.num_segs, spec.tiles_per_seg
    if S < 1 or TPS < 1 or S * TPS > model.max_grid_steps:
        fail("pallas_preflight_grid_bound",
             f"grid ({S}, {TPS}) outside (1..{model.max_grid_steps})")

    smem = 2 * spec.n_slots + max(S, 0) + 1
    if smem > model.smem_slots:
        fail("pallas_preflight_smem_budget",
             f"{smem} scalar param slots ({spec.n_slots} intervals + "
             f"{S} doc counts) > {model.smem_slots}")

    vmem = _vmem_estimate(spec, model)
    if vmem > model.vmem_budget:
        fail("pallas_preflight_vmem_budget",
             f"{vmem} B working set > {model.vmem_budget} B "
             f"({model.vmem_bytes} B * {model.vmem_utilization})")

    order = {r.code: i for i, r in enumerate(RULES)}
    failures.sort(key=lambda f: order[f[0]])
    return Verdict(
        shape=shape, source=source, ok=not failures,
        rule=failures[0][0] if failures else None,
        detail=failures[0][1] if failures else "",
        vmem_bytes=vmem, smem_slots=smem, failures=failures)


# --------------------------------------------------------------------------
# SegmentPlan -> concrete PallasSpec (run_segment's extraction, no launch)
# --------------------------------------------------------------------------

def extract_query_spec(plan, staged, cache=None,
                       lut_run_cap: Optional[int] = None,
                       interpret: bool = True):
    """-> ``(spec, effective_plan, None)`` with the concrete PallasSpec
    ``run_segment`` would build for this plan over ``staged`` (group-range
    probe narrowing included — the probe kernel runs in interpret mode),
    or ``(None, None, reason)`` when the plan is not pallas-eligible."""
    from pinot_tpu.engine.pallas_kernels import (
        DEFAULT_LUT_RUN_CAP,
        PallasKernelCache,
        _DeferredDecline,
        _run_probe_segment,
        _stage_packed,
        _with_bits,
        extract_plan,
        probe_narrowed_plan,
    )

    cap = DEFAULT_LUT_RUN_CAP if lut_run_cap is None else lut_run_cap
    cache = cache if cache is not None else PallasKernelCache()
    reasons: List[str] = []
    defer = _DeferredDecline(reasons.append)
    pp = extract_plan(plan, staged.segment, on_decline=defer,
                      lut_run_cap=cap)
    eff = plan
    if pp is None:
        if not defer.only_group_bound:
            defer.flush()
            return None, None, (reasons or ["unknown"])[0]

        def run_probe(probe_pp):
            return _run_probe_segment(probe_pp, staged, cache, interpret,
                                      reasons.append)

        res = probe_narrowed_plan(plan, staged.segment, run_probe, cap,
                                  reasons.append)
        if res is None:
            return None, None, (reasons or ["unknown"])[0]
        pp, eff = res

    got = _stage_packed(pp, staged, reasons.append)
    if got is None:
        return None, None, (reasons or ["unknown"])[0]
    _cols, bits = got
    tiles = staged.pallas_capacity() // PALLAS_TILE
    spec = _with_bits(
        pp.spec(num_segs=1, tiles_per_seg=tiles, interpret=interpret),
        tuple(bits))
    return spec, eff, None


# --------------------------------------------------------------------------
# the fuzzed shape grid
# --------------------------------------------------------------------------

def _mk_spec(num_segs=1, tiles=3, bits=(8,), filter_tree=("true",),
             n_slots=0, groups=128, aggs=(("count", None, None),),
             value_is_int=(), value_limbs=()):
    """A hand-built PallasSpec for the fuzz grid (remainder-tile default:
    tiles=3 models a capacity % PALLAS_TILE != 0 segment)."""
    from pinot_tpu.engine.pallas_kernels import PallasSpec

    return PallasSpec(
        num_segs=num_segs, tiles_per_seg=tiles, packed_bits=tuple(bits),
        filter_tree=filter_tree, n_slots=n_slots, group_idx=(),
        group_strides=(), group_key_offset=0, num_groups_padded=groups,
        aggs=tuple(aggs), value_is_int=tuple(value_is_int),
        value_limbs=tuple(value_limbs), interpret=True)


def fuzz_specs() -> List[Tuple[str, Any]]:
    """The fuzzed plan-space grid: limb counts, ivs run counts, remainder
    tiles, narrowed group ranges, packed widths — passing shapes prove
    the model admits what the engine emits; failing shapes are the
    predicted-fail fixtures the tests pin rule codes on."""
    shapes: List[Tuple[str, Any]] = []
    fsum = (("sum", ("v", 0), None),)

    # limb planes: the full eligible range, then one past it
    for L in (1, 3, 6):
        shapes.append((f"limbs{L}", _mk_spec(
            aggs=(("sum", ("v64", 0), L),), value_is_int=(True,),
            value_limbs=(L,))))
    shapes.append(("limbs8_over", _mk_spec(
        aggs=(("sum", ("v64", 0), 8),), value_is_int=(True,),
        value_limbs=(8,))))
    shapes.append(("limbs_on_float", _mk_spec(
        aggs=fsum, value_is_int=(False,), value_limbs=(3,))))

    # interval-set runs: in-cap pads, then an SMEM-busting pad
    for runs in (8, 64, 128):
        shapes.append((f"ivs{runs}", _mk_spec(
            filter_tree=("ivs", 0, 0, runs), n_slots=runs,
            aggs=fsum, value_is_int=(False,), value_limbs=(0,))))
    shapes.append(("ivs512_over", _mk_spec(
        filter_tree=("ivs", 0, 0, 512), n_slots=512,
        aggs=fsum, value_is_int=(False,), value_limbs=(0,))))

    # narrowed group ranges: the dense rung's spectrum, then over/unpadded
    for g in (128, 1024, 8192):
        shapes.append((f"groups{g}", _mk_spec(groups=g)))
    shapes.append(("groups16384_over", _mk_spec(groups=16384)))
    shapes.append(("groups8100_unpadded", _mk_spec(groups=8100)))

    # packed widths: every word-aligned width, then a straddling one
    for b in (1, 2, 4, 8, 16, 32):
        shapes.append((f"bits{b}", _mk_spec(bits=(b,))))
    shapes.append(("bits6_straddle", _mk_spec(bits=(6,))))

    # remainder tiles / grid
    shapes.append(("tiles_remainder", _mk_spec(tiles=5)))
    shapes.append(("grid_zero_tiles", _mk_spec(tiles=0)))

    # a VMEM-busting wide-aggregation shape: 48 float sum+min pairs at
    # full group fan-out
    wide_aggs = tuple(("sum", ("v", i), None) for i in range(48)) \
        + tuple(("min", ("v", i), None) for i in range(48))
    shapes.append(("wide96_vmem_over", _mk_spec(
        groups=8192, aggs=wide_aggs, value_is_int=(False,) * 48,
        value_limbs=(0,) * 48)))
    return shapes


# --------------------------------------------------------------------------
# plan-space preflight: SSB matrix + fuzz grid -> verdict table
# --------------------------------------------------------------------------

def preflight_ssb_plans(segs, model: Optional[LoweringModel] = None,
                        lut_run_cap: Optional[int] = None
                        ) -> Tuple[List[Verdict], Dict[str, Tuple]]:
    """Every SSB flight's extracted concrete spec through the model.
    Returns (verdicts, {qid: original plan.spec}) — the plan specs are
    the blocklist keys ``seed_blocklist`` uses for predicted failures."""
    from pinot_tpu.engine.plan import plan_segment
    from pinot_tpu.engine.staging import StagingCache
    from pinot_tpu.query import compile_query
    from pinot_tpu.tools import ssb

    model = model or LoweringModel()
    staged = StagingCache().stage(segs[0])
    verdicts: List[Verdict] = []
    plan_specs: Dict[str, Tuple] = {}
    for qid in sorted(ssb.QUERIES):
        ctx = compile_query(ssb.QUERIES[qid] + " LIMIT 100000")
        plan = plan_segment(ctx, segs[0])
        spec, _eff, reason = extract_query_spec(plan, staged,
                                                lut_run_cap=lut_run_cap)
        if spec is None:
            # not pallas-eligible at all: that is an extraction decline
            # (classified), not a lowering prediction — record it as such
            verdicts.append(Verdict(
                shape=qid, source="ssb", ok=False,
                rule="pallas_preflight_grid_bound",
                detail=f"not extractable: {reason}"))
            plan_specs[qid] = plan.spec
            continue
        v = preflight_spec(spec, model, shape=qid, source="ssb")
        verdicts.append(v)
        plan_specs[qid] = plan.spec
    return verdicts, plan_specs


def run_preflight(segs=None, model: Optional[LoweringModel] = None,
                  lut_run_cap: Optional[int] = None,
                  fuzz: bool = True, rows: int = 6000) -> Dict[str, Any]:
    """The full plan-space preflight -> verdict table dict (the shape the
    bench round JSON and ``GET /debug/pallas`` carry). ``segs``: SSB
    segments to extract flight plans from; when None a small fixture set
    is built in a temp dir."""
    import tempfile

    from pinot_tpu.tools import ssb

    model = model or LoweringModel()
    if segs is None:
        with tempfile.TemporaryDirectory() as td:
            segs = ssb.build_segments(0, td, num_segments=2, rows=rows,
                                      workers=1)
            return run_preflight(segs, model, lut_run_cap, fuzz)
    ssb_verdicts, plan_specs = preflight_ssb_plans(segs, model,
                                                   lut_run_cap)
    verdicts = list(ssb_verdicts)
    if fuzz:
        for label, spec in fuzz_specs():
            verdicts.append(preflight_spec(spec, model, shape=label,
                                           source="fuzz"))
    table = {
        "model": {
            "vmem_bytes": model.vmem_bytes,
            "vmem_utilization": model.vmem_utilization,
            "smem_slots": model.smem_slots,
            "max_groups": model.max_groups,
            "max_limbs": model.max_limbs,
        },
        "shapes": [v.row() for v in verdicts],
        "passed": sum(1 for v in verdicts if v.ok),
        "failed": sum(1 for v in verdicts if not v.ok),
        "ssb_failed": [v.shape for v in ssb_verdicts if not v.ok],
        "_plan_specs": plan_specs,   # stripped before serialization
    }
    return table


def serializable_table(table: Dict[str, Any]) -> Dict[str, Any]:
    """The verdict table without the in-memory plan-spec keys."""
    return {k: v for k, v in table.items() if not k.startswith("_")}


def seed_blocklist(blocklist, table: Dict[str, Any]) -> int:
    """Pre-seed predicted-fail SSB shapes into a per-shape blocklist with
    their ``pallas_preflight_<rule>`` reason; returns how many were
    seeded. The engine then declines those shapes loudly (ledger carries
    the rule) instead of discovering the failure inside Mosaic."""
    plan_specs = table.get("_plan_specs", {})
    n = 0
    for row in table["shapes"]:
        if row["source"] != "ssb" or row["verdict"] == "pass":
            continue
        spec = plan_specs.get(row["shape"])
        if spec is None:
            continue
        blocklist.add(spec, reason=row["rule"])
        n += 1
    return n


def attach_verdicts(executor, table: Dict[str, Any]) -> int:
    """Wire a preflight run into an executor: verdicts surface on
    ``GET /debug/pallas`` and predicted-fail shapes join its blocklist."""
    executor.preflight_verdicts = serializable_table(table)
    return seed_blocklist(executor._pallas_blocked, table)


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m pinot_tpu.tools.preflight",
        description="Static TPU lowering preflight over the SSB plan "
                    "matrix + a fuzzed shape grid.")
    ap.add_argument("--rows", type=int, default=6000,
                    help="fixture rows for SSB plan extraction")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--no-fuzz", action="store_true")
    args = ap.parse_args(argv)

    table = run_preflight(rows=args.rows, fuzz=not args.no_fuzz)
    out = serializable_table(table)
    if args.as_json:
        print(json.dumps(out, indent=1, sort_keys=True))
    else:
        for row in out["shapes"]:
            mark = "PASS" if row["verdict"] == "pass" else \
                f"FAIL {row['rule']}: {row['detail']}"
            print(f"{row['source']:4} {row['shape']:22} {mark}")
        print(f"preflight: {out['passed']} pass, {out['failed']} fail "
              f"(ssb failures: {out['ssb_failed'] or 'none'})")
    return 1 if out["ssb_failed"] else 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
