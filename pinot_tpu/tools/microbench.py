"""Storage/kernel microbenchmarks (the pinot-perf JMH-equivalent).

Re-design of ``pinot-perf`` (41 JMH harnesses, e.g.
``BenchmarkFixedBitSVForwardIndexReader``, ``BenchmarkScanDocIdIterators``,
``BenchmarkCombineGroupBy``; run steps pinot-perf/README.md:28-39): a small
timed-loop runner over the framework's own hot primitives. Usage:

    python -m pinot_tpu.tools.microbench [name ...]

Prints one line per benchmark: name, ops/s (or rows/s), per-op latency.
"""

from __future__ import annotations

import sys
import time
from typing import Callable, Dict, List, Tuple

import numpy as np

N_ROWS = 1 << 20


def _timed(fn: Callable[[], None], min_time_s: float = 0.5,
           warmup: int = 2) -> Tuple[float, int]:
    """(seconds per call, iterations)."""
    for _ in range(warmup):
        fn()
    iters = 0
    t0 = time.perf_counter()
    while True:
        fn()
        iters += 1
        dt = time.perf_counter() - t0
        if dt >= min_time_s:
            return dt / iters, iters


def bench_bitpack() -> Dict:
    """Fixed-bit pack/unpack (ref: BenchmarkFixedBitSVForwardIndexReader)."""
    from pinot_tpu import native

    rng = np.random.default_rng(1)
    ids = rng.integers(0, 1 << 7, N_ROWS).astype(np.int32)
    packed = native.bitpack(ids, 7)
    t_pack, _ = _timed(lambda: native.bitpack(ids, 7))
    t_unpack, _ = _timed(lambda: native.bitunpack(packed, N_ROWS, 7))
    return {"pack_Mrows_s": round(N_ROWS / t_pack / 1e6, 1),
            "unpack_Mrows_s": round(N_ROWS / t_unpack / 1e6, 1),
            "native": native.available()}


def bench_varint_postings() -> Dict:
    """Posting-list encode/decode (ref: RoaringBitmap benchmarks)."""
    from pinot_tpu import native

    rng = np.random.default_rng(2)
    docs = np.unique(rng.integers(0, N_ROWS, N_ROWS // 4)).astype(np.int32)
    blob = native.varint_encode(docs)
    t_enc, _ = _timed(lambda: native.varint_encode(docs))
    t_dec, _ = _timed(lambda: native.varint_decode(blob, len(docs)))
    return {"encode_Mdocs_s": round(len(docs) / t_enc / 1e6, 1),
            "decode_Mdocs_s": round(len(docs) / t_dec / 1e6, 1)}


def bench_dictionary() -> Dict:
    """Sorted-dictionary lookups (ref: BenchmarkDictionary)."""
    from pinot_tpu.segment.dictionary import build_dictionary
    from pinot_tpu.spi.data import DataType

    vals = np.unique(np.random.default_rng(3).integers(0, 1 << 30, 100_000))
    d = build_dictionary(vals, DataType.LONG)
    probes = vals[::7]

    def lookups():
        for v in probes[:1000]:
            d.index_of(int(v))

    t, _ = _timed(lookups)
    return {"index_of_Mops_s": round(1000 / t / 1e6, 3)}


def bench_scan_kernel() -> Dict:
    """Masked filtered-sum over 1M rows — the SVScanDocIdIterator analogue
    (ref: BenchmarkScanDocIdIterators)."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(4)
    fwd = jnp.asarray(rng.integers(0, 1000, N_ROWS).astype(np.int32))
    vals = jnp.asarray(rng.random(N_ROWS).astype(np.float32))

    @jax.jit
    def scan(f, v, lo, hi):
        m = (f >= lo) & (f <= hi)
        return jnp.where(m, v, 0).sum(), m.sum()

    lo, hi = jnp.int32(100), jnp.int32(300)
    jax.block_until_ready(scan(fwd, vals, lo, hi))
    t, _ = _timed(lambda: jax.block_until_ready(scan(fwd, vals, lo, hi)))
    return {"Mrows_s": round(N_ROWS / t / 1e6, 1),
            "backend": jax.default_backend()}


def bench_group_by_kernel() -> Dict:
    """Composed-key segment_sum (ref: BenchmarkCombineGroupBy)."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(5)
    keys = jnp.asarray(rng.integers(0, 1024, N_ROWS).astype(np.int32))
    vals = jnp.asarray(rng.random(N_ROWS).astype(np.float32))

    # arrays as ARGUMENTS: closed-over constants get constant-folded and
    # the measurement degenerates to returning a cached array
    @jax.jit
    def grouped(v, k):
        return jax.ops.segment_sum(v, k, num_segments=1024)

    jax.block_until_ready(grouped(vals, keys))
    t, _ = _timed(lambda: jax.block_until_ready(grouped(vals, keys)))
    return {"Mrows_s": round(N_ROWS / t / 1e6, 1)}


def bench_datatable_wire() -> Dict:
    """Binary columnar DataTable round-trip (ref: BenchmarkDataTableSerDe)."""
    from pinot_tpu.common.datatable import DataTable
    from pinot_tpu.engine.results import DataSchema, QueryStats

    rng = np.random.default_rng(6)
    n = 50_000
    schema = DataSchema(["s", "i", "f"], ["STRING", "LONG", "DOUBLE"])
    rows = [[f"key{i % 1000}", int(v), float(v) / 3]
            for i, v in enumerate(rng.integers(0, 1 << 40, n))]
    dt = DataTable.for_selection(schema, rows, QueryStats())
    raw = dt.to_bytes()
    t_ser, _ = _timed(lambda: dt.to_bytes())
    t_de, _ = _timed(lambda: DataTable.from_bytes(raw))
    return {"serialize_Mrows_s": round(n / t_ser / 1e6, 2),
            "deserialize_Mrows_s": round(n / t_de / 1e6, 2),
            "bytes_per_row": round(len(raw) / n, 1)}


def bench_sql_parse() -> Dict:
    """Parser throughput (ref: BenchmarkQueryParser equivalents)."""
    from pinot_tpu.query import compile_query

    sql = ("SELECT a, b, sum(x), avg(y) FROM t WHERE a IN ('p','q') AND "
           "ts BETWEEN 100 AND 900 AND b != 'z' GROUP BY a, b "
           "ORDER BY sum(x) DESC LIMIT 50")
    t, _ = _timed(lambda: compile_query(sql))
    return {"queries_per_s": round(1 / t, 0)}


BENCHMARKS: Dict[str, Callable[[], Dict]] = {
    "bitpack": bench_bitpack,
    "varint_postings": bench_varint_postings,
    "dictionary": bench_dictionary,
    "scan_kernel": bench_scan_kernel,
    "group_by_kernel": bench_group_by_kernel,
    "datatable_wire": bench_datatable_wire,
    "sql_parse": bench_sql_parse,
}


def main(names: List[str]) -> int:
    import json

    chosen = names or sorted(BENCHMARKS)
    for name in chosen:
        fn = BENCHMARKS.get(name)
        if fn is None:
            print(f"unknown benchmark {name!r}; have {sorted(BENCHMARKS)}",
                  file=sys.stderr)
            return 2
        out = fn()
        print(json.dumps({"bench": name, **out}))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
