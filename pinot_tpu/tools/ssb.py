"""Star Schema Benchmark (SSB) — generator + query suite on a flat table.

The reference benchmarks Pinot with TPC-H/SSB-derived data through
``contrib/pinot-druid-benchmark`` (README.md:1-60: dbgen-generated lineitem,
response-time + throughput runners). SSB's own dbgen emits a ``lineorder``
fact table joined to date/customer/supplier/part dimensions; OLAP stores
(and the Pinot/Druid comparisons) run it **denormalized** — one flat table
with the dimension attributes the 13 queries touch. This module generates
that flat table directly with dbgen-faithful value distributions
(uniform quantity 1..50, discount 0..10, ~25 nations in 5 regions, 1000
brands in 25 categories under 5 mfgrs, 7 order years 1992-1998) scaled by
``sf`` (SF 1 = 6,000,000 lineorder rows).

Queries Q1.1-Q4.3 are the standard SSB flights rewritten against the flat
schema (d_* / c_* / s_* / p_* columns live on the fact row).
"""

from __future__ import annotations

import os
from typing import Dict, List

import numpy as np

from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema

ROWS_PER_SF = 6_000_000

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
# 5 nations per region (dbgen has 25 total); names chosen to match the
# query constants (UNITED STATES in AMERICA, UNITED KINGDOM in EUROPE)
NATIONS = {
    "AFRICA": ["ALGERIA", "ETHIOPIA", "KENYA", "MOROCCO", "MOZAMBIQUE"],
    "AMERICA": ["ARGENTINA", "BRAZIL", "CANADA", "PERU", "UNITED STATES"],
    "ASIA": ["CHINA", "INDIA", "INDONESIA", "JAPAN", "VIETNAM"],
    "EUROPE": ["FRANCE", "GERMANY", "ROMANIA", "RUSSIA", "UNITED KINGDOM"],
    "MIDDLE EAST": ["EGYPT", "IRAN", "IRAQ", "JORDAN", "SAUDI ARABIA"],
}


def ssb_schema() -> Schema:
    D, M = FieldType.DIMENSION, FieldType.METRIC
    I, S = DataType.INT, DataType.STRING
    return Schema("ssb_lineorder", [
        FieldSpec("lo_quantity", I, D),
        FieldSpec("lo_discount", I, D),
        FieldSpec("lo_extendedprice", I, M),
        FieldSpec("lo_revenue", I, M),
        FieldSpec("lo_supplycost", I, M),
        FieldSpec("d_year", I, D),
        FieldSpec("d_yearmonthnum", I, D),
        FieldSpec("d_weeknuminyear", I, D),
        FieldSpec("c_region", S, D),
        FieldSpec("c_nation", S, D),
        FieldSpec("c_city", S, D),
        FieldSpec("s_region", S, D),
        FieldSpec("s_nation", S, D),
        FieldSpec("s_city", S, D),
        FieldSpec("p_mfgr", S, D),
        FieldSpec("p_category", S, D),
        FieldSpec("p_brand1", S, D),
    ])


def _geo(rng: np.random.Generator, n: int):
    """(region, nation, city) columns with dbgen's nested structure:
    10 cities per nation, named '<nation[:9]>N' like dbgen ('UNITED KI1')."""
    region_idx = rng.integers(0, len(REGIONS), n)
    nation_pick = rng.integers(0, 5, n)
    city_pick = rng.integers(0, 10, n)
    regions = np.array(REGIONS)[region_idx]
    nation_table = np.array([NATIONS[r] for r in REGIONS])  # [5, 5]
    nations = nation_table[region_idx, nation_pick]
    city_table = np.array(
        [[f"{nat[:9]:<9}{c}" for c in range(10)]
         for r in REGIONS for nat in NATIONS[r]])           # [25, 10]
    nation_flat_idx = region_idx * 5 + nation_pick
    cities = city_table[nation_flat_idx, city_pick]
    return regions, nations, cities


def _flat_columns(rng: np.random.Generator, n: int) -> Dict[str, np.ndarray]:
    """Every flat column except d_year/d_yearmonthnum (callers draw those:
    globally uniform, or restricted to a segment's time window)."""
    quantity = rng.integers(1, 51, n).astype(np.int64)
    discount = rng.integers(0, 11, n).astype(np.int64)
    # dbgen: extendedprice = quantity * part price (905..~111k cents)
    price = rng.integers(905, 111_000, n)
    extended = (quantity * price).astype(np.int64)
    revenue = (extended * (100 - discount) // 100).astype(np.int64)
    supplycost = rng.integers(540, 66_600, n).astype(np.int64)
    week = rng.integers(1, 54, n).astype(np.int64)

    c_region, c_nation, c_city = _geo(rng, n)
    s_region, s_nation, s_city = _geo(rng, n)

    mfgr_i = rng.integers(1, 6, n)
    cat_i = rng.integers(1, 6, n)
    brand_i = rng.integers(1, 41, n)
    p_mfgr = np.array([f"MFGR#{i}" for i in range(1, 6)])[mfgr_i - 1]
    p_category = np.array(
        [f"MFGR#{m}{c}" for m in range(1, 6) for c in range(1, 6)]
    )[(mfgr_i - 1) * 5 + (cat_i - 1)]
    p_brand1 = np.array(
        [f"MFGR#{m}{c}{b:02d}" for m in range(1, 6) for c in range(1, 6)
         for b in range(1, 41)]
    )[((mfgr_i - 1) * 5 + (cat_i - 1)) * 40 + (brand_i - 1)]

    return {
        "lo_quantity": quantity, "lo_discount": discount,
        "lo_extendedprice": extended, "lo_revenue": revenue,
        "lo_supplycost": supplycost,
        "d_weeknuminyear": week,
        "c_region": c_region, "c_nation": c_nation, "c_city": c_city,
        "s_region": s_region, "s_nation": s_nation, "s_city": s_city,
        "p_mfgr": p_mfgr, "p_category": p_category, "p_brand1": p_brand1,
    }


def generate_flat(sf: float, seed: int = 42,
                  rows: int = 0) -> Dict[str, np.ndarray]:
    """Flattened lineorder columns, ``rows or int(sf * ROWS_PER_SF)`` rows."""
    n = rows or int(sf * ROWS_PER_SF)
    rng = np.random.default_rng(seed)
    cols = _flat_columns(rng, n)
    year = rng.integers(1992, 1999, n).astype(np.int64)
    month = rng.integers(1, 13, n).astype(np.int64)
    cols["d_year"] = year
    cols["d_yearmonthnum"] = year * 100 + month
    return cols


_ALL_MONTHS = [y * 100 + m for y in range(1992, 1999) for m in range(1, 13)]


def _segment_months(i: int, num_segments: int) -> List[int]:
    """Contiguous d_yearmonthnum window for segment ``i`` (84 months split
    across segments — real Pinot segments are time-bounded, and the window
    keeps the Q1.x time filters exercising the server min/max pruner)."""
    per = -(-len(_ALL_MONTHS) // num_segments)
    return _ALL_MONTHS[i * per:(i + 1) * per] or [_ALL_MONTHS[-1]]


def generate_segment_frame(i: int, num_segments: int, n: int,
                           seed: int = 42) -> Dict[str, np.ndarray]:
    """Segment ``i``'s flat rows: dbgen-faithful value distributions with
    d_yearmonthnum drawn from the segment's contiguous month window.
    Segments are INDEPENDENTLY generatable (seeded per segment), which is
    what makes the parallel builder embarrassingly parallel — no global
    sort, no cross-process data movement (ref: per-segment independence of
    SegmentIndexCreationDriverImpl.java:81)."""
    rng = np.random.default_rng(seed * 1_000_003 + i)
    cols = _flat_columns(rng, n)
    months = np.asarray(_segment_months(i, num_segments))
    ym = months[rng.integers(0, len(months), n)]
    cols["d_yearmonthnum"] = ym.astype(np.int64)
    cols["d_year"] = (ym // 100).astype(np.int64)
    return cols


def generate_table(num_segments: int, rows: int,
                   seed: int = 42) -> Dict[str, np.ndarray]:
    """Concatenated per-segment frames — EXACTLY the rows
    ``build_segments(num_segments, rows, seed)`` indexes (for the pandas
    oracle / external baseline side of parity checks)."""
    per = -(-rows // num_segments)
    frames = []
    left = rows
    for i in range(num_segments):
        take = min(per, left)
        if take <= 0:
            break
        frames.append(generate_segment_frame(i, num_segments, take, seed))
        left -= take
    return {k: np.concatenate([f[k] for f in frames]) for k in frames[0]}


PARTITION_COLUMN = "d_year"
NUM_YEARS = 7  # dbgen's 1992..1998


def generate_partitioned_frame(i: int, num_segments: int, n: int,
                               seed: int = 42) -> Dict[str, np.ndarray]:
    """Segment ``i``'s rows holding EXACTLY ONE ``d_year`` value
    (1992 + i mod 7) — the partition-aligned segment layout the broker's
    partition pruner feeds on: a ``d_year`` eq/range predicate then skips
    every server holding no matching segment (ref: Kafka-partitioned
    streams landing one partition per LLC segment)."""
    rng = np.random.default_rng(seed * 2_000_003 + i)
    cols = _flat_columns(rng, n)
    year = 1992 + (i % NUM_YEARS)
    cols["d_year"] = np.full(n, year, dtype=np.int64)
    cols["d_yearmonthnum"] = (year * 100
                              + rng.integers(1, 13, n)).astype(np.int64)
    return cols


def ssb_indexing_config(star_tree: bool = True, num_partitions: int = 0,
                        partition_column: str = PARTITION_COLUMN):
    """Default lineorder indexing: the MULTI-TREE star-tree set that puts
    every SSB flight on a sub-scan rung (ref: StarTreeIndexConfig
    multi-tree resolution; plan-time selection picks the cheapest fitting
    tree per query):

    - tree 0 — the PR-6 primary (Q2.x): category/brand drill-down under
      the region filters, revenue/supplycost pre-aggs, plus the Q1.x
      derived pair so the pair namespace is exercised on the primary too.
    - tree 1 — Q1.x: the ``sum(lo_extendedprice * lo_discount)`` derived
      pair (expression pre-aggregation) over the time/discount/quantity
      filter dims the flight predicates touch.
    - tree 2 — Q3.x: the geo drill-down (region -> nation -> city, both
      sides) with d_yearmonthnum for the Q3.4 month filter.
    - tree 3 — Q4.1/Q4.2: profit (``sum(lo_revenue - lo_supplycost)``
      derived pair) by customer nation / supplier nation × category.
    - tree 4 — Q4.3: profit by supplier city × brand under the
      s_nation/category filters (splitting Q4 across two trees keeps
      record counts bounded: nation×city×brand in ONE split order would
      dedup nothing at SSB scale).

    Keeping one tree per flight family bounds each tree's record count by
    its own dim-tuple space — the cost model the cheapest-tree selection
    scores against. ``num_partitions`` > 0 adds a Modulo
    segment-partition config on ``partition_column`` so the builder
    records per-segment partition metadata (the broker pruner's input);
    ``star_tree=False`` drops the trees (mesh-parity tests want every
    query on the sharded combine)."""
    from pinot_tpu.spi.table import (
        IndexingConfig,
        SegmentPartitionConfig,
        StarTreeIndexConfig,
    )

    trees = [
        StarTreeIndexConfig(
            dimensions_split_order=["d_year", "c_region", "s_region",
                                    "p_category", "p_brand1"],
            function_column_pairs=["SUM__lo_revenue", "SUM__lo_supplycost",
                                   "SUM__lo_extendedprice*lo_discount",
                                   "COUNT__*"],
            max_leaf_records=10_000),
        StarTreeIndexConfig(
            dimensions_split_order=["d_year", "d_yearmonthnum",
                                    "d_weeknuminyear", "lo_discount",
                                    "lo_quantity"],
            function_column_pairs=["SUM__lo_extendedprice*lo_discount",
                                   "SUM__lo_revenue", "COUNT__*"],
            max_leaf_records=10_000),
        StarTreeIndexConfig(
            dimensions_split_order=["d_year", "d_yearmonthnum", "c_region",
                                    "s_region", "c_nation", "s_nation",
                                    "c_city", "s_city"],
            function_column_pairs=["SUM__lo_revenue", "COUNT__*"],
            max_leaf_records=10_000),
        StarTreeIndexConfig(
            dimensions_split_order=["d_year", "c_region", "s_region",
                                    "p_mfgr", "c_nation", "s_nation",
                                    "p_category"],
            function_column_pairs=["SUM__lo_revenue-lo_supplycost",
                                   "COUNT__*"],
            max_leaf_records=10_000),
        StarTreeIndexConfig(
            dimensions_split_order=["d_year", "s_nation", "p_category",
                                    "s_city", "p_brand1"],
            function_column_pairs=["SUM__lo_revenue-lo_supplycost",
                                   "COUNT__*"],
            max_leaf_records=10_000),
    ] if star_tree else []
    spc = SegmentPartitionConfig(column_partition_map={
        partition_column: {"functionName": "Modulo",
                           "numPartitions": num_partitions},
    }) if num_partitions > 0 else None
    return IndexingConfig(star_tree_index_configs=trees,
                          segment_partition_config=spc)


def _build_one(i: int, num_segments: int, n: int, seed: int,
               out_dir: str, partitioned: bool = False,
               star_tree: bool = True) -> str:
    """Worker: generate + build one segment (process-pool entry point)."""
    from pinot_tpu.segment import SegmentBuilder

    if partitioned:
        frame = generate_partitioned_frame(i, num_segments, n, seed)
        name = f"ssb_part_{i}"
        cfg = ssb_indexing_config(star_tree=star_tree,
                                  num_partitions=num_segments)
    else:
        frame = generate_segment_frame(i, num_segments, n, seed)
        name = f"ssb_{i}"
        cfg = ssb_indexing_config(star_tree=star_tree)
    SegmentBuilder(ssb_schema(), name, indexing_config=cfg).build(frame,
                                                                  out_dir)
    return name


def build_segments(sf: float, out_dir: str, num_segments: int = 8,
                   seed: int = 42, rows: int = 0,
                   workers: int = 0, partitioned: bool = False,
                   star_tree: bool = True) -> List:
    """Build + load ``num_segments`` SSB segments. ``workers`` > 1 builds
    segments in a spawn process pool (per-column creators are independent in
    the reference too — SegmentIndexCreationDriverImpl.java:81); 0 picks
    min(num_segments, cpu_count). ``partitioned`` builds the
    one-``d_year``-per-segment layout with Modulo partition metadata
    (broker partition pruning); ``star_tree=False`` skips tree build."""
    from pinot_tpu.segment import load_segment

    n = rows or int(sf * ROWS_PER_SF)
    per = -(-n // num_segments)
    jobs = []
    left = n
    for i in range(num_segments):
        take = min(per, left)
        if take <= 0:
            break
        jobs.append((i, num_segments, take, seed, out_dir, partitioned,
                     star_tree))
        left -= take

    if not workers:
        workers = min(len(jobs), os.cpu_count() or 1)
    if workers > 1 and len(jobs) > 1:
        import multiprocessing as mp

        # SPAWN, not fork: the bench worker calls this with a live JAX
        # runtime whose threads/locks a forked child would inherit
        # mid-flight; the builder itself is numpy-only either way
        with mp.get_context("spawn").Pool(workers) as pool:
            names = pool.starmap(_build_one, jobs)
    else:
        names = [_build_one(*j) for j in jobs]
    return [load_segment(os.path.join(out_dir, nm)) for nm in names]


# The 13 SSB flights on the flat schema (constants follow the spec;
# selectivities match dbgen's).
QUERIES: Dict[str, str] = {
    "Q1.1": "SELECT sum(lo_extendedprice * lo_discount) FROM ssb_lineorder "
            "WHERE d_year = 1993 AND lo_discount BETWEEN 1 AND 3 "
            "AND lo_quantity < 25",
    "Q1.2": "SELECT sum(lo_extendedprice * lo_discount) FROM ssb_lineorder "
            "WHERE d_yearmonthnum = 199401 AND lo_discount BETWEEN 4 AND 6 "
            "AND lo_quantity BETWEEN 26 AND 35",
    "Q1.3": "SELECT sum(lo_extendedprice * lo_discount) FROM ssb_lineorder "
            "WHERE d_weeknuminyear = 6 AND d_year = 1994 "
            "AND lo_discount BETWEEN 5 AND 7 "
            "AND lo_quantity BETWEEN 26 AND 35",
    "Q2.1": "SELECT d_year, p_brand1, sum(lo_revenue) FROM ssb_lineorder "
            "WHERE p_category = 'MFGR#12' AND s_region = 'AMERICA' "
            "GROUP BY d_year, p_brand1 ORDER BY d_year, p_brand1",
    "Q2.2": "SELECT d_year, p_brand1, sum(lo_revenue) FROM ssb_lineorder "
            "WHERE p_brand1 BETWEEN 'MFGR#2221' AND 'MFGR#2228' "
            "AND s_region = 'ASIA' "
            "GROUP BY d_year, p_brand1 ORDER BY d_year, p_brand1",
    "Q2.3": "SELECT d_year, p_brand1, sum(lo_revenue) FROM ssb_lineorder "
            "WHERE p_brand1 = 'MFGR#2239' AND s_region = 'EUROPE' "
            "GROUP BY d_year, p_brand1 ORDER BY d_year, p_brand1",
    "Q3.1": "SELECT c_nation, s_nation, d_year, sum(lo_revenue) "
            "FROM ssb_lineorder "
            "WHERE c_region = 'ASIA' AND s_region = 'ASIA' "
            "AND d_year BETWEEN 1992 AND 1997 "
            "GROUP BY c_nation, s_nation, d_year "
            "ORDER BY d_year ASC, sum(lo_revenue) DESC",
    "Q3.2": "SELECT c_city, s_city, d_year, sum(lo_revenue) "
            "FROM ssb_lineorder "
            "WHERE c_nation = 'UNITED STATES' AND s_nation = 'UNITED STATES' "
            "AND d_year BETWEEN 1992 AND 1997 "
            "GROUP BY c_city, s_city, d_year "
            "ORDER BY d_year ASC, sum(lo_revenue) DESC",
    "Q3.3": "SELECT c_city, s_city, d_year, sum(lo_revenue) "
            "FROM ssb_lineorder "
            "WHERE c_city IN ('UNITED KI1', 'UNITED KI5') "
            "AND s_city IN ('UNITED KI1', 'UNITED KI5') "
            "AND d_year BETWEEN 1992 AND 1997 "
            "GROUP BY c_city, s_city, d_year "
            "ORDER BY d_year ASC, sum(lo_revenue) DESC",
    "Q3.4": "SELECT c_city, s_city, d_year, sum(lo_revenue) "
            "FROM ssb_lineorder "
            "WHERE c_city IN ('UNITED KI1', 'UNITED KI5') "
            "AND s_city IN ('UNITED KI1', 'UNITED KI5') "
            "AND d_yearmonthnum = 199712 "
            "GROUP BY c_city, s_city, d_year "
            "ORDER BY d_year ASC, sum(lo_revenue) DESC",
    "Q4.1": "SELECT d_year, c_nation, sum(lo_revenue - lo_supplycost) "
            "FROM ssb_lineorder "
            "WHERE c_region = 'AMERICA' AND s_region = 'AMERICA' "
            "AND p_mfgr IN ('MFGR#1', 'MFGR#2') "
            "GROUP BY d_year, c_nation ORDER BY d_year, c_nation",
    "Q4.2": "SELECT d_year, s_nation, p_category, "
            "sum(lo_revenue - lo_supplycost) FROM ssb_lineorder "
            "WHERE c_region = 'AMERICA' AND s_region = 'AMERICA' "
            "AND p_mfgr IN ('MFGR#1', 'MFGR#2') "
            "AND d_year IN (1997, 1998) "
            "GROUP BY d_year, s_nation, p_category "
            "ORDER BY d_year, s_nation, p_category",
    "Q4.3": "SELECT d_year, s_city, p_brand1, "
            "sum(lo_revenue - lo_supplycost) FROM ssb_lineorder "
            "WHERE s_nation = 'UNITED STATES' AND d_year IN (1997, 1998) "
            "AND p_category = 'MFGR#14' "
            "GROUP BY d_year, s_city, p_brand1 "
            "ORDER BY d_year, s_city, p_brand1",
}


def pandas_answer(cols: Dict[str, np.ndarray], qid: str):
    """Oracle for parity tests (pandas over the generated columns)."""
    import pandas as pd

    df = pd.DataFrame(cols)
    if qid == "Q1.1":
        m = ((df.d_year == 1993) & df.lo_discount.between(1, 3)
             & (df.lo_quantity < 25))
        return int((df.lo_extendedprice[m] * df.lo_discount[m]).sum())
    if qid == "Q1.2":
        m = ((df.d_yearmonthnum == 199401) & df.lo_discount.between(4, 6)
             & df.lo_quantity.between(26, 35))
        return int((df.lo_extendedprice[m] * df.lo_discount[m]).sum())
    if qid == "Q1.3":
        m = ((df.d_weeknuminyear == 6) & (df.d_year == 1994)
             & df.lo_discount.between(5, 7) & df.lo_quantity.between(26, 35))
        return int((df.lo_extendedprice[m] * df.lo_discount[m]).sum())
    raise ValueError(f"no pandas oracle for {qid}")
