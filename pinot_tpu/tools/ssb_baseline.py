"""External SSB baseline: the 13 flights hand-vectorized over pandas/numpy.

The benchmark's ``vs_baseline`` denominator (ref: the CPU engines in
``contrib/pinot-druid-benchmark/README.md:1-60``). Earlier rounds divided by
this framework's own host execution engine — a strawman (it interprets the
query per segment). This module is an INDEPENDENT, tightly-vectorized
columnar implementation of each query: boolean masks + pandas groupby over
categorical-encoded dimensions, the same "dictionary-encoded column scan"
work a real CPU OLAP engine does, with none of our engine's overheads.
duckdb/polars are not installable in this environment; pandas-over-numpy is
the strongest external CPU runner available. It doubles as the parity
oracle at bench scale (the per-segment host engine stays the oracle in
tests/).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np


def make_frame(cols: Dict[str, np.ndarray]):
    """Columns -> DataFrame with dictionary-encoded (categorical) dims —
    the fair analogue of a columnar engine's dictionary encoding."""
    import pandas as pd

    enc = {}
    for k, v in cols.items():
        enc[k] = pd.Categorical(v) if v.dtype.kind == "U" else v
    return pd.DataFrame(enc)


def _grouped(df, mask, keys: List[str], values, order_desc_value: bool):
    """Filtered group-by sum -> rows [*keys, sum]; ordered by keys, or by
    (first key asc, value desc) for the Q3 flights."""
    sub = df.loc[mask, keys].copy()
    sub["__v"] = values[mask] if isinstance(values, np.ndarray) \
        else np.asarray(values)[mask]
    g = sub.groupby(keys, observed=True, sort=True)["__v"].sum().reset_index()
    if order_desc_value:
        g = g.sort_values([keys[-1], "__v"], ascending=[True, False],
                          kind="stable")
    rows = []
    for rec in g.itertuples(index=False):
        *ks, v = rec
        rows.append(tuple(int(k) if isinstance(k, (int, np.integer)) else
                          str(k) for k in ks) + (float(v),))
    return rows


def run_query(df, qid: str) -> List[Tuple]:
    """One SSB flight; returns rows shaped like the engine's resultTable."""
    c = df
    if qid.startswith("Q1"):
        if qid == "Q1.1":
            m = ((c.d_year == 1993) & (c.lo_discount >= 1)
                 & (c.lo_discount <= 3) & (c.lo_quantity < 25))
        elif qid == "Q1.2":
            m = ((c.d_yearmonthnum == 199401) & (c.lo_discount >= 4)
                 & (c.lo_discount <= 6) & (c.lo_quantity >= 26)
                 & (c.lo_quantity <= 35))
        else:
            m = ((c.d_weeknuminyear == 6) & (c.d_year == 1994)
                 & (c.lo_discount >= 5) & (c.lo_discount <= 7)
                 & (c.lo_quantity >= 26) & (c.lo_quantity <= 35))
        v = (c.lo_extendedprice.to_numpy()[m.to_numpy()]
             * c.lo_discount.to_numpy()[m.to_numpy()]).sum()
        return [(float(v),)]

    rev = c.lo_revenue.to_numpy()
    if qid == "Q2.1":
        m = (c.p_category == "MFGR#12") & (c.s_region == "AMERICA")
        return _grouped(c, m.to_numpy(), ["d_year", "p_brand1"], rev, False)
    if qid == "Q2.2":
        b = c.p_brand1.astype(str)
        m = ((b >= "MFGR#2221") & (b <= "MFGR#2228")
             & (c.s_region == "ASIA").to_numpy())
        return _grouped(c, np.asarray(m), ["d_year", "p_brand1"], rev, False)
    if qid == "Q2.3":
        m = (c.p_brand1 == "MFGR#2239") & (c.s_region == "EUROPE")
        return _grouped(c, m.to_numpy(), ["d_year", "p_brand1"], rev, False)

    if qid == "Q3.1":
        m = ((c.c_region == "ASIA") & (c.s_region == "ASIA")
             & (c.d_year >= 1992) & (c.d_year <= 1997))
        return _grouped(c, m.to_numpy(), ["c_nation", "s_nation", "d_year"],
                        rev, True)
    if qid == "Q3.2":
        m = ((c.c_nation == "UNITED STATES") & (c.s_nation == "UNITED STATES")
             & (c.d_year >= 1992) & (c.d_year <= 1997))
        return _grouped(c, m.to_numpy(), ["c_city", "s_city", "d_year"],
                        rev, True)
    if qid in ("Q3.3", "Q3.4"):
        cities = ["UNITED KI1", "UNITED KI5"]
        m = c.c_city.isin(cities) & c.s_city.isin(cities)
        if qid == "Q3.3":
            m &= (c.d_year >= 1992) & (c.d_year <= 1997)
        else:
            m &= c.d_yearmonthnum == 199712
        return _grouped(c, m.to_numpy(), ["c_city", "s_city", "d_year"],
                        rev, True)

    profit = rev - c.lo_supplycost.to_numpy()
    if qid == "Q4.1":
        m = ((c.c_region == "AMERICA") & (c.s_region == "AMERICA")
             & c.p_mfgr.isin(["MFGR#1", "MFGR#2"]))
        return _grouped(c, m.to_numpy(), ["d_year", "c_nation"], profit,
                        False)
    if qid == "Q4.2":
        m = ((c.c_region == "AMERICA") & (c.s_region == "AMERICA")
             & c.p_mfgr.isin(["MFGR#1", "MFGR#2"])
             & c.d_year.isin([1997, 1998]))
        return _grouped(c, m.to_numpy(),
                        ["d_year", "s_nation", "p_category"], profit, False)
    if qid == "Q4.3":
        m = ((c.s_nation == "UNITED STATES") & c.d_year.isin([1997, 1998])
             & (c.p_category == "MFGR#14"))
        return _grouped(c, m.to_numpy(), ["d_year", "s_city", "p_brand1"],
                        profit, False)
    raise ValueError(f"unknown SSB query {qid!r}")


def rows_match(engine_rows, baseline_rows, rel: float = 1e-9) -> bool:
    """Order-insensitive parity (ORDER BY ties can legally differ)."""
    if len(engine_rows) != len(baseline_rows):
        return False

    def key(row):
        return tuple(str(x) for x in row[:-1])

    a = {key(r): r[-1] for r in engine_rows}
    b = {key(r): r[-1] for r in baseline_rows}
    if set(a) != set(b):
        return False
    for k, v in a.items():
        w = b[k]
        if abs(float(v) - float(w)) > rel * max(1.0, abs(float(w))):
            return False
    return True
