"""Tools: embedded cluster, quickstarts, CLI (ref: pinot-tools)."""

from pinot_tpu.tools.cluster import EmbeddedCluster

__all__ = ["EmbeddedCluster"]
