"""``device`` family: TPU-lowering obligations on the kernel builders.

The static half of the kernel preflight (tools/preflight.py is the
runtime-shape half): abstractly interpret the kernel-builder modules —
``engine/pallas_kernels.py``, ``parallel/combine.py``, ``engine/plan.py``,
``engine/startree_device.py`` — tracking symbolic shape/dtype facts per
ref, and discharge the lowering obligations a real chip would otherwise
discover at Mosaic time:

- ``blockspec`` — every ``pl.BlockSpec`` block shape's LANE (last) dim is
  provably a multiple of 128 (integer arithmetic over PALLAS_TILE, or the
  ``num_groups_padded`` div-128 fact, whose provenance is itself checked:
  every value reaching a spec's ``num_groups_padded`` must be ceil-padded
  to ``_G_CHUNK``); index-map arity matches the grid rank; the out_specs /
  out_shape tuples and the kernel body's output unpack agree.
- ``refs`` — ``value_limbs`` planes size the ref blocks: the count the
  in_specs value-block loop appends and the count the kernel body slices
  ``refs`` with must BOTH be the ``l if l else 1`` accumulation over
  ``spec.value_limbs`` (a drift means the kernel reads someone else's
  plane).
- ``smem-cap`` — SMEM scalar-prefetch slots stay bounded by the
  ``pinot.server.query.pallas.lut.max.runs`` config table: the module's
  ``DEFAULT_LUT_RUN_CAP`` must not exceed the config default, and every
  ``_lut_runs`` cap argument must flow from the threaded ``lut_run_cap``
  (or stay under the config value).
- ``kernel-dtype`` — no i64/f64 inside a Pallas kernel body (Mosaic has
  no i64 vectors; f64 is unsupported on TPU), and no i64 compute outside
  the blessed limb-reassembly functions (``assemble_outputs``, the
  sharded combine's post-kernel widening).
- ``mesh-axis`` — every ``psum``/``pmin``/``pmax``/``all_gather``/
  ``all_to_all``/``axis_index`` axis argument in the combine builders
  resolves to a declared mesh axis name (``SEG_AXIS``/``DOC_AXIS``),
  interprocedurally through helper params (``_cross_reduce``'s
  ``axes``).
- ``pow2-narrow`` — ``narrow_plan_groups`` preserves the pow2 capacity
  slot and routes the narrowed group count through ``_next_pow2``.
- ``idxcap`` — the star-tree device rung's padded index buffer is sized
  by the plan spec's capacity slot.

Like every lint family: pure stdlib ``ast``, scoped by module basename so
test fixtures (scratch copies of the real modules with one seeded
mutation) exercise each obligation. Cross-module constants (staging
``PALLAS_TILE``, config ``DEFAULT_PALLAS_LUT_MAX_RUNS``) are read from
the scanned tree when present, the installed package otherwise — never
imported.
"""

from __future__ import annotations

import ast
import os

from typing import Any, Dict, List, Optional, Set, Tuple

from pinot_tpu.tools.lint.core import (
    Finding,
    LintContext,
    Module,
    register,
)

_PKG_ROOT = os.path.normpath(os.path.join(
    os.path.dirname(os.path.abspath(__file__)), os.pardir, os.pardir))

_LANE = 128

# i64/f64 dtype attribute names that must not appear in kernel bodies
_WIDE_DTYPES = {"int64", "uint64", "float64"}
# top-level functions blessed to hold i64/f64 OUTSIDE kernel bodies:
# the limb-reassembly decode and the sharded combine's post-kernel
# cross-device widening (both run after pallas returns)
_BLESSED_WIDE = {"assemble_outputs", "build_sharded_pallas_kernel"}

_COLLECTIVE_AXIS_ARG = {
    "psum": 1, "pmin": 1, "pmax": 1, "all_gather": 1, "all_to_all": 1,
    "axis_index": 0, "pbroadcast": 1, "ppermute": 1, "pshuffle": 1,
}


# -- cross-module constant loading (mirrors declines._load_tables) ----------

def _module_tree(ctx: LintContext, suffix: str,
                 fallback: str) -> Optional[ast.AST]:
    for mod in ctx.modules:
        if mod.relpath.replace(os.sep, "/").endswith(suffix):
            return mod.tree
    path = os.path.normpath(os.path.join(_PKG_ROOT, fallback))
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return ast.parse(f.read(), filename=path)


def _int_consts(tree: Optional[ast.AST]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    if tree is None:
        return out
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, int) \
                and not isinstance(node.value.value, bool):
            out[node.targets[0].id] = node.value.value
    return out


def _staging_consts(ctx: LintContext) -> Dict[str, int]:
    consts = _int_consts(_module_tree(
        ctx, "engine/staging.py", os.path.join("engine", "staging.py")))
    consts.setdefault("PALLAS_TILE", 4096)
    consts.setdefault("LIMB_BITS", 12)
    return consts


def _config_lut_cap(ctx: LintContext) -> Optional[int]:
    tree = _module_tree(ctx, "spi/config.py",
                        os.path.join("spi", "config.py"))
    return _int_consts(tree).get("DEFAULT_PALLAS_LUT_MAX_RUNS")


# -- tiny symbolic integer evaluator ----------------------------------------

class _Div128:
    """Marker fact: value provably a multiple of 128."""


DIV128 = _Div128()


def _is_ceil_chunk(expr: ast.expr, env: Dict[str, Any]) -> bool:
    """``-(-x // C) * C`` with C a lane-multiple constant."""
    if not (isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Mult)):
        return False
    left, right = expr.left, expr.right
    if not isinstance(right, ast.Name):
        return False
    c = env.get(right.id)
    if not (isinstance(c, int) and c and c % _LANE == 0):
        return False
    return (isinstance(left, ast.UnaryOp)
            and isinstance(left.op, ast.USub)
            and isinstance(left.operand, ast.BinOp)
            and isinstance(left.operand.op, ast.FloorDiv)
            and isinstance(left.operand.right, ast.Name)
            and left.operand.right.id == right.id)


def _eval_int(expr: ast.expr, env: Dict[str, Any]) -> Optional[Any]:
    """-> int, DIV128, or None (unknown)."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int) \
            and not isinstance(expr.value, bool):
        return expr.value
    if isinstance(expr, ast.Name):
        return env.get(expr.id)
    if isinstance(expr, ast.Attribute):
        if expr.attr == "num_groups_padded":
            return DIV128   # provenance checked by _check_gpad
        return None
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.USub):
        v = _eval_int(expr.operand, env)
        return -v if isinstance(v, int) else None
    if isinstance(expr, ast.BinOp):
        if _is_ceil_chunk(expr, env):
            return DIV128
        a = _eval_int(expr.left, env)
        b = _eval_int(expr.right, env)
        if isinstance(a, int) and isinstance(b, int):
            try:
                if isinstance(expr.op, ast.Add):
                    return a + b
                if isinstance(expr.op, ast.Sub):
                    return a - b
                if isinstance(expr.op, ast.Mult):
                    return a * b
                if isinstance(expr.op, ast.FloorDiv):
                    return a // b
                if isinstance(expr.op, ast.LShift):
                    return a << b
            except (ZeroDivisionError, ValueError):
                return None
    return None


def _lane_ok(dim: Any) -> bool:
    if dim is DIV128:
        return True
    return isinstance(dim, int) and dim > 0 and dim % _LANE == 0


# -- shared AST helpers ------------------------------------------------------

def _callee(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _kwarg(node: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in node.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _func_env(fn: ast.AST, base: Dict[str, Any]) -> Dict[str, Any]:
    """Integer env from a function's straight-line assignments."""
    env = dict(base)
    for st in ast.walk(fn):
        if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                and isinstance(st.targets[0], ast.Name):
            v = _eval_int(st.value, env)
            if v is not None:
                env[st.targets[0].id] = v
    return env


# -- blockspec / refs / grid (pallas_kernels.py builders) --------------------

def _tuple_elts(expr: ast.expr) -> Optional[List[ast.expr]]:
    """Flatten a tuple expression, following ``(a, b) + (c,)`` concats."""
    if isinstance(expr, ast.Tuple):
        return list(expr.elts)
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        a = _tuple_elts(expr.left)
        b = _tuple_elts(expr.right)
        if a is not None and b is not None:
            return a + b
    return None


def _is_smem(call: ast.Call) -> bool:
    ms = _kwarg(call, "memory_space")
    return isinstance(ms, ast.Attribute) and ms.attr == "SMEM"


def _block_helpers(fn: ast.AST) -> Dict[str, Tuple[List[ast.expr],
                                                   List[ast.expr],
                                                   Optional[ast.Lambda]]]:
    """Local defs that wrap pl.BlockSpec with a shape concat around their
    single parameter: name -> (prefix elts, suffix elts, index-map
    lambda). Effective call-site shape = prefix + arg + suffix."""
    out = {}
    for st in ast.walk(fn):
        if not isinstance(st, ast.FunctionDef) or st is fn:
            continue
        if len(st.args.args) != 1:
            continue
        param = st.args.args[0].arg
        for sub in ast.walk(st):
            if isinstance(sub, ast.Return) \
                    and isinstance(sub.value, ast.Call) \
                    and _callee(sub.value) == "BlockSpec" \
                    and sub.value.args:
                shape = sub.value.args[0]
                if not (isinstance(shape, ast.BinOp)
                        and isinstance(shape.op, ast.Add)):
                    continue
                lam = (sub.value.args[1]
                       if len(sub.value.args) > 1
                       and isinstance(sub.value.args[1], ast.Lambda)
                       else None)
                if isinstance(shape.left, ast.Tuple) \
                        and isinstance(shape.right, ast.Name) \
                        and shape.right.id == param:
                    out[st.name] = (list(shape.left.elts), [], lam)
                elif isinstance(shape.right, ast.Tuple) \
                        and isinstance(shape.left, ast.Name) \
                        and shape.left.id == param:
                    out[st.name] = ([], list(shape.right.elts), lam)
    return out


def _check_builder(mod: Module, fn: ast.FunctionDef,
                   base_env: Dict[str, Any],
                   findings: List[Finding]) -> None:
    """Blockspec + refs + grid obligations inside one builder function
    that calls pl.pallas_call."""
    env = _func_env(fn, base_env)
    helpers = _block_helpers(fn)

    pallas_call = None
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and _callee(node) == "pallas_call":
            pallas_call = node
            break
    if pallas_call is None:
        return
    grid = _kwarg(pallas_call, "grid")
    grid_rank = len(grid.elts) if isinstance(grid, ast.Tuple) else None

    def note(line: int, sym: str, msg: str) -> None:
        findings.append(Finding("device", mod.relpath, line,
                                f"{fn.name}:{sym}", msg))

    def check_shape(call_line: int, elts: List[ast.expr],
                    anchor: str) -> None:
        if not elts:
            return
        dim = _eval_int(elts[-1], env)
        if not _lane_ok(dim):
            note(call_line, f"blockspec:{anchor}",
                 f"BlockSpec lane dim {ast.unparse(elts[-1])} is not "
                 f"provably a multiple of {_LANE} — Mosaic tiles the "
                 f"last dim by lanes; swap/realign the block shape")

    def check_lambda(call_line: int, lam: Optional[ast.Lambda],
                     anchor: str) -> None:
        if lam is None or grid_rank is None:
            return
        if len(lam.args.args) != grid_rank:
            note(call_line, f"blockspec:{anchor}",
                 f"index map takes {len(lam.args.args)} args but the "
                 f"grid has rank {grid_rank}")

    seen_anchor: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = _callee(node)
        if name == "BlockSpec":
            if _is_smem(node) or not node.args:
                continue
            elts = _tuple_elts(node.args[0])
            if elts is None:
                continue   # helper-internal concat handled at call sites
            anchor = ast.unparse(node.args[0])[:40]
            if anchor in seen_anchor:
                continue
            seen_anchor.add(anchor)
            check_shape(node.lineno, elts, anchor)
            lam = (node.args[1] if len(node.args) > 1
                   and isinstance(node.args[1], ast.Lambda) else None)
            check_lambda(node.lineno, lam, anchor)
        elif name in helpers and node.args:
            prefix, suffix, lam = helpers[name]
            arg_elts = _tuple_elts(node.args[0])
            if arg_elts is None:
                continue
            anchor = f"{name}({ast.unparse(node.args[0])[:36]})"
            if anchor in seen_anchor:
                continue
            seen_anchor.add(anchor)
            check_shape(node.lineno, prefix + arg_elts + suffix, anchor)
            check_lambda(node.lineno, lam, anchor)

    # out_specs / out_shape / kernel output unpack arity
    out_specs = _kwarg(pallas_call, "out_specs")
    out_shape = _kwarg(pallas_call, "out_shape")
    n_specs = len(out_specs.elts) if isinstance(out_specs, ast.Tuple) \
        else None
    n_shape = len(out_shape.elts) if isinstance(out_shape, ast.Tuple) \
        else None
    if n_specs is not None and n_shape is not None and n_specs != n_shape:
        note(pallas_call.lineno, "blockspec:outs",
             f"out_specs has {n_specs} entries but out_shape {n_shape}")

    kernel_fn = None
    if pallas_call.args and isinstance(pallas_call.args[0], ast.Name):
        kname = pallas_call.args[0].id
        for sub in ast.walk(fn):
            if isinstance(sub, ast.FunctionDef) and sub.name == kname:
                kernel_fn = sub
                break
    if kernel_fn is not None and n_specs is not None:
        for st in ast.walk(kernel_fn):
            if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Tuple) \
                    and isinstance(st.value, ast.Subscript) \
                    and isinstance(st.value.value, ast.Name) \
                    and st.value.value.id == "refs" \
                    and isinstance(st.value.slice, ast.Slice) \
                    and st.value.slice.upper is None:
                n_outs = len(st.targets[0].elts)
                if n_outs != n_specs:
                    note(st.lineno, "blockspec:outs",
                         f"kernel unpacks {n_outs} output refs but "
                         f"out_specs binds {n_specs}")

    _check_value_refs(mod, fn, kernel_fn, findings)


def _check_value_refs(mod: Module, fn: ast.FunctionDef,
                      kernel_fn: Optional[ast.FunctionDef],
                      findings: List[Finding]) -> None:
    """``refs`` obligation: the limb-plane ref count (``l if l else 1``
    over spec.value_limbs) must size BOTH the in_specs value-block loop
    and the kernel's values slice."""
    acc_name = None
    for st in ast.walk(fn):
        if isinstance(st, ast.AugAssign) and isinstance(st.op, ast.Add) \
                and isinstance(st.target, ast.Name) \
                and isinstance(st.value, ast.IfExp):
            acc_name = st.target.id
    if acc_name is None:
        return

    def note(line: int, sym: str, msg: str) -> None:
        findings.append(Finding("device", mod.relpath, line,
                                f"{fn.name}:{sym}", msg))

    # in_specs value-block loop: for _ in range(X): in_specs.append(...)
    for st in ast.walk(fn):
        if isinstance(st, ast.For) and isinstance(st.iter, ast.Call) \
                and _callee(st.iter) == "range" \
                and len(st.iter.args) == 1 \
                and isinstance(st.iter.args[0], ast.Name):
            rng = st.iter.args[0].id
            appends_spec = any(
                isinstance(s, ast.Call) and _callee(s) == "append"
                and isinstance(s.func, ast.Attribute)
                and isinstance(s.func.value, ast.Name)
                and s.func.value.id == "in_specs"
                for s in ast.walk(st))
            if appends_spec and rng != acc_name:
                note(st.lineno, "refs:in_specs",
                     f"value ref blocks appended {rng} times but the "
                     f"limb-plane count is {acc_name} — spec.value_limbs "
                     f"planes must size the ref blocks")
    # kernel values slice: refs[a : a + X]
    if kernel_fn is None:
        return
    for st in ast.walk(kernel_fn):
        if isinstance(st, ast.Subscript) \
                and isinstance(st.value, ast.Name) \
                and st.value.id == "refs" \
                and isinstance(st.slice, ast.Slice) \
                and isinstance(st.slice.upper, ast.BinOp) \
                and isinstance(st.slice.upper.op, ast.Add) \
                and isinstance(st.slice.upper.right, ast.Name):
            up = st.slice.upper.right.id
            if up != acc_name:
                note(st.lineno, "refs:slice",
                     f"kernel slices value refs with {up} but the "
                     f"limb-plane count is {acc_name}")


# -- num_groups_padded provenance (gpad) ------------------------------------

def _check_gpad(mod: Module, env: Dict[str, Any],
                findings: List[Finding]) -> None:
    """Every value reaching a spec's ``num_groups_padded`` must be
    ceil-padded to a lane-multiple chunk (the div-128 fact the blockspec
    evaluator relies on)."""
    def assigns_of(fn: ast.AST, name: str) -> List[ast.expr]:
        return [st.value for st in ast.walk(fn)
                if isinstance(st, ast.Assign) and len(st.targets) == 1
                and isinstance(st.targets[0], ast.Name)
                and st.targets[0].id == name]

    for fn in ast.walk(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and _callee(node) in ("PallasSpec", "PallasPlan")):
                continue
            expr = _kwarg(node, "num_groups_padded")
            if expr is None:
                continue
            ok = False
            if isinstance(expr, ast.Attribute):
                ok = expr.attr == "num_groups_padded"
            elif _lane_ok(_eval_int(expr, env)):
                ok = True
            elif isinstance(expr, ast.Name):
                srcs = assigns_of(fn, expr.id)
                ok = bool(srcs) and all(
                    _lane_ok(_eval_int(s, _func_env(fn, env)))
                    or _is_ceil_chunk(s, env)
                    for s in srcs)
            if not ok:
                findings.append(Finding(
                    "device", mod.relpath, node.lineno,
                    f"gpad:{ast.unparse(expr)[:40]}",
                    f"num_groups_padded={ast.unparse(expr)} is not "
                    f"provably lane-padded (ceil to _G_CHUNK); the "
                    f"one-hot chunk loop and out blocks assume %128"))


# -- SMEM cap vs the config table (smem-cap) --------------------------------

def _check_smem_cap(mod: Module, cfg_cap: Optional[int],
                    findings: List[Finding]) -> None:
    if cfg_cap is None:
        return
    env = _int_consts(mod.tree)
    cap = env.get("DEFAULT_LUT_RUN_CAP")
    if cap is not None and cap > cfg_cap:
        line = next((n.lineno for n in ast.walk(mod.tree)
                     if isinstance(n, ast.Assign)
                     and isinstance(n.targets[0], ast.Name)
                     and n.targets[0].id == "DEFAULT_LUT_RUN_CAP"), 0)
        findings.append(Finding(
            "device", mod.relpath, line, "smem-cap:DEFAULT_LUT_RUN_CAP",
            f"DEFAULT_LUT_RUN_CAP={cap} exceeds the config table's "
            f"DEFAULT_PALLAS_LUT_MAX_RUNS={cfg_cap} "
            f"(pinot.server.query.pallas.lut.max.runs) — SMEM "
            f"scalar-prefetch slots would outgrow the budget the "
            f"preflight verifies"))
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and _callee(node) == "_lut_runs"
                and len(node.args) >= 2):
            continue
        arg = node.args[1]
        names = {n.id for n in ast.walk(arg) if isinstance(n, ast.Name)}
        if "lut_run_cap" in names:
            continue
        v = _eval_int(arg, env)
        if isinstance(v, int) and v > cfg_cap:
            findings.append(Finding(
                "device", mod.relpath, node.lineno,
                f"smem-cap:lut_runs:{v}",
                f"_lut_runs cap {v} bypasses the configured "
                f"lut.max.runs bound ({cfg_cap})"))


# -- i64/f64 bans (kernel-dtype) --------------------------------------------

def _kernel_body_names(mod: Module) -> Set[int]:
    """ids of FunctionDef nodes that are pallas kernel bodies (passed by
    name as the first arg to pallas_call, plus their nested defs)."""
    bodies: Set[int] = set()
    for fn in ast.walk(mod.tree):
        if not isinstance(fn, ast.FunctionDef):
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) \
                    and _callee(node) == "pallas_call" \
                    and node.args and isinstance(node.args[0], ast.Name):
                kname = node.args[0].id
                for sub in ast.walk(fn):
                    if isinstance(sub, ast.FunctionDef) \
                            and sub.name == kname:
                        for inner in ast.walk(sub):
                            if isinstance(inner, ast.FunctionDef):
                                bodies.add(id(inner))
                        bodies.add(id(sub))
    return bodies


def _check_dtypes(mod: Module, findings: List[Finding]) -> None:
    bodies = _kernel_body_names(mod)
    seen: Set[str] = set()

    def walk(node: ast.AST, top: Optional[str], in_kernel: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if top is None:
                top = node.name
            in_kernel = in_kernel or id(node) in bodies
        for child in ast.iter_child_nodes(node):
            walk(child, top, in_kernel)
        if isinstance(node, ast.Attribute) \
                and node.attr in _WIDE_DTYPES:
            if in_kernel:
                key = f"kernel:{node.lineno}"
                if key not in seen:
                    seen.add(key)
                    findings.append(Finding(
                        "device", mod.relpath, node.lineno,
                        f"kernel-dtype:{node.attr}:{top}",
                        f"{node.attr} inside a Pallas kernel body — "
                        f"Mosaic has no 64-bit vectors; use the "
                        f"limb-plane scheme (i32 rows + carry chain)"))
            elif top not in _BLESSED_WIDE:
                key = f"out:{node.lineno}"
                if key not in seen:
                    seen.add(key)
                    findings.append(Finding(
                        "device", mod.relpath, node.lineno,
                        f"kernel-dtype:{node.attr}:{top or '<module>'}",
                        f"{node.attr} outside the blessed "
                        f"limb-reassembly functions "
                        f"({sorted(_BLESSED_WIDE)}) — widen only in the "
                        f"post-kernel decode/psum layer"))

    walk(mod.tree, None, False)


# -- mesh axis names (mesh-axis) --------------------------------------------

class _AxisChecker:
    """Interprocedural axis-name resolution for the combine builders."""

    def __init__(self, mod: Module):
        self.mod = mod
        self.axis_values: Set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id.endswith("_AXIS") \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                self.axis_values.add(node.value.value)
        self.axis_names = {
            n.targets[0].id for n in ast.walk(mod.tree)
            if isinstance(n, ast.Assign) and len(n.targets) == 1
            and isinstance(n.targets[0], ast.Name)
            and n.targets[0].id.endswith("_AXIS")}
        # function name -> (def node, enclosing scope chain)
        self.funcs: Dict[str, Tuple[ast.FunctionDef, Tuple]] = {}
        self._index(mod.tree, ())

    def _index(self, node: ast.AST, chain: Tuple) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.FunctionDef):
                self.funcs.setdefault(child.name, (child, chain))
                self._index(child, chain + (child,))
            else:
                self._index(child, chain)

    def _scope_assigns(self, fn: ast.FunctionDef,
                       chain: Tuple) -> Dict[str, ast.expr]:
        """Name -> value expr across the scope chain (outer first), NOT
        descending into nested defs — their assigns are their own."""
        env: Dict[str, ast.expr] = {}

        def local(scope: ast.AST) -> None:
            stack: List[ast.AST] = list(ast.iter_child_nodes(scope))
            while stack:
                st = stack.pop()
                if isinstance(st, (ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                        and isinstance(st.targets[0], ast.Name):
                    env[st.targets[0].id] = st.value
                if isinstance(st, (ast.For, ast.AsyncFor)) \
                        and isinstance(st.target, ast.Name):
                    env[st.target.id] = ("elem", st.iter)
                stack.extend(ast.iter_child_nodes(st))

        for scope in chain + (fn,):
            local(scope)
        return env

    def resolve(self, expr: Any, env: Dict[str, ast.expr],
                params: Set[str], visited: Set[str], depth: int):
        """-> ("ok",) | ("bad", detail) | ("params", set) | ("unknown",)"""
        if isinstance(expr, tuple) and expr and expr[0] == "elem":
            return self.resolve(expr[1], env, params, visited, depth)
        if isinstance(expr, ast.Constant):
            if isinstance(expr.value, str):
                if expr.value in self.axis_values:
                    return ("ok",)
                return ("bad", f"axis {expr.value!r} is not a declared "
                               f"mesh axis {sorted(self.axis_values)}")
            return ("unknown",)
        if isinstance(expr, ast.Name):
            if expr.id in self.axis_names:
                return ("ok",)
            if expr.id in visited:
                return (("params", {expr.id}) if expr.id in params
                        else ("unknown",))
            if expr.id in env:
                return self.resolve(env[expr.id], env, params,
                                    visited | {expr.id}, depth)
            if expr.id in params:
                return ("params", {expr.id})
            return ("unknown",)
        if isinstance(expr, ast.Tuple):
            out_params: Set[str] = set()
            unknown = False
            for e in expr.elts:
                r = self.resolve(e, env, params, visited, depth)
                if r[0] == "bad":
                    return r
                if r[0] == "params":
                    out_params |= r[1]
                elif r[0] == "unknown":
                    unknown = True
            if out_params:
                return ("params", out_params)
            return ("unknown",) if unknown else ("ok",)
        if isinstance(expr, ast.Call) and _callee(expr) == "tuple" \
                and expr.args:
            return self.resolve(expr.args[0], env, params, visited, depth)
        if isinstance(expr, ast.GeneratorExp):
            return self.resolve(expr.generators[0].iter, env, params,
                                visited, depth)
        return ("unknown",)

    def check(self) -> List[Finding]:
        findings: List[Finding] = []
        if not self.axis_values:
            return findings
        # pass 1: direct resolutions + param obligations per function
        obligations: Dict[str, Set[str]] = {}
        for fname, (fn, chain) in self.funcs.items():
            env = self._scope_assigns(fn, chain)
            params = ({a.arg for a in fn.args.args}
                      | {a.arg for a in fn.args.kwonlyargs})
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                cal = _callee(node)
                if cal not in _COLLECTIVE_AXIS_ARG:
                    continue
                idx = _COLLECTIVE_AXIS_ARG[cal]
                arg = (node.args[idx] if len(node.args) > idx
                       else _kwarg(node, "axis_name"))
                if arg is None:
                    continue
                r = self.resolve(arg, env, params, set(), 0)
                if r[0] == "bad":
                    findings.append(Finding(
                        "device", self.mod.relpath, node.lineno,
                        f"mesh-axis:{cal}:{ast.unparse(arg)[:30]}",
                        f"{cal} axis {ast.unparse(arg)}: {r[1]}"))
                elif r[0] == "params":
                    obligations.setdefault(fname, set()).update(r[1])
        # pass 2: param obligations discharge at call sites
        for fname, pnames in obligations.items():
            fn, _chain = self.funcs[fname]
            pos = {a.arg: i for i, a in enumerate(fn.args.args)}
            for caller_name, (caller, cchain) in self.funcs.items():
                env = self._scope_assigns(caller, cchain)
                cparams = {a.arg for a in caller.args.args}
                for node in ast.walk(caller):
                    if not (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Name)
                            and node.func.id == fname):
                        continue
                    for pname in pnames:
                        i = pos.get(pname)
                        arg = (node.args[i] if i is not None
                               and len(node.args) > i
                               else _kwarg(node, pname))
                        if arg is None:
                            continue
                        r = self.resolve(arg, env, cparams, set(), 1)
                        if r[0] == "bad":
                            findings.append(Finding(
                                "device", self.mod.relpath, node.lineno,
                                f"mesh-axis:{fname}:{pname}",
                                f"{fname}({pname}="
                                f"{ast.unparse(arg)[:30]}): {r[1]}"))
                        # params-of-params: one more hop is enough for
                        # the combine builders; deeper stays silent
        return findings


# -- narrow_plan_groups pow2 preservation (pow2-narrow) ---------------------

def _check_narrow(mod: Module, findings: List[Finding]) -> None:
    fn = next((n for n in ast.walk(mod.tree)
               if isinstance(n, ast.FunctionDef)
               and n.name == "narrow_plan_groups"), None)
    if fn is None:
        return
    # names unpacked from plan.spec (the capacity slot must come back)
    spec_names: Set[str] = set()
    assigns: Dict[str, ast.expr] = {}
    for st in ast.walk(fn):
        if isinstance(st, ast.Assign) and len(st.targets) == 1:
            t = st.targets[0]
            if isinstance(t, ast.Tuple) \
                    and isinstance(st.value, ast.Attribute) \
                    and st.value.attr == "spec":
                spec_names |= {e.id for e in t.elts
                               if isinstance(e, ast.Name)}
            elif isinstance(t, ast.Name):
                assigns[t.id] = st.value
    for st in ast.walk(fn):
        if not (isinstance(st, ast.Assign) and len(st.targets) == 1
                and isinstance(st.targets[0], ast.Name)
                and st.targets[0].id == "spec"
                and isinstance(st.value, ast.Tuple)
                and len(st.value.elts) == 5):
            continue
        ng, cap = st.value.elts[3], st.value.elts[4]
        ng_src = assigns.get(ng.id) if isinstance(ng, ast.Name) else None
        if not (isinstance(ng_src, ast.Call)
                and _callee(ng_src) == "_next_pow2"):
            findings.append(Finding(
                "device", mod.relpath, st.lineno, "pow2-narrow:num_groups",
                "narrowed num_groups does not flow through _next_pow2 — "
                "the dense rung and the vmapped cache key assume pow2 "
                "padding survives narrowing"))
        if not (isinstance(cap, ast.Name) and cap.id in spec_names):
            findings.append(Finding(
                "device", mod.relpath, st.lineno, "pow2-narrow:capacity",
                "narrowed spec does not preserve the original capacity "
                "slot — block/tile sizing would drift from the staged "
                "arrays"))


# -- star-tree idx pad sized by the spec capacity (idxcap) ------------------

def _check_idxcap(mod: Module, findings: List[Finding]) -> None:
    for fn in ast.walk(mod.tree):
        if not isinstance(fn, ast.FunctionDef):
            continue
        assigns: Dict[str, ast.expr] = {}
        for st in ast.walk(fn):
            if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name):
                assigns[st.targets[0].id] = st.value
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and _callee(node) == "zeros" and node.args):
                continue
            dt = _kwarg(node, "dtype")
            if not (isinstance(dt, ast.Attribute) and dt.attr == "int32"):
                continue
            size = node.args[0]
            src = assigns.get(size.id) if isinstance(size, ast.Name) \
                else size
            ok = (isinstance(src, ast.Subscript)
                  and isinstance(src.value, ast.Attribute)
                  and src.value.attr == "spec")
            if not ok:
                # symbol keyed on the size expr, not the enclosing def:
                # nested launch closures are walked by both scopes
                findings.append(Finding(
                    "device", mod.relpath, node.lineno,
                    f"idxcap:{ast.unparse(size)[:30]}",
                    "padded index buffer is not sized by the plan "
                    "spec's capacity slot — the kernel's block shapes "
                    "are derived from spec[-1], a drifting pad would "
                    "gather out of bounds"))


# -- family entry ------------------------------------------------------------

@register("device")
def check_device(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    staging = None
    cfg_cap = None
    for mod in ctx.modules:
        base = os.path.basename(mod.relpath)
        if base == "pallas_kernels.py":
            if staging is None:
                staging = _staging_consts(ctx)
                cfg_cap = _config_lut_cap(ctx)
            env = dict(staging)
            env.update(_int_consts(mod.tree))
            for fn in ast.walk(mod.tree):
                if isinstance(fn, ast.FunctionDef):
                    _check_builder(mod, fn, env, findings)
            _check_gpad(mod, env, findings)
            _check_smem_cap(mod, cfg_cap, findings)
            _check_dtypes(mod, findings)
        elif base == "combine.py":
            _check_dtypes(mod, findings)
            findings.extend(_AxisChecker(mod).check())
        elif base == "reduce_device.py":
            # broker-reduce merge kernels: mesh-axis resolution through
            # the reduce helper params (_axis_reduce's / _slice_reduce's
            # ``axis``). NO _check_dtypes — i64 keys/sums are this
            # module's contract
            findings.extend(_AxisChecker(mod).check())
        elif base == "plan.py":
            _check_narrow(mod, findings)
        elif base == "startree_device.py":
            _check_idxcap(mod, findings)
    # one finding per stable key (helpers shared by several call sites
    # would otherwise multiply one root cause)
    seen: Set[str] = set()
    out: List[Finding] = []
    for f in findings:
        if f.key not in seen:
            seen.add(f.key)
            out.append(f)
    return out
