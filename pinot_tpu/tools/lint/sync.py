"""Device-sync taint: implicit host materialization in convoy positions.

``jax`` device values materialize on host through *implicit* syncs —
``np.asarray``/``np.array`` on a device array, ``.item()``/``.tolist()``,
``float()``/``int()``/``bool()`` — each of which blocks the calling thread
until the device program producing the value finishes. Two call contexts
turn that stall into a systemic hazard, exactly the convoy/deadlock class
PR 3 removed the global combine lock to escape:

- **while a lock is held**: every other thread queuing on that lock now
  waits on device execution too (the lock-held set flows through the PR-4
  lock graph: lexical ``with self.<lock>`` regions, the ``*_locked``
  caller-holds convention, and functions name-resolved from call sites
  under a lock, two levels deep);
- **on the launcher dispatcher thread**: the per-mesh dispatcher
  serializes EVERY sharded launch in the process; a sync there stalls all
  queries, not one. Dispatcher reachability starts at
  ``threading.Thread(target=...)`` call sites in launcher modules and
  closes over name-resolved calls. (Worker-pool threads are deliberately
  NOT roots: per-query decode D2H is the design, not a hazard.)
- **inside a metrics/telemetry gauge callback**: callables registered via
  ``MetricsRegistry.gauge`` / ``Telemetry.track_gauge`` run on SCRAPE and
  sampler threads — a device sink there silently stalls every scrape (and
  the telemetry sampler's whole tick) on device execution. Both lambda
  registrations (checked against the registering function's taint set)
  and named-function registrations (which join the lock/dispatcher
  context machinery) are gated.

Taint sources: ``jnp.*`` / ``jax.*`` / ``pallas_call`` call results
(minus host-metadata entry points like ``jax.devices()`` /
``memory_stats()``), plus calls to in-package functions summarized as
returning device values (fixpoint over the scan set). Taint propagates
through arithmetic, subscripts, attribute chains (metadata attributes
like ``.nbytes``/``.shape`` strip it — reading them never syncs), and
conservatively through unresolved calls fed tainted arguments. Sink
results are host values and untainted.

The per-function sink pass runs the taint as a forward dataflow over the
:mod:`dataflow` CFG (union join, no kills), so a sink is only flagged
with the taint that can actually reach it.
"""

from __future__ import annotations

import ast
import os

from typing import Any, Dict, FrozenSet, List, Optional, Set, Tuple

from pinot_tpu.tools.lint.core import (
    Finding,
    LintContext,
    Module,
    attr_base_name,
    register,
)
from pinot_tpu.tools.lint.dataflow import (
    ForwardAnalysis,
    build_cfg,
    stmt_scan,
    walk_no_nested,
)
from pinot_tpu.tools.lint.locks import (
    AMBIG_CAP,
    CONTAINER_METHODS,
    _CallGraph,
    _with_locks,
    collect_classes,
)
from pinot_tpu.tools.lint.pairing import _functions
from pinot_tpu.tools.lint.tracer import _enclosing_scope, shared_index

# attribute reads that never sync (host-side metadata on device arrays)
METADATA_ATTRS = {"nbytes", "shape", "dtype", "ndim", "size", "itemsize",
                  "bits", "vals_per_word", "weak_type", "sharding"}

# jax entry points that return HOST metadata, not device values
NONDEVICE_JAX = {"devices", "local_devices", "device_count",
                 "local_device_count", "memory_stats", "default_backend",
                 "process_index", "process_count", "tree_structure"}

_CAST_SINKS = {"float", "int", "bool"}
_METHOD_SINKS = {"item", "tolist"}
_NP_SINKS = {"asarray", "array"}


class _TaintEngine:
    def __init__(self, ctx: LintContext):
        self.ctx = ctx
        self.idx = shared_index(ctx)
        classes, _ = collect_classes(ctx)
        self.classes = classes
        self.graph = _CallGraph(ctx, classes)
        self.ret_dev: Set[int] = set()

    # -- resolution ---------------------------------------------------------
    def resolve_targets(self, call: ast.Call, mod: Module,
                        scope) -> List[ast.AST]:
        hits: List[ast.AST] = []
        try:
            hit = self.idx.resolve_callable(call.func, mod, scope)
        except Exception:
            hit = None
        if hit is not None:
            hits.append(hit[1])
            return hits
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr not in CONTAINER_METHODS:
            cands = self.graph.methods_by_name.get(f.attr, [])
            if 0 < len(cands) <= AMBIG_CAP:
                hits.extend(fn for _ci, fn in cands)
        return hits

    # -- sources ------------------------------------------------------------
    def is_device_call(self, call: ast.Call, mod: Module) -> bool:
        f = call.func
        if isinstance(f, ast.Attribute):
            if f.attr in NONDEVICE_JAX:
                return False
            if f.attr == "pallas_call":
                return True
            base = attr_base_name(f)
            imps = self.idx.imports.get(mod.relpath, {})
            target = imps.get(base or "")
            if target is not None and target.split(".")[0] == "jax":
                return True
            fi = self.idx.from_imports.get(mod.relpath, {}).get(base or "")
            if fi is not None and fi[0].split(".")[0] == "jax":
                return True
            return False
        if isinstance(f, ast.Name):
            if f.id == "pallas_call":
                return True
            fi = self.idx.from_imports.get(mod.relpath, {}).get(f.id)
            return fi is not None and fi[0].split(".")[0] == "jax" \
                and f.id not in NONDEVICE_JAX
        return False

    # -- sinks --------------------------------------------------------------
    def sink_kind(self, call: ast.Call, S: FrozenSet[str],
                  mod: Module, scope) -> Optional[str]:
        f = call.func
        if isinstance(f, ast.Name) and f.id in _CAST_SINKS \
                and len(call.args) == 1 \
                and self.tainted(call.args[0], S, mod, scope):
            return f"{f.id}()"
        if isinstance(f, ast.Attribute):
            if f.attr in _METHOD_SINKS \
                    and self.tainted(f.value, S, mod, scope):
                return f".{f.attr}()"
            if f.attr in _NP_SINKS and call.args:
                base = attr_base_name(f)
                imps = self.idx.imports.get(mod.relpath, {})
                if imps.get(base or "") == "numpy" \
                        and self.tainted(call.args[0], S, mod, scope):
                    return f"np.{f.attr}()"
        return None

    # -- taint of one expression -------------------------------------------
    def tainted(self, e: ast.expr, S: FrozenSet[str],
                mod: Module, scope) -> bool:
        if isinstance(e, ast.Name):
            return e.id in S
        if isinstance(e, ast.Attribute):
            if e.attr in METADATA_ATTRS:
                return False
            return self.tainted(e.value, S, mod, scope)
        if isinstance(e, ast.Subscript):
            return self.tainted(e.value, S, mod, scope)
        if isinstance(e, ast.BinOp):
            return self.tainted(e.left, S, mod, scope) \
                or self.tainted(e.right, S, mod, scope)
        if isinstance(e, ast.UnaryOp):
            return self.tainted(e.operand, S, mod, scope)
        if isinstance(e, ast.BoolOp):
            return any(self.tainted(v, S, mod, scope) for v in e.values)
        if isinstance(e, ast.Compare):
            return self.tainted(e.left, S, mod, scope) \
                or any(self.tainted(c, S, mod, scope)
                       for c in e.comparators)
        if isinstance(e, ast.IfExp):
            return self.tainted(e.body, S, mod, scope) \
                or self.tainted(e.orelse, S, mod, scope)
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            return any(self.tainted(x, S, mod, scope) for x in e.elts)
        if isinstance(e, ast.Starred):
            return self.tainted(e.value, S, mod, scope)
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                          ast.DictComp)):
            # comprehension scope: coarse subtree scan
            for sub in ast.walk(e):
                if isinstance(sub, ast.Name) and sub.id in S:
                    return True
                if isinstance(sub, ast.Call) \
                        and self.is_device_call(sub, mod):
                    return True
            return False
        if isinstance(e, ast.Call):
            if self.sink_kind(e, S, mod, scope) is not None:
                return False  # sink results are host values
            if self.is_device_call(e, mod):
                return True
            f = e.func
            if isinstance(f, ast.Attribute) \
                    and f.attr not in METADATA_ATTRS \
                    and self.tainted(f.value, S, mod, scope):
                return True
            for t in self.resolve_targets(e, mod, scope):
                if id(t) in self.ret_dev:
                    return True
            return any(self.tainted(a, S, mod, scope) for a in e.args)
        return False

    # -- per-function taint ------------------------------------------------
    def _add_target(self, t: ast.expr, S: Set[str]) -> None:
        if isinstance(t, ast.Name):
            S.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for x in t.elts:
                self._add_target(x, S)
        elif isinstance(t, ast.Starred):
            self._add_target(t.value, S)
        elif isinstance(t, (ast.Subscript, ast.Attribute)):
            base = t
            while isinstance(base, (ast.Subscript, ast.Attribute)):
                base = base.value
            if isinstance(base, ast.Name) and base.id not in ("self", "cls"):
                S.add(base.id)

    def _stmt_additions(self, st: ast.AST, S: FrozenSet[str],
                        mod: Module, scope) -> Set[str]:
        add: Set[str] = set()
        if isinstance(st, ast.Assign):
            if self.tainted(st.value, S, mod, scope):
                for t in st.targets:
                    self._add_target(t, add)
        elif isinstance(st, ast.AnnAssign) and st.value is not None:
            if self.tainted(st.value, S, mod, scope):
                self._add_target(st.target, add)
        elif isinstance(st, ast.AugAssign):
            if self.tainted(st.value, S, mod, scope) \
                    or self.tainted(st.target, S, mod, scope):
                self._add_target(st.target, add)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            if self.tainted(st.iter, S, mod, scope):
                self._add_target(st.target, add)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                if item.optional_vars is not None \
                        and self.tainted(item.context_expr, S, mod, scope):
                    self._add_target(item.optional_vars, add)
        elif isinstance(st, ast.Expr):
            # container mutation with tainted payload: x.append(dev)
            v = st.value
            if isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute)\
                    and v.func.attr in ("append", "extend", "insert") \
                    and isinstance(v.func.value, ast.Name) \
                    and any(self.tainted(a, S, mod, scope) for a in v.args):
                add.add(v.func.value.id)
        return add

    def flow_insensitive_taint(self, fn: ast.AST, mod: Module,
                               scope) -> Set[str]:
        S: Set[str] = set()
        body = getattr(fn, "body", [])
        stmts = [n for n in walk_no_nested(fn) if isinstance(n, ast.stmt)]
        for _ in range(4):
            before = len(S)
            for st in stmts:
                S |= self._stmt_additions(st, frozenset(S), mod, scope)
            if len(S) == before:
                break
        return S

    def returns_device(self, fn: ast.AST, mod: Module, scope) -> bool:
        S = frozenset(self.flow_insensitive_taint(fn, mod, scope))
        for node in walk_no_nested(fn):
            if isinstance(node, ast.Return) and node.value is not None \
                    and self.tainted(node.value, S, mod, scope):
                return True
        return False

    def compute_summaries(self, funcs) -> None:
        for _ in range(5):
            changed = False
            for mod, _qual, fn in funcs:
                if id(fn) in self.ret_dev or isinstance(fn, ast.Lambda):
                    continue
                scope = self.idx.scope_of.get(id(fn))
                if self.returns_device(fn, mod, scope):
                    self.ret_dev.add(id(fn))
                    changed = True
            if not changed:
                break


# -- contexts ---------------------------------------------------------------


def _lock_held_functions(eng: _TaintEngine) -> Dict[int, str]:
    """id(fn) -> witness for functions that may execute with a lock held:
    name-resolved from call sites inside ``with self.<lock>`` blocks (and
    from ``*_locked`` methods), closed one more level (depth 2)."""
    out: Dict[int, str] = {}
    owner: Dict[int, Tuple[Any, str]] = {}  # id(fn) -> (ci, relpath)
    for ci in eng.classes:
        for method in ci.methods.values():
            owner[id(method)] = (ci, ci.module.relpath)

    def visit(node, ci, relpath, held):
        if isinstance(node, ast.With):
            new = held | _with_locks(node, ci) if ci is not None else held
            for item in node.items:
                visit(item.context_expr, ci, relpath, held)
            for st in node.body:
                visit(st, ci, relpath, new)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # closures run later; they are their own functions
        if isinstance(node, ast.Call) and held:
            for ci2, fn2 in eng.graph.resolve(node, ci, relpath):
                rp2 = ci2.module.relpath if ci2 is not None else relpath
                out.setdefault(
                    id(fn2),
                    f"called while {ci.name}.{sorted(held)[0]} is held "
                    f"({relpath}:{node.lineno})")
                owner.setdefault(id(fn2), (ci2, rp2))
        for child in ast.iter_child_nodes(node):
            visit(child, ci, relpath, held)

    for ci in eng.classes:
        for name, method in ci.methods.items():
            if name.endswith("_locked"):
                out.setdefault(
                    id(method),
                    f"{ci.name}.{name} runs under the caller's lock "
                    f"(*_locked convention)")
            for st in method.body:
                visit(st, ci, ci.module.relpath, set())

    # one expansion level: callees of lock-held functions
    frontier = list(out.items())
    for fid, witness in frontier:
        info = owner.get(fid)
        if info is None:
            continue
        ci, relpath = info
        fn = next((m for c in eng.classes for m in c.methods.values()
                   if id(m) == fid), None)
        if fn is None:
            continue
        for node in walk_no_nested(fn):
            if isinstance(node, ast.Call):
                for ci2, fn2 in eng.graph.resolve(node, ci, relpath):
                    out.setdefault(id(fn2), witness + " -> transitive")
    return out


def _dispatcher_functions(eng: _TaintEngine) -> Dict[int, str]:
    """id(fn) -> witness for functions reachable from a launcher module's
    ``threading.Thread(target=...)`` dispatcher loop."""
    out: Dict[int, str] = {}
    roots: List[Tuple[Module, ast.AST, str]] = []
    for mod in eng.ctx.modules:
        if "launcher" not in os.path.basename(mod.relpath):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.id if isinstance(f, ast.Name) else \
                (f.attr if isinstance(f, ast.Attribute) else None)
            if name != "Thread":
                continue
            target = next((k.value for k in node.keywords
                           if k.arg == "target"), None)
            if target is None:
                continue
            scope = _enclosing_scope(eng.idx, mod, node)
            try:
                hit = eng.idx.resolve_callable(target, mod, scope)
            except Exception:
                hit = None
            if hit is not None:
                roots.append((hit[0], hit[1],
                              f"dispatcher thread rooted at "
                              f"{mod.relpath}:{node.lineno}"))
    frontier = list(roots)
    while frontier:
        mod, fn, witness = frontier.pop()
        if id(fn) in out:
            continue
        out[id(fn)] = witness
        scope = eng.idx.scope_of.get(id(fn))
        for node in walk_no_nested(fn):
            if isinstance(node, ast.Call):
                for t in eng.resolve_targets(node, mod, scope):
                    if id(t) not in out:
                        tm = eng.idx.mod_of.get(id(t), mod)
                        frontier.append((tm, t, witness))
    return out


_GAUGE_REGISTRARS = {"gauge", "track_gauge"}


def _gauge_call_arg(node: ast.AST) -> Optional[ast.expr]:
    """The callback argument of a ``<registry>.gauge(name, fn)`` /
    ``track_gauge(name, fn)`` registration, else None."""
    if not isinstance(node, ast.Call) or len(node.args) < 2:
        return None
    f = node.func
    name = f.attr if isinstance(f, ast.Attribute) else \
        (f.id if isinstance(f, ast.Name) else None)
    if name not in _GAUGE_REGISTRARS:
        return None
    return node.args[1]


def _gauge_functions(eng: _TaintEngine) -> Dict[int, str]:
    """id(fn) -> witness for NAMED functions registered as gauge/telemetry
    callbacks — they run on scrape/sampler threads, where a device sink
    stalls every scrape."""
    out: Dict[int, str] = {}
    for mod in eng.ctx.modules:
        for node in ast.walk(mod.tree):
            fnarg = _gauge_call_arg(node)
            if fnarg is None or isinstance(fnarg, ast.Lambda):
                continue
            scope = _enclosing_scope(eng.idx, mod, node)
            try:
                hit = eng.idx.resolve_callable(fnarg, mod, scope)
            except Exception:
                hit = None
            targets: List[ast.AST] = [hit[1]] if hit is not None else []
            if not targets and isinstance(fnarg, ast.Attribute):
                cands = eng.graph.methods_by_name.get(fnarg.attr, [])
                if 0 < len(cands) <= AMBIG_CAP:
                    targets = [fn for _ci, fn in cands]
            for t in targets:
                out.setdefault(
                    id(t),
                    f"registered as a metrics gauge callback "
                    f"({mod.relpath}:{node.lineno}) — runs on scrape "
                    f"threads")
    return out


def _gauge_lambda_findings(eng: _TaintEngine, funcs) -> List[Finding]:
    """Sinks inside gauge-registered LAMBDAS, checked against the
    registering function's flow-insensitive taint set (a lambda closing
    over a device value and materializing it syncs at every scrape)."""
    findings: List[Finding] = []
    seen: Set[str] = set()
    for mod, qual, fn in funcs:
        if isinstance(fn, ast.Lambda):
            continue
        scope = eng.idx.scope_of.get(id(fn))
        lams: List[Tuple[ast.Call, ast.Lambda]] = []
        for node in walk_no_nested(fn):
            fnarg = _gauge_call_arg(node)
            if fnarg is not None and isinstance(fnarg, ast.Lambda):
                lams.append((node, fnarg))
        if not lams:
            continue
        S = frozenset(eng.flow_insensitive_taint(fn, mod, scope))
        for call_node, lam in lams:
            for sub in ast.walk(lam.body):
                if not isinstance(sub, ast.Call):
                    continue
                kind = eng.sink_kind(sub, S, mod, scope)
                if kind is None:
                    continue
                sym = f"{qual}:gauge-lambda:{kind}"
                key = f"{mod.relpath}:{sym}"
                if key in seen:
                    continue
                seen.add(key)
                findings.append(Finding(
                    "sync", mod.relpath, sub.lineno, sym,
                    f"gauge callback registered in {qual}() materializes "
                    f"a device value via {kind} — the sink runs at SCRAPE "
                    f"time, silently stalling every /metrics pull and "
                    f"telemetry sampler tick on device execution"))
    return findings


def _held_map(fn: ast.AST, ci) -> Dict[int, FrozenSet[str]]:
    """ast-node-id -> lock names lexically held there (nested defs reset)."""
    held_at: Dict[int, FrozenSet[str]] = {}

    def visit(node, held: FrozenSet[str]):
        held_at[id(node)] = held
        if isinstance(node, ast.With) and ci is not None:
            inner = held | frozenset(_with_locks(node, ci))
            for item in node.items:
                visit(item.context_expr, held)
            for st in node.body:
                visit(st, inner)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fn:
            return  # closure bodies do not inherit the with
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    visit(fn, frozenset())
    return held_at


# -- the checker ------------------------------------------------------------


@register("sync")
def check_sync(ctx: LintContext) -> List[Finding]:
    eng = _TaintEngine(ctx)
    funcs: List[Tuple[Module, str, ast.AST]] = []
    for mod in ctx.modules:
        for qual, fn in _functions(mod.tree):
            funcs.append((mod, qual, fn))
    eng.compute_summaries(funcs)
    lock_ctx = _lock_held_functions(eng)
    thread_ctx = _dispatcher_functions(eng)
    gauge_ctx = _gauge_functions(eng)

    class_of: Dict[int, Any] = {}
    for ci in eng.classes:
        for m in ci.methods.values():
            class_of[id(m)] = ci

    findings: List[Finding] = list(_gauge_lambda_findings(eng, funcs))
    seen: Set[str] = set()
    for mod, qual, fn in funcs:
        ci = class_of.get(id(fn))
        fname = getattr(fn, "name", qual)
        contexts: List[str] = []
        if fname.endswith("_locked"):
            contexts.append("runs under the caller's lock "
                            "(*_locked convention)")
        if id(fn) in lock_ctx:
            contexts.append(lock_ctx[id(fn)])
        if id(fn) in thread_ctx:
            contexts.append(thread_ctx[id(fn)])
        if id(fn) in gauge_ctx:
            contexts.append(gauge_ctx[id(fn)])
        has_with_lock = ci is not None and any(
            isinstance(n, ast.With) and _with_locks(n, ci)
            for n in walk_no_nested(fn))
        if not contexts and not has_with_lock:
            continue

        scope = eng.idx.scope_of.get(id(fn))
        cfg = build_cfg(fn)
        fa = ForwardAnalysis(
            cfg, frozenset(),
            transfer=lambda S, st, nid: (
                S if st is None
                else S | eng._stmt_additions(st, S, mod, scope)),
            join=lambda a, b: a | b)
        inn = fa.run()
        held_at = _held_map(fn, ci)

        for nid, st in enumerate(cfg.stmts):
            if st is None or not isinstance(st, ast.stmt):
                continue
            S = inn.get(nid)
            if S is None:
                continue
            for call in stmt_scan(st):
                if not isinstance(call, ast.Call):
                    continue
                kind = eng.sink_kind(call, S, mod, scope)
                if kind is None:
                    continue
                held = held_at.get(id(call), frozenset())
                why = list(contexts)
                if held:
                    why.insert(0, f"inside `with self.{sorted(held)[0]}`")
                if not why:
                    continue
                sym = f"{qual}:{kind}"
                key = f"{mod.relpath}:{sym}"
                if key in seen:
                    continue
                seen.add(key)
                findings.append(Finding(
                    "sync", mod.relpath, call.lineno, sym,
                    f"{fname}() materializes a device value via {kind} "
                    f"— implicit device sync {why[0]}; the stall convoys "
                    f"every thread behind this position"))
    return findings
