"""``decline`` family: pallas decline-reason drift check.

Every decline the fused-kernel eligibility path records must resolve to a
code the ledger knows — ``tracing.classify_decline``'s rule table for
``_Ineligible("message")`` raises, the ``DIRECT_DECLINE_CODES`` registry
for ``decline("code")`` calls. The bench loud-fails on any SSB pallas
decline whose reason is ``unknown``; this check moves that failure to lint
time: a NEW decline site in ``engine/pallas_kernels.py`` whose string
neither matches a classifier needle nor names a registered direct code is
flagged before it can ever reach the ledger (the sanitized digit-stripped
fallback would otherwise mint an unregistered ad-hoc code).

Scope: modules named ``pallas_kernels.py`` (the real engine module and
test fixtures alike). Non-constant arguments (``raise _Ineligible(op)``)
are exempt — the runtime ``pallas_`` namespacing in ``extract_plan``
covers them, and the classifier's fallback keeps them non-``unknown``.

The rule table is read from ``common/tracing.py`` via ``ast`` (never
imported: the lint CLI must stay stdlib-only and jax-free)."""

from __future__ import annotations

import ast
import os

from typing import List, Optional, Set, Tuple

from pinot_tpu.tools.lint.core import Finding, LintContext, register

_TRACING_PATH = os.path.normpath(os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    os.pardir, os.pardir, "common", "tracing.py"))


def _load_tables(ctx: LintContext) -> Tuple[List[str], Set[str]]:
    """(classifier needles, known direct codes) from common/tracing.py —
    the copy in the lint context when the scan includes it (so a scan of a
    modified tree checks against ITS table), the installed package's file
    otherwise (fixture scans of standalone files)."""
    tree = None
    for mod in ctx.modules:
        if mod.relpath.replace(os.sep, "/").endswith("common/tracing.py"):
            tree = mod.tree
            break
    if tree is None:
        with open(_TRACING_PATH, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=_TRACING_PATH)
    needles: List[str] = []
    codes: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            tgt = node.target
        else:
            continue
        name = tgt.id if isinstance(tgt, ast.Name) else None
        if name == "_DECLINE_RULES" and isinstance(node.value, ast.Tuple):
            for elt in node.value.elts:
                if (isinstance(elt, ast.Tuple) and len(elt.elts) == 2
                        and all(isinstance(e, ast.Constant)
                                and isinstance(e.value, str)
                                for e in elt.elts)):
                    needles.append(elt.elts[0].value)
                    codes.add(elt.elts[1].value)
        elif name == "DIRECT_DECLINE_CODES":
            call = node.value
            args = call.args if isinstance(call, ast.Call) else []
            for a in args:
                if isinstance(a, (ast.Set, ast.Tuple, ast.List)):
                    for e in a.elts:
                        if isinstance(e, ast.Constant) \
                                and isinstance(e.value, str):
                            codes.add(e.value)
    return needles, codes


def _const_prefix(node: ast.expr) -> Optional[str]:
    """The checkable constant text of a decline argument: full string for
    literals, the joined constant fragments for f-strings, None for
    anything dynamic."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = [v.value for v in node.values
                 if isinstance(v, ast.Constant) and isinstance(v.value, str)]
        return "".join(parts) if parts else None
    return None


@register("decline")
def check_declines(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    targets = [m for m in ctx.modules
               if os.path.basename(m.relpath) == "pallas_kernels.py"]
    if not targets:
        return findings
    needles, codes = _load_tables(ctx)
    for mod in targets:
        func = "<module>"
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func = node.name
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            callee = f.id if isinstance(f, ast.Name) else \
                f.attr if isinstance(f, ast.Attribute) else None
            if callee == "_Ineligible" and node.args:
                msg = _const_prefix(node.args[0])
                if msg is None:
                    continue  # dynamic message: runtime namespacing covers
                if not any(n in msg for n in needles):
                    findings.append(Finding(
                        "decline", mod.relpath, node.lineno,
                        f"ineligible:{msg[:40]}",
                        f"_Ineligible message {msg!r} matches no "
                        f"classify_decline rule — it would classify "
                        f"through the sanitized fallback; add a rule to "
                        f"tracing._DECLINE_RULES"))
            elif callee in ("decline", "on_decline") and node.args:
                code = _const_prefix(node.args[0])
                if code is None:
                    continue
                if code not in codes:
                    findings.append(Finding(
                        "decline", mod.relpath, node.lineno,
                        f"code:{code[:40]}",
                        f"decline code {code!r} is not registered — add "
                        f"it to tracing.DIRECT_DECLINE_CODES (or a "
                        f"_DECLINE_RULES row) so the ledger can never "
                        f"carry an unregistered reason"))
    return findings
