"""JAX tracer safety: jit/vmap/shard_map/pallas roots must stay pure.

A function traced by jax executes ONCE per compile-cache entry; host-side
effects inside it either bake stale values into the compiled program
(``time.*``, ``random.*``, global reads) or break under concurrent
tracing (``threading.*``, global-dict mutation) — the class of bug that
turns a coalesced vmapped launch nondeterministic.

Roots: first arguments of ``jax.jit`` / ``jax.vmap`` / ``shard_map`` /
``pl.pallas_call`` calls and ``@jax.jit``-decorated defs. Reachability is
a conservative intra-package call graph: names resolve through enclosing
scopes, module globals, ``self.`` methods of the same class, and
``from <package module> import name`` — unresolved calls (third-party,
callbacks) are not followed.

Flagged inside reachable functions:

- calls into the ``time`` / ``threading`` / ``random`` / ``socket`` /
  ``subprocess`` modules (resolved through the module's imports);
- ``open()`` / ``input()``;
- ``.item()`` — a device sync that crashes on tracers;
- ``float()/int()/bool()`` directly on a ROOT function's parameter
  (parameters of a jit root are traced by definition);
- mutation of a module-level global (subscript store or mutating method).
"""

from __future__ import annotations

import ast

from typing import Dict, List, Optional, Set, Tuple

from pinot_tpu.tools.lint.core import (
    Finding,
    LintContext,
    Module,
    attr_base_name,
    call_name,
    register,
)

DENY_MODULES = {"time", "threading", "random", "socket", "subprocess"}
DENY_BUILTINS = {"open", "input"}
CAST_BUILTINS = {"float", "int", "bool"}
MUTATORS = {"append", "add", "clear", "pop", "popitem", "update", "extend",
            "remove", "discard", "insert", "setdefault"}

TRACE_ENTRY_ATTRS = {"jit", "vmap", "pallas_call", "shard_map", "pmap"}
TRACE_ENTRY_NAMES = {"jit", "vmap", "pallas_call", "shard_map",
                     "_shard_map", "pmap"}


class _Scope:
    """One function's environment: parent scope + local defs."""

    def __init__(self, mod: Module, node: ast.AST,
                 parent: Optional["_Scope"], cls: Optional[ast.ClassDef]):
        self.mod = mod
        self.node = node
        self.parent = parent
        self.cls = cls
        self.defs: Dict[str, ast.AST] = {}

    def lookup(self, name: str) -> Optional[Tuple[Module, ast.AST,
                                                  "_Scope"]]:
        s: Optional[_Scope] = self
        while s is not None:
            fn = s.defs.get(name)
            if fn is not None:
                return (s.mod, fn, s)
            s = s.parent
        return None


class _Index:
    """Per-module: imports, module-level globals, every function's scope."""

    def __init__(self, ctx: LintContext):
        self.ctx = ctx
        # module alias -> module name ('np' -> 'numpy'), per file
        self.imports: Dict[str, Dict[str, str]] = {}
        # 'from mod import name' -> (module relpath?, source module name)
        self.from_imports: Dict[str, Dict[str, Tuple[str, str]]] = {}
        self.globals: Dict[str, Set[str]] = {}
        self.scope_of: Dict[int, _Scope] = {}   # id(fn node) -> scope
        self.root_scopes: Dict[str, _Scope] = {}  # relpath -> module scope
        self.mod_of: Dict[int, Module] = {}
        # package-module name ('pinot_tpu.engine.kernels') -> Module
        self.pkg_modules: Dict[str, Module] = {}
        for mod in ctx.modules:
            dotted = mod.relpath[:-3].replace("/", ".").replace("\\", ".")
            self.pkg_modules[dotted] = mod
            if dotted.endswith(".__init__"):
                self.pkg_modules[dotted[:-9]] = mod
        for mod in ctx.modules:
            self._index_module(mod)

    def _index_module(self, mod: Module) -> None:
        imps: Dict[str, str] = {}
        fimps: Dict[str, Tuple[str, str]] = {}
        gnames: Set[str] = set()
        for node in mod.tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    imps[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    fimps[a.asname or a.name] = (node.module, a.name)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        gnames.add(t.id)
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name):
                gnames.add(node.target.id)
        self.imports[mod.relpath] = imps
        self.from_imports[mod.relpath] = fimps
        self.globals[mod.relpath] = gnames

        root = _Scope(mod, mod.tree, None, None)
        self.root_scopes[mod.relpath] = root
        self._build_scopes(mod, mod.tree, root, None)

    def _build_scopes(self, mod: Module, node: ast.AST, scope: _Scope,
                      cls: Optional[ast.ClassDef]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope.defs[child.name] = child
                sub = _Scope(mod, child, scope, cls)
                self.scope_of[id(child)] = sub
                self.mod_of[id(child)] = mod
                self._build_scopes(mod, child, sub, cls)
            elif isinstance(child, ast.ClassDef):
                csub = _Scope(mod, child, scope, child)
                self._build_scopes(mod, child, csub, child)
            else:
                self._build_scopes(mod, child, scope, cls)

    # -- resolution ---------------------------------------------------------
    def resolve_callable(self, expr: ast.expr, mod: Module,
                         scope: Optional[_Scope]
                         ) -> Optional[Tuple[Module, ast.AST]]:
        if isinstance(expr, ast.Lambda):
            return (mod, expr)
        if isinstance(expr, ast.Name):
            if scope is not None:
                hit = scope.lookup(expr.id)
                if hit is not None:
                    return (hit[0], hit[1])
            src = self.from_imports[mod.relpath].get(expr.id)
            if src is not None:
                smod = self.pkg_modules.get(src[0])
                if smod is not None:
                    for n in smod.tree.body:
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)) \
                                and n.name == src[1]:
                            return (smod, n)
            return None
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                    and scope is not None and scope.cls is not None:
                for n in scope.cls.body:
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)) \
                            and n.name == expr.attr:
                        return (mod, n)
        return None

    def is_trace_entry(self, call: ast.Call, mod: Module) -> bool:
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr in TRACE_ENTRY_ATTRS:
            base = attr_base_name(f)
            target = self.imports[mod.relpath].get(base or "", base)
            if target in ("jax", "jax.numpy") or f.attr in (
                    "pallas_call", "shard_map"):
                return True
            fi = self.from_imports[mod.relpath].get(base or "")
            if fi is not None and fi[0].startswith("jax"):
                return True
            return False
        if isinstance(f, ast.Name) and f.id in TRACE_ENTRY_NAMES:
            fi = self.from_imports[mod.relpath].get(f.id)
            if fi is not None and fi[0].startswith("jax"):
                return True
            return f.id in ("_shard_map", "shard_map")
        return False


def shared_index(ctx: LintContext) -> _Index:
    """The per-run shared import/scope index: building it walks every
    module's AST, and four families need the same one — memoized on the
    context (read-only after construction)."""
    idx = ctx.memo.get("lint.index")
    if idx is None:
        idx = _Index(ctx)
        ctx.memo["lint.index"] = idx
    return idx


def _jit_decorated(fn: ast.AST, mod: Module, idx: _Index) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        d = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(d, ast.Attribute) and d.attr in ("jit", "pmap"):
            if attr_base_name(d) == "jax" \
                    or idx.imports[mod.relpath].get(
                        attr_base_name(d) or "") == "jax":
                return True
        if isinstance(d, ast.Name) and d.id == "jit":
            fi = idx.from_imports[mod.relpath].get("jit")
            if fi is not None and fi[0].startswith("jax"):
                return True
    return False


@register("tracer")
def check_tracer(ctx: LintContext) -> List[Finding]:
    idx = shared_index(ctx)
    findings: List[Finding] = []

    # -- roots --------------------------------------------------------------
    roots: List[Tuple[Module, ast.AST]] = []
    seen_roots: Set[int] = set()

    def add_root(mod: Module, fn: ast.AST) -> None:
        if id(fn) not in seen_roots:
            seen_roots.add(id(fn))
            roots.append((mod, fn))

    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and _jit_decorated(node, mod, idx):
                add_root(mod, node)
            if isinstance(node, ast.Call) \
                    and idx.is_trace_entry(node, mod) and node.args:
                scope = _enclosing_scope(idx, mod, node)
                hit = idx.resolve_callable(node.args[0], mod, scope)
                if hit is not None:
                    add_root(*hit)

    # -- reachability -------------------------------------------------------
    reach: List[Tuple[Module, ast.AST]] = []
    visited: Set[int] = set()
    frontier = list(roots)
    while frontier:
        mod, fn = frontier.pop()
        if id(fn) in visited:
            continue
        visited.add(id(fn))
        reach.append((mod, fn))
        scope = idx.scope_of.get(id(fn))
        if scope is None and not isinstance(fn, ast.Lambda):
            scope = _enclosing_scope(idx, mod, fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                hit = idx.resolve_callable(node.func, mod, scope)
                if hit is not None and id(hit[1]) not in visited:
                    frontier.append(hit)

    root_ids = {id(fn) for _m, fn in roots}

    # -- denylist scan ------------------------------------------------------
    for mod, fn in reach:
        name = getattr(fn, "name", "<lambda>")
        params: Set[str] = set()
        args = getattr(fn, "args", None)
        if args is not None:
            params = {a.arg for a in
                      list(args.posonlyargs) + list(args.args)
                      + list(args.kwonlyargs)} - {"self"}
        imps = idx.imports[mod.relpath]
        fimps = idx.from_imports[mod.relpath]
        gnames = idx.globals[mod.relpath]
        for node in ast.walk(fn):
            if not isinstance(node, (ast.Call, ast.Assign, ast.AugAssign,
                                     ast.Delete)):
                continue
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute):
                    base = attr_base_name(f)
                    target = imps.get(base or "", None)
                    if target in DENY_MODULES:
                        findings.append(Finding(
                            "tracer", mod.relpath, node.lineno,
                            f"{name}:{target}.{f.attr}",
                            f"traced function {name}() calls "
                            f"{target}.{f.attr}() — host effect inside a "
                            f"jit/vmap/pallas region"))
                    elif f.attr == "item" and not node.args:
                        findings.append(Finding(
                            "tracer", mod.relpath, node.lineno,
                            f"{name}:item",
                            f"traced function {name}() calls .item() — "
                            f"device sync that fails on tracers"))
                elif isinstance(f, ast.Name):
                    fi = fimps.get(f.id)
                    src_mod = fi[0] if fi else None
                    if f.id in DENY_BUILTINS and f.id not in fimps:
                        findings.append(Finding(
                            "tracer", mod.relpath, node.lineno,
                            f"{name}:{f.id}",
                            f"traced function {name}() calls {f.id}() — "
                            f"I/O inside a jit/vmap/pallas region"))
                    elif src_mod in DENY_MODULES or (
                            fi and fi[0].split(".")[0] in DENY_MODULES):
                        findings.append(Finding(
                            "tracer", mod.relpath, node.lineno,
                            f"{name}:{f.id}",
                            f"traced function {name}() calls {f.id}() "
                            f"(from {src_mod}) — host effect inside a "
                            f"traced region"))
                    elif f.id in CAST_BUILTINS and id(fn) in root_ids \
                            and len(node.args) == 1 \
                            and isinstance(node.args[0], ast.Name) \
                            and node.args[0].id in params:
                        findings.append(Finding(
                            "tracer", mod.relpath, node.lineno,
                            f"{name}:{f.id}({node.args[0].id})",
                            f"jit root {name}() calls {f.id}() on traced "
                            f"parameter {node.args[0].id!r} — concretizes "
                            f"a tracer"))
                # global mutation via method call
                if isinstance(f, ast.Attribute) and f.attr in MUTATORS:
                    base = f.value
                    if isinstance(base, ast.Name) and base.id in gnames:
                        findings.append(Finding(
                            "tracer", mod.relpath, node.lineno,
                            f"{name}:mutate:{base.id}",
                            f"traced function {name}() mutates module "
                            f"global {base.id!r} — unsafe under "
                            f"concurrent tracing"))
            else:  # Assign / AugAssign / Delete: global subscript stores
                targets = node.targets if isinstance(
                    node, (ast.Assign, ast.Delete)) else [node.target]
                for t in targets:
                    tt = t
                    while isinstance(tt, ast.Subscript):
                        tt = tt.value
                    if isinstance(tt, ast.Name) and tt.id in gnames \
                            and isinstance(t, ast.Subscript):
                        findings.append(Finding(
                            "tracer", mod.relpath, node.lineno,
                            f"{name}:mutate:{tt.id}",
                            f"traced function {name}() writes into module "
                            f"global {tt.id!r} — unsafe under concurrent "
                            f"tracing"))
    return findings


def _enclosing_scope(idx: _Index, mod: Module,
                     node: ast.AST) -> Optional[_Scope]:
    """Innermost function scope whose span contains ``node`` (line-based);
    module-level call sites resolve against the module's root scope."""
    best: Optional[_Scope] = None
    best_span = None
    ln = getattr(node, "lineno", None)
    if ln is None:
        return idx.root_scopes.get(mod.relpath)
    for fid, scope in idx.scope_of.items():
        fn = scope.node
        if idx.mod_of.get(fid) is not mod:
            continue
        lo = fn.lineno
        hi = fn.end_lineno or fn.lineno
        if lo <= ln <= hi:
            span = hi - lo
            if best_span is None or span < best_span:
                best, best_span = scope, span
    return best if best is not None else idx.root_scopes.get(mod.relpath)
