"""Whole-program thread-topology race analysis (graftlint v5).

The serving tier is a fixed cast of long-lived thread *roles*:

- ``request``    — scheduler query workers, pool ``submit()`` tasks, the
                   REST/mock-S3 front ends, and every public API method
                   (callers run it on their own thread);
- ``dispatcher`` — the per-mesh combine-launch loop (serializes every
                   sharded launch);
- ``prefetch``   — the residency HBM prefetcher;
- ``sampler``    — the telemetry sampler, heartbeats, controller
                   periodics (time-driven daemons);
- ``seal``       — realtime consumer loops and the seal/commit path;
- ``scrape``     — metrics gauge callbacks (run at /metrics pull and
                   sampler ticks);
- ``writer``     — ingest/replication daemons (kafka sim, stream broker,
                   minion workers, state-replica poller).

The family proves, per ``self.X`` field of every scanned class, that one
of these holds — anything else is a finding:

1. **annotated-guarded** — the field carries ``# guarded-by:`` /
   ``# guarded-by-writes:``; the lock-guard family enforces the lock, so
   this family only certifies the annotation exists;
2. **role-confined** — every (reachable) access runs under one role;
3. **immutable-after-publish** — every non-``__init__`` write lexically
   precedes every thread spawn in its function (``q = Queue()`` then
   ``Thread(target=...).start()``: the spawn is the happens-before
   edge), or the field is never written outside ``__init__``;
4. **lock-consistent** — some one lock is lexically held (``with
   self.<lock>:`` or the ``*_locked`` caller-holds convention) at every
   access;
5. **waived** — the declaration line carries ``# race-ok: <reason>``
   with a reason registered in ``tracing.RACE_OK_REASONS`` (conformance-
   tested like decline codes). A waiver on a field that rules 1-4
   already cover is a *dead annotation* — its own finding — so waivers
   cannot rot in place when the field later gains a lock.

Roles come from the **spawn graph**: every ``threading.Thread(target=
...)`` site (role from the thread's ``name=`` literal prefix, falling
back to the spawning module), every pool/scheduler ``submit()`` whose
first argument resolves to an in-package callable (``request``), and
every ``gauge``/``track_gauge`` registration (``scrape``). Public
methods and module functions seed ``request``. Roles close over the
name-resolved call graph (the PR-5 ``_Index`` + lock-graph resolution);
functions no role reaches contribute no accesses (dead code cannot
race). A spawn site whose role cannot be mapped is itself a finding —
the role table is total over the package by construction, the same
conformance discipline the decline registry uses.

True positives are fixed in-code with a deterministic regression test,
never baselined; the whole-package gate stays zero-finding on an empty
baseline.
"""

from __future__ import annotations

import ast
import os
import re

from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from pinot_tpu.tools.lint.core import (
    Finding,
    LintContext,
    Module,
    is_self_attr,
    register,
)
from pinot_tpu.tools.lint.dataflow import walk_no_nested
from pinot_tpu.tools.lint.locks import (
    CONTAINER_METHODS,
    ClassInfo,
    _CallGraph,
    _collect_writes,
    _with_locks,
    collect_classes,
)
from pinot_tpu.tools.lint.sync import _gauge_call_arg
from pinot_tpu.tools.lint.tracer import _enclosing_scope, shared_index

RACE_OK_RE = re.compile(r"race-ok:\s*(?P<reason>[a-z0-9_]+)")

ROLES = ("request", "dispatcher", "prefetch", "sampler", "seal",
         "scrape", "writer")

# thread-name literal prefix -> role (the ``name=`` kwarg of the Thread
# ctor; f-string names contribute their leading literal). First match
# wins; order longest-prefix-first where prefixes overlap.
THREAD_NAME_ROLES: Tuple[Tuple[str, str], ...] = (
    ("combine-launch", "dispatcher"),
    ("hbm-prefetch", "prefetch"),
    ("telemetry-sampler", "sampler"),
    ("heartbeat", "sampler"),
    ("controller-periodic", "sampler"),
    ("state-replica-poller", "writer"),
    ("consumer-", "seal"),
    ("minion-", "writer"),
    ("kafka-sim", "writer"),
    ("stream-broker", "writer"),
    ("mock-s3", "request"),
    ("rest-api", "request"),
    ("prio-query", "request"),
    ("sewf-query", "request"),
)

# spawning-module basename substring -> role, for spawn sites whose
# ``name=`` is not a literal (the launcher names its loop self._name;
# scheduler workers are f"{name}-{i}")
MODULE_ROLES: Tuple[Tuple[str, str], ...] = (
    ("launcher", "dispatcher"),
    ("scheduler", "request"),
)

_TRACING_PATH = os.path.normpath(os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    os.pardir, os.pardir, "common", "tracing.py"))


def _registered_race_reasons(ctx: LintContext) -> FrozenSet[str]:
    """``RACE_OK_REASONS`` parsed from common/tracing.py (ast, never
    imported — lint runs before the jax environment exists): the scanned
    copy when the run includes one, the installed file otherwise."""
    tree: Optional[ast.AST] = None
    for mod in ctx.modules:
        if mod.relpath.replace(os.sep, "/").endswith("common/tracing.py"):
            tree = mod.tree
            break
    if tree is None:
        try:
            with open(_TRACING_PATH, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=_TRACING_PATH)
        except (OSError, SyntaxError):
            return frozenset()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "RACE_OK_REASONS"
                   for t in node.targets):
            continue
        v = node.value
        if isinstance(v, ast.Call) and v.args:
            v = v.args[0]
        if isinstance(v, (ast.Set, ast.List, ast.Tuple)):
            return frozenset(e.value for e in v.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, str))
    return frozenset()


def _thread_name_literal(call: ast.Call) -> Optional[str]:
    for kw in call.keywords:
        if kw.arg != "name":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            return v.value
        if isinstance(v, ast.JoinedStr) and v.values \
                and isinstance(v.values[0], ast.Constant) \
                and isinstance(v.values[0].value, str):
            return v.values[0].value
    return None


def _spawn_role(call: ast.Call, mod: Module) -> Optional[str]:
    name = _thread_name_literal(call)
    if name is not None:
        for prefix, role in THREAD_NAME_ROLES:
            if name.startswith(prefix):
                return role
    base = os.path.basename(mod.relpath)
    for needle, role in MODULE_ROLES:
        if needle in base:
            return role
    return None


class _Access:
    __slots__ = ("qual", "kind", "roles", "held", "line", "exempt",
                 "pre_spawn")

    def __init__(self, qual: str, kind: str, roles: FrozenSet[str],
                 held: FrozenSet[str], line: int, exempt: bool,
                 pre_spawn: bool):
        self.qual = qual
        self.kind = kind
        self.roles = roles
        self.held = held
        self.line = line
        self.exempt = exempt
        self.pre_spawn = pre_spawn


class _Topology:
    """Spawn graph -> per-function role sets -> per-field verdicts."""

    def __init__(self, ctx: LintContext):
        self.ctx = ctx
        self.idx = shared_index(ctx)
        classes, _ = collect_classes(ctx)
        self.classes = classes
        self.graph = _CallGraph(ctx, classes)
        self.roles: Dict[int, Set[str]] = {}       # id(fn) -> role set
        self.spawn_lines: Dict[int, List[int]] = {}  # id(enclosing fn)
        self.findings: List[Finding] = []
        self._callee_memo: Dict[int, List[Tuple[Module, ast.AST]]] = {}

    # -- call resolution ----------------------------------------------------
    def _resolve(self, expr: ast.expr, mod: Module,
                 scope) -> Optional[Tuple[Module, ast.AST]]:
        try:
            return self.idx.resolve_callable(expr, mod, scope)
        except Exception:
            return None

    def _callees(self, mod: Module,
                 fn: ast.AST) -> List[Tuple[Module, ast.AST]]:
        got = self._callee_memo.get(id(fn))
        if got is not None:
            return got
        scope = self.idx.scope_of.get(id(fn))
        if scope is None and not isinstance(fn, ast.Lambda):
            scope = _enclosing_scope(self.idx, mod, fn)
        out: List[Tuple[Module, ast.AST]] = []
        for node in walk_no_nested(fn):
            if not isinstance(node, ast.Call):
                continue
            hit = self._resolve(node.func, mod, scope)
            if hit is not None:
                out.append(hit)
                continue
            f = node.func
            # bare-name fallback only when the method name is UNIQUE in
            # the package: roles are a union, and the lock-graph's
            # AMBIG_CAP=8 smear (fine for may-acquire sets) would stamp
            # a daemon's role onto every class sharing a `merge`/`init`
            if isinstance(f, ast.Attribute) \
                    and f.attr not in CONTAINER_METHODS:
                cands = self.graph.methods_by_name.get(f.attr, [])
                if len(cands) == 1:
                    out.extend((ci.module, m) for ci, m in cands)
        self._callee_memo[id(fn)] = out
        return out

    # -- spawn graph --------------------------------------------------------
    def _spawn_qual(self, scope, mod: Module) -> str:
        node = getattr(scope, "node", None)
        return getattr(node, "name", None) or "<module>"

    def collect_roots(self) -> List[Tuple[Module, ast.AST, str]]:
        roots: List[Tuple[Module, ast.AST, str]] = []
        for mod in self.ctx.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                cname = f.id if isinstance(f, ast.Name) else (
                    f.attr if isinstance(f, ast.Attribute) else None)
                if cname == "Thread":
                    target = next((k.value for k in node.keywords
                                   if k.arg == "target"), None)
                    if target is None:
                        continue
                    scope = _enclosing_scope(self.idx, mod, node)
                    qual = self._spawn_qual(scope, mod)
                    role = _spawn_role(node, mod)
                    if role is None:
                        self.findings.append(Finding(
                            "threads", mod.relpath, node.lineno,
                            f"spawn:{qual}:role",
                            f"thread spawned in {qual}() has no role "
                            f"mapping — name its Thread with a prefix "
                            f"from THREAD_NAME_ROLES (or extend the "
                            f"table) so the race analysis knows which "
                            f"role runs the target"))
                        continue
                    hit = self._resolve(target, mod, scope)
                    if hit is None and isinstance(target, ast.Attribute) \
                            and target.attr == "serve_forever":
                        # stdlib HTTP server loop: its in-package
                        # handlers are public do_* methods, which seed
                        # the request role on their own
                        continue
                    if hit is None:
                        self.findings.append(Finding(
                            "threads", mod.relpath, node.lineno,
                            f"spawn:{qual}:target",
                            f"Thread target in {qual}() does not "
                            f"resolve to an in-package function — the "
                            f"{role} role cannot be propagated; use a "
                            f"direct method/def reference"))
                        continue
                    roots.append((hit[0], hit[1], role))
                    if scope is not None:
                        self.spawn_lines.setdefault(
                            id(scope.node), []).append(node.lineno)
                elif cname == "submit" and node.args:
                    scope = _enclosing_scope(self.idx, mod, node)
                    hit = self._resolve(node.args[0], mod, scope)
                    if hit is not None:
                        roots.append((hit[0], hit[1], "request"))
                        if scope is not None:
                            self.spawn_lines.setdefault(
                                id(scope.node), []).append(node.lineno)
                else:
                    fnarg = _gauge_call_arg(node)
                    if fnarg is None:
                        continue
                    scope = _enclosing_scope(self.idx, mod, node)
                    hit = self._resolve(fnarg, mod, scope)
                    if hit is not None:
                        roots.append((hit[0], hit[1], "scrape"))
        return roots

    # -- role propagation ---------------------------------------------------
    def compute_roles(self) -> None:
        pending: List[Tuple[Module, ast.AST]] = []

        def add(mod: Module, fn: ast.AST, roles: Set[str]) -> None:
            cur = self.roles.setdefault(id(fn), set())
            if not roles <= cur:
                cur |= roles
                pending.append((mod, fn))

        for mod in self.ctx.modules:
            for node in mod.tree.body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and not node.name.startswith("_"):
                    add(mod, node, {"request"})
        for ci in self.classes:
            for name, fn in ci.methods.items():
                if not name.startswith("_") or (
                        name.startswith("__") and name.endswith("__")):
                    add(ci.module, fn, {"request"})
        for mod, fn, role in self.collect_roots():
            add(mod, fn, {role})
        while pending:
            mod, fn = pending.pop()
            roles = set(self.roles[id(fn)])
            for tmod, t in self._callees(mod, fn):
                add(tmod, t, roles)

    # -- access map ---------------------------------------------------------
    def _pre_spawn(self, fn_node: ast.AST, line: int) -> bool:
        spawns = self.spawn_lines.get(id(fn_node))
        return bool(spawns) and line <= min(spawns)

    def _scan_class(self, ci: ClassInfo) -> Tuple[
            Dict[str, List[_Access]], Dict[str, Tuple[str, int]]]:
        accesses: Dict[str, List[_Access]] = {}
        race_ok: Dict[str, Tuple[str, int]] = {}
        for sub in ast.walk(ci.node):
            targets: List[ast.expr] = []
            if isinstance(sub, ast.Assign):
                targets = sub.targets
            elif isinstance(sub, ast.AnnAssign):
                targets = [sub.target]
            else:
                continue
            for t in targets:
                if not is_self_attr(t):
                    continue
                m = ci.module.comment_in_range(
                    sub.lineno, sub.end_lineno or sub.lineno, RACE_OK_RE)
                if m is not None and t.attr not in race_ok:
                    race_ok[t.attr] = (m.group("reason"), sub.lineno)
        # class-body declarations (``x: T = default`` directly under the
        # class) are the other legal waiver site — the analogue of the
        # reference's ``volatile`` on the field declaration itself
        for sub in ci.node.body:
            targets = []
            if isinstance(sub, ast.Assign):
                targets = sub.targets
            elif isinstance(sub, ast.AnnAssign):
                targets = [sub.target]
            for t in targets:
                if not isinstance(t, ast.Name):
                    continue
                m = ci.module.comment_in_range(
                    sub.lineno, sub.end_lineno or sub.lineno, RACE_OK_RE)
                if m is not None and t.id not in race_ok:
                    race_ok[t.id] = (m.group("reason"), sub.lineno)

        for name, method in ci.methods.items():
            writes = _collect_writes(method)
            exempt0 = name in ("__init__", "__del__")
            roles0 = frozenset(self.roles.get(id(method), ()))
            held0 = frozenset(ci.lock_attrs) \
                if name.endswith("_locked") else frozenset()

            def visit(node: ast.AST, fn_node: ast.AST,
                      roles: FrozenSet[str], held: FrozenSet[str],
                      exempt: bool, qual: str) -> None:
                if isinstance(node, ast.With):
                    inner = held | frozenset(_with_locks(node, ci))
                    for item in node.items:
                        visit(item.context_expr, fn_node, roles, held,
                              exempt, qual)
                    for st in node.body:
                        visit(st, fn_node, roles, inner, exempt, qual)
                    return
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    # closures escape the with-block and may run on a
                    # spawned role: reset held locks, switch to the
                    # nested function's own role set when it is rooted
                    nname = getattr(node, "name", "<lambda>")
                    own = self.roles.get(id(node))
                    nroles = frozenset(own) if own else roles
                    nexempt = exempt and not own
                    nheld = frozenset(ci.lock_attrs) \
                        if nname.endswith("_locked") else frozenset()
                    body = node.body if isinstance(node.body, list) \
                        else [node.body]
                    for st in body:
                        visit(st, node, nroles, nheld, nexempt,
                              f"{qual}.{nname}")
                    return
                if isinstance(node, ast.Attribute) and is_self_attr(node):
                    f = node.attr
                    if roles and f not in ci.lock_attrs \
                            and f not in ci.methods:
                        accesses.setdefault(f, []).append(_Access(
                            qual=qual,
                            kind="write" if id(node) in writes
                            else "read",
                            roles=roles, held=held, line=node.lineno,
                            exempt=exempt,
                            pre_spawn=self._pre_spawn(
                                fn_node, node.lineno)))
                for child in ast.iter_child_nodes(node):
                    visit(child, fn_node, roles, held, exempt, qual)

            for stmt in method.body:
                visit(stmt, method, roles0, held0, exempt0,
                      f"{ci.name}.{name}")
        return accesses, race_ok

    # -- verdicts -----------------------------------------------------------
    def _verdict(self, ci: ClassInfo, field: str, accs: List[_Access],
                 race_ok: Dict[str, Tuple[str, int]],
                 registered: FrozenSet[str]) -> None:
        ro = race_ok.get(field)

        def dead(why: str) -> None:
            self.findings.append(Finding(
                "threads", ci.module.relpath, ro[1],
                f"{ci.name}.{field}:race-ok-dead",
                f"stale `# race-ok: {ro[0]}` on {ci.name}.{field}: "
                f"{why} — drop the waiver so it cannot mask a future "
                f"regression"))

        if field in ci.guarded:
            if ro is not None:
                dead("the field is `# guarded-by:` annotated; the lock, "
                     "not the waiver, is the invariant")
            return
        live = [a for a in accs if not a.exempt]
        all_roles: Set[str] = set()
        for a in live:
            all_roles |= a.roles
        if len(all_roles) <= 1:
            if ro is not None:
                only = next(iter(sorted(all_roles)), "no live role")
                dead(f"every access is confined to one role ({only})")
            return
        writes = [a for a in live if a.kind == "write"]
        if all(a.pre_spawn for a in writes):
            if ro is not None:
                dead("immutable after publish — every write precedes "
                     "every spawn in its function (or lives in "
                     "__init__)")
            return
        common: Optional[Set[str]] = None
        for a in live:
            common = set(a.held) if common is None else common & a.held
        if common:
            if ro is not None:
                dead(f"every access already holds "
                     f"self.{sorted(common)[0]}")
            return
        if ro is not None:
            if ro[0] in registered:
                return
            self.findings.append(Finding(
                "threads", ci.module.relpath, ro[1],
                f"{ci.name}.{field}:race-ok-reason",
                f"`# race-ok: {ro[0]}` on {ci.name}.{field} is not a "
                f"registered reason — add it to "
                f"tracing.RACE_OK_REASONS (conformance-tested) or use "
                f"a registered one"))
            return
        w = writes[0] if writes else live[0]
        self.findings.append(Finding(
            "threads", ci.module.relpath, w.line,
            f"{ci.name}.{field}",
            f"{ci.name}.{field} is touched by roles "
            f"{{{', '.join(sorted(all_roles))}}} with no consistent "
            f"lock (witness {w.kind} in {w.qual}(), line {w.line}) — "
            f"guard it, confine it to one role, publish it before "
            f"spawn, or waive it with a registered `# race-ok:` "
            f"reason"))

    # -- driver -------------------------------------------------------------
    def run(self) -> List[Finding]:
        self.compute_roles()
        registered = _registered_race_reasons(self.ctx)
        for ci in self.classes:
            accesses, race_ok = self._scan_class(ci)
            for field in sorted(set(accesses) | set(race_ok)):
                self._verdict(ci, field, accesses.get(field, []),
                              race_ok, registered)
        return self.findings


@register("threads", whole_program=True)
def check_threads(ctx: LintContext) -> List[Finding]:
    return _Topology(ctx).run()
