"""HBM accounting conservation: resident inserts/removals must balance.

The residency manager's contract (engine/residency.py, PR 2) is a
conservation law: every resident that leaves the entries dict must have
its device arrays released exactly once (after the manager lock drops —
the lock-order family owns that half), and every resident that enters
must be re-measured against the byte budget. A removal whose resident is
neither released nor handed to the caller leaks HBM until GC; an insert
that skips accounting lets ``stagedBytes`` drift from reality until the
next unrelated refresh. Three paired-effect rules, run as a forward
obligation analysis over the :mod:`dataflow` CFG — **including exception
edges**, so a release that only lives on the fall-through of a ``try`` is
caught:

- **remove -> release** (classes that define ``_release_all``, on fields
  whose values carry the ``.resident`` protocol): ``pop``/``del``/
  ``clear`` creates an obligation on the variables holding the removed
  resident(s); the obligation is discharged by a ``*release*`` call
  mentioning a holder, or by *returning* a holder (the caller inherits
  the obligation — method summaries record which return positions carry
  it, and call sites of summarized methods re-create it on the caller's
  targets). ``if e is not None`` guards prune the nothing-was-removed
  branch. A bare ``self.F.pop(k)`` whose result is discarded can never be
  released and is flagged outright.
- **insert -> accounting**: an insert into the entries dict must reach,
  on every fall-through path, a method that (transitively) writes a
  ``*bytes*`` counter field. Exception paths are exempt — the query is
  dying and the next refresh re-measures.
- **host-tier removal -> accounting** (entries fields whose name contains
  ``host`` — the host-RAM spill tier): unlike the device tier, whose
  ``stagedBytes`` is re-derived by walking residents on every refresh,
  host-tier bytes are a running counter adjusted at each transition —
  so every demotion that inserts must account host bytes (the insert
  rule above) AND every promotion/drop that removes must reach a
  ``*bytes*`` write on all paths *including exception edges* (a pop
  whose accounting lives only on the try fall-through drifts the host
  budget forever). This is a second obligation on top of remove ->
  release.
- **cache-field parity** (classes defining both ``nbytes()`` and
  ``release()``): every field such a class populates outside ``__init__``
  must be read by ``nbytes()`` AND cleared by ``release()`` — a staged
  cache that accounting cannot see, or that eviction cannot drop, is the
  tiered-storage follow-up's landmine.
- **idxacct** (every function, package-wide): a ``.index_slice(...)``
  call pins a freshly-built device idx array on a staged resident, so it
  must reach a residency ``.account(...)`` call (or a direct ``*bytes*``
  counter write) on every fall-through path before exit — otherwise the
  pinned array inflates the resident's true footprint while the budget's
  running view predates it. Exception paths are exempt for the same
  reason as the insert rule: ``nbytes()`` walks the slice cache, so the
  next refresh re-measures.
- **spanpair** (every function, package-wide): a ``span_begin(...)`` call
  must reach a ``span_end`` mentioning its holder on ALL paths including
  exception edges (the hostacct machinery over the same CFG) — an open
  span that never closes corrupts the query's trace tree AND pins its
  attribute payload for the query lifetime. Discharges: a ``span_end``
  call naming the holder, returning the holder (the caller owns the
  close), storing it on an attribute (a teardown hook owns it), or a
  nested function that closes it (the done-callback shape). A bare
  ``span_begin`` whose result is discarded can never be closed and is
  flagged outright. ``with recorder.span(...)`` creates no obligation —
  the context manager self-closes.
"""

from __future__ import annotations

import ast

from typing import Any, Dict, FrozenSet, List, Optional, Set, Tuple

from pinot_tpu.tools.lint.core import (
    Finding,
    LintContext,
    Module,
    is_self_attr,
    register,
)
from pinot_tpu.tools.lint.dataflow import (
    ForwardAnalysis,
    build_cfg,
    stmt_scan,
    walk_no_nested,
)

# obligation id: (kind, lineno, col); kind in {"remove", "insert", "call"}
_State = Dict[Tuple, Tuple[bool, FrozenSet[str]]]


def _mentions(node: Optional[ast.AST], names: FrozenSet[str]) -> bool:
    if node is None or not names:
        return False
    return any(isinstance(n, ast.Name) and n.id in names
               for n in ast.walk(node))


def _self_field_call(node: ast.AST, field: str, attr: str
                     ) -> Optional[ast.Call]:
    """``self.<field>.<attr>(...)`` call, or None."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr == attr \
            and is_self_attr(node.func.value, field):
        return node
    return None


class _ClassModel:
    """Everything the obligation analysis needs about one manager class."""

    def __init__(self, mod: Module, node: ast.ClassDef):
        self.mod = mod
        self.node = node
        self.name = node.name
        self.methods: Dict[str, ast.FunctionDef] = {
            n.name: n for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self.entries_fields = self._entries_fields()
        self.accounting = self._accounting_methods()
        # method name -> set of return positions carrying obligations
        # ("whole" for non-tuple returns); filled by the summary pass
        self.summaries: Dict[str, Set[Any]] = {}

    def _entry_vars(self, fn: ast.AST, field: str) -> Set[str]:
        """Locals bound from ``self.<field>`` lookups/pops/iteration."""
        out: Set[str] = set()
        for n in walk_no_nested(fn):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                v = n.value
                if isinstance(v, ast.Subscript) \
                        and is_self_attr(v.value, field):
                    out.add(n.targets[0].id)
                elif isinstance(v, ast.Call) \
                        and isinstance(v.func, ast.Attribute) \
                        and v.func.attr in ("get", "pop") \
                        and is_self_attr(v.func.value, field):
                    out.add(n.targets[0].id)
            if isinstance(n, (ast.For, ast.AsyncFor)) \
                    and _mentions_field(n.iter, field):
                t = n.target
                for x in ([t] if isinstance(t, ast.Name) else
                          getattr(t, "elts", [])):
                    if isinstance(x, ast.Name):
                        out.add(x.id)
        return out

    def _entries_fields(self) -> Set[str]:
        """Fields whose looked-up values have ``.resident`` accessed —
        the residents dict(s) this class manages."""
        fields: Set[str] = set()
        candidates: Set[str] = set()
        for fn in self.methods.values():
            for n in walk_no_nested(fn):
                if isinstance(n, ast.Attribute) and is_self_attr(n) \
                        and not isinstance(n.value, ast.Attribute):
                    candidates.add(n.attr)
        for field in candidates:
            for fn in self.methods.values():
                evars = self._entry_vars(fn, field)
                if not evars:
                    continue
                for n in walk_no_nested(fn):
                    if isinstance(n, ast.Attribute) \
                            and n.attr == "resident" \
                            and isinstance(n.value, ast.Name) \
                            and n.value.id in evars:
                        fields.add(field)
                        break
                if field in fields:
                    break
        return fields

    def _accounting_methods(self) -> Set[str]:
        """Methods that (transitively) write a ``*bytes*`` counter."""
        direct: Set[str] = set()
        for name, fn in self.methods.items():
            for n in walk_no_nested(fn):
                targets: List[ast.expr] = []
                if isinstance(n, ast.Assign):
                    targets = n.targets
                elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                    targets = [n.target]
                for t in targets:
                    if isinstance(t, ast.Attribute) and is_self_attr(t) \
                            and "bytes" in t.attr.lower():
                        direct.add(name)
        changed = True
        while changed:
            changed = False
            for name, fn in self.methods.items():
                if name in direct:
                    continue
                for n in walk_no_nested(fn):
                    if isinstance(n, ast.Call) \
                            and isinstance(n.func, ast.Attribute) \
                            and isinstance(n.func.value, ast.Name) \
                            and n.func.value.id == "self" \
                            and n.func.attr in direct:
                        direct.add(name)
                        changed = True
                        break
        return direct


def _mentions_field(node: ast.AST, field: str) -> bool:
    return any(isinstance(n, ast.Attribute) and is_self_attr(n, field)
               for n in ast.walk(node))


def _parse_none_test(test: ast.expr) -> Optional[Tuple[str, bool]]:
    """-> (var, none_when_true) for ``x is None`` / ``x is not None`` /
    ``x`` / ``not x`` tests; None otherwise."""
    if isinstance(test, ast.Compare) and len(test.ops) == 1 \
            and isinstance(test.left, ast.Name) \
            and isinstance(test.comparators[0], ast.Constant) \
            and test.comparators[0].value is None:
        if isinstance(test.ops[0], ast.Is):
            return (test.left.id, True)
        if isinstance(test.ops[0], ast.IsNot):
            return (test.left.id, False)
    if isinstance(test, ast.Name):
        return (test.id, False)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
            and isinstance(test.operand, ast.Name):
        return (test.operand.id, True)
    return None


class _MethodAnalysis:
    def __init__(self, model: _ClassModel, mname: str,
                 fn: ast.FunctionDef, use_summaries: bool):
        self.model = model
        self.mname = mname
        self.fn = fn
        self.use_summaries = use_summaries
        self.entry_vars: Set[str] = set()
        for f in model.entries_fields:
            self.entry_vars |= model._entry_vars(fn, f)
        # captured resident lists (for .clear()): vars assigned from an
        # expression that both references the entries field and reads
        # ``.resident``
        self.captured: Set[str] = set()
        for n in walk_no_nested(fn):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                for f in model.entries_fields:
                    if _mentions_field(n.value, f) and any(
                            isinstance(s, ast.Attribute)
                            and s.attr == "resident"
                            for s in ast.walk(n.value)):
                        self.captured.add(n.targets[0].id)
        self.immediate: List[Tuple[int, str]] = []
        self.obligation_lines: Dict[Tuple, str] = {}

    # -- events in one statement -------------------------------------------
    def _stmt_targets(self, st: ast.AST) -> Set[str]:
        out: Set[str] = set()
        if isinstance(st, ast.Assign):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    out |= {x.id for x in t.elts if isinstance(x, ast.Name)}
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign)) \
                and isinstance(st.target, ast.Name):
            out.add(st.target.id)
        for n in stmt_scan(st):
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)\
                    and n.func.attr in ("append", "extend") \
                    and isinstance(n.func.value, ast.Name):
                out.add(n.func.value.id)
        return out

    def transfer(self, state: _State, st: Optional[ast.AST],
                 nid: int) -> _State:
        if st is None or not isinstance(st, (ast.stmt,)):
            return state
        out: _State = dict(state)
        all_holders = frozenset(
            h for (p, hs) in out.values() if p for h in hs)

        # (a) holder extension: x = <holder-expr> / x.append(holder.resident)
        ext: Set[str] = set()
        if isinstance(st, ast.Assign) and _mentions(st.value, all_holders):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    ext.add(t.id)
        if isinstance(st, ast.AugAssign) \
                and isinstance(st.target, ast.Name) \
                and _mentions(st.value, all_holders):
            ext.add(st.target.id)
        for n in stmt_scan(st):
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)\
                    and n.func.attr in ("append", "extend") \
                    and isinstance(n.func.value, ast.Name) \
                    and any(_mentions(a, all_holders) for a in n.args):
                ext.add(n.func.value.id)
        if ext:
            for oid, (p, hs) in list(out.items()):
                if p and hs & all_holders:
                    out[oid] = (p, hs | frozenset(ext))

        # (b) satisfaction
        released: Set[str] = set()
        accounted = False
        for n in stmt_scan(st):
            if isinstance(n, ast.Call):
                fname = n.func.attr if isinstance(n.func, ast.Attribute) \
                    else (n.func.id if isinstance(n.func, ast.Name) else "")
                if "release" in fname:
                    for sub in ([n.func.value] if isinstance(
                            n.func, ast.Attribute) else []) + list(n.args):
                        for x in ast.walk(sub):
                            if isinstance(x, ast.Name):
                                released.add(x.id)
                if isinstance(n.func, ast.Attribute) \
                        and isinstance(n.func.value, ast.Name) \
                        and n.func.value.id == "self" \
                        and n.func.attr in self.model.accounting:
                    accounted = True
            targets = []
            if isinstance(n, ast.Assign):
                targets = n.targets
            elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                targets = [n.target]
            for t in targets:
                if isinstance(t, ast.Attribute) and is_self_attr(t) \
                        and "bytes" in t.attr.lower():
                    accounted = True
        for oid, (p, hs) in list(out.items()):
            if not p:
                continue
            if oid[0] in ("remove", "call") and hs & released:
                out[oid] = (False, hs)
            elif oid[0] in ("insert", "hostacct") and accounted:
                out[oid] = (False, hs)
        if isinstance(st, ast.Return) and st.value is not None:
            for oid, (p, hs) in list(out.items()):
                if p and oid[0] in ("remove", "call") \
                        and _mentions(st.value, hs):
                    out[oid] = (False, hs)
                    self._record_summary(st.value, hs)

        # (c) kills: plain rebind of a holder to something unrelated
        if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                and isinstance(st.targets[0], ast.Name) \
                and not _mentions(st.value, all_holders):
            dead = st.targets[0].id
            for oid, (p, hs) in list(out.items()):
                if dead in hs:
                    out[oid] = (p, hs - {dead})

        # (d) new obligations
        self._new_obligations(st, out)
        return out

    def _record_summary(self, value: ast.expr, hs: FrozenSet[str]) -> None:
        summ = self.model.summaries.setdefault(self.mname, set())
        if isinstance(value, ast.Tuple):
            for i, elt in enumerate(value.elts):
                if _mentions(elt, hs):
                    summ.add(i)
        else:
            summ.add("whole")

    def _host_obligation(self, f: str, node: ast.AST, out: _State,
                         what: str, holders: FrozenSet[str]) -> None:
        """Host-tier removal -> accounting: entries fields named ``*host*``
        keep a running byte counter, so every removal must reach a
        ``*bytes*`` write (exception edges included — see exc_filter,
        which exempts only inserts). ``holders`` carries the popped
        entry's variables so ``is None`` guards prune the
        nothing-was-removed branch, same as the remove rule."""
        if "host" not in f.lower():
            return
        oid = ("hostacct", node.lineno, node.col_offset)
        out.setdefault(oid, (True, holders))
        self.obligation_lines[oid] = (
            f"host-tier {what} on self.{f}")

    def _new_obligations(self, st: ast.stmt, out: _State) -> None:
        for f in self.model.entries_fields:
            for n in stmt_scan(st):
                pop = _self_field_call(n, f, "pop") \
                    or _self_field_call(n, f, "popitem")
                if pop is not None:
                    holders = frozenset(self._stmt_targets(st))
                    oid = ("remove", pop.lineno, pop.col_offset)
                    if holders:
                        out.setdefault(oid, (True, holders))
                        self.obligation_lines[oid] = (
                            f"resident popped from self.{f}")
                    else:
                        self.immediate.append((
                            pop.lineno,
                            f"self.{f}.pop() result is discarded — the "
                            f"removed resident can never be released"))
                    self._host_obligation(f, pop, out, "pop", holders)
                clr = _self_field_call(n, f, "clear")
                if clr is not None:
                    if self.captured:
                        oid = ("remove", clr.lineno, clr.col_offset)
                        out.setdefault(oid,
                                       (True, frozenset(self.captured)))
                        self.obligation_lines[oid] = (
                            f"residents cleared from self.{f}")
                    else:
                        self.immediate.append((
                            clr.lineno,
                            f"self.{f}.clear() drops every resident "
                            f"without capturing them for release"))
                    self._host_obligation(f, clr, out, "clear",
                                          frozenset(self.captured))
            if isinstance(st, ast.Delete):
                for t in st.targets:
                    if isinstance(t, ast.Subscript) \
                            and is_self_attr(t.value, f):
                        oid = ("remove", st.lineno, st.col_offset)
                        out.setdefault(
                            oid, (True, frozenset(self.entry_vars)))
                        self.obligation_lines[oid] = (
                            f"resident deleted from self.{f}")
                        self._host_obligation(f, st, out, "delete",
                                              frozenset(self.entry_vars))
            if isinstance(st, ast.Assign) and self.model.accounting:
                for t in st.targets:
                    if isinstance(t, ast.Subscript) \
                            and is_self_attr(t.value, f):
                        oid = ("insert", st.lineno, st.col_offset)
                        out.setdefault(oid, (True, frozenset()))
                        self.obligation_lines[oid] = (
                            f"resident inserted into self.{f}")
        # caller obligations from summarized self-calls
        if self.use_summaries and isinstance(
                st, (ast.Assign, ast.AugAssign)):
            call = st.value if isinstance(st.value, ast.Call) else None
            if call is not None and isinstance(call.func, ast.Attribute) \
                    and isinstance(call.func.value, ast.Name) \
                    and call.func.value.id == "self":
                summ = self.model.summaries.get(call.func.attr)
                if summ:
                    holders: Set[str] = set()
                    if isinstance(st, ast.AugAssign) \
                            and isinstance(st.target, ast.Name):
                        holders.add(st.target.id)
                    elif isinstance(st, ast.Assign):
                        for t in st.targets:
                            if isinstance(t, ast.Name):
                                holders.add(t.id)
                            elif isinstance(t, ast.Tuple):
                                for i, x in enumerate(t.elts):
                                    if (i in summ or "whole" in summ) \
                                            and isinstance(x, ast.Name):
                                        holders.add(x.id)
                    if holders:
                        oid = ("call", st.lineno, st.col_offset)
                        out.setdefault(oid, (True, frozenset(holders)))
                        self.obligation_lines[oid] = (
                            f"unreleased residents returned by "
                            f"self.{call.func.attr}()")

    # -- run ----------------------------------------------------------------
    def run(self) -> Dict[Tuple, str]:
        cfg = build_cfg(self.fn)

        def join(a: _State, b: _State) -> _State:
            out = dict(a)
            for oid, (p, h) in b.items():
                if oid in out:
                    p0, h0 = out[oid]
                    out[oid] = (p or p0, h0 | h)
                else:
                    out[oid] = (p, h)
            return out

        def refine(state: _State, test, is_true: bool) -> _State:
            if test is None:
                return state
            parsed = _parse_none_test(test)
            if parsed is None:
                return state
            var, none_when_true = parsed
            if none_when_true != is_true:
                return state
            out: _State = {}
            for oid, (p, h) in state.items():
                if p and var in h:
                    h2 = h - {var}
                    out[oid] = (p if h2 else False, h2)
                else:
                    out[oid] = (p, h)
            return out

        def exc_filter(state: _State) -> _State:
            # inserts are exempt on exception paths (the next refresh
            # re-measures); removals still must release
            return {oid: v for oid, v in state.items()
                    if oid[0] != "insert"}

        fa = ForwardAnalysis(cfg, {}, self.transfer, join,
                             refine=refine, exc_filter=exc_filter)
        inn = fa.run()
        exit_state = inn.get(cfg.exit, {})
        leaks: Dict[Tuple, str] = {}
        for oid, (p, _h) in exit_state.items():
            if p:
                leaks[oid] = self.obligation_lines.get(oid, "resident")
        return leaks


@register("conservation")
def check_conservation(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                methods = {n.name for n in node.body
                           if isinstance(n, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))}
                if "_release_all" in methods:
                    _check_manager(mod, node, findings)
                if "nbytes" in methods and "release" in methods:
                    _check_cache_parity(mod, node, findings)
                    _check_chunkacct(mod, node, findings)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _check_spanpair(mod, node, findings)
                _check_idxacct(mod, node, findings)
    return findings


# --------------------------------------------------------------------------
# idxacct: a pinned idx-array slice must reach byte accounting on every
# fall-through path — the index-rung residency obligation
# --------------------------------------------------------------------------

class _IdxAcctAnalysis:
    """Forward obligation analysis over one function: a ``.index_slice(...)``
    call grows a staged resident's device footprint (the docId gather array
    is pinned in the resident's slice cache), so every fall-through path to
    exit must pass a residency ``.account(...)`` call or a direct ``*bytes*``
    counter write. Exception edges are exempt — ``nbytes()`` walks the slice
    cache, so the next refresh re-measures (same rationale as the insert
    rule)."""

    def __init__(self, fn: ast.AST):
        self.fn = fn
        self.obligation_lines: Dict[Tuple, int] = {}

    @staticmethod
    def _opens(st: ast.stmt) -> Optional[int]:
        for n in stmt_scan(st):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "index_slice":
                return n.lineno
        return None

    @staticmethod
    def _discharges(st: ast.stmt) -> bool:
        targets: List[ast.expr] = []
        if isinstance(st, ast.Assign):
            targets = st.targets
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            targets = [st.target]
        for t in targets:
            if isinstance(t, ast.Attribute) and is_self_attr(t) \
                    and "bytes" in t.attr.lower():
                return True
        for n in stmt_scan(st):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "account":
                return True
        return False

    def transfer(self, state: Dict[Tuple, bool], st: Optional[ast.AST],
                 nid: int) -> Dict[Tuple, bool]:
        if st is None or not isinstance(st, ast.stmt):
            return state
        out = dict(state)
        if self._discharges(st):
            out = {oid: False for oid in out}
        line = self._opens(st)
        if line is not None:
            oid = ("idx", st.lineno, getattr(st, "col_offset", 0))
            out[oid] = True
            self.obligation_lines[oid] = line
        return out

    def run(self) -> List[int]:
        cfg = build_cfg(self.fn)

        def join(a: Dict[Tuple, bool],
                 b: Dict[Tuple, bool]) -> Dict[Tuple, bool]:
            out = dict(a)
            for oid, p in b.items():
                out[oid] = out.get(oid, False) or p
            return out

        fa = ForwardAnalysis(cfg, {}, self.transfer, join,
                             exc_filter=lambda s: {})
        inn = fa.run()
        exit_state = inn.get(cfg.exit, {})
        return sorted(self.obligation_lines[oid]
                      for oid, p in exit_state.items() if p)


def _check_idxacct(mod: Module, fn: ast.AST,
                   findings: List[Finding]) -> None:
    if not any(isinstance(n, ast.Call)
               and isinstance(n.func, ast.Attribute)
               and n.func.attr == "index_slice"
               for n in walk_no_nested(fn)):
        return
    for line in _IdxAcctAnalysis(fn).run():
        findings.append(Finding(
            "conservation", mod.relpath, line,
            f"{fn.name}:idxacct",
            f"index_slice in {fn.name}() pins a device idx array on a "
            f"path that exits without reaching byte accounting — the "
            f"resident's budgeted footprint predates the pinned slice"))


# --------------------------------------------------------------------------
# spanpair: span_begin must reach span_end on all paths (exception edges
# included) — the trace-tree integrity obligation
# --------------------------------------------------------------------------

def _call_last_name(n: ast.Call) -> str:
    f = n.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _names_in(nodes) -> Set[str]:
    out: Set[str] = set()
    for node in nodes:
        for x in ast.walk(node):
            if isinstance(x, ast.Name):
                out.add(x.id)
    return out


class _SpanPairAnalysis:
    """Forward obligation analysis over one function: every span_begin
    assigned to a local must meet a span_end naming it on every path to
    exit — the same CFG/exception-edge machinery the hostacct obligation
    uses, scoped package-wide (spans open anywhere)."""

    def __init__(self, fn: ast.AST):
        self.fn = fn
        self.immediate: List[int] = []
        self.obligation_lines: Dict[Tuple, int] = {}

    def transfer(self, state: _State, st: Optional[ast.AST],
                 nid: int) -> _State:
        if st is None or not isinstance(st, ast.stmt):
            return state
        out: _State = dict(state)
        all_holders = frozenset(
            h for (p, hs) in out.values() if p for h in hs)

        # discharges
        ended: Set[str] = set()
        for n in stmt_scan(st):
            if isinstance(n, ast.Call) \
                    and _call_last_name(n) == "span_end":
                ended |= _names_in(list(n.args)
                                   + [k.value for k in n.keywords])
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # done-callback shape: a nested function owning the close
            # discharges at its def (the closure pins the span until then)
            for n in ast.walk(st):
                if isinstance(n, ast.Call) \
                        and _call_last_name(n) == "span_end":
                    ended |= _names_in(list(n.args)
                                       + [k.value for k in n.keywords])
        returned: Set[str] = set()
        if isinstance(st, ast.Return) and st.value is not None:
            returned = _names_in([st.value])
        stored_names: Set[str] = set()
        if all_holders:
            for n in stmt_scan(st):
                if isinstance(n, ast.Assign) \
                        and any(isinstance(t, ast.Attribute)
                                for t in n.targets):
                    stored_names |= _names_in([n.value]) & all_holders
        for oid, (p, hs) in list(out.items()):
            if p and hs & (ended | returned | stored_names):
                out[oid] = (False, hs)

        # kills: rebinding a holder to something unrelated
        if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                and isinstance(st.targets[0], ast.Name) \
                and not _mentions(st.value, all_holders):
            dead = st.targets[0].id
            for oid, (p, hs) in list(out.items()):
                if dead in hs:
                    out[oid] = (p, hs - {dead})

        # new obligations
        for n in stmt_scan(st):
            if not (isinstance(n, ast.Call)
                    and _call_last_name(n) == "span_begin"):
                continue
            holders: Set[str] = set()
            if isinstance(st, ast.Assign):
                for t in st.targets:
                    if isinstance(t, ast.Name):
                        holders.add(t.id)
            elif isinstance(st, ast.AnnAssign) \
                    and isinstance(st.target, ast.Name):
                holders.add(st.target.id)
            elif isinstance(st, ast.Return):
                continue  # returned to the caller: it owns the close
            if holders:
                oid = ("span", n.lineno, n.col_offset)
                out.setdefault(oid, (True, frozenset(holders)))
                self.obligation_lines[oid] = n.lineno
            elif isinstance(st, ast.Expr):
                # bare call, result discarded: can never be closed
                self.immediate.append(n.lineno)
            # attribute-target assigns fall through obligation-free: the
            # span escaped to object state, a teardown hook owns it
        return out

    def run(self) -> List[int]:
        cfg = build_cfg(self.fn)

        def join(a: _State, b: _State) -> _State:
            out = dict(a)
            for oid, (p, h) in b.items():
                if oid in out:
                    p0, h0 = out[oid]
                    out[oid] = (p or p0, h0 | h)
                else:
                    out[oid] = (p, h)
            return out

        def refine(state: _State, test, is_true: bool) -> _State:
            if test is None:
                return state
            parsed = _parse_none_test(test)
            if parsed is None:
                return state
            var, none_when_true = parsed
            if none_when_true != is_true:
                return state
            out: _State = {}
            for oid, (p, h) in state.items():
                if p and var in h:
                    h2 = h - {var}
                    out[oid] = (p if h2 else False, h2)
                else:
                    out[oid] = (p, h)
            return out

        fa = ForwardAnalysis(cfg, {}, self.transfer, join, refine=refine,
                             exc_filter=lambda s: s)
        inn = fa.run()
        exit_state = inn.get(cfg.exit, {})
        return [self.obligation_lines[oid]
                for oid, (p, _h) in sorted(exit_state.items()) if p]


def _check_spanpair(mod: Module, fn: ast.AST,
                    findings: List[Finding]) -> None:
    if not any(isinstance(n, ast.Call)
               and _call_last_name(n) == "span_begin"
               for n in walk_no_nested(fn)):
        return
    sa = _SpanPairAnalysis(fn)
    for line in sa.run():
        findings.append(Finding(
            "conservation", mod.relpath, line,
            f"{fn.name}:spanpair",
            f"span_begin in {fn.name}() never reaches span_end on some "
            f"path (exception edges included) — the span tree is left "
            f"open and the query's trace is corrupted"))
    for line in sa.immediate:
        findings.append(Finding(
            "conservation", mod.relpath, line,
            f"{fn.name}:spanpair-discard",
            f"span_begin result discarded in {fn.name}() — the span can "
            f"never be closed"))


def _check_manager(mod: Module, node: ast.ClassDef,
                   findings: List[Finding]) -> None:
    model = _ClassModel(mod, node)
    if not model.entries_fields:
        return
    skip = {"__init__", "__del__", "_release_all"}
    # pass 1: build return-position summaries
    for mname, fn in model.methods.items():
        if mname in skip:
            continue
        _MethodAnalysis(model, mname, fn, use_summaries=False).run()
    # pass 2: full analysis with caller obligations
    for mname, fn in model.methods.items():
        if mname in skip:
            continue
        ma = _MethodAnalysis(model, mname, fn, use_summaries=True)
        leaks = ma.run()
        for (kind, line, _col), what in sorted(leaks.items()):
            if kind == "insert":
                findings.append(Finding(
                    "conservation", mod.relpath, line,
                    f"{model.name}.{mname}:insert",
                    f"{what} in {mname}() without re-running byte "
                    f"accounting on every fall-through path — "
                    f"stagedBytes drifts from the budget"))
            elif kind == "hostacct":
                findings.append(Finding(
                    "conservation", mod.relpath, line,
                    f"{model.name}.{mname}:hostacct",
                    f"{what} in {mname}() never reaches a byte-counter "
                    f"write on some path (exception edges included) — "
                    f"the host-tier running byte total drifts from "
                    f"reality"))
            else:
                findings.append(Finding(
                    "conservation", mod.relpath, line,
                    f"{model.name}.{mname}:{kind}",
                    f"{what} in {mname}() is neither released nor "
                    f"returned to the caller on some path (exception "
                    f"edges included) — HBM leaks until GC"))
        for line, msg in ma.immediate:
            findings.append(Finding(
                "conservation", mod.relpath, line,
                f"{model.name}.{mname}:discard",
                f"{msg} (in {mname}())"))


def _check_cache_parity(mod: Module, node: ast.ClassDef,
                        findings: List[Finding]) -> None:
    methods = {n.name: n for n in node.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    nbytes_fn = methods["nbytes"]
    release_fn = methods["release"]
    fields: Dict[str, Tuple[str, int]] = {}
    for mname, fn in methods.items():
        if mname in ("__init__", "release", "nbytes"):
            continue
        for n in walk_no_nested(fn):
            targets: List[ast.expr] = []
            if isinstance(n, ast.Assign):
                targets = n.targets
            elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                targets = [n.target]
            for t in targets:
                base = t
                while isinstance(base, ast.Subscript):
                    base = base.value
                if isinstance(base, ast.Attribute) and is_self_attr(base):
                    fields.setdefault(base.attr, (mname, n.lineno))
            # mutating-call population (``self.F.setdefault(k, arrays)``,
            # ``.update``, ``.append``): the star-tree node-array shape —
            # a cache filled without a plain subscript assignment must
            # still obey the nbytes()/release() parity contract
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                    and n.func.attr in ("setdefault", "update", "append") \
                    and n.args:
                base = n.func.value
                while isinstance(base, ast.Subscript):
                    base = base.value
                if isinstance(base, ast.Attribute) and is_self_attr(base):
                    fields.setdefault(base.attr, (mname, n.lineno))
    read_in_nbytes = {n.attr for n in ast.walk(nbytes_fn)
                      if isinstance(n, ast.Attribute) and is_self_attr(n)}
    cleared: Set[str] = set()
    for n in ast.walk(release_fn):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                if isinstance(t, ast.Attribute) and is_self_attr(t):
                    cleared.add(t.attr)
        if isinstance(n, ast.Delete):
            for t in n.targets:
                if isinstance(t, ast.Attribute) and is_self_attr(t):
                    cleared.add(t.attr)
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in ("clear", "pop", "popitem") \
                and isinstance(n.func.value, ast.Attribute) \
                and is_self_attr(n.func.value):
            cleared.add(n.func.value.attr)
    for field, (mname, line) in sorted(fields.items()):
        if field not in read_in_nbytes:
            findings.append(Finding(
                "conservation", mod.relpath, line,
                f"{node.name}.{field}:nbytes",
                f"{node.name}.{field} is populated in {mname}() but "
                f"never counted in nbytes() — resident bytes invisible "
                f"to the HBM budget"))
        if field not in cleared:
            findings.append(Finding(
                "conservation", mod.relpath, line,
                f"{node.name}.{field}:release",
                f"{node.name}.{field} is populated in {mname}() but "
                f"never cleared in release() — device arrays outlive "
                f"eviction"))


# --------------------------------------------------------------------------
# chunkacct: every chunk append must reach the running byte counter on all
# paths (exception edges included) — the mutable-staging watermark
# accounting obligation
# --------------------------------------------------------------------------

class _ChunkAcctAnalysis:
    """Forward obligation analysis over one method: a store into a
    ``self.*chunk*`` collection opens an obligation that only a ``*bytes*``
    counter write (direct, or via one of the class's accounting methods)
    discharges; any path reaching exit with the obligation pending has
    grown the device image without telling the HBM budget."""

    def __init__(self, fn: ast.AST, accounting: Set[str]):
        self.fn = fn
        self.accounting = accounting
        self.obligation_lines: Dict[Tuple, int] = {}

    @staticmethod
    def chunk_store_line(st: ast.stmt) -> Optional[int]:
        """Line of a subscript store into a self.*chunk* field, or None."""
        targets: List[ast.expr] = []
        if isinstance(st, ast.Assign):
            targets = st.targets
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            targets = [st.target]
        for t in targets:
            if not isinstance(t, ast.Subscript):
                continue
            base = t
            while isinstance(base, ast.Subscript):
                base = base.value
            if isinstance(base, ast.Attribute) and is_self_attr(base) \
                    and "chunk" in base.attr.lower():
                return st.lineno
        return None

    def _discharges(self, st: ast.stmt) -> bool:
        targets: List[ast.expr] = []
        if isinstance(st, ast.Assign):
            targets = st.targets
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            targets = [st.target]
        for t in targets:
            if isinstance(t, ast.Attribute) and is_self_attr(t) \
                    and "bytes" in t.attr.lower():
                return True
        for n in stmt_scan(st):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and isinstance(n.func.value, ast.Name) \
                    and n.func.value.id == "self" \
                    and n.func.attr in self.accounting:
                return True
        return False

    def transfer(self, state: Dict[Tuple, bool], st: Optional[ast.AST],
                 nid: int) -> Dict[Tuple, bool]:
        if st is None or not isinstance(st, ast.stmt):
            return state
        out = dict(state)
        if self._discharges(st):
            out = {oid: False for oid in out}
        line = self.chunk_store_line(st)
        if line is not None:
            oid = ("chunk", st.lineno, getattr(st, "col_offset", 0))
            out[oid] = True
            self.obligation_lines[oid] = line
        return out

    def run(self) -> List[int]:
        cfg = build_cfg(self.fn)

        def join(a: Dict[Tuple, bool],
                 b: Dict[Tuple, bool]) -> Dict[Tuple, bool]:
            out = dict(a)
            for oid, p in b.items():
                out[oid] = out.get(oid, False) or p
            return out

        fa = ForwardAnalysis(cfg, {}, self.transfer, join,
                             exc_filter=lambda s: s)
        inn = fa.run()
        exit_state = inn.get(cfg.exit, {})
        return sorted(self.obligation_lines[oid]
                      for oid, p in exit_state.items() if p)


def _check_chunkacct(mod: Module, node: ast.ClassDef,
                     findings: List[Finding]) -> None:
    """Dispatched for every nbytes()+release() resident class; only
    classes that append into ``self.*chunk*`` collections are analyzed."""
    model = _ClassModel(mod, node)
    for mname, fn in model.methods.items():
        if mname == "__init__":
            continue
        store_lines = [
            line for st in walk_no_nested(fn) if isinstance(st, ast.stmt)
            for line in [_ChunkAcctAnalysis.chunk_store_line(st)]
            if line is not None]
        if not store_lines:
            continue
        if not model.accounting:
            for line in store_lines:
                findings.append(Finding(
                    "conservation", mod.relpath, line,
                    f"{node.name}.{mname}:chunkacct",
                    f"{node.name}.{mname}() appends a device chunk but the "
                    f"class has no byte-counter accounting method — staged "
                    f"bytes invisible to the HBM budget"))
            continue
        analysis = _ChunkAcctAnalysis(fn, model.accounting)
        for line in analysis.run():
            findings.append(Finding(
                "conservation", mod.relpath, line,
                f"{node.name}.{mname}:chunkacct",
                f"{node.name}.{mname}() appends a device chunk on a path "
                f"that exits without updating the byte counter — the HBM "
                f"budget drifts from the true staged footprint"))
