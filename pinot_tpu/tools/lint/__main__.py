"""``python -m pinot_tpu.tools.lint [--baseline FILE] [paths...]``

Runs all checker families — the PR-4 AST tier (lock discipline, lease
pairing, tracer safety, wire/config consistency) and the dataflow tier
(kernel param protocol, device-sync taint, HBM accounting conservation) —
and exits non-zero on any finding not covered by the baseline (or an
inline ``# lint: ignore[...]``). With no paths, lints the whole
``pinot_tpu`` package. Stdlib-only: safe to run before the environment
can import jax.

``--json`` prints one JSON object per finding (key, family, file, line,
message) for CI / bench-harness annotation; ``--sarif`` prints one SARIF
2.1.0 log for code-scanning UIs (one reportingDescriptor per family);
``--families`` restricts the run to a comma-separated subset (see
``--list-families``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from pinot_tpu.tools.lint.core import (
    DEFAULT_BASELINE,
    checker_names,
    run_lint,
    select_changed,
)


def to_sarif(findings) -> dict:
    """SARIF 2.1.0 log for the findings: one run, one rule per family.

    The shape code-scanning UIs ingest — ``runs[0].tool.driver.rules``
    enumerates every registered family (so a clean run still advertises
    what was checked), each result carries the stable baseline key as
    its ``partialFingerprints`` entry so re-runs dedupe line moves the
    same way the baseline does.
    """
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri": "https://example.invalid/graftlint",
                "rules": [{"id": name,
                           "shortDescription": {"text": name}}
                          for name in checker_names()],
            }},
            "results": [{
                "ruleId": f.checker,
                "level": "error",
                "message": {"text": f.message},
                "partialFingerprints": {"graftlintKey/v1": f.key},
                "locations": [{
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.path.replace(os.sep, "/")},
                        "region": {"startLine": max(1, f.line)},
                    },
                }],
            } for f in findings],
        }],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m pinot_tpu.tools.lint",
        description="AST + dataflow invariant checker: lock discipline, "
                    "lease pairing, tracer safety, wire/config "
                    "consistency, kernel param protocol, device-sync "
                    "taint, HBM accounting conservation.")
    ap.add_argument("paths", nargs="*",
                    help="files or directories to lint "
                         "(default: the pinot_tpu package)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON of accepted finding keys "
                         "(default: tools/lint/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--keys", action="store_true",
                    help="print baseline keys instead of messages "
                         "(for composing baseline entries)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output: one JSON object per "
                         "finding (key, family, file, line, message)")
    ap.add_argument("--sarif", action="store_true", dest="as_sarif",
                    help="emit one SARIF 2.1.0 log on stdout (one rule "
                         "per family) for code-scanning UIs")
    ap.add_argument("--families", default=None, metavar="F1,F2",
                    help="run only the named checker families "
                         "(comma-separated; see --list-families)")
    ap.add_argument("--list-families", action="store_true",
                    help="print the registered family names and exit")
    ap.add_argument("--changed", default=None, metavar="GIT_REF",
                    help="lint only package files changed vs GIT_REF, "
                         "plus their direct imports and transitive "
                         "reverse importers (the file set the "
                         "interprocedural families need)")
    args = ap.parse_args(argv)

    if args.list_families:
        for name in checker_names():
            print(name)
        return 0

    families = None
    if args.families is not None:
        families = [s.strip() for s in args.families.split(",") if s.strip()]
        known = set(checker_names())
        unknown = [f for f in families if f not in known]
        if unknown:
            print(f"unknown families: {', '.join(unknown)} "
                  f"(known: {', '.join(sorted(known))})", file=sys.stderr)
            return 2

    paths = args.paths
    if not paths:
        import pinot_tpu

        paths = [os.path.dirname(os.path.abspath(pinot_tpu.__file__))]

    wp_root = None
    if args.changed is not None:
        if args.paths:
            print("--changed replaces explicit paths; pass one or the "
                  "other", file=sys.stderr)
            return 2
        # whole-program families (threads, configkeys) still analyze the
        # full package; only their findings are scoped to the changed set
        wp_root = paths[0]
        try:
            paths = select_changed(args.changed, paths[0])
        except Exception as e:  # not a repo / bad ref: loud, non-lint exit
            print(f"--changed {args.changed}: {e}", file=sys.stderr)
            return 2
        if not paths:
            if args.as_sarif:
                print(json.dumps(to_sarif([]), sort_keys=True))
            elif not args.as_json:
                print("graftlint: no changed package files",
                      file=sys.stderr)
            return 0

    baseline = None if args.no_baseline else args.baseline
    new, accepted = run_lint(paths, baseline=baseline, families=families,
                             whole_program_root=wp_root)
    if args.as_sarif:
        print(json.dumps(to_sarif(new), indent=2, sort_keys=True))
        return 1 if new else 0
    for f in new:
        if args.as_json:
            print(json.dumps({"key": f.key, "family": f.checker,
                              "file": f.path, "line": f.line,
                              "message": f.message}, sort_keys=True))
        else:
            print(f.key if args.keys else f.render())
    n_sup = len(accepted)
    if not args.as_json:
        print(f"graftlint: {len(new)} finding(s)"
              + (f", {n_sup} baselined/suppressed" if n_sup else ""),
              file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
