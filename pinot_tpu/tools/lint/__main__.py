"""``python -m pinot_tpu.tools.lint [--baseline FILE] [paths...]``

Runs all four checker families and exits non-zero on any finding not
covered by the baseline (or an inline ``# lint: ignore[...]``). With no
paths, lints the whole ``pinot_tpu`` package. Stdlib-only: safe to run
before the environment can import jax.
"""

from __future__ import annotations

import argparse
import os
import sys

from pinot_tpu.tools.lint.core import DEFAULT_BASELINE, run_lint


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m pinot_tpu.tools.lint",
        description="AST invariant checker: lock discipline, lease "
                    "pairing, tracer safety, wire/config consistency.")
    ap.add_argument("paths", nargs="*",
                    help="files or directories to lint "
                         "(default: the pinot_tpu package)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON of accepted finding keys "
                         "(default: tools/lint/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--keys", action="store_true",
                    help="print baseline keys instead of messages "
                         "(for composing baseline entries)")
    args = ap.parse_args(argv)

    paths = args.paths
    if not paths:
        import pinot_tpu

        paths = [os.path.dirname(os.path.abspath(pinot_tpu.__file__))]

    baseline = None if args.no_baseline else args.baseline
    new, accepted = run_lint(paths, baseline=baseline)
    for f in new:
        print(f.key if args.keys else f.render())
    n_sup = len(accepted)
    print(f"graftlint: {len(new)} finding(s)"
          + (f", {n_sup} baselined/suppressed" if n_sup else ""),
          file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
