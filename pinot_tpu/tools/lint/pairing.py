"""Resource pairing: acquire-like calls must release on ALL paths.

Pairs checked (the lease/refcount protocols of the residency manager and
the table data managers):

- ``begin_query`` / ``end_query``       (HBM residency QueryLease)
- ``_begin_lease`` / ``end_query``      (executor wrapper for the above)
- ``acquire_segments`` / ``release_segments``  (segment refcounts)
- ``acquire`` / ``release``             (bare refcount style)
- ``admit`` / ``release``               (admission-gate tickets)

For each function that calls the acquire half:

- if the acquired resource *escapes* (returned, yielded, stored on
  ``self``, or stashed into a container that is itself the function's
  product), local analysis cannot conclude — skipped; the function is a
  resource constructor and its callers are checked instead;
- otherwise a matching release call must exist in the ``finally`` of a
  ``try`` that either encloses the acquire or follows it in the same
  block (``with`` context managers on the resource also count);
- a release that exists but is NOT exception-safe (reachable only on the
  fall-through path) is the classic 8-thread-hang shape and is flagged.

Bare ``acquire``/``release`` is checked only when the receiver is a plain
local name — ``self.quota.acquire(table)`` styles (long-lived token
managers with no release half) and threading primitives are excluded.
"""

from __future__ import annotations

import ast

from typing import Dict, List, Optional, Set, Tuple

from pinot_tpu.tools.lint.core import (
    Finding,
    LintContext,
    call_name,
    is_self_attr,
    register,
)
from pinot_tpu.tools.lint.locks import collect_classes

PAIRS = [
    ("begin_query", "end_query"),
    ("_begin_lease", "end_query"),
    ("acquire_segments", "release_segments"),
    ("acquire", "release"),
    # admission-gate tickets (server/admission.py): a rejected/errored
    # query must free its slot on every path or the gate convoys shut
    ("admit", "release"),
]
BARE_PAIRS = {"acquire"}  # resource = the receiver, not the return value


def _functions(tree: ast.AST):
    """Every function in the module, with its qualname."""
    out: List[Tuple[str, ast.FunctionDef]] = []

    def rec(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append((prefix + child.name, child))
                rec(child, prefix + child.name + ".")
            elif isinstance(child, ast.ClassDef):
                rec(child, prefix + child.name + ".")
            else:
                rec(child, prefix)

    rec(tree, "")
    return out


def _blocks_after(func: ast.AST, target: ast.AST) -> List[ast.Try]:
    """Try statements that can cover ``target``: ancestors whose body holds
    it, plus later siblings in every enclosing statement list."""
    trys: List[ast.Try] = []

    def rec(node: ast.AST) -> bool:
        """True when ``target`` is in this subtree."""
        found = False
        body_lists = [getattr(node, f) for f in ("body", "orelse",
                                                 "finalbody", "handlers")
                      if getattr(node, f, None)]
        flat: List[List[ast.AST]] = []
        for bl in body_lists:
            items = []
            for st in bl:
                items.append(st)
            flat.append(items)
        for stmts in flat:
            hit_idx = None
            for i, st in enumerate(stmts):
                if st is target or rec(st):
                    hit_idx = i
                    found = True
                    break
            if hit_idx is not None:
                for later in stmts[hit_idx:]:
                    if isinstance(later, ast.Try):
                        trys.append(later)
        if found and isinstance(node, ast.Try):
            trys.append(node)
        return found or any(
            target is c for c in ast.walk(node) if c is target)

    rec(func)
    return trys


def _contains_target(node: ast.AST, target: ast.AST) -> bool:
    return any(c is target for c in ast.walk(node))


def _release_in(nodes: List[ast.AST], release: str,
                resource: Optional[str]) -> bool:
    for n in nodes:
        for sub in ast.walk(n):
            if isinstance(sub, ast.Call) and call_name(sub) == release:
                if resource is None:
                    return True
                names = {a.id for a in ast.walk(sub)
                         if isinstance(a, ast.Name)}
                if resource in names:
                    return True
    return False


def _escapes(func: ast.AST, name: str, release: str) -> bool:
    """Does local ``name`` escape this function (so pairing is the
    caller's job)? Returned/yielded, stored onto an attribute/subscript,
    stashed via a container method, or passed to any call that is not the
    release half."""
    for node in ast.walk(func):
        if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
            v = getattr(node, "value", None)
            if v is not None and any(isinstance(x, ast.Name) and x.id == name
                                     for x in ast.walk(v)):
                return True
        if isinstance(node, ast.Assign):
            if any(isinstance(t, (ast.Attribute, ast.Subscript))
                   for t in node.targets) \
                    and any(isinstance(x, ast.Name) and x.id == name
                            for x in ast.walk(node.value)):
                return True
        if isinstance(node, ast.Call) and call_name(node) != release:
            for a in node.args:
                if isinstance(a, ast.Name) and a.id == name:
                    return True
            f = node.func  # container stash: out.append(sdm)
            if isinstance(f, ast.Attribute) \
                    and any(isinstance(x, ast.Name) and x.id == name
                            for arg in node.args for x in ast.walk(arg)):
                return True
    return False


@register("pairing")
def check_pairing(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    # threading-primitive attribute names across the scanned classes:
    # their acquire/release is flow control, not a refcount protocol
    classes, _ = collect_classes(ctx)
    lock_attr_names: Set[str] = set()
    for ci in classes:
        lock_attr_names |= ci.lock_attrs

    for mod in ctx.modules:
        for qualname, func in _functions(mod.tree):
            for acquire, release in PAIRS:
                _check_one(mod, qualname, func, acquire, release,
                           lock_attr_names, findings)
    return findings


def _check_one(mod, qualname: str, func: ast.AST, acquire: str,
               release: str, lock_attr_names: Set[str],
               findings: List[Finding]) -> None:
    own_body_funcs = {id(n) for sub in ast.walk(func)
                      if isinstance(sub, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                      and sub is not func
                      for n in ast.walk(sub)}
    for stmt in ast.walk(func):
        if id(stmt) in own_body_funcs:
            continue  # nested defs are their own checked functions
        if not isinstance(stmt, ast.Call) or call_name(stmt) != acquire:
            continue
        if stmt.func is not None and isinstance(stmt.func, ast.Attribute):
            recv = stmt.func.value
        else:
            recv = None
        if acquire in BARE_PAIRS:
            # only plain-local receivers are checkable refcount handles
            if not isinstance(recv, ast.Name) \
                    or recv.id in lock_attr_names:
                continue
            resource = recv.id
            if _escapes(func, resource, release):
                continue
        else:
            # resource = assignment target of the acquire call
            resource = _assign_target(func, stmt)
            if resource is None:
                # bare acquire with a discarded result: nothing can ever
                # release it
                findings.append(Finding(
                    "pairing", mod.relpath, stmt.lineno,
                    f"{qualname}:{acquire}",
                    f"{acquire}() result is discarded — the matching "
                    f"{release}() can never run"))
                continue
            if _escapes(func, resource, release):
                continue

        trys = _blocks_after(func, stmt)
        safe = any(_release_in(t.finalbody, release, resource)
                   for t in trys)
        if not safe and _with_manages(func, resource):
            safe = True
        if safe:
            continue
        anywhere = _release_in([func], release, resource)
        if anywhere:
            findings.append(Finding(
                "pairing", mod.relpath, stmt.lineno,
                f"{qualname}:{acquire}",
                f"{release}({resource}) is not in a `finally` reachable "
                f"from {acquire}() — an exception leaks the resource"))
        else:
            findings.append(Finding(
                "pairing", mod.relpath, stmt.lineno,
                f"{qualname}:{acquire}",
                f"{acquire}() has no matching {release}() on any path "
                f"in {qualname}()"))


def _assign_target(func: ast.AST, call: ast.Call) -> Optional[str]:
    for node in ast.walk(func):
        if isinstance(node, ast.Assign) and node.value is call:
            t = node.targets[0]
            if isinstance(t, ast.Name):
                return t.id
        if isinstance(node, ast.withitem) and node.context_expr is call:
            if isinstance(node.optional_vars, ast.Name):
                return node.optional_vars.id
    return None


def _with_manages(func: ast.AST, resource: str) -> bool:
    for node in ast.walk(func):
        if isinstance(node, ast.With):
            for item in node.items:
                e = item.context_expr
                if isinstance(e, ast.Name) and e.id == resource:
                    return True
                if isinstance(item.optional_vars, ast.Name) \
                        and item.optional_vars.id == resource:
                    return True
    return False
