"""``decisions`` family: decision-path totality over the ledger scope.

The system's credo since the decision ledger landed is "every fallback
explained": rung selection, reduce-path choice, routing prunes, hybrid
splits, and seal swaps all record WHY the declined alternative lost. The
``decline`` family (PR 11) and the reason-namespace conformance scan
(PR 15) check that every reason *literal* is registered — this family
closes the other half: for every function in the declared scope registry
below, every path that returns into the declined alternative — including
paths through exception handlers — must REACH a recorder call
(``record_decision`` / an ``on_decline``-style hook) before it exits.

Built on the PR-5 CFG tier: a must-analysis of one "recorded" bit over
:func:`dataflow.build_cfg`'s statement-level CFG (exception edges carry
the raising statement's pre-state, so a handler that swallows and
returns None must record on its own). Two scope modes:

- ``none`` — only explicit ``return None`` / bare ``return`` exits are
  decline exits (the scoped rung-probe shape: a non-None return means
  the rung SERVED, and ``return decline(...)`` / delegation returns
  record through their callee);
- ``all`` — every return and the implicit fall-through must be recorded
  (the scoped always-record shape: routing prunes, the hybrid split,
  the seal swap ledger every outcome).

Escaping raises are never findings — an exception that leaves the
function is loud by construction. Three discharges keep the zero
baseline honest without taint-widening:

- a ``not a decline`` / ``record(s|ed) its own reason`` comment on the
  exit's lines — the in-code annotation that an early None is a
  structural miss (no trees, no filter), not a silenced decline;
- ``x = f(..., on_decline=<hook>)`` followed by ``if x is None: return
  None`` — the callee records on every None it returns, so the caller's
  pass-through is covered (tracked per assigned name, killed on
  reassignment);
- branch edges testing the function's own ``on_decline`` hook against
  None: on the hook-is-None side recording is vacuous (recording IS the
  hook; without one there is nothing to drop).

The family also re-checks every literal reason argument at a recorder
call inside the scoped functions against the reason registry parsed
from ``common/tracing.py`` (ast, never imported) — non-literal reasons
(``e.reason_code``, f-strings) are the bench's runtime validation's
job, not lint's.

True positives are fixed in-code, never baselined.
"""

from __future__ import annotations

import ast
import os
import re

from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from pinot_tpu.tools.lint.core import (
    Finding,
    LintContext,
    Module,
    call_name,
    register,
)
from pinot_tpu.tools.lint.dataflow import ForwardAnalysis, build_cfg, \
    stmt_scan

_TRACING_PATH = os.path.normpath(os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    os.pardir, os.pardir, "common", "tracing.py"))

# scope registry: module basename -> {function name: exit mode}. The
# basename keying lets test fixtures named like the real modules run the
# same rules (the declines-family convention); real-module collisions
# are resolved by the function-name lookup (parallel/executor.py defines
# none of engine/executor.py's scoped probes).
SCOPE: Dict[str, Dict[str, str]] = {
    "executor.py": {"_try_star_tree": "none", "_try_pallas": "none",
                    "_star_tree_pick": "none"},
    "startree_exec.py": {"pick_star_tree": "none",
                         "resolve_matches": "none"},
    "index_exec.py": {"try_index_rung": "none"},
    "pallas_kernels.py": {"extract_plan": "none",
                          "probe_narrowed_plan": "none"},
    "mutable_staging.py": {"_serve": "none", "_try_index_gather": "none"},
    "reduce.py": {"_fold_group_by": "none", "_fold_rows": "none",
                  "_finish_group_by": "none", "_device_group_by": "none"},
    "routing.py": {"_partition_prune": "all", "_time_prune": "all"},
    "broker.py": {"_split_hybrid": "all"},
    "data_manager.py": {"on_sealed": "all"},
}

# call names that record a decision (the ledger entrypoint plus every
# recorder closure/method convention in the scoped modules)
RECORDERS = frozenset({
    "record_decision", "on_decline", "decline", "declined", "note",
    "_decline", "_decline_rung", "_decline_device", "_chose",
    "_chose_rung", "_hybrid_route",
})

# the hook parameter name whose None-guard makes recording vacuous
HOOK_PARAM = "on_decline"

DISCHARGE_RE = re.compile(
    r"not a decline|record(?:s|ed)? (?:its|their) own reason")

_DYNAMIC_REASON = re.compile(r"tree\d+\Z")

_TABLE_NAME = re.compile(r"^[A-Z0-9_]+(?:_REASONS|_CODES)\Z")


def _load_registered_reasons(ctx: LintContext) -> FrozenSet[str]:
    """Every registered reason code, parsed from common/tracing.py — the
    scanned copy when the lint run includes one (so fixture trees check
    against THEIR table), the installed package's file otherwise."""
    tree = None
    for mod in ctx.modules:
        if mod.relpath.replace(os.sep, "/").endswith("common/tracing.py"):
            tree = mod.tree
            break
    if tree is None:
        with open(_TRACING_PATH, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=_TRACING_PATH)
    codes: Set[str] = set()

    def strings_of(node: ast.expr) -> Set[str]:
        out: Set[str] = set()
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "frozenset":
            for a in node.args:
                out |= strings_of(a)
        elif isinstance(node, (ast.Set, ast.Tuple, ast.List)):
            for e in node.elts:
                out |= strings_of(e)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.add(node.value)
        elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
            out |= strings_of(node.left) | strings_of(node.right)
        elif isinstance(node, ast.GeneratorExp):
            pass  # computed namespace slices: covered by their source set
        return out

    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        if _TABLE_NAME.match(name):
            codes |= strings_of(node.value)
        elif name == "_DECLINE_RULES" and isinstance(node.value, ast.Tuple):
            for elt in node.value.elts:
                if isinstance(elt, ast.Tuple) and len(elt.elts) == 2 \
                        and isinstance(elt.elts[1], ast.Constant) \
                        and isinstance(elt.elts[1].value, str):
                    codes.add(elt.elts[1].value)
    return frozenset(codes)


# -- the "recorded" must-analysis -------------------------------------------

# state: (recorded, srvars) — recorded is the must-bit, srvars the names
# currently bound to a self-recording call's result
_State = Tuple[bool, FrozenSet[str]]


def _is_self_recording_call(node: ast.expr) -> bool:
    return (isinstance(node, ast.Call)
            and any(kw.arg == HOOK_PARAM
                    and not (isinstance(kw.value, ast.Constant)
                             and kw.value.value is None)
                    for kw in node.keywords))


def _records(st: ast.AST) -> bool:
    """Does this ONE CFG statement call a recorder (no nested defs)?"""
    for node in stmt_scan(st):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in RECORDERS:
                return True
    return False


def _is_name_none_test(test: ast.expr, names: FrozenSet[str],
                       want_is_none: bool) -> bool:
    """``<n> is None`` (want_is_none) / ``<n> is not None`` for n in
    ``names``."""
    if not (isinstance(test, ast.Compare) and len(test.ops) == 1
            and isinstance(test.left, ast.Name)
            and test.left.id in names
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None):
        return False
    op = test.ops[0]
    return isinstance(op, ast.Is) if want_is_none \
        else isinstance(op, ast.IsNot)


def _analyze(mod: Module, func: ast.AST, mode: str,
             registered: FrozenSet[str]) -> List[Finding]:
    findings: List[Finding] = []
    cfg = build_cfg(func)
    hook_names = frozenset(
        a.arg for a in list(func.args.args) + list(func.args.kwonlyargs)
        if a.arg == HOOK_PARAM)

    def transfer(state: _State, st: Optional[ast.AST], _n: int) -> _State:
        if st is None:
            return state
        recorded, srvars = state
        if _records(st):
            recorded = True
        if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                and isinstance(st.targets[0], ast.Name):
            name = st.targets[0].id
            if _is_self_recording_call(st.value):
                srvars = srvars | {name}
            elif name in srvars:
                srvars = srvars - {name}
        elif isinstance(st, ast.Assign):
            killed = {t.id for tgt in st.targets
                      if isinstance(tgt, ast.Tuple)
                      for t in tgt.elts if isinstance(t, ast.Name)}
            killed |= {tgt.id for tgt in st.targets
                       if isinstance(tgt, ast.Name)}
            if killed & srvars:
                srvars = srvars - killed
        return (recorded, srvars)

    def join(a: _State, b: _State) -> _State:
        return (a[0] and b[0], a[1] & b[1])

    def refine(state: _State, test: Optional[ast.expr],
               is_true: bool) -> _State:
        if test is None:
            return state
        recorded, srvars = state
        vacuous_names = hook_names | srvars
        if is_true and _is_name_none_test(test, vacuous_names, True):
            return (True, srvars)
        if not is_true and _is_name_none_test(test, vacuous_names, False):
            return (True, srvars)
        return state

    analysis = ForwardAnalysis(cfg, (False, frozenset()), transfer, join,
                               refine=refine)
    inn = analysis.run()

    def discharged(st: ast.AST) -> bool:
        lo = st.lineno
        hi = getattr(st, "end_lineno", lo) or lo
        # annotations ride the exit statement or spill to the line after
        # (the codebase's continuation-comment idiom)
        return mod.comment_in_range(lo, hi + 1, DISCHARGE_RE) is not None

    returns = [n for n, st in enumerate(cfg.stmts)
               if isinstance(st, ast.Return)]
    checked: List[int] = []
    for n in returns:
        st = cfg.stmts[n]
        is_none_exit = st.value is None or (
            isinstance(st.value, ast.Constant) and st.value.value is None)
        if mode == "none" and not is_none_exit:
            continue
        checked.append(n)

    qual = func.name
    exit_ord = {n: i for i, n in enumerate(sorted(
        checked, key=lambda n: (cfg.stmts[n].lineno,
                                cfg.stmts[n].col_offset)))}
    for n in checked:
        state = inn.get(n)
        if state is None:
            continue  # unreachable
        out_recorded = transfer(state, cfg.stmts[n], n)[0]
        if out_recorded or discharged(cfg.stmts[n]):
            continue
        st = cfg.stmts[n]
        findings.append(Finding(
            "decisions", mod.relpath, st.lineno,
            f"{qual}:exit{exit_ord[n]}",
            f"{qual} can exit into the declined alternative at line "
            f"{st.lineno} without a ledger record — every fallback path "
            f"must reach record_decision/on_decline (or carry a "
            f"'not a decline' annotation)"))

    if mode == "all":
        # the implicit fall-through exit must be recorded too
        for n, _ in enumerate(cfg.stmts):
            st = cfg.stmts[n]
            if n == cfg.entry or isinstance(st, (ast.Return, ast.Raise)):
                continue
            for m, lbl in cfg.succ[n]:
                if m != cfg.exit or lbl == "exc":
                    continue
                state = inn.get(n)
                if state is None:
                    continue
                out = transfer(state, st, n)
                if isinstance(lbl, tuple):
                    out = refine(out, lbl[1], lbl[0] == "true")
                if out[0] or (st is not None and discharged(st)):
                    continue
                line = getattr(st, "lineno", func.lineno)
                findings.append(Finding(
                    "decisions", mod.relpath, line,
                    f"{qual}:fallthrough",
                    f"{qual} can fall through to its end without a "
                    f"ledger record — this decision point must record "
                    f"every outcome"))
                break
    return findings


# -- reason-literal conformance at scoped recorder calls --------------------

def _reason_literals(node: ast.Call) -> List[str]:
    """Checkable literal reason(s) of a recorder call: [] when the
    reason is dynamic (Name/attribute/f-string — runtime validation's
    job)."""
    name = call_name(node)
    if name == "record_decision":
        reason: Optional[ast.expr] = None
        if len(node.args) >= 5:
            reason = node.args[4]
        else:
            for kw in node.keywords:
                if kw.arg == "reason":
                    reason = kw.value
    else:
        reason = next(
            (a for a in node.args
             if isinstance(a, ast.Constant) and isinstance(a.value, str)
             or isinstance(a, ast.IfExp)),
            None)
    if isinstance(reason, ast.Constant) and isinstance(reason.value, str):
        return [reason.value]
    if isinstance(reason, ast.IfExp):
        return [b.value for b in (reason.body, reason.orelse)
                if isinstance(b, ast.Constant)
                and isinstance(b.value, str)]
    return []


def _check_reasons(mod: Module, func: ast.AST,
                   registered: FrozenSet[str]) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(func):
        if not isinstance(node, ast.Call) \
                or call_name(node) not in RECORDERS:
            continue
        for code in _reason_literals(node):
            if code in registered or _DYNAMIC_REASON.fullmatch(code):
                continue
            findings.append(Finding(
                "decisions", mod.relpath, node.lineno,
                f"{func.name}:reason:{code[:40]}",
                f"reason {code!r} recorded in {func.name} is not in any "
                f"registered namespace (tracing.reason_registry()) — "
                f"register it so the ledger never carries an unknown "
                f"code"))
    return findings


@register("decisions")
def check_decisions(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    targets = [(m, SCOPE[os.path.basename(m.relpath)])
               for m in ctx.modules
               if os.path.basename(m.relpath) in SCOPE]
    if not targets:
        return findings
    registered = _load_registered_reasons(ctx)
    for mod, scope in targets:
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            mode = scope.get(node.name)
            if mode is None:
                continue
            findings.extend(_analyze(mod, node, mode, registered))
            findings.extend(_check_reasons(mod, node, registered))
    return findings
