"""graftlint: AST-based invariant checker for the pinot_tpu codebase.

Four checker families, each born from a bug an advisor had to find by hand
(ISSUE 4; the PR-2 ``stage()`` get-then-set race, the ``_evict_batch`` key
bug, the lazy CRC32C table race):

- ``lock-guard`` / ``lock-order`` (locks.py): ``# guarded-by: <lock>``
  annotated fields must only be touched under ``with self.<lock>``; the
  cross-module lock-acquisition graph must be free of A->B / B->A
  inversions.
- ``pairing`` (pairing.py): ``begin_query``/``end_query``,
  ``acquire_segments``/``release_segments`` and refcount
  ``acquire``/``release`` must pair through a ``finally`` (or context
  manager) on every path.
- ``tracer`` (tracer.py): functions reachable from ``jax.jit`` / ``vmap`` /
  ``shard_map`` / ``pallas_call`` roots must not call host-side
  nondeterminism (``time.*``, ``threading.*``, ``random.*``, I/O,
  ``.item()``, global mutation).
- ``wire`` / ``config`` (wire.py): every ``QueryStats`` field must ride the
  DataTable wire (``to_dict`` / ``merge`` / ``_stats_from_dict``); every
  ``pinot.server.*`` / ``pinot.broker.*`` key string must be declared in
  ``spi/config.py``'s ``CommonConstants``.

ISSUE 5 adds the interprocedural dataflow tier (dataflow.py: per-function
CFG with exception edges, forward abstract interpretation, and a
path-enumerating dispatch executor with call-graph summaries):

- ``protocol`` (protocol.py): the positional static-param pack/unpack
  contract between ``engine/plan.py`` and every ``pc.take()`` consumer —
  per-op counts against ``_FILTER_PARAMS``/``_VALUE_PARAMS``, the
  (strides, bases) group-epilogue order, ``_bases`` int32-narrowing
  safety, ``_next_pow2`` drift, and unfinished-cursor tails.
- ``sync`` (sync.py): device-value taint reaching an implicit
  host-materialization sink (``np.asarray``, ``.item()``, ``float()`` …)
  while a lock is held or on the launcher dispatcher thread.
- ``conservation`` (conservation.py): paired-effect proof that every
  resident removal releases (exception edges included), every insert
  re-runs byte accounting, and ``nbytes()``/``release()`` classes count
  and clear every field they populate.

ISSUE 11 adds ``decline`` (declines.py): every ``_Ineligible("...")`` /
``decline("...")`` literal in ``engine/pallas_kernels.py`` must resolve
to a registered ledger code (``tracing._DECLINE_RULES`` needle or
``DIRECT_DECLINE_CODES`` entry) — new decline sites can never reach the
ledger as an unregistered reason.

ISSUE 15 adds ``device`` (device.py), the static half of the TPU kernel
preflight: BlockSpec lane alignment + grid/index-map arity +
``value_limbs`` ref sizing in the Pallas builders, the SMEM ivs-run cap
vs the ``pallas.lut.max.runs`` config table, i64/f64 bans inside kernel
bodies (i64 blessed only in the limb-reassembly layer), ``psum``/
``shard_map`` mesh-axis-name consistency across the combine builders
(interprocedurally through helper params), pow2-capacity preservation in
``narrow_plan_groups``, and the star-tree index-pad capacity contract.
The CLI also gains ``--changed <git-ref>`` (lint only changed files +
their direct imports + transitive reverse importers).

Pure stdlib ``ast`` — importing this package must never pull jax or the
engine (the CLI runs in CI before anything else).
"""

from pinot_tpu.tools.lint.core import (
    Finding,
    LintContext,
    load_baseline,
    run_lint,
)

__all__ = ["Finding", "LintContext", "load_baseline", "run_lint"]
