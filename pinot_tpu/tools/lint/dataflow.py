"""Interprocedural dataflow engine for graftlint's v2 families.

Three reusable pieces, all stdlib-``ast`` (importing this module must never
pull jax — the CLI runs before the environment can):

- :func:`build_cfg` — a per-function, statement-level control-flow graph.
  ``If``/``While``/``For`` statements become *test* nodes with labeled
  ``("true", test)`` / ``("false", test)`` out-edges; ``try`` statements
  contribute **exception edges** (every statement lexically inside a
  ``try`` body gets an ``"exc"`` edge to each handler entry and to the
  ``finally`` entry), ``return``/``raise`` route through enclosing
  ``finally`` blocks, and the ``finally`` frontier also reaches EXIT (the
  re-raise continuation). Statements *outside* any ``try`` are not assumed
  to raise — that keeps the path set honest enough for zero-baseline gating
  while still modeling the handler/finally shapes the conservation family
  must see.
- :class:`ForwardAnalysis` — a generic worklist forward abstract
  interpretation over a CFG: caller supplies ``init``/``transfer``/``join``
  plus optional ``refine`` (branch pruning on test edges) and
  ``exc_filter`` (state surgery on exception edges; ``"exc"`` edges
  propagate the raising node's PRE-state, since its effect may not have
  applied).
- :class:`DispatchExecutor` — a bounded micro-interpreter for the
  *dispatch-function* shape (``op = spec[0]; if op == "eq": ...``) that the
  kernel param protocol lives in. It tracks an environment of
  constant-string value sets (assignments, slices like ``op[3:]``,
  ``+``-concatenation, ``startswith``), prunes branches whose tests it can
  decide, counts protocol events (``pc.take()`` calls, ``params.append``)
  per path via a caller-supplied counter, and follows name-resolved calls
  through :func:`take_summary`-style **call summaries** (cycle-guarded:
  recursion or variable-count callees mark the path ``unknown`` instead of
  guessing). Paths end in ``return`` / ``raise`` / fall-through outcomes;
  checks skip ``unknown`` outcomes rather than report on them.

The three v2 checker families compose these: ``protocol`` uses the
executor + summaries, ``sync`` runs taint as a ForwardAnalysis and chases
the lock/thread call graphs, ``conservation`` runs paired-effect
obligations over the exception-edged CFG.
"""

from __future__ import annotations

import ast

from typing import Any, Callable, Dict, FrozenSet, List, Optional, Tuple

# -- CFG --------------------------------------------------------------------


class CFG:
    """Statement-level CFG: node 0 = ENTRY, 1 = EXIT; every other node
    carries one ast statement (compound statements are their own *test* /
    marker nodes; bodies hang off labeled edges). Edge labels:

    - ``None`` — unconditional fall-through;
    - ``("true", test)`` / ``("false", test)`` — branch edges out of an
      ``If``/``While`` test node (``For`` uses ``None`` tests);
    - ``"exc"`` — exception edge (carries the raising node's PRE-state).
    """

    def __init__(self, func: ast.AST):
        self.func = func
        self.stmts: List[Optional[ast.AST]] = []
        self.succ: List[List[Tuple[int, Any]]] = []
        self.entry = self.add(None)
        self.exit = self.add(None)

    def add(self, stmt: Optional[ast.AST]) -> int:
        self.stmts.append(stmt)
        self.succ.append([])
        return len(self.stmts) - 1

    def edge(self, a: int, b: int, label: Any = None) -> None:
        self.succ[a].append((b, label))


# id(func node) -> (func node, CFG): the shared-CFG cache (lint core v5).
# Several families build the CFG of the same function in one suite run —
# and parsed Modules are themselves cached across runs, so ast node
# identities persist. The entry pins the func node (strong ref) so its
# id cannot be recycled while the memo holds it; ForwardAnalysis keeps
# all per-run state on itself, never on the CFG, so sharing is safe.
_CFG_MEMO: Dict[int, Tuple[ast.AST, CFG]] = {}
_CFG_MEMO_CAP = 32768


def build_cfg(func: ast.AST) -> CFG:
    hit = _CFG_MEMO.get(id(func))
    if hit is not None and hit[0] is func:
        return hit[1]
    cfg = _build_cfg(func)
    if len(_CFG_MEMO) >= _CFG_MEMO_CAP:
        _CFG_MEMO.clear()
    _CFG_MEMO[id(func)] = (func, cfg)
    return cfg


def _build_cfg(func: ast.AST) -> CFG:
    cfg = CFG(func)
    loop_stack: List[Dict[str, Any]] = []
    finally_stack: List[int] = []
    exc_stack: List[List[int]] = []

    def attach(preds, n: int) -> None:
        for p, lbl in preds:
            cfg.edge(p, n, lbl)

    def simple(st, preds) -> int:
        n = cfg.add(st)
        attach(preds, n)
        if exc_stack:
            for t in exc_stack[-1]:
                cfg.edge(n, t, "exc")
        return n

    def seq(stmts, preds):
        for st in stmts:
            preds = do(st, preds)
            if not preds:
                break
        return preds

    def do(st, preds):
        if isinstance(st, ast.If):
            n = simple(st, preds)
            out = seq(st.body, [(n, ("true", st.test))])
            if st.orelse:
                out = out + seq(st.orelse, [(n, ("false", st.test))])
            else:
                out = out + [(n, ("false", st.test))]
            return out
        if isinstance(st, (ast.While, ast.For, ast.AsyncFor)):
            n = simple(st, preds)
            test = st.test if isinstance(st, ast.While) else None
            ctx: Dict[str, Any] = {"breaks": [], "test": n}
            loop_stack.append(ctx)
            body_out = seq(st.body, [(n, ("true", test))])
            loop_stack.pop()
            attach(body_out, n)  # back edge
            out = [(n, ("false", test))] + ctx["breaks"]
            if st.orelse:
                out = seq(st.orelse, out)
            return out
        if isinstance(st, ast.Break):
            n = simple(st, preds)
            if loop_stack:
                loop_stack[-1]["breaks"].append((n, None))
            return []
        if isinstance(st, ast.Continue):
            n = simple(st, preds)
            if loop_stack:
                cfg.edge(n, loop_stack[-1]["test"])
            return []
        if isinstance(st, ast.Return):
            n = simple(st, preds)
            cfg.edge(n, finally_stack[-1] if finally_stack else cfg.exit)
            return []
        if isinstance(st, ast.Raise):
            n = simple(st, preds)  # simple() wired the handler edges
            if not exc_stack:
                cfg.edge(n, finally_stack[-1] if finally_stack
                         else cfg.exit)
            return []
        if isinstance(st, ast.Try):
            hmarks = [cfg.add(h) for h in st.handlers]
            fmark = cfg.add(st) if st.finalbody else None
            targets = list(hmarks)
            if fmark is not None:
                targets.append(fmark)
            exc_stack.append(targets or [cfg.exit])
            if fmark is not None:
                finally_stack.append(fmark)
            body_out = seq(st.body, preds)
            if st.orelse:
                body_out = seq(st.orelse, body_out)
            exc_stack.pop()
            houts: List[Tuple[int, Any]] = []
            for h, m in zip(st.handlers, hmarks):
                houts += seq(h.body, [(m, None)])
            if fmark is not None:
                finally_stack.pop()
                attach(body_out + houts, fmark)
                fout = seq(st.finalbody, [(fmark, None)])
                for p, lbl in fout:
                    cfg.edge(p, cfg.exit, lbl)  # re-raise continuation
                return fout
            return body_out + houts
        if isinstance(st, (ast.With, ast.AsyncWith)):
            n = simple(st, preds)
            return seq(st.body, [(n, None)])
        n = simple(st, preds)
        return [(n, None)]

    out = seq(getattr(func, "body", []), [(cfg.entry, None)])
    attach(out, cfg.exit)
    return cfg


# -- forward analysis -------------------------------------------------------


class ForwardAnalysis:
    """Worklist forward dataflow over a :class:`CFG`.

    ``transfer(state, stmt, node_id) -> state`` must not mutate its input;
    ``join(a, b)`` merges; states must support ``==``. ``refine(state,
    test, is_true)`` optionally prunes along branch edges; ``exc_filter``
    optionally drops state components along ``"exc"`` edges.
    """

    def __init__(self, cfg: CFG, init: Any,
                 transfer: Callable[[Any, Optional[ast.AST], int], Any],
                 join: Callable[[Any, Any], Any],
                 refine: Optional[Callable] = None,
                 exc_filter: Optional[Callable] = None,
                 max_steps: int = 20000):
        self.cfg = cfg
        self.init = init
        self.transfer = transfer
        self.join = join
        self.refine = refine
        self.exc_filter = exc_filter
        self.max_steps = max_steps
        self.inn: Dict[int, Any] = {}

    def run(self) -> Dict[int, Any]:
        cfg = self.cfg
        self.inn = {cfg.entry: self.init}
        work = [cfg.entry]
        steps = 0
        while work and steps < self.max_steps:
            steps += 1
            n = work.pop()
            s = self.inn.get(n)
            if s is None:
                continue
            out = self.transfer(s, cfg.stmts[n], n)
            for m, lbl in cfg.succ[n]:
                if lbl == "exc":
                    v = s if self.exc_filter is None else self.exc_filter(s)
                elif isinstance(lbl, tuple) and self.refine is not None:
                    v = self.refine(out, lbl[1], lbl[0] == "true")
                else:
                    v = out
                cur = self.inn.get(m)
                nv = v if cur is None else self.join(cur, v)
                if nv != cur:
                    self.inn[m] = nv
                    work.append(m)
        return self.inn


# -- constant-set expression evaluation -------------------------------------

# env: var name (or ("idx0", param) for the dispatch subscript P[0]) ->
# frozenset of possible constant values. Sets stay tiny (cap below).
_SET_CAP = 8


def eval_expr(e: ast.expr, env: Dict[Any, FrozenSet]) -> Optional[FrozenSet]:
    """Possible constant values of ``e`` under ``env``, or None (unknown)."""
    if isinstance(e, ast.Constant):
        return frozenset([e.value])
    if isinstance(e, ast.Name):
        return env.get(e.id)
    if isinstance(e, ast.Subscript):
        idx = e.slice
        if isinstance(e.value, ast.Name) and isinstance(idx, ast.Constant) \
                and idx.value == 0:
            seed = env.get(("idx0", e.value.id))
            if seed is not None:
                return seed
        base = eval_expr(e.value, env)
        if base is None:
            return None
        out = set()
        for b in base:
            try:
                if isinstance(idx, ast.Constant):
                    out.add(b[idx.value])
                elif isinstance(idx, ast.Slice):
                    lo = idx.lower.value if isinstance(
                        idx.lower, ast.Constant) else None
                    hi = idx.upper.value if isinstance(
                        idx.upper, ast.Constant) else None
                    if idx.lower is not None and lo is None:
                        return None
                    if idx.upper is not None and hi is None:
                        return None
                    out.add(b[lo:hi])
                else:
                    return None
            except (TypeError, IndexError, KeyError):
                return None
        return frozenset(out) if len(out) <= _SET_CAP else None
    if isinstance(e, ast.BinOp) and isinstance(e.op, ast.Add):
        a, b = eval_expr(e.left, env), eval_expr(e.right, env)
        if a is None or b is None:
            return None
        try:
            out = frozenset(x + y for x in a for y in b)
        except TypeError:
            return None
        return out if len(out) <= _SET_CAP else None
    if isinstance(e, ast.IfExp):
        t = truth(e.test, env)
        if t == "true":
            return eval_expr(e.body, env)
        if t == "false":
            return eval_expr(e.orelse, env)
        a, b = eval_expr(e.body, env), eval_expr(e.orelse, env)
        if a is None or b is None:
            return None
        out = a | b
        return out if len(out) <= _SET_CAP else None
    if isinstance(e, ast.Compare) and len(e.ops) == 1:
        left = eval_expr(e.left, env)
        right = eval_expr(e.comparators[0], env)
        if left is None or right is None:
            return None
        op = e.ops[0]
        out = set()
        for a in left:
            for b in right:
                try:
                    if isinstance(op, (ast.Eq, ast.Is)):
                        out.add(a == b)
                    elif isinstance(op, (ast.NotEq, ast.IsNot)):
                        out.add(a != b)
                    elif isinstance(op, ast.In):
                        out.add(a in b)
                    elif isinstance(op, ast.NotIn):
                        out.add(a not in b)
                    else:
                        return None
                except TypeError:
                    return None
        return frozenset(out)
    if isinstance(e, ast.Call) and isinstance(e.func, ast.Attribute) \
            and e.func.attr == "startswith" and len(e.args) == 1:
        recv = eval_expr(e.func.value, env)
        arg = eval_expr(e.args[0], env)
        if recv is None or arg is None:
            return None
        try:
            return frozenset(r.startswith(a) for r in recv for a in arg)
        except (TypeError, AttributeError):
            return None
    if isinstance(e, ast.UnaryOp) and isinstance(e.op, ast.Not):
        v = eval_expr(e.operand, env)
        return None if v is None else frozenset(not x for x in v)
    if isinstance(e, ast.BoolOp):
        vals = [truth(v, env) for v in e.values]
        want = "false" if isinstance(e.op, ast.And) else "true"
        if any(v == want for v in vals):
            return frozenset([want == "true"])
        if all(v == ("true" if want == "false" else "false") for v in vals):
            return frozenset([want != "true"])
        return None
    if isinstance(e, ast.Tuple):
        elts = [eval_expr(x, env) for x in e.elts]
        if any(v is None for v in elts):
            return None
        out = {()}
        for v in elts:
            out = {t + (x,) for t in out for x in v}
            if len(out) > _SET_CAP:
                return None
        return frozenset(out)
    return None


def truth(test: ast.expr, env: Dict[Any, FrozenSet]) -> str:
    """'true' | 'false' | 'both' — decidability of ``test`` under ``env``."""
    v = eval_expr(test, env)
    if v is None:
        return "both"
    bools = {bool(x) for x in v}
    if bools == {True}:
        return "true"
    if bools == {False}:
        return "false"
    return "both"


# -- dispatch executor ------------------------------------------------------


class Outcome:
    """One executed path: ``kind`` in {'return', 'raise', 'fall'},
    ``count`` = protocol events on the path, ``node`` = the terminating
    Return/Raise statement (None for fall-through), ``unknown`` = the
    count cannot be trusted (loop/recursion/unresolved cursor escape)."""

    __slots__ = ("kind", "count", "env", "node", "unknown")

    def __init__(self, kind, count, env, node, unknown):
        self.kind = kind
        self.count = count
        self.env = env
        self.node = node
        self.unknown = unknown


class DispatchExecutor:
    """Path-enumerating micro-interpreter for dispatch-shaped functions.

    ``count_stmt(node, env) -> (int, bool)`` counts protocol events in ONE
    statement/expression subtree (it must not descend into nested ``def``
    bodies) and reports whether the count is unreliable. Tests it can
    decide under the environment prune paths; loops containing events and
    try-blocks keep the analysis honest by flagging ``unknown``.
    """

    def __init__(self, count_stmt: Callable, budget: int = 600):
        self.count_stmt = count_stmt
        self.budget = budget

    def run(self, body: List[ast.stmt],
            env: Dict[Any, FrozenSet]) -> List[Outcome]:
        self._steps = 0
        falls, terms = self._block(body, [(0, dict(env), False)])
        for c, e, u in falls:
            terms.append(Outcome("fall", c, e, None, u))
        return terms

    # states: list of (count, env, unknown)
    def _block(self, stmts, states):
        terms: List[Outcome] = []
        for st in stmts:
            if not states:
                break
            states, t = self._stmt(st, states)
            terms += t
            states = self._dedup(states)
        return states, terms

    def _dedup(self, states):
        seen = set()
        out = []
        for c, e, u in states:
            key = (c, u, tuple(sorted(e.items(), key=repr)))
            if key not in seen:
                seen.add(key)
                out.append((c, e, u))
        if len(out) > 48:  # path blow-up: collapse to one unknown state
            return [(out[0][0], out[0][1], True)]
        return out

    def _events(self, node, env):
        n, unk = self.count_stmt(node, env)
        return n, unk

    def _stmt(self, st, states):
        self._steps += 1
        if self._steps > self.budget:
            return ([(c, e, True) for c, e, _ in states], [])
        terms: List[Outcome] = []

        if isinstance(st, ast.If):
            out_states = []
            for c, e, u in states:
                tn, tu = self._events(st.test, e)
                c2, u2 = c + tn, u or tu
                t = truth(st.test, e)
                if t in ("true", "both"):
                    s, tt = self._block(st.body, [(c2, dict(e), u2)])
                    out_states += s
                    terms += tt
                if t in ("false", "both"):
                    if st.orelse:
                        s, tt = self._block(st.orelse, [(c2, dict(e), u2)])
                        out_states += s
                        terms += tt
                    else:
                        out_states.append((c2, dict(e), u2))
            return out_states, terms

        if isinstance(st, ast.Return):
            for c, e, u in states:
                n, unk = (0, False) if st.value is None \
                    else self._events(st.value, e)
                terms.append(Outcome("return", c + n, e, st, u or unk))
            return [], terms

        if isinstance(st, ast.Raise):
            for c, e, u in states:
                terms.append(Outcome("raise", c, e, st, u))
            return [], terms

        if isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
            n, unk = self._events(st, {})
            has_flow = any(isinstance(x, (ast.Return, ast.Raise))
                           for x in ast.walk(st))
            bad = unk or n > 0 or has_flow
            return ([(c, e, u or bad) for c, e, u in states], terms)

        if isinstance(st, ast.Try):
            out_states = []
            for c, e, u in states:
                body_s, tt = self._block(st.body, [(c, dict(e), u)])
                terms += tt
                hs = []
                for h in st.handlers:
                    s, tt = self._block(h.body, [(c, dict(e), True)])
                    hs += s
                    terms += tt
                merged = body_s + hs
                if st.finalbody:
                    merged, tt = self._block(st.finalbody, merged)
                    terms += tt
                out_states += merged
            return out_states, terms

        if isinstance(st, (ast.With, ast.AsyncWith)):
            out_states = []
            for c, e, u in states:
                n = 0
                unk = False
                for item in st.items:
                    dn, du = self._events(item.context_expr, e)
                    n += dn
                    unk = unk or du
                s, tt = self._block(st.body, [(c + n, dict(e), u or unk)])
                out_states += s
                terms += tt
            return out_states, terms

        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef, ast.Pass, ast.Global,
                           ast.Nonlocal, ast.Import, ast.ImportFrom)):
            return states, terms

        # simple statement: count events, update env on Name assignments
        out_states = []
        for c, e, u in states:
            n, unk = self._events(st, e)
            e2 = dict(e)
            if isinstance(st, ast.Assign) and len(st.targets) == 1:
                t = st.targets[0]
                if isinstance(t, ast.Name):
                    v = eval_expr(st.value, e)
                    if v is not None:
                        e2[t.id] = v
                    else:
                        e2.pop(t.id, None)
                elif isinstance(t, ast.Tuple):
                    for x in t.elts:
                        if isinstance(x, ast.Name):
                            e2.pop(x.id, None)
            elif isinstance(st, (ast.AugAssign, ast.AnnAssign)) \
                    and isinstance(st.target, ast.Name):
                e2.pop(st.target.id, None)
            out_states.append((c + n, e2, u or unk))
        return out_states, terms


def stmt_scan(st: ast.AST):
    """Nodes belonging to ONE CFG statement node. Compound statements
    contribute only their *header* (test / iter / with-items) — their
    bodies are separate CFG nodes, and scanning them here would apply
    body effects before the body's predecessors ran (and then again at
    the body nodes). Simple statements yield their full no-nested-def
    subtree."""
    if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef)):
        return
    if isinstance(st, (ast.If, ast.While)):
        yield st
        yield from walk_no_nested(st.test)
        return
    if isinstance(st, (ast.For, ast.AsyncFor)):
        yield st
        yield from walk_no_nested(st.target)
        yield from walk_no_nested(st.iter)
        return
    if isinstance(st, (ast.With, ast.AsyncWith)):
        yield st
        for item in st.items:
            yield from walk_no_nested(item.context_expr)
            if item.optional_vars is not None:
                yield from walk_no_nested(item.optional_vars)
        return
    if isinstance(st, ast.Try):
        yield st  # the finally-marker node; body/handlers are their own
        return
    if isinstance(st, ast.ExceptHandler):
        yield st  # handler-entry marker
        if st.type is not None:
            yield from walk_no_nested(st.type)
        return
    yield from walk_no_nested(st)


def walk_no_nested(node: ast.AST):
    """Document-order (pre-order DFS) walk that does not descend into
    nested function/lambda bodies (their events belong to the nested
    function, not this path)."""
    stack = [node]
    first = True
    while stack:
        cur = stack.pop()
        if not first and isinstance(cur, (ast.FunctionDef,
                                          ast.AsyncFunctionDef, ast.Lambda)):
            continue
        first = False
        yield cur
        stack.extend(reversed(list(ast.iter_child_nodes(cur))))


class SummaryTable:
    """Cycle-guarded call summaries: function node -> exact event count,
    or None when the callee's count varies by path / recurses / is too
    dynamic to trust. ``counter_for(fn)`` supplies the event counter in
    the CALLEE's own resolution context (module imports, nesting scope),
    so summaries compose interprocedurally."""

    def __init__(self, counter_for: Callable[[ast.AST], Callable]):
        self._counter_for = counter_for
        self._memo: Dict[int, Optional[int]] = {}
        self._in_progress: set = set()

    def summary(self, fn: ast.AST) -> Optional[int]:
        key = id(fn)
        if key in self._memo:
            return self._memo[key]
        if key in self._in_progress:
            return None  # recursion: refuse to guess
        self._in_progress.add(key)
        try:
            if isinstance(fn, ast.Lambda):
                has_take = any(
                    isinstance(s, ast.Call)
                    and isinstance(s.func, ast.Attribute)
                    and s.func.attr == "take" and not s.args
                    for s in ast.walk(fn.body))
                res: Optional[int] = None if has_take else 0
            else:
                ex = DispatchExecutor(self._counter_for(fn))
                outs = [o for o in ex.run(list(getattr(fn, "body", [])), {})
                        if o.kind in ("return", "fall")]
                counts = {o.count for o in outs if not o.unknown}
                if any(o.unknown for o in outs) or len(counts) != 1:
                    res = None
                else:
                    res = counts.pop()
        finally:
            self._in_progress.discard(key)
        self._memo[key] = res
        return res
