"""``exactness`` family: numeric-exactness proof guards.

The engine's integer-sum and composite-key paths are exact only inside
proven bounds: i64 folds stay below ``2**62`` (headroom for one more
doubling), f64 carries integers exactly only below ``2**53``, and the
composite group-key space must fit under the i64 pad sentinel. Those
bounds used to live as raw ``1 << 62`` / ``float(1 << 53)`` literals
scattered across the kernel, reduce, and broker tiers — one typo'd bit
width away from silent wrong sums. PR 19 hoists them into
``common/bounds.py`` as named, derivation-commented constants; this
family keeps them there:

1. **literal ban** — any ``1 << 62`` / ``1 << 53`` / ``2 ** 62`` /
   ``2 ** 53`` expression outside ``common/bounds.py`` is a finding.
   Wide-bound arithmetic must reference the named constant so the
   derivation comment travels with every use.

2. **dtype-evidence pairing** — a comparison against an i64-family
   bound (``I64_FOLD_BOUND``, ``I64_KEY_SPACE_BOUND``) inside a
   function with no integer-dtype evidence in scope (or an
   ``F64_EXACT_INT_BOUND`` comparison with no float64 evidence) is a
   finding: the guard proves nothing about a value of the wrong dtype.

3. **required guards** — the functions in ``REQUIRED_GUARDS`` are the
   known sum-reassembly sites; each must reference at least one bounds
   constant. Deleting the guard (the mutation this family exists to
   catch) is a finding even though no banned literal remains.
"""

from __future__ import annotations

import ast
import os
import re

from typing import Dict, List, Tuple

from pinot_tpu.tools.lint.core import (
    Finding,
    LintContext,
    Module,
    register,
)

# the named bounds (common/bounds.py) and the dtype family each proves
BOUNDS_NAMES = frozenset({
    "I64_FOLD_BOUND", "F64_EXACT_INT_BOUND", "I64_KEY_SPACE_BOUND",
    "I64_PAD_SENTINEL",
})
_I64_BOUNDS = frozenset({"I64_FOLD_BOUND", "I64_KEY_SPACE_BOUND"})
_F64_BOUNDS = frozenset({"F64_EXACT_INT_BOUND"})

# evidence that the guarded value really is of the bound's dtype family
_I64_EVIDENCE = re.compile(
    r"int64|_i64|i64_|\bint\(|is_integral|kind == \"i\"")
_F64_EVIDENCE = re.compile(
    r"float64|_f64|f64_|\bfloat\(|kind == \"f\"")

# known sum-reassembly sites: module basename -> functions that MUST
# reference a named bound (guard-deletion tripwire)
REQUIRED_GUARDS: Dict[str, Tuple[str, ...]] = {
    "reduce.py": ("_finish_group_by",),
    "reduce_device.py": ("f64_sum_exact", "encode_composite_keys"),
    "pallas_kernels.py": ("extract_plan",),
}

_WIDE_SHIFTS = {62, 53}


def _is_banned_literal(node: ast.BinOp) -> bool:
    if not (isinstance(node.left, ast.Constant)
            and isinstance(node.right, ast.Constant)
            and isinstance(node.left.value, int)
            and isinstance(node.right.value, int)):
        return False
    if isinstance(node.op, ast.LShift):
        return node.left.value == 1 and node.right.value in _WIDE_SHIFTS
    if isinstance(node.op, ast.Pow):
        return node.left.value == 2 and node.right.value in _WIDE_SHIFTS
    return False


def _bound_names_in(node: ast.AST) -> set:
    names = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in BOUNDS_NAMES:
            names.add(n.id)
        elif isinstance(n, ast.Attribute) and n.attr in BOUNDS_NAMES:
            names.add(n.attr)
    return names


def _func_source(mod: Module, func: ast.AST) -> str:
    end = getattr(func, "end_lineno", func.lineno) or func.lineno
    return "\n".join(mod.lines[func.lineno - 1:end])


def _check_module(mod: Module) -> List[Finding]:
    findings: List[Finding] = []
    base = os.path.basename(mod.relpath)

    if base != "bounds.py":
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.BinOp) and _is_banned_literal(node):
                findings.append(Finding(
                    "exactness", mod.relpath, node.lineno,
                    f"L{node.lineno}:wide_literal",
                    "raw wide-bound literal (1 << 62 / 1 << 53 family) — "
                    "use the named constant from common/bounds.py so the "
                    "derivation travels with the guard"))

    # dtype-evidence pairing + required-guard presence, per function
    required = set(REQUIRED_GUARDS.get(base, ()))
    for func in ast.walk(mod.tree):
        if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        used = _bound_names_in(func)
        src = _func_source(mod, func)
        if func.name in required:
            required.discard(func.name)
            if not used:
                findings.append(Finding(
                    "exactness", mod.relpath, func.lineno,
                    f"{func.name}:guard_missing",
                    f"{func.name} is a sum-reassembly site but references "
                    f"no common/bounds.py constant — the exactness guard "
                    f"has been removed"))
                continue
        if not used:
            continue
        # evidence source: the function name itself counts (f64_sum_exact)
        hay = func.name + "\n" + src
        if used & _I64_BOUNDS and not _I64_EVIDENCE.search(hay):
            findings.append(Finding(
                "exactness", mod.relpath, func.lineno,
                f"{func.name}:i64_evidence",
                f"{func.name} compares against an i64 bound "
                f"({sorted(used & _I64_BOUNDS)}) but shows no integer-"
                f"dtype evidence — the guard proves nothing about a "
                f"non-i64 value"))
        if used & _F64_BOUNDS and not _F64_EVIDENCE.search(hay):
            findings.append(Finding(
                "exactness", mod.relpath, func.lineno,
                f"{func.name}:f64_evidence",
                f"{func.name} compares against F64_EXACT_INT_BOUND but "
                f"shows no float64-dtype evidence — the guard proves "
                f"nothing about a non-f64 value"))
    for missing in sorted(required):
        findings.append(Finding(
            "exactness", mod.relpath, 1, f"{missing}:guard_site_missing",
            f"{base} must define sum-reassembly site {missing} with a "
            f"bounds-constant guard (REQUIRED_GUARDS)"))
    return findings


@register("exactness")
def check_exactness(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.modules:
        findings.extend(_check_module(mod))
    return findings
