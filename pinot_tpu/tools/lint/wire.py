"""Schema/config consistency: string-level drift catchers.

``wire``: every public field of the ``QueryStats`` dataclass must

- be referenced (``self.<field>``) in ``QueryStats.to_dict`` — the wire
  serialization both framings share,
- be referenced (``other.<field>``) in ``QueryStats.merge`` — the
  cross-segment/shard/server combine,
- appear as a keyword in ``DataTable._stats_from_dict`` — the decode side,

so "added a stat, forgot the wire" fails lint instead of silently dropping
the stat at the first broker hop. The launcher's ``LAUNCH_MAX_KEYS``
(merge-by-max stat keys) must each appear as a literal inside
``QueryStats.merge`` — the two modules encode the same semantics.

``config``: every ``pinot.server.*`` / ``pinot.broker.*`` string literal
anywhere in the scanned tree must be a declared constant value in
``CommonConstants`` (spi/config.py) — undeclared keys are typo'd or
undocumented knobs.

``wire`` also carries the COLUMN-KIND dispatch obligation: the DataTable
wire (common/datatable.py) assigns one ``_COL_<KIND> = <int>`` ordinal
per column kind, and

- ``_encode_column`` and ``_decode_column`` must each reference EVERY
  kind (a new kind must update both sides of the wire),
- any function/method anywhere in the scanned tree that dispatches on
  kinds (references two or more ``_COL_*`` int constants — the
  ``columns()`` consumers' dispatch shape) must reference ALL of them,
  so a new kind cannot silently fall through a partial dispatch.

Non-int ``_COL_*`` assignments (tuples like ``_COL_NUMERIC``) are kind
GROUPS, not kinds — helpers built on them don't count as dispatchers.

All passes no-op when the anchor class/function isn't in the scanned
file set (fixture runs), so they stay usable on arbitrary paths.
"""

from __future__ import annotations

import ast
import re

from typing import Dict, List, Optional, Set, Tuple

from pinot_tpu.tools.lint.core import (
    Finding,
    LintContext,
    Module,
    register,
)

CONFIG_KEY_RE = re.compile(r"^pinot\.(server|broker)\.")


def _find_class(ctx: LintContext, name: str
                ) -> Optional[Tuple[Module, ast.ClassDef]]:
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef) and node.name == name:
                return (mod, node)
    return None


def _method(cls: ast.ClassDef, name: str) -> Optional[ast.FunctionDef]:
    for n in cls.body:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and n.name == name:
            return n
    return None


def _attr_refs(fn: ast.AST, base: str) -> Set[str]:
    """Attribute names read off ``base`` (e.g. 'self' or 'other')."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == base:
            out.add(node.attr)
    return out


def _dataclass_fields(cls: ast.ClassDef) -> List[Tuple[str, int]]:
    out: List[Tuple[str, int]] = []
    for n in cls.body:
        if isinstance(n, ast.AnnAssign) and isinstance(n.target, ast.Name) \
                and not n.target.id.startswith("_"):
            out.append((n.target.id, n.lineno))
    return out


@register("wire")
def check_wire(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = list(_check_column_kinds(ctx))
    hit = _find_class(ctx, "QueryStats")
    if hit is None:
        return findings
    mod, cls = hit
    fields = _dataclass_fields(cls)
    to_dict = _method(cls, "to_dict")
    merge = _method(cls, "merge")
    ser_refs = _attr_refs(to_dict, "self") if to_dict else set()
    merge_refs = (_attr_refs(merge, "other") | _attr_refs(merge, "self")) \
        if merge else set()

    decode_kwargs: Optional[Set[str]] = None
    decode_loc: Tuple[str, int] = (mod.relpath, cls.lineno)
    for m2 in ctx.modules:
        for node in ast.walk(m2.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == "_stats_from_dict":
                decode_kwargs = set()
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        fname = sub.func.id if isinstance(sub.func, ast.Name)\
                            else getattr(sub.func, "attr", None)
                        if fname == "QueryStats":
                            decode_kwargs |= {k.arg for k in sub.keywords
                                              if k.arg}
                decode_loc = (m2.relpath, node.lineno)

    for field, line in fields:
        if to_dict is not None and field not in ser_refs:
            findings.append(Finding(
                "wire", mod.relpath, line, f"QueryStats.{field}:to_dict",
                f"QueryStats.{field} is not serialized in to_dict() — "
                f"the stat never reaches the DataTable wire"))
        if merge is not None and field not in merge_refs:
            findings.append(Finding(
                "wire", mod.relpath, line, f"QueryStats.{field}:merge",
                f"QueryStats.{field} is not combined in merge() — "
                f"the stat is dropped at segment/shard/server merge"))
        if decode_kwargs is not None and field not in decode_kwargs:
            findings.append(Finding(
                "wire", decode_loc[0], decode_loc[1],
                f"QueryStats.{field}:_stats_from_dict",
                f"QueryStats.{field} is not decoded in "
                f"_stats_from_dict() — the stat is lost on receive"))

    # LAUNCH_MAX_KEYS <-> merge() literal agreement (launcher vs results)
    if merge is not None:
        for m2 in ctx.modules:
            for node in m2.tree.body:
                if isinstance(node, ast.Assign) \
                        and any(isinstance(t, ast.Name)
                                and t.id == "LAUNCH_MAX_KEYS"
                                for t in node.targets) \
                        and isinstance(node.value, (ast.Tuple, ast.List)):
                    keys = [e.value for e in node.value.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str)]
                    merge_lits = {n.value for n in ast.walk(merge)
                                  if isinstance(n, ast.Constant)
                                  and isinstance(n.value, str)}
                    for k in keys:
                        if k not in merge_lits:
                            findings.append(Finding(
                                "wire", m2.relpath, node.lineno,
                                f"LAUNCH_MAX_KEYS.{k}",
                                f"LAUNCH_MAX_KEYS entry {k!r} is not a "
                                f"max-merged key in QueryStats.merge() — "
                                f"launcher and results disagree on merge "
                                f"semantics"))
    return findings


COL_KIND_RE = re.compile(r"^_COL_[A-Z0-9]+$")


def _name_refs(fn: ast.AST, names: Set[str]) -> Set[str]:
    """Which of ``names`` are read (as bare Names) inside ``fn``."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and node.id in names:
            out.add(node.id)
    return out


def _check_column_kinds(ctx: LintContext) -> List[Finding]:
    """Column-kind dispatch obligations (see module doc). Anchored on the
    module that defines ``_encode_column``; no-op when it's not scanned."""
    findings: List[Finding] = []
    anchor = None
    kinds: Set[str] = set()
    kind_line = 0
    for mod in ctx.modules:
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == "_encode_column":
                anchor = mod
    if anchor is None:
        return findings
    for node in anchor.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and COL_KIND_RE.match(node.targets[0].id) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, int):
            kinds.add(node.targets[0].id)
            kind_line = max(kind_line, node.lineno)
    if not kinds:
        return findings

    required = {"_encode_column", "_decode_column"}
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            refs = _name_refs(node, kinds)
            is_required = node.name in required and mod is anchor
            if not is_required and len(refs) < 2:
                continue  # not a kind dispatcher (single-kind helpers ok)
            missing = sorted(kinds - refs)
            if missing:
                findings.append(Finding(
                    "wire", mod.relpath, node.lineno,
                    f"colkind.{node.name}",
                    f"{node.name} dispatches on column kinds but does not "
                    f"handle {', '.join(missing)} — a new wire column "
                    f"kind must update every encode/decode/accessor "
                    f"dispatch (DataTable columns() consumers included)"))
    return findings


@register("config")
def check_config(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    hit = _find_class(ctx, "CommonConstants")
    if hit is None:
        return findings
    _mod, cls = hit
    declared: Set[str] = set()
    for n in cls.body:
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Constant) \
                and isinstance(n.value.value, str):
            declared.add(n.value.value)

    seen: Set[str] = set()
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and CONFIG_KEY_RE.match(node.value) \
                    and node.value not in declared:
                if node.value in seen:
                    continue
                seen.add(node.value)
                findings.append(Finding(
                    "config", mod.relpath, node.lineno, node.value,
                    f"config key {node.value!r} is not declared in "
                    f"CommonConstants (spi/config.py) — undeclared keys "
                    f"are invisible to operators and prone to typos"))
    return findings
