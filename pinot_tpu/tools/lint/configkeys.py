"""``configkeys`` family: ``pinot.*`` config-key conformance.

Pinot's reference implementation centralises every cluster config key in
``CommonConstants`` and validates query options against it; keys that
drift from the constants class become silently-ignored knobs. This
repo's analogue is ``spi/config.py``: every ``pinot.*`` key read through
``PinotConfiguration`` must resolve to a declared ``CommonConstants``
constant, every declared key must actually be read somewhere, and the
README's operator-facing config table must list every key with the code
default. Three rules:

1. **read resolution** (always runs, file-list and package scans): a
   ``get``/``get_int``/``get_float``/``get_bool``/``get_str`` call whose
   key argument is a ``pinot.*`` string literal not declared as a
   ``CommonConstants`` value — or an attribute ``*_KEY``/``*_PREFIX``
   name that ``CommonConstants`` does not define — is a finding. Keys
   are born in ``spi/config.py``, never inline.

2. **unread keys** (package scans only — needs the whole tree): a
   declared ``*_KEY``/``*_PREFIX`` string constant with no attribute
   access (any alias: ``CommonConstants.X`` or ``_CC.X``) and no equal
   string literal in any other scanned module is dead surface — a
   finding on the declaration.

3. **README table** (package scans with a README next to the tree): the
   block between ``<!-- config-keys:begin -->`` and ``<!-- config-keys:
   end -->`` must contain a row for every declared key, and where a
   name-mapped ``DEFAULT_<base>`` constant exists its documented default
   must match the code default — stale docs are findings, auto-checked.

Rules 2-3 key off a scanned module whose relpath ends ``spi/config.py``.
The family registers ``whole_program=True``: a ``--changed`` run hands
it the full package (the key universe and the declaration module are
global facts) and scopes its findings to the changed set afterwards.
"""

from __future__ import annotations

import ast
import os
import re

from typing import Dict, List, Optional, Set, Tuple

from pinot_tpu.tools.lint.core import (
    Finding,
    LintContext,
    Module,
    call_name,
    register,
)

_CONFIG_PATH = os.path.normpath(os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    os.pardir, os.pardir, "spi", "config.py"))

_GETTERS = frozenset({"get", "get_int", "get_float", "get_bool", "get_str"})
_KEY_ATTR = re.compile(r".*(_KEY|_PREFIX)\Z")

_TABLE_BEGIN = "<!-- config-keys:begin -->"
_TABLE_END = "<!-- config-keys:end -->"


def _constants_class(tree: ast.AST) -> Optional[ast.ClassDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "CommonConstants":
            return node
    return None


def _load_declared(ctx: LintContext) -> Tuple[
        Dict[str, str], Dict[str, object], Optional[Module]]:
    """(key-name -> key-value, default-name -> default-value, the scanned
    config module if the scan includes one). Prefers the scanned copy so
    fixture trees check against THEIR declarations."""
    tree = None
    cfg_mod: Optional[Module] = None
    for mod in ctx.modules:
        rel = mod.relpath.replace(os.sep, "/")
        if rel.endswith("spi/config.py") \
                and _constants_class(mod.tree) is not None:
            tree, cfg_mod = mod.tree, mod
            break
    if tree is None:
        with open(_CONFIG_PATH, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=_CONFIG_PATH)
    keys: Dict[str, str] = {}
    defaults: Dict[str, object] = {}
    cls = _constants_class(tree)
    if cls is None:
        return keys, defaults, cfg_mod
    for st in cls.body:
        if not (isinstance(st, ast.Assign) and len(st.targets) == 1
                and isinstance(st.targets[0], ast.Name)
                and isinstance(st.value, ast.Constant)):
            continue
        name = st.targets[0].id
        if _KEY_ATTR.match(name) and isinstance(st.value.value, str):
            keys[name] = st.value.value
        elif name.startswith("DEFAULT_"):
            defaults[name] = st.value.value
    return keys, defaults, cfg_mod


def _key_arg(node: ast.Call) -> Optional[ast.expr]:
    if node.args:
        return node.args[0]
    for kw in node.keywords:
        if kw.arg == "key":
            return kw.value
    return None


def _cc_aliases(mod: Module) -> Set[str]:
    """Local names bound to CommonConstants (``CommonConstants`` itself
    or an import alias like executor.py's ``_CC``)."""
    aliases: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == "CommonConstants":
                    aliases.add(a.asname or a.name)
    return aliases


def _check_reads(mod: Module, declared: Dict[str, str]) -> List[Finding]:
    findings: List[Finding] = []
    values = set(declared.values())
    names = set(declared)
    aliases = _cc_aliases(mod)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) \
                or call_name(node) not in _GETTERS:
            continue
        arg = _key_arg(node)
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                and arg.value.startswith("pinot."):
            if arg.value not in values:
                findings.append(Finding(
                    "configkeys", mod.relpath, node.lineno,
                    f"key:{arg.value}",
                    f"config key {arg.value!r} read inline is not "
                    f"declared in spi/config.py CommonConstants — keys "
                    f"are born there, never inline"))
        elif isinstance(arg, ast.Attribute) and _KEY_ATTR.match(arg.attr) \
                and isinstance(arg.value, ast.Name) \
                and arg.value.id in aliases:
            if arg.attr not in names:
                findings.append(Finding(
                    "configkeys", mod.relpath, node.lineno,
                    f"attr:{arg.attr}",
                    f"config read references CommonConstants.{arg.attr} "
                    f"which spi/config.py does not declare"))
    return findings


def _render_default(value: object) -> str:
    if isinstance(value, bool):
        return "true" if value else "false"
    return str(value)


def _check_readme(cfg_mod: Module, declared: Dict[str, str],
                  defaults: Dict[str, object]) -> List[Finding]:
    findings: List[Finding] = []
    readme = os.path.normpath(os.path.join(
        os.path.dirname(os.path.abspath(cfg_mod.path)),
        os.pardir, os.pardir, "README.md"))
    if not os.path.exists(readme):
        return findings  # fixture trees without docs: nothing to check
    with open(readme, encoding="utf-8") as f:
        text = f.read()
    begin = text.find(_TABLE_BEGIN)
    end = text.find(_TABLE_END)
    if begin < 0 or end < 0:
        findings.append(Finding(
            "configkeys", cfg_mod.relpath, 1, "readme:table_missing",
            f"README.md has no {_TABLE_BEGIN} .. {_TABLE_END} config-key "
            f"table — the operator-facing key list must be auto-checked"))
        return findings
    block = text[begin:end]
    base_line = text[:begin].count("\n") + 1
    # row: | `pinot....` | `default` | prose |
    rows: Dict[str, Tuple[str, int]] = {}
    for i, line in enumerate(block.splitlines()):
        m = re.match(r"\|\s*`([^`]+)`\s*\|\s*([^|]*)\|", line)
        if m:
            rows[m.group(1)] = (m.group(2).strip().strip("`").strip(),
                                base_line + i)
    for name, value in sorted(declared.items()):
        if value not in rows:
            findings.append(Finding(
                "configkeys", cfg_mod.relpath, 1, f"readme:missing:{name}",
                f"declared key {value!r} ({name}) has no row in the "
                f"README config-key table"))
            continue
        base = name[:-len("_KEY")] if name.endswith("_KEY") else None
        if base is None or ("DEFAULT_" + base) not in defaults:
            continue
        doc_default, _line = rows[value]
        code_default = _render_default(defaults["DEFAULT_" + base])
        if doc_default != code_default:
            findings.append(Finding(
                "configkeys", cfg_mod.relpath, 1, f"readme:stale:{name}",
                f"README documents default {doc_default!r} for {value!r} "
                f"but the code default (DEFAULT_{base}) is "
                f"{code_default!r} — the table is auto-checked against "
                f"spi/config.py"))
    return findings


@register("configkeys", whole_program=True)
def check_configkeys(ctx: LintContext) -> List[Finding]:
    declared, defaults, cfg_mod = _load_declared(ctx)
    findings: List[Finding] = []
    for mod in ctx.modules:
        findings.extend(_check_reads(mod, declared))

    if cfg_mod is None:
        return findings  # file-list scan: global rules need the tree

    # unread declared keys: an attribute access (any import alias) or an
    # equal string literal in some OTHER scanned module
    read_attrs: Set[str] = set()
    read_literals: Set[str] = set()
    for mod in ctx.modules:
        if mod is cfg_mod:
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute):
                read_attrs.add(node.attr)
            elif isinstance(node, ast.Constant) \
                    and isinstance(node.value, str):
                read_literals.add(node.value)
    for name, value in sorted(declared.items()):
        if name in read_attrs or value in read_literals:
            continue
        line = 1
        for st in ast.walk(cfg_mod.tree):
            if isinstance(st, ast.Assign) and st.targets \
                    and isinstance(st.targets[0], ast.Name) \
                    and st.targets[0].id == name:
                line = st.lineno
                break
        findings.append(Finding(
            "configkeys", cfg_mod.relpath, line, f"unread:{name}",
            f"declared key {value!r} ({name}) is never read anywhere in "
            f"the scanned tree — dead config surface"))

    findings.extend(_check_readme(cfg_mod, declared, defaults))
    return findings
