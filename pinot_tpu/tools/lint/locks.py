"""Lock discipline: ``# guarded-by:`` fields + the lock-order graph.

Convention (documented in README "Invariants & lint"):

- A field whose every access must hold a lock is annotated on its
  ``__init__`` assignment line::

      self._entries = OrderedDict()   # guarded-by: _lock

  ``# guarded-by-writes: _lock`` guards mutations only (for fields whose
  reads are deliberately lock-free — atomic dict gets under the GIL).
- The named lock must be a ``threading.Lock/RLock/Condition/Semaphore``
  attribute of the same class; accesses count as guarded inside a
  ``with self.<lock>:`` block in the same method.
- Methods named ``*_locked`` assert "caller holds the lock" and are exempt
  (the call-site discipline covers them); ``__init__``/``__del__`` are
  exempt (no concurrent access during construction/teardown).
- Nested ``def``/``lambda`` bodies do NOT inherit the enclosing ``with``:
  closures escape (metric gauge lambdas run on scrape threads).

The lock-order pass builds a cross-module acquisition graph: while holding
lock A, any reachable acquisition of lock B (direct nesting, or through a
name-resolved call chain up to depth 2) adds edge A->B. Only *inversions*
(both A->B and B->A present) are findings — edges themselves are the
design.
"""

from __future__ import annotations

import ast
import re

from typing import Dict, List, Optional, Set, Tuple

from pinot_tpu.tools.lint.core import (
    Finding,
    LintContext,
    Module,
    attr_base_name,
    call_name,
    is_self_attr,
    register,
)

GUARD_RE = re.compile(
    r"guarded-by(?P<writes>-writes)?:\s*(?P<lock>[A-Za-z_][A-Za-z0-9_]*)")

LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                  "BoundedSemaphore"}

# dict/list/set/deque methods that mutate their receiver in place:
# ``self.field.pop(...)`` is a WRITE to the guarded structure
MUTATORS = {"append", "appendleft", "add", "clear", "discard", "extend",
            "extendleft", "insert", "move_to_end", "pop", "popitem",
            "popleft", "remove", "reverse", "setdefault", "sort", "update"}

# resolving ``obj.m(...)`` by bare method name across the package: names
# defined in more classes than this are too ambiguous to chase (noise)
AMBIG_CAP = 8

# never bare-name-resolve these: they are overwhelmingly builtin container
# operations (``self._cache.clear()`` must not resolve to SomeClass.clear)
CONTAINER_METHODS = MUTATORS | {"get", "keys", "values", "items", "copy",
                                "join", "put", "wait", "notify",
                                "notify_all", "acquire", "release_lock",
                                "set", "count", "index"}

CALL_DEPTH = 2


class ClassInfo:
    def __init__(self, module: Module, node: ast.ClassDef):
        self.module = module
        self.node = node
        self.name = node.name
        self.lock_attrs: Set[str] = set()
        # field -> (lock attr, writes_only, decl line)
        self.guarded: Dict[str, Tuple[str, bool, int]] = {}
        self.methods: Dict[str, ast.FunctionDef] = {
            n.name: n for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self.base_names = [b.id for b in node.bases
                           if isinstance(b, ast.Name)]


def _is_lock_factory(value: ast.expr) -> bool:
    if not isinstance(value, ast.Call):
        return False
    f = value.func
    name = f.attr if isinstance(f, ast.Attribute) else \
        (f.id if isinstance(f, ast.Name) else None)
    return name in LOCK_FACTORIES


def collect_classes(ctx: LintContext) -> Tuple[List[ClassInfo], List[Finding]]:
    cached = ctx.memo.get("lint.classes")
    if cached is not None:
        classes, findings = cached
        return classes, list(findings)
    classes: List[ClassInfo] = []
    findings: List[Finding] = []
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            ci = ClassInfo(mod, node)
            for sub in ast.walk(node):
                targets: List[ast.expr] = []
                if isinstance(sub, ast.Assign):
                    targets = sub.targets
                elif isinstance(sub, ast.AnnAssign):
                    targets = [sub.target]
                else:
                    continue
                for t in targets:
                    if not is_self_attr(t):
                        continue
                    if _is_lock_factory(getattr(sub, "value", None)):
                        ci.lock_attrs.add(t.attr)
                    m = mod.comment_in_range(
                        sub.lineno, sub.end_lineno or sub.lineno, GUARD_RE)
                    if m is not None:
                        ci.guarded[t.attr] = (m.group("lock"),
                                              bool(m.group("writes")),
                                              sub.lineno)
            if ci.lock_attrs or ci.guarded:
                classes.append(ci)
            elif ci.methods:
                classes.append(ci)  # still needed for call resolution
    _inherit_lock_attrs(classes)
    for ci in classes:
        for field, (lock, _w, line) in ci.guarded.items():
            if lock not in ci.lock_attrs:
                findings.append(Finding(
                    "lock-guard", ci.module.relpath, line,
                    f"{ci.name}.{field}:annotation",
                    f"guarded-by names {lock!r}, which is not a "
                    f"threading lock attribute of {ci.name}"))
    # memoized per context: four+ families build this same table; the
    # findings are stored immutably (callers extend the returned list)
    ctx.memo["lint.classes"] = (classes, tuple(findings))
    return classes, findings


def _inherit_lock_attrs(classes: List[ClassInfo]) -> None:
    """A subclass guards fields with locks its base's ``__init__`` created
    (``super().__init__()`` runs first); union lock_attrs down the
    name-resolved base chain (fixpoint over the scanned set)."""
    by_name = {c.name: c for c in classes}
    changed = True
    while changed:
        changed = False
        for ci in classes:
            for b in ci.base_names:
                base = by_name.get(b)
                if base is not None and not base.lock_attrs <= ci.lock_attrs:
                    ci.lock_attrs |= base.lock_attrs
                    changed = True


# -- write detection --------------------------------------------------------

def _base_self_attr(node: ast.expr) -> Optional[ast.Attribute]:
    """The ``self.X`` at the bottom of a subscript/attribute chain."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and is_self_attr(node):
        return node
    return None


def _collect_writes(func: ast.AST) -> Set[int]:
    """ids of ``self.X`` Attribute nodes that are writes: direct stores,
    subscript stores/deletes bottoming at the field, mutator-method calls."""
    writes: Set[int] = set()

    def mark_target(t: ast.expr) -> None:
        if is_self_attr(t):
            writes.add(id(t))
            return
        base = _base_self_attr(t)
        if base is not None:
            writes.add(id(base))
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                mark_target(e)

    for node in ast.walk(func):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                mark_target(t)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            mark_target(node.target)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                mark_target(t)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in MUTATORS:
            base = node.func.value
            if is_self_attr(base):
                writes.add(id(base))
            else:
                b = _base_self_attr(base)
                if b is not None:
                    writes.add(id(b))
    return writes


# -- guard traversal --------------------------------------------------------

def _with_locks(node: ast.With, ci: ClassInfo) -> Set[str]:
    got: Set[str] = set()
    for item in node.items:
        e = item.context_expr
        if is_self_attr(e) and e.attr in ci.lock_attrs:
            got.add(e.attr)
    return got


def _check_method(ci: ClassInfo, method: ast.FunctionDef,
                  findings: List[Finding]) -> None:
    writes = _collect_writes(method)
    seen: Set[Tuple[str, str]] = set()  # (field, kind) dedup per method

    def visit(node: ast.AST, held: Set[str]) -> None:
        if isinstance(node, ast.With):
            inner = held | _with_locks(node, ci)
            for item in node.items:
                visit(item.context_expr, held)
            for stmt in node.body:
                visit(stmt, inner)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # closures escape the with-block; nothing is held at call time
            name = getattr(node, "name", "<lambda>")
            inner: Set[str] = set(ci.lock_attrs) \
                if name.endswith("_locked") else set()
            body = node.body if isinstance(node.body, list) else [node.body]
            for stmt in body:
                visit(stmt, inner)
            return
        if isinstance(node, ast.Attribute) and is_self_attr(node) \
                and node.attr in ci.guarded:
            lock, writes_only, _ = ci.guarded[node.attr]
            is_write = id(node) in writes
            if (is_write or not writes_only) and lock not in held:
                kind = "write" if is_write else "read"
                if (node.attr, kind) not in seen:
                    seen.add((node.attr, kind))
                    findings.append(Finding(
                        "lock-guard", ci.module.relpath, node.lineno,
                        f"{ci.name}.{node.attr}:{method.name}",
                        f"{kind} of {ci.name}.{node.attr} outside "
                        f"`with self.{lock}` in {method.name}()"))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in method.body:
        visit(stmt, set())


@register("lock-guard")
def check_lock_guard(ctx: LintContext) -> List[Finding]:
    classes, findings = collect_classes(ctx)
    for ci in classes:
        if not ci.guarded:
            continue
        for name, method in ci.methods.items():
            if name in ("__init__", "__del__") or name.endswith("_locked"):
                continue
            _check_method(ci, method, findings)
    return findings


# -- lock-order graph -------------------------------------------------------

class _CallGraph:
    """Name-based, conservative call resolution across the scanned files."""

    def __init__(self, ctx: LintContext, classes: List[ClassInfo]):
        self.classes = classes
        self.by_class_name: Dict[str, ClassInfo] = {c.name: c
                                                    for c in classes}
        self.methods_by_name: Dict[str, List[Tuple[ClassInfo,
                                                   ast.FunctionDef]]] = {}
        for ci in classes:
            for name, fn in ci.methods.items():
                self.methods_by_name.setdefault(name, []).append((ci, fn))
        self.module_funcs: Dict[Tuple[str, str], ast.FunctionDef] = {}
        for mod in ctx.modules:
            for node in mod.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.module_funcs[(mod.relpath, node.name)] = node
        self._acq_memo: Dict[Tuple[int, int], Set[str]] = {}

    def resolve(self, call: ast.Call, ci: Optional[ClassInfo],
                relpath: str) -> List[Tuple[Optional[ClassInfo], ast.AST]]:
        f = call.func
        out: List[Tuple[Optional[ClassInfo], ast.AST]] = []
        if isinstance(f, ast.Name):
            fn = self.module_funcs.get((relpath, f.id))
            if fn is not None:
                out.append((None, fn))
            return out
        if not isinstance(f, ast.Attribute):
            return out
        if isinstance(f.value, ast.Name) and f.value.id == "self" \
                and ci is not None:
            target = self._self_method(ci, f.attr)
            if target is not None:
                out.append(target)
                return out
        if f.attr in CONTAINER_METHODS:
            return out
        cands = self.methods_by_name.get(f.attr, [])
        if 0 < len(cands) <= AMBIG_CAP:
            out.extend(cands)
        fn = self.module_funcs.get((relpath, f.attr))
        if fn is not None:
            out.append((None, fn))
        return out

    def _self_method(self, ci: ClassInfo, name: str
                     ) -> Optional[Tuple[ClassInfo, ast.AST]]:
        seen = set()
        cur: Optional[ClassInfo] = ci
        while cur is not None and cur.name not in seen:
            seen.add(cur.name)
            if name in cur.methods:
                return (cur, cur.methods[name])
            cur = next((self.by_class_name[b] for b in cur.base_names
                        if b in self.by_class_name), None)
        return None

    def acquired(self, ci: Optional[ClassInfo], fn: ast.AST,
                 depth: int, relpath: str) -> Set[str]:
        """Locks (``Class.attr``) this function may acquire, following
        name-resolved calls up to ``depth`` levels."""
        memo_key = (id(fn), depth)
        got = self._acq_memo.get(memo_key)
        if got is not None:
            return got
        self._acq_memo[memo_key] = set()  # cycle guard
        out: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.With) and ci is not None:
                for a in _with_locks(node, ci):
                    out.add(f"{ci.name}.{a}")
            if depth > 0 and isinstance(node, ast.Call):
                for ci2, fn2 in self.resolve(node, ci, relpath):
                    rp2 = ci2.module.relpath if ci2 is not None else relpath
                    out |= self.acquired(ci2, fn2, depth - 1, rp2)
        self._acq_memo[memo_key] = out
        return out


@register("lock-order")
def check_lock_order(ctx: LintContext) -> List[Finding]:
    classes, _ = collect_classes(ctx)
    graph = _CallGraph(ctx, classes)
    # (A, B) -> first witness "path:line"
    edges: Dict[Tuple[str, str], str] = {}

    def walk(node: ast.AST, ci: Optional[ClassInfo], relpath: str,
             held: Set[str]) -> None:
        if isinstance(node, ast.With):
            new = {f"{ci.name}.{a}" for a in _with_locks(node, ci)} \
                if ci is not None else set()
            for L in held:
                for M in new:
                    if L != M:
                        edges.setdefault((L, M), f"{relpath}:{node.lineno}")
            for item in node.items:
                walk(item.context_expr, ci, relpath, held)
            for stmt in node.body:
                walk(stmt, ci, relpath, held | new)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            body = node.body if isinstance(node.body, list) else [node.body]
            for stmt in body:
                walk(stmt, ci, relpath, set())
            return
        if isinstance(node, ast.Call) and held:
            for ci2, fn2 in graph.resolve(node, ci, relpath):
                rp2 = ci2.module.relpath if ci2 is not None else relpath
                for M in graph.acquired(ci2, fn2, CALL_DEPTH, rp2):
                    for L in held:
                        if L != M:
                            edges.setdefault(
                                (L, M), f"{relpath}:{node.lineno}")
        for child in ast.iter_child_nodes(node):
            walk(child, ci, relpath, held)

    for ci in classes:
        for method in ci.methods.values():
            for stmt in method.body:
                walk(stmt, ci, ci.module.relpath, set())
    for (rel, name), fn in graph.module_funcs.items():
        for stmt in fn.body:
            walk(stmt, None, rel, set())

    findings: List[Finding] = []
    reported: Set[Tuple[str, str]] = set()
    for (a, b), w1 in sorted(edges.items()):
        if (b, a) in edges and (b, a) not in reported:
            reported.add((a, b))
            w2 = edges[(b, a)]
            path, line = w1.rsplit(":", 1)
            findings.append(Finding(
                "lock-order", path, int(line),
                "<->".join(sorted((a, b))),
                f"lock-order inversion: {a} -> {b} at {w1} but "
                f"{b} -> {a} at {w2} (potential deadlock)"))
    return findings
