"""Lint core: module model, finding model, checker registry, baseline.

A *finding* carries a stable ``key`` (``checker:path:symbol`` — no line
numbers, so unrelated edits don't churn the baseline) plus the file:line
for humans. Suppression, in priority order:

- inline: a ``# lint: ignore[<checker>] — reason`` comment on the flagged
  line (use sparingly; the reason is part of the convention);
- baseline: an entry in the checked-in baseline JSON
  (``tools/lint/baseline.json``), each with a mandatory ``reason`` —
  the accepted-violation set, ideally empty.

Shared-work tier (v5): ``load_modules`` keeps a cross-run parse cache
(one ``ast.parse`` + tokenize per file *content*, reused by every family
and every ``run_lint`` call), each :class:`LintContext` carries a
``memo`` dict the expensive cross-family artifacts hang off (the lock
class table, the import-resolution index), and :mod:`dataflow` memoizes
CFG construction per function node — so 14 families cost one parse, one
class scan, one index, and one CFG per function, not 14.

Families registered with ``whole_program=True`` (thread topology,
config-key conformance) reason over the *entire* package at once: under
a scoped run (``--changed``) they are handed the full-package context
and only their findings are filtered down to the selected files — a
spawn-site edit in file A can surface a role violation in untouched
file B, and scoping must not hide the edge, only the noise.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

_IGNORE_RE = re.compile(r"lint:\s*ignore\[([a-z\-,\s]+)\]")

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


@dataclass(frozen=True)
class Finding:
    checker: str   # lock-guard | lock-order | pairing | tracer | wire | config
    path: str      # path as scanned (relative when the scan root is)
    line: int
    symbol: str    # stable anchor: Class.field:method, func qualname, key...
    message: str

    @property
    def key(self) -> str:
        return f"{self.checker}:{self.path}:{self.symbol}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.checker}] {self.message}"


class Module:
    """One parsed source file: AST + raw lines + comment map (the AST drops
    comments, and ``# guarded-by:`` / ``# lint: ignore`` live in them)."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()
        self.comments: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(source).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = \
                        tok.string.lstrip("#").strip()
        except (tokenize.TokenError, IndentationError):  # partial map is ok
            pass

    def comment_in_range(self, lo: int, hi: int,
                         pattern: "re.Pattern") -> Optional["re.Match"]:
        """First comment line in [lo, hi] matching ``pattern`` (multi-line
        statements carry their annotation on any of their lines)."""
        for ln in range(lo, hi + 1):
            c = self.comments.get(ln)
            if c:
                m = pattern.search(c)
                if m:
                    return m
        return None

    def ignored(self, line: int, checker: str) -> bool:
        m = _IGNORE_RE.search(self.comments.get(line, ""))
        if m is None:
            return False
        names = {s.strip() for s in m.group(1).split(",")}
        return checker in names or "all" in names


class LintContext:
    """Everything a checker sees: the parsed modules, keyed by relpath.

    ``memo`` is the per-run shared-artifact cache: families that build
    the same expensive structure (the ``# guarded-by:`` class table, the
    import-resolution index) stash it here so the 14-family suite pays
    for it once. Keys are namespaced strings (``"lint.classes"``,
    ``"lint.index"``); values must be treated as immutable by readers.
    """

    def __init__(self, modules: List[Module]):
        self.modules = modules
        self.by_path: Dict[str, Module] = {m.relpath: m for m in modules}
        self.memo: Dict[str, object] = {}

    def module_of(self, relpath: str) -> Optional[Module]:
        return self.by_path.get(relpath)


# -- registry ---------------------------------------------------------------

CheckFn = Callable[[LintContext], List[Finding]]
_CHECKERS: List[Tuple[str, CheckFn]] = []
_WHOLE_PROGRAM: set = set()


def register(name: str, whole_program: bool = False):
    """Register a checker family. ``whole_program=True`` marks families
    whose findings depend on files *outside* the scanned set (the thread
    spawn graph, package-wide config-key rules): scoped runs give them
    the full package and filter their findings, instead of starving them
    of the cross-file edges they exist to check."""
    def deco(fn: CheckFn) -> CheckFn:
        _CHECKERS.append((name, fn))
        if whole_program:
            _WHOLE_PROGRAM.add(name)
        return fn
    return deco


def whole_program_families() -> frozenset:
    _load_checkers()
    return frozenset(_WHOLE_PROGRAM)


def checker_names() -> List[str]:
    _load_checkers()
    return [n for n, _ in _CHECKERS]


_LOADED = False


def _load_checkers() -> None:
    """Import the checker modules exactly once (registration side effect)."""
    global _LOADED
    if _LOADED:
        return
    from pinot_tpu.tools.lint import (  # noqa: F401
        configkeys,
        conservation,
        decisions,
        declines,
        device,
        exactness,
        locks,
        pairing,
        protocol,
        sync,
        threads,
        tracer,
        wire,
    )
    _LOADED = True


# -- file collection --------------------------------------------------------

def _collect_files(paths: Sequence[str]) -> List[Tuple[str, str]]:
    """-> [(abspath, display path)], deterministic order."""
    out: List[Tuple[str, str]] = []
    for p in paths:
        if os.path.isfile(p):
            out.append((p, os.path.basename(p)))
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d != "__pycache__" and not d.startswith("."))
            for f in sorted(files):
                if f.endswith(".py"):
                    ap = os.path.join(root, f)
                    out.append((ap, os.path.relpath(ap, os.path.dirname(p))))
    return out


# (abspath, display path) -> parsed Module, reused across run_lint calls
# when the file CONTENT is unchanged (compared exactly — mtimes lie on
# fast rewrites). One ast.parse + tokenize per file per edit, however
# many families or consecutive runs consume it. Bounded: cleared
# wholesale past the cap (fixture-heavy test sessions churn tmp files).
_MODULE_CACHE: Dict[Tuple[str, str], Module] = {}
_MODULE_CACHE_CAP = 4096


def load_modules(paths: Sequence[str]) -> Tuple[LintContext, List[Finding]]:
    modules: List[Module] = []
    findings: List[Finding] = []
    for ap, rel in _collect_files(paths):
        try:
            with open(ap, encoding="utf-8") as f:
                src = f.read()
            key = (os.path.abspath(ap), rel)
            hit = _MODULE_CACHE.get(key)
            if hit is not None and hit.source == src:
                modules.append(hit)
                continue
            m = Module(ap, rel, src)
            if len(_MODULE_CACHE) >= _MODULE_CACHE_CAP:
                _MODULE_CACHE.clear()
            _MODULE_CACHE[key] = m
            modules.append(m)
        except SyntaxError as e:
            findings.append(Finding(
                "parse", rel, e.lineno or 0, "syntax",
                f"cannot parse: {e.msg}"))
    return LintContext(modules), findings


# -- changed-file selection (--changed <git-ref>) ---------------------------

def _imported_modules(tree: ast.AST) -> List[str]:
    """Dotted module names a parsed file imports (absolute imports; the
    codebase convention). ``from a.b import c`` contributes both ``a.b``
    and ``a.b.c`` — ``c`` may be a module."""
    out: List[str] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            out.extend(a.name for a in node.names)
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            out.append(node.module)
            out.extend(f"{node.module}.{a.name}" for a in node.names)
    return out


def build_import_graph(pkg_dir: str) -> Dict[str, List[str]]:
    """abs file -> abs files it imports, over one package tree."""
    pkg_name = os.path.basename(os.path.normpath(pkg_dir))
    parent = os.path.dirname(os.path.normpath(pkg_dir))

    def module_file(dotted: str) -> Optional[str]:
        if not dotted.startswith(pkg_name + ".") and dotted != pkg_name:
            return None
        rel = dotted.split(".")
        cand = os.path.join(parent, *rel) + ".py"
        if os.path.isfile(cand):
            return cand
        init = os.path.join(parent, *rel, "__init__.py")
        return init if os.path.isfile(init) else None

    graph: Dict[str, List[str]] = {}
    for ap, _rel in _collect_files([pkg_dir]):
        try:
            with open(ap, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=ap)
        except SyntaxError:
            graph[ap] = []
            continue
        deps = []
        for dotted in _imported_modules(tree):
            mf = module_file(dotted)
            if mf and mf != ap:
                deps.append(mf)
        graph[ap] = sorted(set(deps))
    return graph


def select_changed(ref: str, pkg_dir: str) -> List[str]:
    """Package files to lint for ``--changed <ref>``: files changed vs
    the git ref, plus their DIRECT imports (interprocedural families
    compare against the modules a changed file talks to — protocol needs
    plan.py next to a changed consumer) plus their TRANSITIVE reverse
    importers (a changed module can break every consumer's obligations).
    """
    import subprocess

    pkg_dir = os.path.abspath(pkg_dir)
    res = subprocess.run(
        ["git", "diff", "--name-only", ref, "--", "*.py"],
        cwd=pkg_dir, capture_output=True, text=True, check=True)
    root = subprocess.run(
        ["git", "rev-parse", "--show-toplevel"],
        cwd=pkg_dir, capture_output=True, text=True, check=True
    ).stdout.strip()
    changed = set()
    for line in res.stdout.splitlines():
        ap = os.path.abspath(os.path.join(root, line.strip()))
        if ap.startswith(pkg_dir + os.sep) and os.path.isfile(ap):
            changed.add(ap)
    if not changed:
        return []
    graph = build_import_graph(pkg_dir)
    importers: Dict[str, List[str]] = {}
    for src, deps in graph.items():
        for d in deps:
            importers.setdefault(d, []).append(src)
    selected = set(changed)
    frontier = list(changed)              # reverse: transitive
    while frontier:
        f = frontier.pop()
        for imp in importers.get(f, []):
            if imp not in selected:
                selected.add(imp)
                frontier.append(imp)
    # forward: one hop of context for EVERY selected file — base classes
    # (inherited lock annotations), pack-side plan.py for protocol, the
    # tracing/config tables — without pulling the transitive world in
    for f in list(selected):
        selected.update(graph.get(f, []))
    return sorted(selected)


# -- baseline ---------------------------------------------------------------

def load_baseline(path: Optional[str]) -> Dict[str, str]:
    """-> {finding key: reason}. Missing file = empty baseline."""
    if not path or not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    out: Dict[str, str] = {}
    for e in data.get("entries", []):
        out[e["key"]] = e.get("reason", "")
    return out


# -- runner -----------------------------------------------------------------

def run_lint(paths: Sequence[str], baseline: Optional[str] = None,
             families: Optional[Sequence[str]] = None,
             whole_program_root: Optional[str] = None
             ) -> Tuple[List[Finding], List[Finding]]:
    """Run every registered checker over ``paths``.

    ``families`` restricts the run to the named checker families
    (parse errors always report). ``whole_program_root`` (set by
    ``--changed``) names the package directory whole-program families
    analyze in full: they see every package file — the spawn graph and
    config-key universe don't truncate at the changed set — and their
    findings are then scoped down to the files in ``paths``. Returns
    ``(new, accepted)``: findings not covered by the baseline, and
    findings the baseline (or an inline ignore) covers. Exit policy is
    the caller's (the CLI exits non-zero iff ``new`` is non-empty).
    """
    _load_checkers()
    if families is not None:
        wanted = set(families)
        unknown = wanted - {n for n, _ in _CHECKERS}
        if unknown:
            raise ValueError(
                f"unknown lint families {sorted(unknown)}; "
                f"known: {[n for n, _ in _CHECKERS]}")
    ctx, findings = load_modules(paths)
    wp_ctx: Optional[LintContext] = None
    selected_abs: set = set()
    if whole_program_root is not None and any(
            n in _WHOLE_PROGRAM for n, _ in _CHECKERS
            if families is None or n in families):
        wp_ctx, _ = load_modules([whole_program_root])
        selected_abs = {os.path.abspath(m.path) for m in ctx.modules}
    for name, fn in _CHECKERS:
        if families is not None and name not in families:
            continue
        if wp_ctx is not None and name in _WHOLE_PROGRAM:
            for f in fn(wp_ctx):
                m = wp_ctx.module_of(f.path)
                if m is None or os.path.abspath(m.path) in selected_abs:
                    findings.append(f)
        else:
            findings.extend(fn(ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.checker, f.symbol))

    accepted_keys = load_baseline(baseline)
    new: List[Finding] = []
    accepted: List[Finding] = []
    for f in findings:
        mod = ctx.module_of(f.path)
        if mod is None and wp_ctx is not None:
            mod = wp_ctx.module_of(f.path)
        if mod is not None and mod.ignored(f.line, f.checker):
            accepted.append(f)
        elif f.key in accepted_keys:
            accepted.append(f)
        else:
            new.append(f)
    return new, accepted


# -- shared AST helpers (used by several checkers) --------------------------

def call_name(node: ast.Call) -> Optional[str]:
    """Last path segment of the called thing: ``a.b.c(...)`` -> 'c'."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def attr_base_name(node: ast.expr) -> Optional[str]:
    """Root Name of an attribute chain: ``a.b.c`` -> 'a'; None otherwise."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def is_self_attr(node: ast.expr, attr: Optional[str] = None) -> bool:
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and (attr is None or node.attr == attr))
