"""Kernel param-protocol verification: plan.py's pack order vs every
``pc.take()`` consumer.

The positional static-param protocol between ``engine/plan.py`` (which
appends runtime arrays to a flat ``params`` list while compiling the spec)
and the cursor consumers (``engine/kernels.py`` ``_emit_filter`` /
``_emit_value`` / the kernel group epilogue, and
``engine/pallas_kernels.py`` ``extract_plan``'s nested ``walk`` /
``compile_vexpr``) has no type system: drift produces silently wrong
query results, not a crash. The declared protocol lives in two dict
literals in plan.py — ``_FILTER_PARAMS`` and ``_VALUE_PARAMS`` (params
consumed per spec op) — and this family proves, per op, that both sides
agree with it:

- **pack side** (``protocol`` / append counts): every function that
  appends to a ``params`` list and returns spec tuples is path-executed;
  at each ``return ("<op>", ...)`` the number of ``params.append`` calls
  on that path must equal the table's count for the op.
- **consume side** (take counts): every dispatch-shaped function with
  ``pc.take()`` calls (``op = spec[0]; if op == "eq": ...``) is executed
  once per table op with the op pinned; the takes on surviving paths must
  equal the table count. Paths that ``raise`` decline the op (the pallas
  extractor's ``_Ineligible``) and are exempt; ``_emit_filter`` and
  ``_emit_value`` are *total* consumers — an op they fail to handle, or a
  branch they handle for an op missing from the table, is drift.
- **group epilogue order**: the pack side's ordered
  ``params.append(strides)`` / ``params.append(...bases...)`` sequence
  must match, in order, every consumer's stride/base-named
  ``... = pc.take()`` assignments (swapping the two takes is the
  classic silent-wrong-results drift).
- **int32 range safety**: narrowing a ``_bases`` element with
  ``.astype(int32)`` *before* the key subtraction wraps i64 graw/gexpr
  offsets — only the ``strat == "gdict"`` branch (dictIds are i32 by
  construction) may cast the base directly.
- **pow2-padding consistency**: every ``_next_pow2`` definition in the
  package must be structurally identical, and the launcher's vmapped
  kernel cache must key on a ``_next_pow2``-padded size (unbounded batch
  sizes would mint unbounded compile variants).
- **cursor tails**: a function that builds a ``_ParamCursor`` and takes
  from it must either call ``.finish()`` (the runtime mirror asserting
  full consumption) or hand the cursor to another function.

Built on :mod:`pinot_tpu.tools.lint.dataflow` (DispatchExecutor +
SummaryTable) and :mod:`tracer`'s resolution index. All checks discover
their anchors structurally (by table/function shape, not hardcoded
paths), so fixtures and scratch copies lint the same way the package
does.
"""

from __future__ import annotations

import ast

from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from pinot_tpu.tools.lint.core import (
    Finding,
    LintContext,
    Module,
    register,
)
from pinot_tpu.tools.lint.dataflow import (
    DispatchExecutor,
    SummaryTable,
    eval_expr,
    walk_no_nested,
)
from pinot_tpu.tools.lint.pairing import _functions
from pinot_tpu.tools.lint.tracer import shared_index

# ops the spec tree uses structurally (children carry the params)
_STRUCTURAL = {"and", "or", "not"}
# consumers that must handle EVERY op of their table (by function name)
_TOTAL_CONSUMERS = {"_emit_filter": "filter", "_emit_value": "value"}


def _is_take(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "take" and not node.args)


def _param_tables(ctx: LintContext):
    """-> (merged op->count table, filter table, value table, module)."""
    filt: Dict[str, int] = {}
    val: Dict[str, int] = {}
    home: Optional[Module] = None
    for mod in ctx.modules:
        for node in mod.tree.body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            t = node.targets[0]
            if not isinstance(t, ast.Name) \
                    or t.id not in ("_FILTER_PARAMS", "_VALUE_PARAMS") \
                    or not isinstance(node.value, ast.Dict):
                continue
            d: Dict[str, int] = {}
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) and isinstance(k.value, str) \
                        and isinstance(v, ast.Constant) \
                        and isinstance(v.value, int):
                    d[k.value] = v.value
            if t.id == "_FILTER_PARAMS":
                filt.update(d)
                home = home or mod
            else:
                val.update(d)
                home = home or mod
    merged = dict(filt)
    merged.update(val)
    return merged, filt, val, home


class _Resolver:
    """Shared call resolution + take/append summaries over the scan set."""

    def __init__(self, ctx: LintContext):
        self.idx = shared_index(ctx)
        self.take_sums = SummaryTable(self._take_counter_for)

    def _ctx_of(self, fn: ast.AST):
        mod = self.idx.mod_of.get(id(fn))
        scope = self.idx.scope_of.get(id(fn))
        return mod, scope

    def resolve(self, func_expr, mod, scope):
        if mod is None:
            return None
        try:
            return self.idx.resolve_callable(func_expr, mod, scope)
        except Exception:
            return None

    def _take_counter_for(self, fn: ast.AST):
        mod, scope = self._ctx_of(fn)
        cursors = cursor_names(fn)
        return self.take_counter(mod, scope, cursors)

    def take_counter(self, mod, scope, cursors: Set[str]):
        def count(node, env):
            n, unk = 0, False
            for sub in walk_no_nested(node):
                if not isinstance(sub, ast.Call):
                    continue
                if _is_take(sub):
                    n += 1
                    continue
                hit = self.resolve(sub.func, mod, scope)
                if hit is not None:
                    s = self.take_sums.summary(hit[1])
                    if s is None:
                        unk = True
                    else:
                        n += s
                elif any(isinstance(a, ast.Name) and a.id in cursors
                         for a in sub.args):
                    unk = True  # cursor escapes to unresolved code
            return n, unk
        return count

    def append_counter(self, mod, scope):
        def count(node, env):
            n, unk = 0, False
            for sub in walk_no_nested(node):
                if not isinstance(sub, ast.Call):
                    continue
                f = sub.func
                if isinstance(f, ast.Attribute) \
                        and f.attr in ("append", "insert") \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id == "params":
                    n += 1
                    continue
                if any(isinstance(a, ast.Name) and a.id == "params"
                       for a in sub.args):
                    # forwarding the pack list is fine when the callee is
                    # in-package (its own returns are checked); opaque
                    # forwarding makes this path unverifiable
                    if self.resolve(sub.func, mod, scope) is None:
                        unk = True
            return n, unk
        return count


def cursor_names(fn: ast.AST) -> Set[str]:
    """Names that hold a param cursor in ``fn``: receivers of ``.take()``
    and targets of ``_ParamCursor(...)`` assignments."""
    out: Set[str] = set()
    for node in walk_no_nested(fn):
        if _is_take(node) and isinstance(node.func.value, ast.Name):
            out.add(node.func.value.id)
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            f = node.value.func
            name = f.id if isinstance(f, ast.Name) else \
                (f.attr if isinstance(f, ast.Attribute) else None)
            if name == "_ParamCursor":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


def _dispatch_param(fn: ast.AST) -> Optional[str]:
    """The parameter P whose ``P[0]`` drives the op dispatch, if any."""
    args = getattr(fn, "args", None)
    if args is None:
        return None
    params = {a.arg for a in list(args.posonlyargs) + list(args.args)}
    for node in walk_no_nested(fn):
        if isinstance(node, ast.Subscript) \
                and isinstance(node.value, ast.Name) \
                and node.value.id in params \
                and isinstance(node.slice, ast.Constant) \
                and node.slice.value == 0:
            return node.value.id
    return None


def _group_label(name: str) -> Optional[str]:
    n = name.lower()
    if "stride" in n:
        return "strides"
    if "base" in n:
        return "bases"
    return None


def _first_label(expr: ast.expr) -> Optional[str]:
    for node in ast.walk(expr):
        if isinstance(node, ast.Name):
            lbl = _group_label(node.id)
            if lbl:
                return lbl
    return None


@register("protocol")
def check_protocol(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    table, filt, val, home = _param_tables(ctx)
    res = _Resolver(ctx)

    funcs: List[Tuple[Module, str, ast.AST]] = []
    for mod in ctx.modules:
        for qual, fn in _functions(mod.tree):
            funcs.append((mod, qual, fn))

    if table:
        _check_consumers(funcs, table, filt, val, res, findings)
        _check_pack_side(funcs, table, res, findings)
    _check_group_order(funcs, findings)
    _check_bases_narrowing(funcs, findings)
    _check_pow2(ctx, findings)
    _check_cursor_finish(funcs, findings)
    return findings


# -- consume side -----------------------------------------------------------

def _own_stmts(fn: ast.AST) -> List[ast.stmt]:
    return list(getattr(fn, "body", []))


def _check_consumers(funcs, table, filt, val, res: _Resolver, findings):
    for mod, qual, fn in funcs:
        has_take = any(_is_take(n) for n in walk_no_nested(fn))
        if not has_take:
            continue
        p = _dispatch_param(fn)
        if p is None:
            continue
        scope = res.idx.scope_of.get(id(fn))
        counter = res.take_counter(mod, scope, cursor_names(fn))
        name = getattr(fn, "name", "<lambda>")
        total_table = (filt if _TOTAL_CONSUMERS.get(name) == "filter"
                       else val if _TOTAL_CONSUMERS.get(name) == "value"
                       else None)
        for op, expected in sorted(table.items()):
            env: Dict = {("idx0", p): frozenset([op])}
            ex = DispatchExecutor(counter)
            outs = ex.run(_own_stmts(fn), env)
            live = [o for o in outs if o.kind in ("return", "fall")]
            if not live:
                if total_table is not None and op in total_table:
                    findings.append(Finding(
                        "protocol", mod.relpath, fn.lineno,
                        f"{qual}:{op}:unhandled",
                        f"{name}() has no consuming branch for spec op "
                        f"{op!r} declared in the param table — specs "
                        f"carrying it fail or misconsume the cursor"))
                continue
            counts = {o.count for o in live if not o.unknown}
            if counts and counts != {expected}:
                got = "/".join(str(c) for c in sorted(counts))
                findings.append(Finding(
                    "protocol", mod.relpath, fn.lineno,
                    f"{qual}:{op}",
                    f"{name}() consumes {got} param(s) for spec op "
                    f"{op!r}; the declared protocol packs {expected} — "
                    f"pack/unpack drift silently corrupts results"))
        if total_table is not None:
            _check_coverage(mod, qual, fn, p, table, findings)


def _check_coverage(mod, qual, fn, p, table, findings):
    """Ops a total consumer dispatches on must exist in the table (a new
    branch without a table entry breaks the pack-side walkers)."""
    opvars = {("sub", p)}
    names: Set[str] = set()
    for node in walk_no_nested(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Subscript) \
                and isinstance(node.value.value, ast.Name) \
                and node.value.value.id == p \
                and isinstance(node.value.slice, ast.Constant) \
                and node.value.slice.value == 0:
            names.add(node.targets[0].id)
    allowed = set(table) | _STRUCTURAL
    seen: Set[str] = set()
    for node in walk_no_nested(fn):
        if not isinstance(node, ast.Compare) or len(node.ops) != 1 \
                or not isinstance(node.ops[0], (ast.Eq, ast.In)):
            continue
        left = node.left
        is_op = (isinstance(left, ast.Name) and left.id in names) or (
            isinstance(left, ast.Subscript)
            and isinstance(left.value, ast.Name) and left.value.id == p
            and isinstance(left.slice, ast.Constant)
            and left.slice.value == 0)
        if not is_op:
            continue
        comp = node.comparators[0]
        consts = []
        if isinstance(comp, ast.Constant) and isinstance(comp.value, str):
            consts = [comp.value]
        elif isinstance(comp, (ast.Tuple, ast.Set, ast.List)):
            consts = [e.value for e in comp.elts
                      if isinstance(e, ast.Constant)
                      and isinstance(e.value, str)]
        for c in consts:
            if c not in allowed and c not in seen:
                seen.add(c)
                findings.append(Finding(
                    "protocol", mod.relpath, node.lineno,
                    f"{qual}:{c}:untabled",
                    f"{getattr(fn, 'name', qual)}() handles spec op {c!r} "
                    f"that is missing from the param-count table — the "
                    f"pack-side walkers will misindex params for it"))


# -- pack side --------------------------------------------------------------

def _return_tuples(value: ast.expr) -> List[ast.Tuple]:
    if isinstance(value, ast.Tuple):
        return [value]
    if isinstance(value, ast.IfExp):
        return _return_tuples(value.body) + _return_tuples(value.orelse)
    return []


def _check_pack_side(funcs, table, res: _Resolver, findings):
    for mod, qual, fn in funcs:
        has_append = any(
            isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
            and n.func.attr in ("append", "insert")
            and isinstance(n.func.value, ast.Name)
            and n.func.value.id == "params"
            for n in walk_no_nested(fn))
        if not has_append:
            continue
        returns_specs = any(
            isinstance(n, ast.Return) and n.value is not None
            and _return_tuples(n.value)
            for n in walk_no_nested(fn))
        if not returns_specs:
            continue
        scope = res.idx.scope_of.get(id(fn))
        counter = res.append_counter(mod, scope)
        ex = DispatchExecutor(counter)
        outs = ex.run(_own_stmts(fn), {})
        reported: Set[str] = set()
        for o in outs:
            if o.kind != "return" or o.unknown or o.node is None \
                    or o.node.value is None:
                continue
            for tup in _return_tuples(o.node.value):
                if not tup.elts:
                    continue
                ops = eval_expr(tup.elts[0], o.env)
                if ops is None:
                    continue
                for op in ops:
                    if not isinstance(op, str) or op not in table \
                            or op in reported:
                        continue
                    if o.count != table[op]:
                        reported.add(op)
                        findings.append(Finding(
                            "protocol", mod.relpath, o.node.lineno,
                            f"{qual}:pack:{op}",
                            f"{getattr(fn, 'name', qual)}() appends "
                            f"{o.count} param(s) on a path returning spec "
                            f"op {op!r}; the declared protocol says "
                            f"{table[op]} — consumers will misalign the "
                            f"cursor"))


# -- group epilogue order ---------------------------------------------------

def _pack_group_seq(fn: ast.AST) -> List[Tuple[str, int]]:
    out: List[Tuple[str, int]] = []
    for node in walk_no_nested(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)\
                and node.func.attr in ("append", "insert") \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "params" and node.args:
            arg = node.args[-1]
            lbl = _first_label(arg)
            if lbl:
                out.append((lbl, node.lineno))
    return out


def _consume_group_seq(fn: ast.AST) -> List[Tuple[str, int]]:
    out: List[Tuple[str, int]] = []
    for node in walk_no_nested(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and any(_is_take(s) for s in ast.walk(node.value)):
            lbl = _group_label(node.targets[0].id)
            if lbl:
                out.append((lbl, node.lineno))
    return out


def _check_group_order(funcs, findings):
    packs = []
    for mod, qual, fn in funcs:
        seq = _pack_group_seq(fn)
        if len({lbl for lbl, _ in seq}) >= 2:
            packs.append((mod, qual, fn, seq))
    if not packs:
        return
    canon = [lbl for lbl, _ in packs[0][3]]
    for mod, qual, fn, seq in packs[1:]:
        if [lbl for lbl, _ in seq] != canon:
            findings.append(Finding(
                "protocol", mod.relpath, seq[0][1],
                f"{qual}:group-pack-order",
                f"{qual}() packs group params as "
                f"{[lbl for lbl, _ in seq]} but {packs[0][1]}() packs "
                f"{canon} — one of them is wrong"))
    for mod, qual, fn in funcs:
        seq = _consume_group_seq(fn)
        if not seq:
            continue
        got = [lbl for lbl, _ in seq]
        if got != canon:
            findings.append(Finding(
                "protocol", mod.relpath, seq[0][1],
                f"{qual}:group-order",
                f"{qual}() consumes group static params as {got} but the "
                f"pack side writes {canon} — reordered/missing pc.take() "
                f"silently mis-keys every grouped result"))


# -- int32 range safety of _bases ------------------------------------------

def _check_bases_narrowing(funcs, findings):
    for mod, qual, fn in funcs:
        bases_vars = {t for t, _ in
                      ((n.targets[0].id, n) for n in walk_no_nested(fn)
                       if isinstance(n, ast.Assign) and len(n.targets) == 1
                       and isinstance(n.targets[0], ast.Name)
                       and any(_is_take(s) for s in ast.walk(n.value)))
                      if _group_label(t) == "bases"}
        if not bases_vars:
            continue

        def scan(node, in_gdict: bool):
            if isinstance(node, ast.If):
                test = node.test
                is_gdict = (isinstance(test, ast.Compare)
                            and len(test.ops) == 1
                            and isinstance(test.ops[0], ast.Eq)
                            and isinstance(test.comparators[0], ast.Constant)
                            and test.comparators[0].value == "gdict")
                for st in node.body:
                    scan(st, in_gdict or is_gdict)
                for st in node.orelse:
                    scan(st, in_gdict)
                return
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "astype" \
                    and isinstance(node.func.value, ast.Subscript) \
                    and isinstance(node.func.value.value, ast.Name) \
                    and node.func.value.value.id in bases_vars \
                    and not in_gdict:
                findings.append(Finding(
                    "protocol", mod.relpath, node.lineno,
                    f"{qual}:bases-narrowing",
                    f"{qual}() narrows a _bases offset with .astype() "
                    f"before the key subtraction outside the gdict "
                    f"branch — i64 graw/gexpr offsets would wrap in "
                    f"int32"))
                return
            for child in ast.iter_child_nodes(node):
                if not isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef, ast.Lambda)):
                    scan(child, in_gdict)

        for st in getattr(fn, "body", []):
            scan(st, False)


# -- pow2-padding consistency -----------------------------------------------

def _check_pow2(ctx: LintContext, findings):
    defs: List[Tuple[Module, ast.FunctionDef, str]] = []
    for mod in ctx.modules:
        for node in mod.tree.body:
            if isinstance(node, ast.FunctionDef) \
                    and node.name == "_next_pow2":
                dump = ast.dump(ast.Module(body=node.body, type_ignores=[]))
                defs.append((mod, node, dump))
    if len({d for _, _, d in defs}) > 1:
        first = defs[0][2]
        for mod, node, dump in defs[1:]:
            if dump != first:
                findings.append(Finding(
                    "protocol", mod.relpath, node.lineno,
                    "_next_pow2:drift",
                    f"_next_pow2 in {mod.relpath} differs from "
                    f"{defs[0][0].relpath} — plan padding and launcher "
                    f"batch padding must round identically or vmapped "
                    f"coalescing misaligns"))
    # the vmapped kernel cache must key on pow2-padded sizes
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            touches = any(
                (isinstance(s, ast.Subscript)
                 and isinstance(s.value, ast.Attribute)
                 and s.value.attr == "_vmapped")
                or (isinstance(s, ast.Call)
                    and isinstance(s.func, ast.Attribute)
                    and s.func.attr in ("get", "setdefault")
                    and isinstance(s.func.value, ast.Attribute)
                    and s.func.value.attr == "_vmapped")
                for s in ast.walk(node))
            if not touches:
                continue
            calls_pow2 = any(
                isinstance(s, ast.Call) and (
                    (isinstance(s.func, ast.Name)
                     and s.func.id == "_next_pow2")
                    or (isinstance(s.func, ast.Attribute)
                        and s.func.attr == "_next_pow2"))
                for s in ast.walk(node))
            if not calls_pow2:
                findings.append(Finding(
                    "protocol", mod.relpath, node.lineno,
                    f"{node.name}:vmapped-pow2",
                    f"{node.name}() keys the _vmapped batch cache without "
                    f"_next_pow2 padding — unpadded sizes mint unbounded "
                    f"compile variants"))


# -- cursor tails -----------------------------------------------------------

def _check_cursor_finish(funcs, findings):
    for mod, qual, fn in funcs:
        makes_cursor = False
        cursor_vars: Set[str] = set()
        for node in walk_no_nested(fn):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                f = node.value.func
                name = f.id if isinstance(f, ast.Name) else \
                    (f.attr if isinstance(f, ast.Attribute) else None)
                if name == "_ParamCursor":
                    makes_cursor = True
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            cursor_vars.add(t.id)
        if not makes_cursor:
            continue
        has_take = any(_is_take(n) for n in walk_no_nested(fn))
        if not has_take:
            continue
        finished = any(
            isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
            and n.func.attr == "finish"
            for n in walk_no_nested(fn))
        escapes = any(
            isinstance(n, ast.Call) and not _is_take(n)
            and any(isinstance(a, ast.Name) and a.id in cursor_vars
                    for a in n.args)
            for n in walk_no_nested(fn))
        if not finished and not escapes:
            findings.append(Finding(
                "protocol", mod.relpath, fn.lineno,
                f"{qual}:unfinished-cursor",
                f"{qual}() builds a _ParamCursor and takes from it but "
                f"never asserts full consumption (.finish()) — an "
                f"unconsumed tail means pack/unpack drift goes unnoticed"))
