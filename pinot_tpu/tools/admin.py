"""pinot-tpu admin CLI.

Re-design of the reference's ``PinotAdministrator.java:86`` (40 subcommands
under pinot-tools/.../admin/command/): the subset a single-box user needs —
launch ingestion jobs, start an embedded cluster with REST endpoints, post
queries, run the quickstart. Invoke as ``python -m pinot_tpu <command>``.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def _cmd_launch_ingestion_job(args) -> int:
    """Ref: LaunchDataIngestionJobCommand."""
    from pinot_tpu.ingestion.batchjob import run_ingestion_job

    seg_dirs = run_ingestion_job(args.jobSpecFile)
    for d in seg_dirs:
        print(d)
    print(f"built {len(seg_dirs)} segment(s)", file=sys.stderr)
    return 0


def _cmd_post_query(args) -> int:
    """Ref: PostQueryCommand — POST /query/sql against a broker."""
    import urllib.request

    body = json.dumps({"sql": args.query}).encode()
    req = urllib.request.Request(
        f"http://{args.brokerHost}:{args.brokerPort}/query/sql",
        data=body, headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=args.timeout) as resp:
        print(resp.read().decode())
    return 0


def _cmd_quickstart(args) -> int:
    """Ref: Quickstart.java — embedded cluster + bundled data + sample
    queries. Loads the reference-layout baseballStats configs when a
    directory is given, else generates a demo dataset."""
    import numpy as np

    from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
    from pinot_tpu.spi.table import TableConfig
    from pinot_tpu.tools.cluster import EmbeddedCluster

    cluster = EmbeddedCluster(num_servers=1, data_dir=args.dataDir)
    if args.exampleDir:
        import glob as globmod
        import os

        schema_file = globmod.glob(os.path.join(args.exampleDir,
                                                "*_schema.json"))[0]
        table_file = globmod.glob(os.path.join(
            args.exampleDir, "*_offline_table_config.json"))[0]
        schema = Schema.from_file(schema_file)
        table_config = TableConfig.from_file(table_file)
        cluster.create_table(table_config, schema)
        job_files = globmod.glob(os.path.join(args.exampleDir,
                                              "ingestionJobSpec.yaml"))
        if job_files:
            from pinot_tpu.ingestion.batchjob import run_ingestion_job

            run_ingestion_job(job_files[0], cluster=cluster,
                              schema=schema, table_config=table_config)
        table = schema.schema_name
    else:
        rng = np.random.default_rng(7)
        n = 10_000
        schema = Schema("quickstart", [
            FieldSpec("city", DataType.STRING),
            FieldSpec("value", DataType.LONG, FieldType.METRIC)])
        table_config = TableConfig("quickstart")
        cluster.create_table(table_config, schema)
        cluster.ingest_rows("quickstart_OFFLINE", schema, {
            "city": np.array(["sf", "nyc", "sea"])[rng.integers(0, 3, n)],
            "value": rng.integers(0, 1000, n).astype(np.int64)})
        table = "quickstart"

    for sql in (args.query or
                [f"SELECT count(*) FROM {table}"]):
        resp = cluster.query(sql)
        print(json.dumps(resp.to_dict(), default=str))
    cluster.shutdown()
    return 0


def _cmd_start_cluster(args) -> int:
    """StartController/Broker/Server in one process with REST endpoints
    (ref: QuickstartRunner + Start*Command)."""
    from pinot_tpu.tools.cluster import EmbeddedCluster
    from pinot_tpu.transport.rest import serve_cluster

    cluster = EmbeddedCluster(num_servers=args.servers,
                              data_dir=args.dataDir)
    apis = serve_cluster(cluster, controller_port=args.controllerPort,
                         broker_port=args.brokerPort)
    print(f"controller http://localhost:{args.controllerPort} | "
          f"broker http://localhost:{args.brokerPort} "
          f"({args.servers} server(s)); ctrl-c to stop")
    try:
        import time

        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        for api in apis:
            api.stop()
        cluster.shutdown()
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pinot_tpu",
        description="pinot-tpu administration (ref: PinotAdministrator)")
    sub = p.add_subparsers(dest="command", required=True)

    j = sub.add_parser("LaunchDataIngestionJob",
                       help="run a segment generation job spec (yaml)")
    j.add_argument("-jobSpecFile", required=True)
    j.set_defaults(fn=_cmd_launch_ingestion_job)

    q = sub.add_parser("PostQuery", help="POST sql to a running broker")
    q.add_argument("-query", required=True)
    q.add_argument("-brokerHost", default="localhost")
    q.add_argument("-brokerPort", type=int, default=8099)
    q.add_argument("-timeout", type=float, default=60.0)
    q.set_defaults(fn=_cmd_post_query)

    qs = sub.add_parser("Quickstart",
                        help="embedded cluster + data + sample queries")
    qs.add_argument("-exampleDir", default=None,
                    help="dir with *_schema.json, *_offline_table_config."
                         "json, ingestionJobSpec.yaml (reference layout)")
    qs.add_argument("-dataDir", default="/tmp/pinot_tpu_quickstart")
    qs.add_argument("-query", action="append")
    qs.set_defaults(fn=_cmd_quickstart)

    c = sub.add_parser("StartCluster",
                       help="embedded cluster with REST endpoints")
    c.add_argument("-servers", type=int, default=1)
    c.add_argument("-controllerPort", type=int, default=9000)
    c.add_argument("-brokerPort", type=int, default=8099)
    c.add_argument("-dataDir", default="/tmp/pinot_tpu_cluster")
    c.set_defaults(fn=_cmd_start_cluster)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
