"""Sharded query executor: multi-segment queries over a TPU mesh.

Drop-in ``ServerQueryExecutor`` whose aggregation/group-by combine runs the
whole segment list as ONE device program (SegmentBatch stacked arrays,
shard_map over the mesh, psum/pmin/pmax merge — see parallel/combine.py)
instead of a per-segment host loop. Queries the device kernels don't cover
fall back to the per-segment / host paths of the base class, mirroring the
reference's plan-node selection (ref: InstancePlanMakerImplV2.java:227).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from jax.sharding import Mesh

from pinot_tpu.common.tracing import (
    maybe_span,
    record_decision,
    stats_tracer,
)
from pinot_tpu.engine.executor import (
    ServerQueryExecutor,
    decode_grouped_result,
    decode_scalar_result,
    filter_fingerprint,
    grouped_rung,
)
from pinot_tpu.engine.plan import PlanError, SegmentPlan, plan_segment
from pinot_tpu.engine.results import AggResult, GroupByResult, QueryStats
from pinot_tpu.parallel.batch import SegmentBatch
from pinot_tpu.parallel.combine import (
    DOC_AXIS,
    SEG_AXIS,
    ShardedKernelCache,
    device_stage_column,
    make_combine_mesh,
    pad_segments,
)
from pinot_tpu.query.context import QueryContext
from pinot_tpu.segment.immutable import ImmutableSegment


class ShardedQueryExecutor(ServerQueryExecutor):
    """Executor whose combine phase is a sharded device program."""

    def __init__(self, mesh: Optional[Mesh] = None, doc_shards: int = 1,
                 **kwargs):
        super().__init__(**kwargs)
        self.mesh = mesh if mesh is not None else make_combine_mesh(
            doc_shards=doc_shards)
        self.sharded_kernels = ShardedKernelCache(self.mesh)
        self._batches: Dict[Tuple[str, ...], SegmentBatch] = {}
        # (batch, column, S) -> device-committed sharded arrays: the batch
        # analogue of the per-segment staging (H2D paid once, reused across
        # queries). Byte-accounted + evictable through self.residency as
        # one _BatchResident per batch.
        self._device_cols: Dict[Tuple[str, str, int], Dict] = {}
        # Two-tier query cache. The PARAM tier is keyed on the exact
        # (sql, filter fp, batch, S) and holds this literal set's plan +
        # device-committed runtime params (cheap entries; a dashboard
        # emitting unique literals may churn it affordably). The LAUNCH
        # tier is keyed on the literal-normalized plan fingerprint — the
        # plan spec, whose literals ride in params — and holds the
        # expensive compiled call closures; unique-literal queries HIT
        # here, reusing the compiled kernel + staged-column bindings
        # instead of churning them out of one flat LRU. The launch-tier
        # key doubles as the launcher's coalescing identity.
        import threading
        from collections import OrderedDict

        self._param_cache: "OrderedDict[Tuple, Tuple]" = OrderedDict()
        self._param_cache_cap = 256
        self._launch_cache: "OrderedDict[Tuple, object]" = OrderedDict()
        self._launch_cache_cap = 128
        self._cache_lock = threading.Lock()
        self._device_cols_lock = threading.Lock()
        self._batches_lock = threading.Lock()
        # multi-device combine programs carry collectives (psum/all_gather):
        # interleaved launches from two threads deadlock the runtime. The
        # old process-global _combine_lock is gone — every launch now flows
        # through the per-mesh LaunchScheduler, whose single dispatcher
        # thread totally orders device programs AND coalesces same-kernel
        # requests into one micro-batched launch (parallel/launcher.py).
        from pinot_tpu.parallel.launcher import launcher_for_mesh
        from pinot_tpu.spi.config import CommonConstants, PinotConfiguration

        cfg = self.config if self.config is not None else PinotConfiguration()
        self._launch_max_batch = max(1, cfg.get_int(
            CommonConstants.LAUNCH_MAX_BATCH_KEY,
            CommonConstants.DEFAULT_LAUNCH_MAX_BATCH))
        self.launcher = launcher_for_mesh(self.mesh)
        # adaptive micro-batch window knobs ride the shared per-mesh
        # dispatcher (last executor to configure wins — one serving config
        # per process in practice)
        self.launcher.set_window(
            max_ms=cfg.get_float(CommonConstants.LAUNCH_WINDOW_MS_KEY,
                                 CommonConstants.DEFAULT_LAUNCH_WINDOW_MS),
            hot_ms=cfg.get_float(
                CommonConstants.LAUNCH_WINDOW_HOT_MS_KEY,
                CommonConstants.DEFAULT_LAUNCH_WINDOW_HOT_MS))
        # PallasSpec -> jitted sharded fused kernel (literal params stay
        # runtime args, so same-shape queries share the compile)
        self._pallas_sharded: Dict = {}
        # cross-query column dedup: the per-segment staging path borrows a
        # resident batch's sharded copy of a column instead of staging a
        # second device copy (engine/staging.py consults this hook)
        self.residency.column_borrower = self._borrow_batch_column
        self._borrows = 0

    # -- combine overrides --------------------------------------------------
    def _sliced_lease(self, stats):
        """The sliced lease when admission granted budget-sliced execution
        (working set over the HBM budget, largest segment fits), else
        None."""
        lease = self._lease_of(stats)
        return lease if lease is not None and lease.sliced else None

    def _any_star_tree_fit(self, ctx, aggs, segments) -> bool:
        """Star-tree-eligible queries take the per-segment path: each
        segment's node slice rides the DEVICE star-tree rung
        (engine/startree_device.py) and partials merge through
        GroupByResult — the pre-aggregated records beat a dense sharded
        scan (ref: the star-tree plan wins in
        AggregationGroupByOrderByPlanNode.java:66-87), and the launch
        dispatcher keeps coalescing the non-fit traffic unchanged. All
        segments of a table share their indexing config, so the first
        segment carrying trees is representative — one fit check, not K."""
        return any(self._star_tree_pick(ctx, aggs, s) is not None
                   for s in segments
                   if getattr(s, "star_trees", None))

    def _index_rung_fit(self, ctx, segments) -> bool:
        """Selective indexed filters take the per-segment path too: the
        PR-18 docId-gather rung ships a handful of matching rows per
        segment, which beats a dense sharded scan of every row — same
        rationale as the star-tree routing above, gated on the index
        cost model saying EVERY segment stays under the selectivity
        threshold (index_exec.batch_index_eligible)."""
        from pinot_tpu.engine import index_exec

        return index_exec.batch_index_eligible(self, ctx, segments)

    def _execute_aggregation(self, ctx, aggs, segments, stats):
        if self._any_star_tree_fit(ctx, aggs, segments):
            return ServerQueryExecutor._execute_aggregation(
                self, ctx, aggs, segments, stats)
        if self.use_device and self._index_rung_fit(ctx, segments):
            return ServerQueryExecutor._execute_aggregation(
                self, ctx, aggs, segments, stats)
        if self.use_device and self._sliced_lease(stats) is not None:
            return self._execute_sliced(ctx, aggs, segments, stats,
                                        grouped=False)
        if self.use_device and len(segments) > 1 \
                and self._device_admitted(stats):
            try:
                batch, out, plan = self._run_sharded(ctx, segments, stats)
                return decode_scalar_result(plan, batch, out)
            except (PlanError, ValueError) as e:
                # ValueError: segments not batchable (mixed layouts/schemas,
                # batch.py) — the per-segment path still serves them
                record_decision(
                    stats, "sharded_combine", "per_segment",
                    "sharded_combine",
                    e.reason_code if isinstance(e, PlanError)
                    else "segments_not_batchable")
        return super()._execute_aggregation(ctx, aggs, segments, stats)

    def _execute_group_by(self, ctx, aggs, segments, stats):
        if self._any_star_tree_fit(ctx, aggs, segments):
            return ServerQueryExecutor._execute_group_by(
                self, ctx, aggs, segments, stats)
        if self.use_device and self._index_rung_fit(ctx, segments):
            return ServerQueryExecutor._execute_group_by(
                self, ctx, aggs, segments, stats)
        if self.use_device and self._sliced_lease(stats) is not None:
            return self._execute_sliced(ctx, aggs, segments, stats,
                                        grouped=True)
        if self.use_device and len(segments) > 1 \
                and self._device_admitted(stats):
            try:
                batch, out, plan = self._run_sharded(ctx, segments, stats)
                return decode_grouped_result(plan, batch, out)
            except (PlanError, ValueError) as e:
                record_decision(
                    stats, "sharded_combine", "per_segment",
                    "sharded_combine",
                    e.reason_code if isinstance(e, PlanError)
                    else "segments_not_batchable")
        return super()._execute_group_by(ctx, aggs, segments, stats)

    def _execute_sliced(self, ctx, aggs, segments, stats, grouped: bool):
        """Budget-sliced sharded combine: a working set over the HBM
        budget streams through it in budget-sized slices — stage k
        segments, launch through the existing dispatcher (slices are just
        more launches to coalesce), merge partials with the existing
        AggResult/GroupByResult merges, unpin + demote-to-host, repeat —
        so a table 10x over HBM still rides the device kernels instead of
        spilling to the host engine. Slice sizing comes from
        ``plan_slices`` (drift-corrected estimates, mesh seg-axis pad
        included); when even one padded slice cannot fit, the per-segment
        sliced path (base class, serial stage/execute/demote) serves."""
        lease = self._lease_of(stats)
        slices = self.residency.plan_slices(
            segments, ctx.referenced_columns(), lease,
            pad_to=self.mesh.shape[SEG_AXIS])
        base = (ServerQueryExecutor._execute_group_by if grouped
                else ServerQueryExecutor._execute_aggregation)
        if slices is None:
            record_decision(stats, "sharded_combine", "per_segment_sliced",
                            "sharded_sliced", "slice_pad_over_budget")
            return base(self, ctx, aggs, segments, stats)
        merged = GroupByResult() if grouped else None
        for i, chunk in enumerate(slices):
            part = None
            with maybe_span(stats, "Slice", index=i,
                            segments=len(chunk)):
                if len(chunk) > 1:
                    try:
                        batch, out, plan = self._run_sharded(ctx, chunk,
                                                             stats)
                        part = (decode_grouped_result(plan, batch, out)
                                if grouped
                                else decode_scalar_result(plan, batch, out))
                    except (PlanError, ValueError):
                        part = None  # per-segment path serves this slice
                if part is None:
                    part = base(self, ctx, aggs, chunk, stats)
                if grouped:
                    merged.merge(part, aggs)
                elif merged is None:
                    merged = part
                else:
                    merged.merge(part, aggs)
                # slice boundary: unpin + demote so the next slice fits; a
                # repeat pass over the same data promotes from the host
                # tier
                self.residency.release_slice(lease)
        return merged

    # -- sharded execution ---------------------------------------------------
    def batch_for(self, segments: List[ImmutableSegment],
                  lease=None) -> SegmentBatch:
        key = tuple(s.segment_name for s in segments)
        if any(getattr(s, "valid_doc_ids", None) is not None
               for s in segments):
            # a bitmap attached AFTER a batch was built must not serve the
            # stale arrays; drop any cached batch ONCE and reject so the
            # per-segment path — which consults the bitmap — serves
            with self._batches_lock:
                b = self._batches.get(key)
            if b is not None:
                self._evict_batch(b)
            raise ValueError("upsert-managed segments are not batchable")
        with self._batches_lock:
            b = self._batches.get(key)
        if b is None or any(cached is not seg for cached, seg
                            in zip(b.segments, segments)):
            # identity check: a reloaded segment keeps its name but must not
            # serve stale device arrays (same guard as the staging path)
            if b is not None:
                self._evict_batch(b)
            # host-tier promotion first: a demoted batch's SegmentBatch
            # (host stacked arrays + unified dictionaries intact) re-stages
            # with plain device_puts, skipping dictionary unification
            b = self._adopt_host_batch(key, segments, lease)
            if b is None:
                b = SegmentBatch(segments)
            with self._batches_lock:
                # a concurrent builder may have won the insert; serve its
                # batch so both threads share one set of device arrays
                cur = self._batches.get(key)
                if cur is not None and all(c is s for c, s in
                                           zip(cur.segments, segments)):
                    return cur
                self._batches[key] = b
        return b

    def _adopt_host_batch(self, key: Tuple[str, ...],
                          segments: List[ImmutableSegment],
                          lease=None) -> Optional[SegmentBatch]:
        """Promote a demoted batch from the residency host tier: the image
        carries the old SegmentBatch object, whose host-side stacked
        arrays and unified dictionaries survived demotion — re-staging is
        one H2D ``device_put`` per column instead of a re-unification."""
        name = "batch(" + ",".join(key) + ")"
        image = self.residency.promote_host(name, segments, lease)
        if image is None:
            return None
        batch = image.batch
        image.release()
        return batch

    def _evict_batch(self, batch: SegmentBatch) -> None:
        """Drop EVERYTHING derived from a batch: the batch registration,
        its sharded device columns, its compiled query-cache entries
        (their call_fns close over the device arrays — a stale entry would
        keep serving a reloaded segment's OLD data), and its residency
        accounting. The old code matched query-cache keys on k[1] — the
        filter fingerprint slot, never the batch name — so compiled plans
        (and the arrays their closures pinned) survived eviction."""
        name = batch.metadata.segment_name
        with self._batches_lock:
            for k, b in list(self._batches.items()):
                if b is batch:
                    del self._batches[k]
        with self._device_cols_lock:
            for k in [k for k in self._device_cols if k[0] == name]:
                del self._device_cols[k]
        with self._cache_lock:
            # both tiers carry the batch name at slot [-2]
            for cache in (self._param_cache, self._launch_cache):
                for k in [k for k in cache if k[-2] == name]:
                    del cache[k]
        self.residency.discard(name)

    def evict_segment(self, segment_name: str) -> None:
        """A segment holds device bytes through BOTH the per-segment staged
        entry and every cached batch that includes it (batches are keyed by
        segment-name tuples, so one segment can ride in many). Eviction
        must clear them all or reload/unassignment leaks stale arrays."""
        with self._batches_lock:
            stale = [b for k, b in self._batches.items()
                     if segment_name in k]
        for b in stale:
            self._evict_batch(b)
        super().evict_segment(segment_name)

    def _run_sharded(self, ctx: QueryContext,
                     segments: List[ImmutableSegment],
                     stats: QueryStats):
        from pinot_tpu.engine.kernels import unpack_outputs

        lease = self._lease_of(stats)
        batch = self.batch_for(segments, lease)
        # the batch's device arrays are a resident like any staged segment:
        # byte-accounted, LRU-ordered, and PINNED through this query's lease
        # so another thread's budget enforcement cannot free arrays a
        # launched combine program is reading
        bkey = batch.metadata.segment_name
        self.residency.register(bkey, lambda: _BatchResident(self, batch),
                                same=lambda r: r.batch is batch, lease=lease)
        S = pad_segments(batch.num_segments, self.mesh.shape[SEG_AXIS])

        # the filter fingerprint distinguishes same-SQL contexts whose
        # filter was rewritten (hybrid time boundary advancing, IN_SUBQUERY
        # idset refresh) — without it a stale compiled plan would serve
        pkey = (ctx.sql if ctx.sql is not None else repr(ctx),
                filter_fingerprint(ctx), batch.metadata.segment_name, S)
        with self._cache_lock:
            cached = self._param_cache.get(pkey)
            if cached is not None:
                self._param_cache.move_to_end(pkey)
                plan, launch_key, params = cached
                kernel = self._launch_cache.get(launch_key)
                if kernel is not None:
                    self._launch_cache.move_to_end(launch_key)
        if cached is None:
            plan = plan_segment(ctx, batch)
            kernel, params, plan = self._bind_launch(plan, batch, S, stats)
            self._remember(pkey, plan, kernel, params)
        elif kernel is None:
            # launch tier evicted under this param entry: rebind (the plan
            # is in hand, so this costs a kernel-cache lookup, not a
            # replan; a probe-narrowed plan re-extracts directly without
            # re-probing — its num_groups is already inside the bound)
            kernel, params, plan = self._bind_launch(plan, batch, S, stats)
            self._remember(pkey, plan, kernel, params)
        num_docs = self._device_num_docs(batch, S)

        # span covers dispatcher queue + launch + D2H; the queue-vs-work
        # split comes from the launch request's measured queue wait
        rec = stats_tracer(stats)
        sp = rec.span_begin("ShardedCombine") if rec is not None else None
        req_out: list = []
        try:
            out = self._launch_sharded(pkey, plan, batch, S, kernel, params,
                                       num_docs, stats, req_out)
        finally:
            if sp is not None:
                req = req_out[-1] if req_out else None
                rec.span_end(
                    sp,
                    queue_ms=(round(req.queue_wait_ms, 3)
                              if req is not None else None),
                    kernel="pallas" if req is not None
                    and req.kernel.is_pallas else "jnp",
                    segments=batch.num_segments,
                    batch_size=req.batch_size if req is not None else 0,
                    mesh=f"{self.mesh.shape[SEG_AXIS]}x"
                         f"{self.mesh.shape[DOC_AXIS]}")

        # arrays were staged above: re-measure the resident and enforce the
        # budget now rather than waiting for end_query
        self.residency.account(bkey, lease)
        # estimate-drift feedback for the batch path: the admission/slice
        # estimates were per-segment sums; the measured batch bytes (incl.
        # the mesh seg-axis pad) are the truth slicing should pick k from
        # on the next pass
        if lease is not None and lease._est:
            est = sum(lease._est.get(s.segment_name, 0) for s in segments)
            measured = self.residency.resident_nbytes(bkey)
            if est > 0 and measured > 0:
                self.residency.observe_estimate(est, measured)

        stats.num_segments_processed += batch.num_segments
        stats.total_docs += batch.num_docs
        seg_matched = out["seg_matched"][:batch.num_segments]
        stats.num_docs_scanned += int(seg_matched.sum())
        stats.num_segments_matched += int((seg_matched > 0).sum())
        if plan.spec[2]:  # grouped: record the ladder rung that served
            rung = grouped_rung(plan.spec, out)
            stats.group_by_rung = (rung if stats.group_by_rung
                                   in (None, rung) else "mixed")
        return batch, out, plan

    def _launch_sharded(self, pkey, plan, batch, S, kernel, params,
                        num_docs, stats, req_out):
        """Dispatch through the launch scheduler with the pallas->jnp
        repair path; returns the unpacked output tree and appends the
        final launch request to ``req_out`` (the span above reads its
        queue wait)."""
        from pinot_tpu.engine.kernels import unpack_outputs

        try:
            req = self.launcher.submit(kernel, params, num_docs)
            req_out.append(req)
            packed = req.result()
        except (PlanError, ValueError):
            raise
        except Exception:
            # jax.jit compiles lazily: a Mosaic lowering failure on the real
            # chip surfaces HERE, not at bind time. Fall back to the jnp
            # combine, repair both cache tiers, and block THIS query shape
            # only (a process-wide kill switch would cost every other query
            # its fused kernel).
            if not kernel.is_pallas:
                raise
            import logging

            logging.getLogger(__name__).exception(
                "sharded pallas kernel failed at run; disabling pallas "
                "for this query shape")
            # block the ORIGINAL spec: a probe-narrowed plan's own spec is
            # never what _bind_pallas checks (it sees the planner's plan)
            orig = getattr(plan, "_narrowed_from", plan.spec)
            self._pallas_blocked.add(orig)
            # evict the poisoned compiled kernel too — the blocklist makes
            # it unreachable, so keeping it only leaks the closure.
            # snapshot + pop: two threads can fail on the same kernel
            # concurrently, and the second delete must be a no-op
            # (probe kernels key ("probe", spec, orig plan spec) — the
            # last slot matches either way)
            for k in list(self._pallas_sharded):
                if k[-1] in (plan.spec, orig):
                    self._pallas_sharded.pop(k, None)
            # evict FIRST: the jnp bind may itself raise PlanError (pallas
            # pads tiles where the jnp path demands divisibility), and the
            # poisoned entries must not survive that
            with self._cache_lock:
                self._param_cache.pop(pkey, None)
                self._launch_cache.pop(kernel.key, None)
            record_decision(stats, "pallas", "jnp_combine",
                            "pallas_combine", "pallas_exec_failed")
            kernel, params, plan = self._bind_jnp(plan, batch, S)
            self._remember(pkey, plan, kernel, params)
            req = self.launcher.submit(kernel, params, num_docs)
            req_out.append(req)
            packed = req.result()
        # coalescing outcome -> per-query stats (merged across shards and
        # servers; see QueryStats.merge for the sum-vs-max key split).
        # Accumulate instead of overwrite: a sliced combine calls this once
        # per slice and the query's launch story is the sum
        cur = {
            "launches": 1,
            "coalesced": 1 if req.batch_size > 1 else 0,
            "batchSize": req.batch_size,
            "launchesSaved": req.launches_saved,
            "queueWaitMs": round(req.queue_wait_ms, 3),
        }
        if stats.launch:
            for k, v in cur.items():
                if k in ("batchSize", "queueWaitMs"):
                    stats.launch[k] = max(stats.launch.get(k, 0), v)
                else:
                    stats.launch[k] = stats.launch.get(k, 0) + v
        else:
            stats.launch = cur
        # ONE D2H fetch decodes the entire query result
        return unpack_outputs(packed, plan.spec, num_seg=S)

    def _remember(self, pkey: Tuple, plan: SegmentPlan, kernel, params
                  ) -> None:
        """Insert/refresh both cache tiers (LRU-capped)."""
        with self._cache_lock:
            self._param_cache[pkey] = (plan, kernel.key, params)
            self._param_cache.move_to_end(pkey)
            if len(self._param_cache) > self._param_cache_cap:
                self._param_cache.popitem(last=False)

    def _launch_kernel(self, launch_key: Tuple, make_call, is_pallas: bool):
        """Get-or-create the launch-tier entry: the coalescable
        LaunchKernel every same-shape query (any literals) shares."""
        from pinot_tpu.parallel.launcher import LaunchKernel

        with self._cache_lock:
            kernel = self._launch_cache.get(launch_key)
            if kernel is not None:
                self._launch_cache.move_to_end(launch_key)
                return kernel
        call = make_call()
        with self._cache_lock:
            kernel = self._launch_cache.get(launch_key)
            if kernel is None:
                kernel = LaunchKernel(launch_key, call,
                                      is_pallas=is_pallas,
                                      max_batch=self._launch_max_batch)
                self._launch_cache[launch_key] = kernel
                if len(self._launch_cache) > self._launch_cache_cap:
                    self._launch_cache.popitem(last=False)
            return kernel

    def _bind_launch(self, plan: SegmentPlan, batch: SegmentBatch, S: int,
                     stats: Optional[QueryStats] = None):
        """-> (LaunchKernel, device params, effective plan): fused Pallas
        when eligible, jnp masked-vector combine otherwise. The kernel is
        shared across literals (its key is the literal-normalized plan
        fingerprint); the params are this query's runtime arrays,
        committed to device once (per-call H2D uploads are tunnel
        roundtrips the serving path cannot afford). The effective plan is
        what the output decodes against — the probe-narrowed plan when
        the group-range probe collapsed a large sparse key space, the
        input plan otherwise. Binding happens once per shape (cache
        miss), so the pallas decline recorded here is the per-shape
        decision — NOT re-counted on every repeat query."""
        bound = self._bind_pallas(plan, batch, S, stats)
        if bound is not None:
            return bound
        return self._bind_jnp(plan, batch, S)

    def _bind_jnp(self, plan: SegmentPlan, batch: SegmentBatch, S: int):
        """params, num_docs -> packed output via the jnp combine."""
        import jax

        from jax.sharding import NamedSharding, PartitionSpec as P

        # reject before paying dictionary unification + H2D staging
        if plan.spec[-1] % self.mesh.shape[DOC_AXIS]:
            raise PlanError(
                f"capacity {plan.spec[-1]} !| doc axis "
                f"{self.mesh.shape[DOC_AXIS]}")
        cols = {name: self._staged_column(batch, name, S)
                for name in plan.columns}
        col_layouts = tuple(sorted(
            (name, tuple(sorted(t.keys()))) for name, t in cols.items()))
        launch_key = ("jnp", plan.spec, col_layouts,
                      batch.metadata.segment_name, S)

        def make_call():
            fn = self.sharded_kernels.get(plan.spec, col_layouts)
            return lambda params, num_docs: fn(cols, params, num_docs)

        kernel = self._launch_kernel(launch_key, make_call, is_pallas=False)
        params = jax.device_put(
            tuple(plan.params), NamedSharding(self.mesh, P()))
        return kernel, params, plan

    def _bind_pallas(self, plan: SegmentPlan, batch: SegmentBatch, S: int,
                     stats: Optional[QueryStats] = None):
        """(LaunchKernel, device params, effective plan) via the sharded
        fused Pallas kernel (VERDICT r3 item 2: the flagship kernel serves
        the combine path), or None when the plan/backing isn't eligible —
        every None records its reason on the decision ledger (the "why is
        pallas_kernels 0" forensics the BENCH rounds were missing).

        Large sparse group spaces (SSB Q3.2/Q4.3) run the group-range
        PROBE first — the same fused scan with min/max-of-dictId rows over
        the whole batch, reduced across the mesh — and bind against the
        probe-narrowed plan, so the dense one-hot rung serves shapes the
        plan-time narrowing alone cannot admit."""
        import logging

        from dataclasses import replace

        import jax

        from jax.sharding import NamedSharding, PartitionSpec as P

        from pinot_tpu.engine.pallas_kernels import (
            _DeferredDecline,
            extract_plan,
            probe_narrowed_plan,
        )
        from pinot_tpu.parallel.combine import (
            build_sharded_pallas_kernel,
            build_sharded_pallas_probe,
        )

        def declined(reason: str) -> None:
            record_decision(stats, "pallas", "jnp_combine",
                            "pallas_combine", reason)

        interpret = self._pallas_mode()
        if interpret is None:
            # auto-disable on a non-TPU backend records under the BACKEND
            # point (the fallback stays explained per query) instead of
            # the pallas point, which is reserved for real eligibility
            # gaps; explicit config keeps the pallas-point record
            point = "backend" if self.use_pallas is None else "pallas"
            record_decision(stats, point, "jnp_combine", "pallas_combine",
                            "pallas_disabled_on_backend")
            return None
        orig_spec = getattr(plan, "_narrowed_from", plan.spec)
        if orig_spec in self._pallas_blocked:
            # preflight-seeded shapes carry their predicted rule code
            declined(self._pallas_blocked.reason_for(orig_spec))
            return None
        n_seg = self.mesh.shape[SEG_AXIS]
        n_doc = self.mesh.shape[DOC_AXIS]
        tiles = batch.pallas_tiles(min_tiles=n_doc)

        def spec_of(p):
            return p.spec(num_segs=S // n_seg, tiles_per_seg=tiles // n_doc,
                          interpret=bool(interpret))

        def run_probe(probe_pp):
            """Stage the probe's packed columns batch-wide and launch the
            sharded probe through the dispatcher; -> out_mm rows."""
            packed_cols, bits = [], []
            for nm in probe_pp.packed_names:
                staged = self._staged_pallas(batch, nm, S, "packed")
                if staged is None:
                    declined("pallas_column_not_packable")
                    return None
                packed_cols.append(staged[0])
                bits.append(staged[1])
            probe_spec = replace(spec_of(probe_pp), packed_bits=tuple(bits))
            launch_key = ("pallas_probe", probe_spec, orig_spec,
                          batch.metadata.segment_name, S)

            def make_call():
                kkey = ("probe", probe_spec, orig_spec)
                fn = self._pallas_sharded.get(kkey)
                if fn is None:
                    fn = build_sharded_pallas_probe(probe_spec, self.mesh)
                    self._pallas_sharded[kkey] = fn
                return lambda params, num_docs: fn(params, packed_cols,
                                                   num_docs)

            probe_kernel = self._launch_kernel(launch_key, make_call,
                                               is_pallas=True)
            pparams = jax.device_put(probe_pp.static_params,
                                     NamedSharding(self.mesh, P()))
            req = self.launcher.submit(probe_kernel, pparams,
                                       self._device_num_docs(batch, S))
            return np.asarray(req.result())

        eff = plan
        defer = _DeferredDecline(declined)
        pp = extract_plan(plan, batch, on_decline=defer,
                          lut_run_cap=self._pallas_lut_runs)
        if pp is None:
            if not defer.only_group_bound:
                defer.flush()
                return None
            try:
                res = probe_narrowed_plan(plan, batch, run_probe,
                                          self._pallas_lut_runs, declined)
            except Exception:
                logging.getLogger(__name__).exception(
                    "sharded pallas group probe failed; using jnp combine")
                declined("pallas_build_failed")
                return None
            if res is None:
                return None
            pp, eff = res
        try:
            packed_cols, bits = [], []
            for nm in pp.packed_names:
                staged = self._staged_pallas(batch, nm, S, "packed")
                if staged is None:
                    declined("pallas_column_not_packable")
                    return None
                packed_cols.append(staged[0])
                bits.append(staged[1])
            value_cols = []
            vlimbs = pp.value_limbs or (0,) * len(pp.value_names)
            for nm, limbs in zip(pp.value_names, vlimbs):
                if limbs:
                    staged = self._staged_pallas(batch, nm, S, "limb",
                                                 limbs=limbs)
                    if staged is None:
                        declined("pallas_value_layout_unsupported")
                        return None
                    value_cols.extend(staged)
                    continue
                staged = self._staged_pallas(batch, nm, S, "value")
                if staged is None:
                    declined("pallas_value_layout_unsupported")
                    return None
                value_cols.append(staged)
            spec = replace(spec_of(pp), packed_bits=tuple(bits))
            launch_key = ("pallas", spec, eff.spec,
                          batch.metadata.segment_name, S)

            def make_call():
                # keyed by (spec, eff.spec): the closure bakes the plan
                # spec into the output layout, and distinct plans CAN
                # collide on spec alone (num_groups_padded rounds to 128)
                kkey = (spec, eff.spec)
                fn = self._pallas_sharded.get(kkey)
                if fn is None:
                    fn = build_sharded_pallas_kernel(spec, eff.spec,
                                                     self.mesh)
                    self._pallas_sharded[kkey] = fn
                return lambda params, num_docs: fn(params, packed_cols,
                                                   value_cols, num_docs)

            kernel = self._launch_kernel(launch_key, make_call,
                                         is_pallas=True)
            params = jax.device_put(pp.static_params,
                                    NamedSharding(self.mesh, P()))
        except Exception:
            logging.getLogger(__name__).exception(
                "sharded pallas build failed; using jnp combine")
            declined("pallas_build_failed")
            return None
        return kernel, params, eff

    def _staged_pallas(self, batch: SegmentBatch, name: str, S: int,
                       kind: str, limbs: int = 0):
        """Device-committed pallas-layout arrays per (batch, column, S):
        kind 'packed' -> (words, bits); kind 'value' -> values array;
        kind 'limb' -> list of ``limbs`` i32 limb planes (i64-staged
        columns riding the multi-limb accumulation)."""
        import jax

        from jax.sharding import NamedSharding, PartitionSpec as P

        key = (batch.metadata.segment_name, f"__pallas_{kind}:{name}", S)
        with self._device_cols_lock:
            staged = self._device_cols.get(key)
        if staged is None:
            sharding = NamedSharding(
                self.mesh, P(SEG_AXIS, DOC_AXIS, None, None))
            n_doc = self.mesh.shape[DOC_AXIS]
            if kind == "packed":
                host = batch.packed_column_batch(name, pad_segments=S,
                                                 min_tiles=n_doc)
                if host is None:
                    return None
                words, bits = host
                staged = (jax.device_put(words, sharding), bits)
            elif kind == "limb":
                host = batch.value_limb_batch(name, limbs, pad_segments=S,
                                              min_tiles=n_doc)
                if host is None:
                    return None
                staged = [jax.device_put(p, sharding) for p in host]
            else:
                host = batch.value_column_batch(name, pad_segments=S,
                                                min_tiles=n_doc)
                if host is None:
                    return None
                staged = jax.device_put(host, sharding)
            with self._device_cols_lock:
                self._device_cols[key] = staged
        return staged

    def _device_num_docs(self, batch: SegmentBatch, S: int):
        """Per-segment doc counts committed to device once per (batch, S)."""
        import jax

        from jax.sharding import NamedSharding, PartitionSpec as P

        key = (batch.metadata.segment_name, "__num_docs", S)
        with self._device_cols_lock:
            nd = self._device_cols.get(key)
        if nd is None:
            nd = jax.device_put(batch.num_docs_array(pad_to=S),
                                NamedSharding(self.mesh, P(SEG_AXIS)))
            with self._device_cols_lock:
                self._device_cols[key] = nd
        return nd

    def _staged_column(self, batch: SegmentBatch, name: str, S: int) -> Dict:
        key = (batch.metadata.segment_name, name, S)
        with self._device_cols_lock:
            tree = self._device_cols.get(key)
        if tree is None:
            tree = device_stage_column(
                self.mesh, batch.stacked_column(name, pad_segments=S))
            with self._device_cols_lock:
                self._device_cols[key] = tree
        return tree

    def evict_batches(self) -> None:
        with self._batches_lock:
            batches = list(self._batches.values())
            self._batches.clear()
        with self._device_cols_lock:
            self._device_cols.clear()
        with self._cache_lock:
            self._param_cache.clear()
            self._launch_cache.clear()
        for b in batches:
            self.residency.discard(b.metadata.segment_name)

    # -- cross-query column dedup (per-segment path borrows batch copies) ----
    def _borrow_batch_column(self, segment: ImmutableSegment, name: str):
        """A StagedSegment column served FROM a resident batch's sharded
        device copy instead of a second host->device staging pass. Only
        sound when the device bytes coincide: SV column, the batch's
        padded capacity equals the segment's, and — for dictionary
        columns — this segment's remap into the unified dictionary is the
        identity (its value set IS the union), so unified dictIds equal
        segment dictIds. The unified dictvals array is shared outright
        (the same device buffer backs both paths: real HBM dedup); the
        forward row is a device-side slice (no H2D, no host remap).
        Returns a StagedColumn or None when nothing compatible is
        resident."""
        from pinot_tpu.engine.staging import StagedColumn, staged_int_dtype

        with self._batches_lock:
            batches = [(k, b) for k, b in self._batches.items()
                       if segment.segment_name in k]
        for key, batch in batches:
            try:
                i = key.index(segment.segment_name)
            except ValueError:
                continue
            if batch.segments[i] is not segment:
                continue  # reloaded segment: the batch copy is stale
            if batch.capacity != segment.padded_capacity:
                continue  # row slice would have the wrong length
            bname = batch.metadata.segment_name
            with self._device_cols_lock:
                tree = next((v for k2, v in self._device_cols.items()
                             if k2[0] == bname and k2[1] == name), None)
            if not isinstance(tree, dict) or "fwd" not in tree:
                continue
            cm = segment.metadata.columns.get(name)
            if cm is None or not cm.single_value:
                continue
            if cm.has_dictionary:
                remaps = batch._remaps.get(name)
                if remaps is None:
                    continue
                r = remaps[i]
                if (len(r) != cm.cardinality
                        or int(r[-1]) != cm.cardinality - 1
                        or not np.array_equal(r, np.arange(cm.cardinality,
                                                           dtype=r.dtype))):
                    continue  # unified ids differ from segment ids
                want_dtype = np.dtype(np.int32)
            elif cm.data_type.is_integral:
                want_dtype = staged_int_dtype(cm)
            else:
                want_dtype = np.dtype(np.float64)
            fwd = tree["fwd"]
            if fwd.dtype != want_dtype:
                continue  # merged stats narrowed differently: not the
                # same bytes the per-segment contract stages
            sc = StagedColumn(data_type=cm.data_type,
                              has_dictionary=cm.has_dictionary)
            sc.fwd = fwd[i]
            if cm.has_dictionary and cm.data_type.is_numeric:
                dv = tree.get("dictvals")
                if dv is None:
                    continue
                sc.dictvals = dv  # SAME device buffer: zero-copy dedup
            if cm.has_nulls:
                nb = tree.get("null")
                if nb is None:
                    continue
                sc.null = nb[i]
            self._borrows += 1
            self.residency.note_borrow(bname)
            return sc
        return None


class _BatchHostImage:
    """Host-RAM tier image of a demoted sharded batch: the SegmentBatch
    object itself IS the host copy — its ``_stacked`` numpy trees and
    unified dictionaries are exactly what ``device_stage_column`` re-puts,
    so promotion (``batch_for`` -> ``_adopt_host_batch``) skips dictionary
    unification / remapping / stacking and pays only H2D. The residency
    manager byte-accounts the retained host arrays against the host
    budget; ``segment_names`` lets ``evict()`` drop every image containing
    a removed/reloaded segment."""

    __slots__ = ("batch", "segment_names")

    def __init__(self, batch: SegmentBatch):
        self.batch = batch
        self.segment_names = tuple(s.segment_name for s in batch.segments)

    def matches(self, segments) -> bool:
        b = self.batch
        return (b is not None and segments is not None
                and len(b.segments) == len(segments)
                and all(c is s for c, s in zip(b.segments, segments)))

    def nbytes(self) -> int:
        b = self.batch
        if b is None:
            return 0
        total = 0
        for tree in b._stacked.values():
            for k, v in tree.items():
                if k != "__S":
                    total += int(getattr(v, "nbytes", 0) or 0)
        return total

    def release(self) -> None:
        self.batch = None


class _BatchResident:
    """Residency adapter for one SegmentBatch's device-column set: nbytes
    walks the executor's ``_device_cols`` entries for the batch, release
    drops the batch wholesale (arrays + compiled closures). Lock order is
    residency lock -> executor cache locks, never the reverse."""

    __slots__ = ("executor", "batch")

    def __init__(self, executor: ShardedQueryExecutor, batch: SegmentBatch):
        self.executor = executor
        self.batch = batch

    def nbytes(self) -> int:
        name = self.batch.metadata.segment_name
        with self.executor._device_cols_lock:
            staged = [v for k, v in self.executor._device_cols.items()
                      if k[0] == name]
        return sum(_tree_nbytes(v) for v in staged)

    def release(self) -> None:
        self.executor._evict_batch(self.batch)

    def demote(self) -> Optional[_BatchHostImage]:
        """Demotion to the host-RAM tier: the batch's stacked numpy trees
        (host-resident build byproducts) become the image; the device
        arrays AND the compiled closures that pin them drop through the
        normal batch eviction. Returns None when nothing was stacked —
        nothing worth keeping, plain release semantics apply."""
        image = _BatchHostImage(self.batch)
        self.executor._evict_batch(self.batch)
        return image if image.nbytes() > 0 else None


def _tree_nbytes(obj) -> int:
    """Device bytes of a staged-column value: dict trees of arrays, the
    (words, bits) packed tuples, or bare arrays."""
    if obj is None:
        return 0
    if isinstance(obj, dict):
        return sum(_tree_nbytes(v) for v in obj.values())
    if isinstance(obj, (tuple, list)):
        return sum(_tree_nbytes(v) for v in obj)
    return int(getattr(obj, "nbytes", 0) or 0)
