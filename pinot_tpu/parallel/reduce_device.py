"""Device-resident broker reduce: group-by merge over the broker mesh.

The "last hop over ICI" (ROADMAP): when the embedded cluster's servers
and broker share the process, per-server group-by partials are already
host arrays that never crossed a wire — so the broker merge can stay on
the same device substrate the per-segment kernels used, instead of the
PR-14 host lexsort. The shape mirrors the reference's broker-side
``IndexedTable`` upsert-merge (GroupByDataTableReducer.java:66) mapped
onto ``shard_map`` + ICI collectives, the same machinery as
``parallel/combine.py``'s cross-segment merge:

- keys composite-encode to ONE non-negative i64 per row (injective
  codes: first-occurrence ranks for str, ``np.unique`` ranks for f64,
  min-offset for i64 — equal rows and ONLY equal rows collide, which is
  all the contract needs because the caller's stable
  ``argsort(first_idx)`` restores oracle insertion order afterwards);
- the concatenated (keys, states, arrival-index) block pads to a shared
  pow2 capacity and scatters over the 1-D broker mesh (``MERGE_AXIS``);
- **dense rung** (composite space <= ``DEFAULT_DEVICE_REDUCE_DENSE_SLOTS``):
  each device ``segment_sum``/``min``/``max``-scatters its shard into the
  full [space] slot array and partials merge over the mesh axis —
  ``psum``/``pmin``/``pmax`` for small slot spaces (replicated output),
  an ``all_to_all`` slice exchange + local fold past ``_PSUM_SLOTS``
  (each device merges one slot-space slice, so the combine moves each
  slot once over ICI instead of replicating the full array to every
  device) — the group-by analogue of the dense aggregation rung in
  ``engine/kernels.py``;
- **sort rung** (larger spaces): ``all_gather`` the composite keys, one
  global argsort + first-occurrence compaction + rank scatter — the
  ``_sparse_cross_combine`` shape from combine.py over i64 keys.

Only shapes whose folds are provably order-independent reach here (the
caller in ``broker/reduce.py`` declines i64 near-overflow sums,
non-integral f64 sums, NaN keys, obj states — each with a registered
``reduce:device->host:<reason>`` ledger record), so the merged states
are bit-identical to the host fold regardless of reduction order.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.common.bounds import (
    F64_EXACT_INT_BOUND,
    I64_KEY_SPACE_BOUND,
    I64_PAD_SENTINEL,
)
from pinot_tpu.spi.config import CommonConstants

# the broker merge mesh is 1-D: every device holds one shard of the
# concatenated (keys, states) block and partials meet over this axis
MERGE_AXIS = "merge"

# composite keys are non-negative and < I64_KEY_SPACE_BOUND
# (encode_composite_keys declines anything larger), so i64 max is a safe
# pad/sentinel key that sorts strictly after every live key
_PAD_KEY = I64_PAD_SENTINEL

# caps (spi/config.py): dense-rung slot budget and padded-row ceiling
DENSE_SLOTS = CommonConstants.DEFAULT_DEVICE_REDUCE_DENSE_SLOTS
MAX_MERGE_ROWS = CommonConstants.DEFAULT_DEVICE_REDUCE_MAX_ROWS

# dense-rung combine flavor split: slot spaces at or under this budget
# all-reduce with psum/pmin/pmax (replicated output, no reshard); larger
# spaces exchange slot-space slices with all_to_all and fold locally, so
# each slot crosses ICI once instead of being replicated to every device
_PSUM_SLOTS = 1 << 12


_MESH = None
_MESH_FAILED = False
_KERNELS: Dict[Tuple, object] = {}


def broker_mesh():
    """The (cached) 1-D broker merge mesh over every visible device, or
    None when no usable device backend exists — the caller records
    ``reduce_device_mesh_unavailable`` and serves from the host path."""
    global _MESH, _MESH_FAILED
    if _MESH is not None or _MESH_FAILED:
        return _MESH
    try:
        from pinot_tpu.engine import ensure_x64

        ensure_x64()  # i64 keys/sums through the collectives
        import jax

        from jax.sharding import Mesh

        devices = jax.devices()
        if not devices:
            raise RuntimeError("no devices")
        _MESH = Mesh(np.asarray(devices), (MERGE_AXIS,))
    except Exception:
        _MESH_FAILED = True
        _MESH = None
    return _MESH


def reset_mesh_cache() -> None:
    """Test hook: drop the cached mesh + compiled kernels."""
    global _MESH, _MESH_FAILED
    _MESH = None
    _MESH_FAILED = False
    _KERNELS.clear()


def encode_composite_keys(key_cols: List[np.ndarray]
                          ) -> Tuple[Optional[np.ndarray], int]:
    """Concatenated key columns -> (one non-negative i64 composite per
    row, composite space size), or ``(None, 0)`` when the space cannot
    fit the i64 budget (the caller declines
    ``reduce_device_key_space_overflow``).

    Column encodings only need to be INJECTIVE — equal rows and ONLY
    equal rows collide on the composite (the caller restores oracle
    insertion order from ``argsort(first_idx)``, so code ORDER never
    leaks into the output): str columns take first-occurrence ranks
    from one dict pass (no O(n log n) string sort), f64 columns
    rank-encode through ``np.unique`` (which merges -0.0/0.0 exactly
    like the host lexsort runs do), i64 columns shift by their minimum.
    NaN keys never reach here (pre-declined)."""
    n = int(key_cols[0].shape[0]) if key_cols else 0
    comp = np.zeros(n, dtype=np.int64)
    space = 1
    for a in key_cols:
        if a.dtype.kind == "i":
            lo = int(a.min())
            r = int(a.max()) - lo + 1
            codes = a.astype(np.int64) - lo
        elif a.dtype.kind == "f":
            _, inv = np.unique(a, return_inverse=True)
            codes = inv.astype(np.int64).reshape(n)
            r = int(codes.max()) + 1 if n else 1
        else:
            lut: Dict = {}
            codes = np.fromiter(
                (lut.setdefault(v, len(lut)) for v in a.tolist()),
                dtype=np.int64, count=n)
            r = len(lut) if n else 1
        if r < 1 or space > I64_KEY_SPACE_BOUND // r:
            return None, 0
        comp = comp * r + codes
        space *= r
    return comp, space


def f64_sum_exact(arr: np.ndarray) -> bool:
    """True when folding ``arr`` is order-independent in f64: finite,
    integral-valued, and total absolute mass under 2^53 (every partial
    sum is then an exactly-representable integer)."""
    if not bool(np.isfinite(arr).all()):
        return False
    if not bool((arr == np.floor(arr)).all()):
        return False
    return float(np.abs(arr).sum()) < F64_EXACT_INT_BOUND


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


def _merge_cap(n: int, n_dev: int) -> int:
    """Padded row capacity: ``n`` rounded up to an eighth-of-octave step
    (the next multiple of ``next_pow2(n) / 8``). At most 8 distinct caps
    per power of two keeps the compiled-kernel cache bounded like pure
    pow2 padding would, but the pad tail every scatter still has to
    chew through stays under 12.5% instead of up to 100%. Steps are
    clamped to ``n_dev`` (a pow2), so ``cap % n_dev == 0`` always."""
    step = max(_next_pow2(n) // 8, n_dev, 1)
    return -(-max(n, 1) // step) * step


def _pad_identity(arr: np.ndarray, op: str) -> Tuple[int, float]:
    """Fold identity for the pad tail (pads scatter into a dropped slot
    either way; the identity keeps them inert even there)."""
    if op == "sum":
        return 0
    if arr.dtype.kind == "i":
        info = np.iinfo(arr.dtype)
        return info.max if op == "min" else info.min
    return np.inf if op == "min" else -np.inf


def _axis_reduce(v, op: str, axis, mesh):
    """psum/pmin/pmax over one mesh axis (size-1 axes are a no-op — the
    single-device broker mesh still runs the same program)."""
    import jax

    if mesh.shape[axis] == 1:
        return v
    if op == "sum":
        return jax.lax.psum(v, axis)
    if op == "min":
        return jax.lax.pmin(v, axis)
    if op == "max":
        return jax.lax.pmax(v, axis)
    raise AssertionError(op)


def _slice_reduce(v, op: str, axis, mesh):
    """all_to_all slice exchange + local fold over one mesh axis: pad
    the per-device [m] slot partials to an axis-size multiple, trade
    slot-space slices so every device holds all partials of ONE slice,
    and fold them locally — each slot crosses ICI once (vs psum's
    replicated output), and the result shards as [m_pad // n_dev] per
    device (``out_specs=P(axis)`` reassembles the [m_pad] array; the
    pad tail carries the fold identity, so the merged arrival-index
    tail stays at ``segment_min``'s identity and the host's live-slot
    compaction never selects it)."""
    import jax
    import jax.numpy as jnp

    n_dev = mesh.shape[axis]
    m = int(v.shape[0])
    pad_to = -(-m // n_dev) * n_dev
    if op == "sum":
        fill = 0
    elif jnp.issubdtype(v.dtype, jnp.integer):
        info = jnp.iinfo(v.dtype)
        fill = info.max if op == "min" else info.min
    else:
        fill = jnp.inf if op == "min" else -jnp.inf
    v = jnp.pad(v, (0, pad_to - m), constant_values=fill)
    v = v.reshape(n_dev, pad_to // n_dev)
    v = jax.lax.all_to_all(v, axis, 0, 0)
    red = {"sum": jnp.sum, "min": jnp.min, "max": jnp.max}[op]
    return red(v, axis=0)


def _build_dense_merge(mesh, space: int, ops: Tuple[str, ...],
                       dtypes: Tuple[str, ...], a2a: bool):
    """Dense rung: each device scatters its local shard into the FULL
    [space] slot array (one segment op per aggregation + arrival-index
    min), then slot partials combine over the mesh axis — psum/pmin/
    pmax (replicated [space] outputs) for small spaces,
    ``_slice_reduce``'s all_to_all exchange (sharded outputs) when
    ``a2a``. The merged arrival-index doubles as the live-slot mask
    (``segment_min``'s identity, i32 max, survives ONLY in slots no
    real row touched — pads all carry ``comp == space``, the dropped
    slot), so no separate occupancy scatter is needed; the host
    compacts live slots either way."""
    import jax
    import jax.numpy as jnp

    from jax.sharding import PartitionSpec as P

    from pinot_tpu.parallel.combine import _shard_map

    seg_op = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min,
              "max": jax.ops.segment_max}

    def _combine(v, op):
        # axis literals live HERE (not threaded further) so the lint
        # family's one-hop mesh-axis resolution sees them
        if a2a:
            return _slice_reduce(v, op, MERGE_AXIS, mesh)
        return _axis_reduce(v, op, MERGE_AXIS, mesh)

    def per_device(comp, idx, vals):
        # pads carry comp == space: one extra slot swallows them
        min_idx = jax.ops.segment_min(idx, comp,
                                      num_segments=space + 1)[:space]
        min_idx = _combine(min_idx, "min")
        leaves = tuple(
            _combine(seg_op[op](v, comp, num_segments=space + 1)[:space], op)
            for v, op in zip(vals, ops))
        return min_idx, leaves

    sharded = _shard_map(
        per_device, mesh=mesh,
        in_specs=(P(MERGE_AXIS), P(MERGE_AXIS), [P(MERGE_AXIS)] * len(ops)),
        out_specs=P(MERGE_AXIS) if a2a else P())
    return jax.jit(sharded)


def _build_sort_merge(mesh, cap: int, ops: Tuple[str, ...],
                      dtypes: Tuple[str, ...]):
    """Sort rung (composite spaces past the dense slot budget): gather
    the padded [cap] composite block over the mesh axis, ONE global
    argsort, first-occurrence compaction, and a rank scatter per
    aggregation — the ``_sparse_cross_combine`` shape from combine.py
    over i64 keys. Pad keys (i64 max) sort strictly last, so ranks
    0..n_live-1 enumerate the groups in ascending composite order."""
    import jax
    import jax.numpy as jnp

    from jax.sharding import PartitionSpec as P

    from pinot_tpu.parallel.combine import _shard_map

    seg_op = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min,
              "max": jax.ops.segment_max}
    SENT = jnp.int64(_PAD_KEY)

    def _gather(x):
        if mesh.shape[MERGE_AXIS] == 1:
            return x
        return jax.lax.all_gather(x, MERGE_AXIS, tiled=True)

    def per_device(comp, idx, vals):
        keys = _gather(comp)                               # [cap]
        order = jnp.argsort(keys)
        sk = keys[order]
        valid = sk != SENT
        first = valid & jnp.concatenate(
            [jnp.ones((1,), dtype=bool), sk[1:] != sk[:-1]])
        n_live = first.sum(dtype=jnp.int32)
        rank = jnp.cumsum(first) - 1                       # [cap]
        rank = jnp.where(valid, rank, cap)                 # pad bucket
        min_idx = jax.ops.segment_min(_gather(idx)[order], rank,
                                      num_segments=cap + 1)[:cap]
        leaves = tuple(
            seg_op[op](_gather(v)[order], rank,
                       num_segments=cap + 1)[:cap]
            for v, op in zip(vals, ops))
        return n_live, min_idx, leaves

    sharded = _shard_map(
        per_device, mesh=mesh,
        in_specs=(P(MERGE_AXIS), P(MERGE_AXIS), [P(MERGE_AXIS)] * len(ops)),
        out_specs=P())
    return jax.jit(sharded)


def device_group_merge(mesh, comp: np.ndarray, space: int,
                       vals: List[np.ndarray], ops: List[str]
                       ) -> Tuple[np.ndarray, List[np.ndarray]]:
    """Merge the concatenated group-by block on device.

    -> ``(first_idx, folded)``: per merged group (in ascending composite
    order — any fixed enumeration works, the caller's stable
    ``argsort(first_idx)`` restores oracle insertion order), the
    earliest input row index and one exactly-folded state array per
    aggregation — the same contract as the host path's
    ``lexsort_runs`` + ``fold_grouped_runs`` + ``order[starts]``."""
    n = int(comp.shape[0])
    n_dev = int(mesh.shape[MERGE_AXIS])
    cap = _merge_cap(n, n_dev)
    rung = "dense" if space <= DENSE_SLOTS else "sort"

    comp_p = np.full(cap, space if rung == "dense" else _PAD_KEY,
                     dtype=np.int64)
    comp_p[:n] = comp
    idx_p = np.full(cap, np.iinfo(np.int32).max, dtype=np.int32)
    idx_p[:n] = np.arange(n, dtype=np.int32)
    vals_p = []
    for v, op in zip(vals, ops):
        vp = np.full(cap, _pad_identity(v, op), dtype=v.dtype)
        vp[:n] = v
        vals_p.append(vp)

    a2a = rung == "dense" and n_dev > 1 and space > _PSUM_SLOTS
    dtypes = tuple(str(v.dtype) for v in vals)
    key = (id(mesh), rung, a2a, cap, space if rung == "dense" else 0,
           tuple(ops), dtypes)
    fn = _KERNELS.get(key)
    if fn is None:
        if rung == "dense":
            fn = _build_dense_merge(mesh, space, tuple(ops), dtypes, a2a)
        else:
            fn = _build_sort_merge(mesh, cap, tuple(ops), dtypes)
        _KERNELS[key] = fn
    if rung == "dense":
        min_idx, leaves = fn(comp_p, idx_p, vals_p)
        # live slots are exactly those some real row touched: the
        # merged arrival-index still at segment_min's identity marks
        # an untouched (or pad-tail) slot
        mi = np.asarray(min_idx)
        live = np.flatnonzero(mi < np.iinfo(np.int32).max)
        first_idx = mi[live].astype(np.int64)
        folded = [np.asarray(lf)[live] for lf in leaves]
    else:
        n_live, min_idx, leaves = fn(comp_p, idx_p, vals_p)
        k = int(n_live)
        first_idx = np.asarray(min_idx)[:k].astype(np.int64)
        folded = [np.asarray(lf)[:k] for lf in leaves]
    return first_idx, folded
