"""Distributed execution: segment batches sharded over TPU meshes.

The TPU-native combine layer (ref: SURVEY.md §2.12 parallelism inventory):
segments stack into unified-dictionary batches (batch.py), shard over a
``jax.sharding.Mesh`` with ``shard_map``, and merge partial aggregates via
ICI collectives (combine.py). ``ShardedQueryExecutor`` (executor.py) is the
drop-in server executor over that path.
"""

from pinot_tpu.parallel.batch import SegmentBatch
from pinot_tpu.parallel.combine import (
    DOC_AXIS,
    SEG_AXIS,
    build_sharded_kernel,
    make_combine_mesh,
)
from pinot_tpu.parallel.executor import ShardedQueryExecutor
from pinot_tpu.parallel.launcher import (
    LaunchKernel,
    LaunchScheduler,
    launcher_for_mesh,
)

__all__ = [
    "SegmentBatch",
    "ShardedQueryExecutor",
    "LaunchKernel",
    "LaunchScheduler",
    "launcher_for_mesh",
    "make_combine_mesh",
    "build_sharded_kernel",
    "SEG_AXIS",
    "DOC_AXIS",
]
