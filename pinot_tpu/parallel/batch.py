"""Segment batches: N segments unified into one device-executable block.

The device-side combine (ref: ``BaseCombineOperator.java:55`` merging
per-segment partials thread-by-thread) needs per-segment partial states that
are *directly addable* on device. Per-segment dictionaries make dictIds
incomparable across segments, so a batch re-keys every dictionary column into
a **unified table-level dictionary** (host-side merge of the per-segment
sorted dictionaries) and stacks the remapped forward indexes into
``[num_segments, capacity]`` arrays. Group-by keys and DISTINCTCOUNT
presence bitmaps composed from unified dictIds then merge across
segments/devices with plain ``sum``/``max`` — i.e. ``psum``/``pmax`` over
ICI (SURVEY.md §2.12 "Intra-server segment parallelism").

A batch duck-types the segment interfaces the planner reads
(``metadata.column()``, ``data_source().dictionary``, ``padded_capacity``)
so ``plan_segment`` plans once against the unified key space.
"""

from __future__ import annotations

from collections.abc import Mapping
from dataclasses import replace
from typing import Any, Dict, List, Optional

import numpy as np

from pinot_tpu.segment.dictionary import (
    Dictionary,
    NumericDictionary,
    StringDictionary,
)
from pinot_tpu.segment.immutable import ImmutableSegment
from pinot_tpu.segment.metadata import ColumnMetadata, SegmentMetadata
from pinot_tpu.spi.data import DataType


class _LazyColumnMap(Mapping):
    """Column name -> merged ColumnMetadata, merged on first access (wide
    tables don't pay dictionary unification for columns a query never
    touches — mirrors the lazy per-column staging in engine/staging.py)."""

    def __init__(self, batch: "SegmentBatch"):
        self._batch = batch

    def __getitem__(self, name: str) -> "ColumnMetadata":
        return self._batch._merged_column(name)

    def __iter__(self):
        return iter(self._batch.segments[0].metadata.columns)

    def __len__(self) -> int:
        return len(self._batch.segments[0].metadata.columns)


class BatchDataSource:
    """Column access over the whole batch (planner-facing)."""

    def __init__(self, batch: "SegmentBatch", name: str):
        self.name = name
        self.metadata = batch.metadata.column(name)
        self.dictionary: Optional[Dictionary] = batch.unified_dictionary(name)


class SegmentBatch:
    """N same-table segments, re-keyed to unified dictionaries and stacked
    into fixed-shape arrays ready for sharded device execution."""

    def __init__(self, segments: List[ImmutableSegment]):
        if not segments:
            raise ValueError("empty segment batch")
        for s in segments:
            if getattr(s, "is_mutable", False):
                # consuming segments grow under the batch's feet; the frozen
                # stacked arrays would serve stale data (host path serves them)
                raise ValueError(f"mutable segment {s.segment_name!r} "
                                 "cannot join a device batch")
            if getattr(s, "valid_doc_ids", None) is not None:
                raise ValueError(f"upsert segment {s.segment_name!r} "
                                 "cannot join a device batch")
        self.segments = segments
        first = segments[0].metadata
        cols = set(first.columns.keys())
        for s in segments[1:]:
            if set(s.metadata.columns.keys()) != cols:
                raise ValueError("segments in a batch must share a schema")

        self.capacity = max(s.padded_capacity for s in segments)
        self._dicts: Dict[str, Optional[Dictionary]] = {}
        # per column: list of per-segment remap arrays (old dictId -> unified)
        self._remaps: Dict[str, List[np.ndarray]] = {}
        self._merged: Dict[str, ColumnMetadata] = {}
        self._stacked: Dict[str, Dict[str, np.ndarray]] = {}
        self._data_sources: Dict[str, BatchDataSource] = {}

        self.metadata = SegmentMetadata(
            segment_name="batch(" + ",".join(s.segment_name for s in segments) + ")",
            table_name=first.table_name,
            schema=first.schema,
            num_docs=sum(s.num_docs for s in segments),
            padded_capacity=self.capacity,
            time_column=first.time_column,
            columns=_LazyColumnMap(self),
        )

    # -- segment duck-type (planner interface) -----------------------------
    @property
    def segment_name(self) -> str:
        return self.metadata.segment_name

    @property
    def num_docs(self) -> int:
        return self.metadata.num_docs

    @property
    def padded_capacity(self) -> int:
        return self.capacity

    @property
    def num_segments(self) -> int:
        return len(self.segments)

    def data_source(self, column: str) -> BatchDataSource:
        ds = self._data_sources.get(column)
        if ds is None:
            self.metadata.column(column)
            ds = BatchDataSource(self, column)
            self._data_sources[column] = ds
        return ds

    def unified_dictionary(self, column: str) -> Optional[Dictionary]:
        self._merged_column(column)
        return self._dicts.get(column)

    def num_docs_array(self, pad_to: int = 0) -> np.ndarray:
        """[S] per-segment doc counts (0 for pad segments)."""
        n = max(pad_to, self.num_segments)
        out = np.zeros(n, dtype=np.int32)
        for i, s in enumerate(self.segments):
            out[i] = s.num_docs
        return out

    # -- unified dictionary construction -----------------------------------
    def _merged_column(self, name: str) -> ColumnMetadata:
        cm = self._merged.get(name)
        if cm is None:
            cm = self._merge_column(name)
            self._merged[name] = cm
        return cm

    def _merge_column(self, name: str) -> ColumnMetadata:
        cms = [s.metadata.column(name) for s in self.segments]
        base = cms[0]
        for cm in cms[1:]:
            if (cm.data_type is not base.data_type
                    or cm.single_value != base.single_value
                    or cm.has_dictionary != base.has_dictionary):
                raise ValueError(f"column {name!r} layout differs across batch")
        has_nulls = any(cm.has_nulls for cm in cms)
        max_mv = max(cm.max_num_multi_values for cm in cms)
        total_entries = sum(cm.total_number_of_entries for cm in cms)

        if base.has_dictionary:
            dicts = [s.data_source(name).dictionary for s in self.segments]
            unified, remaps = _merge_dictionaries(dicts, base.data_type)
            self._dicts[name] = unified
            self._remaps[name] = remaps
            card = unified.cardinality
            min_v, max_v = unified.min_value, unified.max_value
        else:
            card = sum(cm.cardinality for cm in cms)
            vals = [cm.min_value for cm in cms if cm.min_value is not None]
            min_v = min(vals) if vals else None
            vals = [cm.max_value for cm in cms if cm.max_value is not None]
            max_v = max(vals) if vals else None

        return replace(
            base, cardinality=card, min_value=min_v, max_value=max_v,
            is_sorted=False, has_nulls=has_nulls,
            has_inverted_index=False,
            max_num_multi_values=max_mv,
            total_number_of_entries=total_entries)

    # -- stacked device-ready arrays ---------------------------------------
    def stacked_column(self, name: str, pad_segments: int = 0) -> Dict[str, np.ndarray]:
        """The batch analogue of ``StagedColumn.tree()``: per-segment arrays
        get a leading ``[S]`` axis; shared arrays (``dictvals``) do not.
        ``pad_segments`` extends S with empty segments (num_docs=0)."""
        cached = self._stacked.get(name)
        if cached is not None and cached["__S"] >= max(pad_segments, self.num_segments):
            out = dict(cached)
            out.pop("__S")
            return out

        cm = self.metadata.column(name)
        S = max(pad_segments, self.num_segments)
        cap = self.capacity
        out: Dict[str, np.ndarray] = {}

        if cm.single_value:
            if cm.has_dictionary:
                fwd = np.zeros((S, cap), dtype=np.int32)
                for i, seg in enumerate(self.segments):
                    raw = np.asarray(seg.data_source(name).forward_index)
                    fwd[i, :raw.shape[0]] = self._remaps[name][i][raw]
            else:
                # same narrowing contract as engine/staging.py: integral by
                # stats bounds; raw floats stay f64 for exact filter literals
                from pinot_tpu.engine.staging import staged_int_dtype

                dt = (staged_int_dtype(cm) if cm.data_type.is_integral
                      else np.float64)
                fwd = np.zeros((S, cap), dtype=dt)
                for i, seg in enumerate(self.segments):
                    raw = np.asarray(seg.data_source(name).forward_index)
                    fwd[i, :raw.shape[0]] = raw.astype(dt)
            out["fwd"] = fwd
        else:
            max_mv = max(cm.max_num_multi_values, 1)
            mv = np.zeros((S, cap, max_mv), dtype=np.int32)
            cnt = np.zeros((S, cap), dtype=np.int32)
            for i, seg in enumerate(self.segments):
                dense, counts = seg.data_source(name).dense_mv()
                remapped = self._remaps[name][i][dense]
                mv[i, :dense.shape[0], :dense.shape[1]] = remapped
                cnt[i, :counts.shape[0]] = counts
            out["mv"] = mv
            out["mvcount"] = cnt

        if cm.has_dictionary and cm.data_type.is_numeric:
            from pinot_tpu.engine.staging import staged_int_dtype

            vals = np.asarray(self._dicts[name].device_values())
            out["dictvals"] = vals.astype(
                staged_int_dtype(cm) if cm.data_type.is_integral
                else np.float32)

        if cm.has_nulls:
            nb = np.zeros((S, cap), dtype=bool)
            for i, seg in enumerate(self.segments):
                b = seg.data_source(name).null_bitmap
                if b is not None:
                    nb[i, :np.asarray(b).shape[0]] = np.asarray(b)
            out["null"] = nb

        self._stacked[name] = dict(out, __S=S)
        return out


    # -- pallas layouts (planar bit-packed / decoded values), batch-wide ----
    def pallas_capacity(self) -> int:
        """Per-segment doc capacity padded to whole Pallas tiles."""
        from pinot_tpu.engine.staging import PALLAS_TILE

        return -(-self.capacity // PALLAS_TILE) * PALLAS_TILE

    def packed_column_batch(self, name: str, pad_segments: int = 0,
                            min_tiles: int = 1):
        """(words [S, tiles, W//128, 128] u32, bits) planar bit-packed
        UNIFIED dictIds for the sharded fused kernel, or None when the
        column has no dictionary / isn't SV (see staging.PackedColumn for
        the per-segment analogue and the planar layout contract).
        ``min_tiles`` rounds the tile count up (doc-axis sharding needs
        tiles % mesh doc size == 0; pad tiles mask out via num_docs)."""
        from pinot_tpu.engine.staging import PALLAS_TILE, pack_bits

        cm = self.metadata.column(name)
        if not (cm.has_dictionary and cm.single_value):
            return None
        fwd = self.stacked_column(name, pad_segments=pad_segments)["fwd"]
        S = fwd.shape[0]
        bits = pack_bits(max(1, (max(cm.cardinality - 1, 1)).bit_length()))
        K = 32 // bits
        W = PALLAS_TILE // K
        tiles = self.pallas_tiles(min_tiles)
        ids = np.zeros((S, tiles * PALLAS_TILE), dtype=np.uint32)
        ids[:, :fwd.shape[1]] = fwd.astype(np.uint32)
        planes = ids.reshape(S, tiles, K, W)
        words = np.zeros((S, tiles, W), dtype=np.uint32)
        for k in range(K):
            words |= planes[:, :, k, :] << np.uint32(k * bits)
        return words.reshape(S, tiles, W // 128, 128), bits

    def pallas_tiles(self, min_tiles: int = 1) -> int:
        """Tile count per segment, rounded up to a multiple of min_tiles."""
        from pinot_tpu.engine.staging import PALLAS_TILE

        t = self.pallas_capacity() // PALLAS_TILE
        return -(-t // min_tiles) * min_tiles

    def value_column_batch(self, name: str, pad_segments: int = 0,
                           min_tiles: int = 1):
        """[S, tiles, TILE/128, 128] f32/i32 per-doc numeric values, or None
        when the column can't serve fused-kernel value rows (i64-staged
        columns ride ``value_limb_batch`` planes instead)."""
        from pinot_tpu.engine.staging import PALLAS_TILE, staged_int_dtype

        cm = self.metadata.column(name)
        if not (cm.single_value and cm.data_type.is_numeric):
            return None
        tree = self.stacked_column(name, pad_segments=pad_segments)
        fwd = tree["fwd"]
        if cm.has_dictionary:
            vals = tree["dictvals"][fwd]           # unified dictId gather
        else:
            vals = fwd
        if cm.data_type.is_integral:
            if staged_int_dtype(cm) != np.dtype(np.int32):
                return None
            vals = vals.astype(np.int32)
        else:
            vals = vals.astype(np.float32)
        S = vals.shape[0]
        tiles = self.pallas_tiles(min_tiles)
        out = np.zeros((S, tiles * PALLAS_TILE), dtype=vals.dtype)
        out[:, :vals.shape[1]] = vals
        return out.reshape(S, tiles, PALLAS_TILE // 128, 128)

    def value_limb_batch(self, name: str, limbs: int, pad_segments: int = 0,
                         min_tiles: int = 1):
        """i64-staged value column as ``limbs`` pre-split 12-bit limb
        planes, each [S, tiles, TILE/128, 128] i32 — the batch analogue of
        ``StagedSegment.value_limb_planes`` (identical split scheme, so the
        sharded fused kernel's limb accumulation is bit-exact with the
        per-segment path). None when the column isn't integral SV."""
        from pinot_tpu.engine.staging import LIMB_BITS, PALLAS_TILE

        cm = self.metadata.column(name)
        if not (cm.single_value and cm.data_type.is_numeric
                and cm.data_type.is_integral):
            return None
        tree = self.stacked_column(name, pad_segments=pad_segments)
        fwd = tree["fwd"]
        if cm.has_dictionary:
            v = tree["dictvals"].astype(np.int64)[fwd]
        else:
            v = fwd.astype(np.int64)
        S = v.shape[0]
        tiles = self.pallas_tiles(min_tiles)
        padded = np.zeros((S, tiles * PALLAS_TILE), dtype=np.int64)
        padded[:, :v.shape[1]] = v
        mask = np.int64((1 << LIMB_BITS) - 1)
        planes = []
        for k in range(limbs):
            if k < limbs - 1:
                p = ((padded >> (k * LIMB_BITS)) & mask).astype(np.int32)
            else:
                p = (padded >> (k * LIMB_BITS)).astype(np.int32)
            planes.append(p.reshape(S, tiles, PALLAS_TILE // 128, 128))
        return planes


def _merge_dictionaries(dicts: List[Dictionary], data_type: DataType):
    """Merge per-segment sorted dictionaries into one table-level dictionary;
    returns (unified, [per-segment oldId->newId remap arrays])."""
    if data_type.is_numeric:
        arrays = [np.asarray(d.device_values()) for d in dicts]
        unified_vals = np.unique(np.concatenate(arrays))
        unified: Dictionary = NumericDictionary(unified_vals, data_type)
        remaps = [np.searchsorted(unified_vals, a).astype(np.int32)
                  for a in arrays]
        return unified, remaps

    value_lists = [d.get_values(range(d.cardinality)) for d in dicts]
    all_vals = sorted(set().union(*[set(v) for v in value_lists]))
    unified = StringDictionary.from_values(all_vals, data_type)
    index = {v: i for i, v in enumerate(all_vals)}
    remaps = [np.asarray([index[v] for v in vals], dtype=np.int32)
              for vals in value_lists]
    return unified, remaps
