"""Sharded multi-segment combine: shard_map over a device mesh + ICI collectives.

TPU-native re-design of the instance-level combine
(ref: ``BaseCombineOperator.java:55-140`` — N executor tasks over the segment
list, partials merged through a BlockingQueue). Here the segment list is a
:class:`SegmentBatch` stacked into ``[S, capacity]`` arrays and sharded over
a 2-D ``jax.sharding.Mesh``:

- ``seg`` axis: segments data-parallel across devices (the reference's
  task-per-segment-group parallelism),
- ``doc`` axis: the doc dimension of every segment split across devices
  (the "context parallelism" of the scan, SURVEY.md §5).

Each device runs the single-segment kernel body (vmapped over its local
segments) and partials merge with ``psum``/``pmin``/``pmax`` over **both**
mesh axes — XLA lowers these to ICI all-reduces. The merged result is
replicated, so the host decode is identical to the single-segment path.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pinot_tpu.engine.kernels import (
    _SENTINEL_KEY,
    build_kernel_body,
    compact_from_sorted,
    pack_outputs,
    partial_reduce_ops,
    sparse_mode,
)
from pinot_tpu.engine.plan import PlanError

SEG_AXIS = "seg"
DOC_AXIS = "doc"


def _shard_map(f, mesh: Mesh, in_specs, out_specs):
    """jax.shard_map across jax versions: the top-level export (check_vma
    kwarg) landed after 0.4.x, where the API lives in jax.experimental
    with the older check_rep spelling. Replication checking stays off
    either way (pack_outputs concatenates psum'd and all_gather'd leaves,
    which the checker can't see through)."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    for kwargs in ({"check_vma": False}, {"check_rep": False}, {}):
        try:
            return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kwargs)
        except TypeError:
            continue
    raise RuntimeError("no usable shard_map signature in this jax")

# shard spec per staged-column array kind. dictvals is the unified
# dictionary: replicated (every device gathers from the full dictionary).
KIND_SPEC = {
    "fwd": P(SEG_AXIS, DOC_AXIS),
    "mv": P(SEG_AXIS, DOC_AXIS, None),
    "mvcount": P(SEG_AXIS, DOC_AXIS),
    "null": P(SEG_AXIS, DOC_AXIS),
    "dictvals": P(),
}


def device_stage_column(mesh: Mesh, tree: Dict[str, np.ndarray]):
    """Host column arrays -> committed device arrays with the combine
    shardings (the sharded analogue of StagedSegment: pay H2D once, reuse
    across queries)."""
    return {k: jax.device_put(v, NamedSharding(mesh, KIND_SPEC[k]))
            for k, v in tree.items()}


def make_combine_mesh(devices: Optional[List] = None,
                      doc_shards: int = 1) -> Mesh:
    """Mesh over all (or given) devices: segments over ``seg``, the doc
    dimension over ``doc``. ``doc_shards`` must divide the device count."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if n % doc_shards:
        raise ValueError(f"doc_shards {doc_shards} !| {n} devices")
    arr = np.asarray(devices).reshape(n // doc_shards, doc_shards)
    return Mesh(arr, (SEG_AXIS, DOC_AXIS))


def _local_reduce(v: jnp.ndarray, op: str) -> jnp.ndarray:
    if op == "sum":
        return v.sum(axis=0)
    if op == "min":
        return v.min(axis=0)
    if op == "max":
        return v.max(axis=0)
    raise AssertionError(op)


def _cross_reduce(v: jnp.ndarray, op: str, axes, mesh: Mesh) -> jnp.ndarray:
    # collectives only over axes with >1 device: a size-1 axis is a no-op,
    # and single-chip AOT backends may lower only Sum all-reduces
    axes = tuple(a for a in axes if mesh.shape[a] > 1)
    if not axes:
        return v
    if op == "sum":
        return jax.lax.psum(v, axes)
    if op == "min":
        return jax.lax.pmin(v, axes)
    if op == "max":
        return jax.lax.pmax(v, axes)
    raise AssertionError(op)


def _sparse_cross_combine(partials, reducers, K, axes, mesh):
    """Merge per-segment SPARSE compact partials across segments and mesh
    axes. Dense partials share a key space and merge with psum; sparse
    compacts carry DIFFERENT key sets per segment/shard, so the merge is:
    all_gather every (keys, leaves) compact over both mesh axes, then
    re-sort + re-group the concatenated [M = total_compacts * K] entries
    into one [K] compact (the device analogue of the reference's
    IndexedTable upsert-merge of map-based group-by blocks,
    BaseCombineOperator merge for group-by). Segment-level overflow
    (compact_n > K anywhere) propagates so the decode rejects rather than
    truncates."""
    SENT = jnp.int32(_SENTINEL_KEY)

    def gather(x):
        for a in axes:
            if mesh.shape[a] > 1:
                x = jax.lax.all_gather(x, a, tiled=True)
        return x

    keys = gather(partials["ck"]).reshape(-1)          # [M]
    seg_n = gather(partials["compact_n"]).max()
    M = keys.shape[0]
    order = jnp.argsort(keys)
    sk = keys[order]
    valid = sk != SENT
    first, n_live, uniq = compact_from_sorted(sk, K)
    rank = jnp.cumsum(first) - 1                       # [M] sorted-pos rank
    rank = jnp.where(valid & (rank < K), rank, K)      # overflow bucket
    scatter = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min,
               "max": jax.ops.segment_max}

    def merge_leaf(leaf, op):
        v = gather(leaf).reshape(M)[order]
        return scatter[op](v, rank, num_segments=K + 1)[:K]

    out = {}
    for key, ops in reducers.items():
        if key == "num_matched":
            continue
        val = partials[key]
        if isinstance(val, tuple):
            out[key] = tuple(merge_leaf(v, op) for v, op in zip(val, ops))
        else:
            out[key] = merge_leaf(val, ops[0])
    out["ck"] = uniq
    # if ANY per-segment compact overflowed, its keys were truncated before
    # this merge — surface a count > K so unpack raises (host path serves)
    out["compact_n"] = jnp.maximum(n_live, seg_n)
    # rung flag: 'sort' wins if ANY shard's hash table overflowed
    rung = partials.get("rung")
    if rung is not None:
        out["rung"] = _cross_reduce(rung.max(), "max", axes, mesh)
    return out


class ShardedKernelCache:
    """(spec, mesh-shape) -> compiled sharded combine kernel."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self._cache: Dict[Tuple, object] = {}

    def get(self, spec: Tuple, col_layouts: Tuple[Tuple[str, Tuple[str, ...]], ...]):
        key = (spec, col_layouts)
        fn = self._cache.get(key)
        if fn is None:
            fn = build_sharded_kernel(spec, self.mesh, col_layouts)
            self._cache[key] = fn
        return fn

    def __len__(self) -> int:
        return len(self._cache)


def build_sharded_kernel(spec: Tuple, mesh: Mesh,
                         col_layouts: Tuple[Tuple[str, Tuple[str, ...]], ...]):
    """Compile the sharded combine for one kernel spec.

    ``col_layouts``: per staged column, its array keys (('fwd',),
    ('mv','mvcount'), +'dictvals'/'null') — static so the shard specs and
    vmap axes are built once per (spec, layout).
    """
    n_seg = mesh.shape[SEG_AXIS]
    n_doc = mesh.shape[DOC_AXIS]
    capacity = spec[-1]
    if capacity % n_doc:
        # PlanError so the executor falls back to the per-segment path
        raise PlanError(f"capacity {capacity} !| doc axis {n_doc}")
    local_cap = capacity // n_doc
    sparse_k = sparse_mode(spec)
    # sparse specs build BOTH sparse-rung bodies: the hash body runs first
    # for every local segment, and a device-level lax.cond reruns the sort
    # body only when a hash table overflowed. The cond must sit OUTSIDE the
    # segment vmap — a cond under vmap lowers to select and would execute
    # (and pay for) the sort on every query.
    body = build_kernel_body(spec, capacity_override=local_cap,
                             sparse_k=sparse_k,
                             sparse_rung="hash" if sparse_k else "cond")
    body_sort = (build_kernel_body(spec, capacity_override=local_cap,
                                   sparse_k=sparse_k, sparse_rung="sort")
                 if sparse_k else None)
    reducers = partial_reduce_ops(spec)

    kind_axis = {"fwd": 0, "mv": 0, "mvcount": 0, "null": 0, "dictvals": None}

    cols_spec = {name: {k: KIND_SPEC[k] for k in keys}
                 for name, keys in col_layouts}
    cols_axes = {name: {k: kind_axis[k] for k in keys}
                 for name, keys in col_layouts}

    def per_device(cols, params, num_docs):
        doc_off = (jax.lax.axis_index(DOC_AXIS) * local_cap).astype(jnp.int32)

        def one_segment(seg_cols, nd):
            return body(seg_cols, params, nd, doc_off)

        partials = jax.vmap(one_segment, in_axes=(cols_axes, 0))(cols, num_docs)
        axes = (SEG_AXIS, DOC_AXIS)
        if sparse_k:
            # hash-rung overflow anywhere in this device's segments -> rerun
            # them all through the sort body (one branch executes; the
            # cross-shard merge is rung-agnostic, so devices may disagree)
            hash_partials = partials

            def _sort_all(_):
                return jax.vmap(
                    lambda seg_cols, nd: body_sort(seg_cols, params, nd,
                                                   doc_off),
                    in_axes=(cols_axes, 0))(cols, num_docs)

            partials = jax.lax.cond(hash_partials["rung"].max() > 0,
                                    _sort_all, lambda _: hash_partials,
                                    None)
            out = _sparse_cross_combine(partials, reducers, sparse_k,
                                        axes, mesh)
        else:
            out = {}
            for key, val in partials.items():
                ops = reducers[key]
                if isinstance(val, tuple):
                    out[key] = tuple(
                        _cross_reduce(_local_reduce(v, op), op, axes, mesh)
                        for v, op in zip(val, ops))
                else:
                    out[key] = _cross_reduce(_local_reduce(val, ops[0]),
                                             ops[0], axes, mesh)
        # per-segment matched doc counts [S] (stats parity with the
        # per-segment executor: numSegmentsMatched / numDocsScanned)
        if "num_matched" in partials:
            local = partials["num_matched"]            # [S_local]
        else:
            local = partials["presence"].sum(axis=1)   # [S_local]
        if mesh.shape[DOC_AXIS] > 1:
            local = jax.lax.psum(local, DOC_AXIS)
        if mesh.shape[SEG_AXIS] > 1:
            local = jax.lax.all_gather(local, SEG_AXIS, tiled=True)
        out["seg_matched"] = local
        # ONE replicated f64 vector out: a single D2H fetch serves the whole
        # decode (the tunnel-latency fix; see kernels.output_layout)
        return pack_outputs(out, spec)

    sharded = _shard_map(
        per_device, mesh=mesh,
        in_specs=(cols_spec, P(), P(SEG_AXIS)),
        out_specs=P())
    return jax.jit(sharded)


def pad_segments(n: int, n_seg: int) -> int:
    """Segments padded up to a multiple of the seg-axis size."""
    return ((n + n_seg - 1) // n_seg) * n_seg


# --------------------------------------------------------------------------
# sharded fused-Pallas combine: the flagship serving path for eligible
# aggregation/group-by queries. Each device runs the fused scan kernel
# (pallas_kernels.build_kernel) over its local [S_local, T_local] shard of
# the planar bit-packed batch; partials merge with psum/pmin/pmax over ICI.
# --------------------------------------------------------------------------

def build_sharded_pallas_kernel(spec, plan_spec: Tuple, mesh: Mesh):
    """jitted fn(static_params, packed_cols, value_cols, num_docs) ->
    packed f64 vector.

    ``spec`` is a pallas_kernels.PallasSpec already sized PER DEVICE
    (num_segs/tiles_per_seg local to one mesh cell); inputs are
    device-committed arrays sharded (seg, doc) over the mesh:
    packed [S, T, W/128, 128] u32, values [S, T, TILE/128, 128] f32/i32,
    num_docs [S] i32, static_params [2*n_slots] i32 replicated (interval
    literals stay runtime args so same-shape queries share the compile)."""
    from pinot_tpu.engine.pallas_kernels import (
        _row_layout,
        assemble_outputs,
        build_kernel,
    )
    from pinot_tpu.engine.staging import PALLAS_TILE

    T_l = spec.tiles_per_seg
    call = build_kernel(spec)
    _, _, mm_row, _, _, _ = _row_layout(spec)
    axes = (SEG_AXIS, DOC_AXIS)

    def per_device(static_params, packed_cols, value_cols, num_docs):
        doc_base = (jax.lax.axis_index(DOC_AXIS)
                    * (T_l * PALLAS_TILE)).astype(jnp.int32)
        params = jnp.concatenate([
            static_params.astype(jnp.int32).reshape(-1),
            num_docs.astype(jnp.int32), doc_base[None]])
        out_f, out_i, out_mm, out_seg = call(params, *packed_cols,
                                             *value_cols)
        out_f = _cross_reduce(out_f, "sum", axes, mesh)
        # per-device int accumulator rows are i32-bounded by the kernel's
        # per-step carry-chain normalization (pallas_kernels.build_kernel);
        # widen before the mesh psum so the cross-device limb totals can't
        # wrap (O(groups) cost only)
        out_i = _cross_reduce(out_i.astype(jnp.int64), "sum", axes, mesh)
        if mm_row:
            rows = list(out_mm)
            for (_, kind), r in mm_row.items():
                rows[r] = _cross_reduce(out_mm[r], kind, axes, mesh)
            out_mm = jnp.stack(rows)
        seg_local = out_seg.sum(axis=1)            # [S_l]
        seg_local = _cross_reduce(seg_local, "sum", (DOC_AXIS,), mesh)
        if mesh.shape[SEG_AXIS] > 1:
            seg_local = jax.lax.all_gather(seg_local, SEG_AXIS, tiled=True)
        tree = assemble_outputs(plan_spec, spec, out_f, out_i, out_mm,
                                seg_matched=seg_local)
        return pack_outputs(tree, plan_spec)

    pk_spec = P(SEG_AXIS, DOC_AXIS, None, None)
    n_value_refs = sum(l if l else 1 for l in
                       (spec.value_limbs or (0,) * len(spec.value_is_int)))
    sharded = _shard_map(
        per_device, mesh=mesh,
        in_specs=(P(),
                  [pk_spec] * len(spec.packed_bits),
                  [pk_spec] * n_value_refs,
                  P(SEG_AXIS)),
        out_specs=P())
    return jax.jit(sharded)


def build_sharded_pallas_probe(spec, mesh: Mesh):
    """jitted fn(static_params, packed_cols, num_docs) -> out_mm rows,
    min/max-reduced over both mesh axes.

    ``spec`` is the group-range PROBE PallasSpec
    (pallas_kernels.probe_plan_of): the same fused unpack+filter scan with
    one masked (min, max)-of-dictId aggregation pair per group column and
    no matmul — the narrowing pass that collapses large sparse composed
    key spaces onto the dense one-hot rung. Totally ordered through the
    launch dispatcher like any other multi-device program."""
    from pinot_tpu.engine.pallas_kernels import _row_layout, build_kernel
    from pinot_tpu.engine.staging import PALLAS_TILE

    T_l = spec.tiles_per_seg
    call = build_kernel(spec)
    _, _, mm_row, _, _, _ = _row_layout(spec)
    axes = (SEG_AXIS, DOC_AXIS)

    def per_device(static_params, packed_cols, num_docs):
        doc_base = (jax.lax.axis_index(DOC_AXIS)
                    * (T_l * PALLAS_TILE)).astype(jnp.int32)
        params = jnp.concatenate([
            static_params.astype(jnp.int32).reshape(-1),
            num_docs.astype(jnp.int32), doc_base[None]])
        _f, _i, out_mm, _s = call(params, *packed_cols)
        rows = list(out_mm)
        for (_, kind), r in mm_row.items():
            rows[r] = _cross_reduce(out_mm[r], kind, axes, mesh)
        return jnp.stack(rows)

    pk_spec = P(SEG_AXIS, DOC_AXIS, None, None)
    sharded = _shard_map(
        per_device, mesh=mesh,
        in_specs=(P(), [pk_spec] * len(spec.packed_bits), P(SEG_AXIS)),
        out_specs=P())
    return jax.jit(sharded)
