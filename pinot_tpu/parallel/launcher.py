"""Cross-query launch coalescing: micro-batched device dispatch.

The sharded combine used to serialize every multi-device launch under a
process-global lock (interleaved collective programs deadlock the runtime),
so N concurrent queries paid N back-to-back device programs — the measured
QPS story was ~1.0x scaling at 4 client threads. This module turns that
serialization point into a *coalescing* point, the device-query analogue of
continuous batching in an inference server (and of the reference's sized
combine pools, ``BaseCombineOperator.java:55``):

- Queries never call a compiled combine directly. They submit a
  :class:`_LaunchRequest` — ``(LaunchKernel, runtime params, num_docs)`` —
  to the per-mesh :class:`LaunchScheduler` and block on a future.
- A single daemon dispatcher thread drains the queue. Because only this
  thread ever launches device programs, the old ``_combine_lock`` becomes an
  *emergent property* of the design: launches are totally ordered, so
  collective programs can never interleave, with no lock held across the
  serving path.
- While one program runs, waiting requests pile up. The dispatcher groups
  them by **compiled-kernel identity** (``LaunchKernel.key`` — the
  literal-normalized plan fingerprint, so same-shape queries with different
  literals share a kernel):

  * requests whose runtime params are the *same device arrays* (exact
    repeats served by the executor's param cache) share ONE launch and ONE
    result buffer (dedup);
  * distinct param sets stack along a new leading axis and run as ONE
    vmapped launch (sizes padded to powers of two so compile variants stay
    bounded), each query's future receiving its row of the output.

- Different-shape queries pipeline through the queue in arrival order
  instead of convoying behind a lock: while query A's caller decodes its
  result, the dispatcher is already launching query B.

A kernel whose vmapped form fails to build/run (e.g. a batching rule a
backend can't lower) is marked non-batchable and its group falls back to
serial launches on the dispatcher thread — coalescing degrades to the old
serialized behavior, never to a wrong answer.
"""

from __future__ import annotations

import logging
import threading
import time

from collections import OrderedDict, deque
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

# stats keys whose QueryStats.launch merge takes MAX (the rest sum); shared
# with engine/results.py so wire merge and launcher agree on semantics
LAUNCH_MAX_KEYS = ("batchSize", "queueWaitMs")


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


class LaunchKernel:
    """One coalescable compiled combine program.

    ``call(params, num_docs) -> packed`` is the solo form (params are this
    query's runtime arrays; everything else — staged columns, mesh, output
    layout — is closed over). ``key`` is the literal-normalized identity two
    requests must share to ride one launch: same compiled kernel, same
    staged arrays, same num_docs source. The vmapped form is built lazily
    per padded batch size and maps ONLY over params (``in_axes=(0, None)``),
    so staged columns are broadcast, not copied per batch element.
    """

    __slots__ = ("key", "call", "is_pallas", "max_batch", "batchable",
                 "_vmapped", "_lock")

    def __init__(self, key: Tuple, call, is_pallas: bool = False,
                 max_batch: int = 8):
        self.key = key
        self.call = call
        self.is_pallas = is_pallas
        self.max_batch = max(1, int(max_batch))
        # flips False on the first vmapped failure; the group then runs
        # serially forever (correctness over throughput)
        self.batchable = self.max_batch > 1
        self._vmapped: Dict[int, Any] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def run_one(self, params, num_docs):
        return self.call(params, num_docs)

    def run_many(self, params_list: List[Any], num_docs) -> List[Any]:
        """One vmapped launch over ``len(params_list)`` stacked param sets;
        returns one output row per param set (device-sliced, D2H deferred
        to each caller's decode). Sizes pad up to a power of two with
        repeats of the last param set so the jit cache holds at most
        log2(max_batch) batched variants per kernel."""
        import jax
        import jax.numpy as jnp

        n = len(params_list)
        size = min(_next_pow2(n), _next_pow2(self.max_batch))
        padded = list(params_list) + [params_list[-1]] * (size - n)
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *padded)
        with self._lock:
            fn = self._vmapped.get(size)
            if fn is None:
                # vmap of the jitted solo call: pjit's batching rule traces
                # the inner program with a leading batch dim and caches the
                # compile in the inner jit's own cache (no outer jit — that
                # would bake the closed-over staged columns in as constants)
                fn = jax.vmap(self.call, in_axes=(0, None))
                self._vmapped[size] = fn
        out = fn(stacked, num_docs)
        return [out[j] for j in range(n)]


class _LaunchRequest:
    """One query's pending launch + its coalescing outcome (the fields the
    executor copies into ``QueryStats.launch``)."""

    __slots__ = ("kernel", "params", "num_docs", "future", "t_submit",
                 "batch_size", "queue_wait_ms", "launches_saved", "deduped")

    def __init__(self, kernel: LaunchKernel, params, num_docs):
        self.kernel = kernel
        self.params = params
        self.num_docs = num_docs
        self.future: Future = Future()
        self.t_submit = time.perf_counter()
        self.batch_size = 1
        self.queue_wait_ms = 0.0
        self.launches_saved = 0
        self.deduped = False

    def result(self, timeout: Optional[float] = None):
        return self.future.result(timeout)


class LaunchScheduler:
    """Per-mesh dispatcher: one daemon thread owns every device launch."""

    def __init__(self, name: str = "combine-launch"):
        self._name = name
        # writes-only guard: queue-depth gauges read len() lock-free
        # (GIL-atomic), mutation stays on the condition
        self._queue: "deque[_LaunchRequest]" = deque()  # guarded-by-writes: _cond
        self._cond = threading.Condition()
        self._thread: Optional[threading.Thread] = None  # guarded-by-writes: _cond
        self._closed = False  # guarded-by: _cond
        # adaptive micro-batch window: when the arrival-rate EWMA says the
        # queue is HOT (inter-arrival <= hot threshold), the dispatcher
        # holds up to window_max_ms for stragglers before grouping — vmap
        # batches get bigger exactly when traffic would fill them; idle
        # traffic never waits (window collapses to zero). Writes-only
        # guards: the dispatcher reads these lock-free between drains.
        self.window_max_ms = 1.0  # guarded-by-writes: _cond
        self.window_hot_ms = 2.0  # guarded-by-writes: _cond
        self._arrival_ewma_ms: Optional[float] = None  # guarded-by-writes: _cond
        self._last_arrival: Optional[float] = None  # guarded-by: _cond
        # cumulative counters (process lifetime; bench suites diff
        # stats_snapshot() marks, /debug/launches serves snapshot()).
        # Writes-only guard: gauge lambdas read single counters lock-free;
        # stats_snapshot() takes the lock for a consistent cut.
        self._stats_lock = threading.Lock()
        self.requests = 0  # guarded-by-writes: _stats_lock
        self.launches = 0  # guarded-by-writes: _stats_lock
        self.coalesced_launches = 0  # guarded-by-writes: _stats_lock
        self.launches_saved = 0  # guarded-by-writes: _stats_lock
        self.deduped_requests = 0  # guarded-by-writes: _stats_lock
        self.batched_requests = 0  # guarded-by-writes: _stats_lock
        self.failures = 0  # guarded-by-writes: _stats_lock
        self.max_batch_size = 0  # guarded-by-writes: _stats_lock
        self.queue_wait_ms_total = 0.0  # guarded-by-writes: _stats_lock
        self.queue_wait_ms_max = 0.0  # guarded-by-writes: _stats_lock
        self.window_waits = 0  # guarded-by-writes: _stats_lock
        self.window_gathered = 0  # guarded-by-writes: _stats_lock
        self.window_last_ms = 0.0  # guarded-by-writes: _stats_lock
        self._registries: List[Any] = []  # guarded-by-writes: _stats_lock

    # -- submission ----------------------------------------------------------
    def submit(self, kernel: LaunchKernel, params, num_docs) -> _LaunchRequest:
        req = _LaunchRequest(kernel, params, num_docs)
        with self._cond:
            if self._closed:
                raise RuntimeError(f"launch scheduler {self._name} is closed")
            if self._thread is None or not self._thread.is_alive():
                # also revives a dispatcher a defensive-coded bug killed:
                # queued waiters must never hang on a dead thread
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name=self._name)
                self._thread.start()
            self._note_arrival_locked(req.t_submit)
            self._queue.append(req)
            self._cond.notify()
        return req

    def _note_arrival_locked(self, now: float) -> None:
        """Arrival-rate EWMA feeding the adaptive window (caller holds
        ``_cond``). A gap far beyond the hot threshold RESETS the average —
        the first queries after an idle stretch must not inherit a hot
        window from yesterday's burst."""
        if self._last_arrival is not None:
            dt_ms = (now - self._last_arrival) * 1e3
            e = self._arrival_ewma_ms
            if e is None or dt_ms > 8 * max(self.window_hot_ms, 0.001):
                self._arrival_ewma_ms = dt_ms
            else:
                self._arrival_ewma_ms = 0.2 * dt_ms + 0.8 * e
        self._last_arrival = now

    def set_window(self, max_ms: Optional[float] = None,
                   hot_ms: Optional[float] = None) -> None:
        """Configure the adaptive micro-batch window: ``max_ms`` = the
        straggler hold cap (<= 0 disables), ``hot_ms`` = the inter-arrival
        EWMA threshold below which traffic counts as hot."""
        with self._cond:
            if max_ms is not None:
                self.window_max_ms = float(max_ms)
            if hot_ms is not None:
                self.window_hot_ms = float(hot_ms)

    def close(self) -> None:
        """Stop accepting; the dispatcher drains what's queued and exits.
        Only meaningful for privately-owned schedulers (the per-mesh
        registry keeps its daemons for the process lifetime)."""
        with self._cond:
            self._closed = True
            self._cond.notify()

    # -- dispatcher ----------------------------------------------------------
    def _window_hold_s(self, n_drained: int) -> float:
        """Adaptive window decision for one drain: hold only when traffic
        is HOT (EWMA inter-arrival under the hot threshold) and the drain
        is still small enough that stragglers would grow the vmap group.
        Idle traffic returns 0.0 — no added latency at low QPS."""
        w = self.window_max_ms
        if w <= 0 or n_drained >= 8:
            return 0.0
        ewma = self._arrival_ewma_ms
        if ewma is None or ewma > self.window_hot_ms:
            return 0.0
        return w / 1e3

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if not self._queue and self._closed:
                    return
                drained = list(self._queue)
                self._queue.clear()
            hold_s = self._window_hold_s(len(drained))
            if hold_s > 0:
                # hot queue: hold for stragglers so this drain's vmap
                # groups get bigger — the micro-batch window
                deadline = time.perf_counter() + hold_s
                gathered = 0
                with self._cond:
                    while not self._closed:
                        remaining = deadline - time.perf_counter()
                        if remaining <= 0:
                            break
                        self._cond.wait(remaining)
                    if self._queue:
                        gathered = len(self._queue)
                        drained += list(self._queue)
                        self._queue.clear()
                with self._stats_lock:
                    self.window_waits += 1
                    self.window_gathered += gathered
                    self.window_last_ms = hold_s * 1e3
                self._mark("LAUNCH_WINDOW_WAITS", 1)
                self._mark("LAUNCH_WINDOW_GATHERED", gathered)
            # group by compiled-kernel identity, preserving the arrival
            # order of the FIRST request of each group (FIFO fairness across
            # shapes; later same-shape arrivals ride the earlier slot)
            groups: "OrderedDict[Tuple, List[_LaunchRequest]]" = OrderedDict()
            for req in drained:
                groups.setdefault(req.kernel.key, []).append(req)
            for reqs in groups.values():
                # a failure escaping _launch_group (import error, a bug in
                # the grouping itself) must still complete every waiter's
                # future — the alternative is N client threads hung forever
                # on a dead dispatcher
                try:
                    self._launch_group(reqs)
                except BaseException as e:  # noqa: BLE001
                    log.exception("launch group failed outside the "
                                  "per-request paths")
                    for r in reqs:
                        if not r.future.done():
                            r.future.set_exception(e)

    def _launch_group(self, reqs: List[_LaunchRequest]) -> None:
        import jax

        kernel = reqs[0].kernel
        num_docs = reqs[0].num_docs
        now = time.perf_counter()
        for r in reqs:
            r.queue_wait_ms = (now - r.t_submit) * 1e3
        # dedup exact repeats: the executor's param cache hands identical
        # queries the SAME device param objects, so identity is the test
        uniq: List[Any] = []
        req_slot: List[int] = []
        seen: Dict[int, int] = {}
        for r in reqs:
            slot = seen.get(id(r.params))
            if slot is None:
                slot = len(uniq)
                seen[id(r.params)] = slot
                uniq.append(r.params)
            req_slot.append(slot)

        outs: List[Any] = [None] * len(uniq)
        errs: List[Optional[BaseException]] = [None] * len(uniq)
        launches = 0
        if len(uniq) == 1:
            try:
                outs[0] = kernel.run_one(uniq[0], num_docs)
            except BaseException as e:  # noqa: BLE001 — futures carry it
                errs[0] = e
            launches = 1
        else:
            start = 0
            while start < len(uniq):
                chunk = uniq[start:start + kernel.max_batch]
                if kernel.batchable and len(chunk) > 1:
                    try:
                        rows = kernel.run_many(chunk, num_docs)
                        outs[start:start + len(chunk)] = rows
                        launches += 1
                        start += len(chunk)
                        continue
                    except BaseException:  # noqa: BLE001 — serial fallback
                        log.exception(
                            "vmapped combine launch failed for %r; "
                            "disabling coalescing for this kernel",
                            kernel.key[:2])
                        kernel.batchable = False
                        # path-decision ledger: a kernel degrading to
                        # serial launches is a throughput decline worth
                        # explaining (no per-query stats on the
                        # dispatcher thread — the process histogram
                        # carries it)
                        from pinot_tpu.common.tracing import record_decision

                        record_decision(None, "launch", "serial_launches",
                                        "vmap_batch", "vmap_failed")
                for j, p in enumerate(chunk):
                    try:
                        outs[start + j] = kernel.run_one(p, num_docs)
                    except BaseException as e:  # noqa: BLE001
                        errs[start + j] = e
                    launches += 1
                start += len(chunk)
        # wait INSIDE the dispatcher before the next group: device execution
        # stays totally ordered (the no-interleaved-collectives invariant)
        # and the queue keeps filling while this program runs — which is
        # exactly what makes the next drain coalesce
        try:
            jax.block_until_ready([o for o in outs if o is not None])
        except BaseException:  # noqa: BLE001 — surface at the fetch instead
            pass

        n = len(reqs)
        for r, slot in zip(reqs, req_slot):
            r.batch_size = n
            r.launches_saved = n - launches
            r.deduped = req_slot.count(slot) > 1
            if errs[slot] is not None:
                r.future.set_exception(errs[slot])
            else:
                r.future.set_result(outs[slot])
        self._note(reqs, uniq, launches,
                   n_failed=sum(e is not None for e in errs))

    # -- stats / observability ----------------------------------------------
    def _note(self, reqs, uniq, launches: int, n_failed: int) -> None:
        n = len(reqs)
        wait = [r.queue_wait_ms for r in reqs]
        # windowed dispatcher-queue-wait histogram: the launch tier's
        # sliding-percentile view (per-mesh, no table attribution here)
        from pinot_tpu.common.telemetry import TELEMETRY

        wh = TELEMETRY.histo("", "launch_queue")
        for w in wait:
            wh.record(w)
        with self._stats_lock:
            self.requests += n
            self.launches += launches
            self.failures += n_failed
            if n > launches:
                self.coalesced_launches += 1
                self.launches_saved += n - launches
            self.deduped_requests += n - len(uniq)
            if len(uniq) > 1 and launches < len(uniq):
                self.batched_requests += n - (n - len(uniq))
            if n > self.max_batch_size:
                self.max_batch_size = n
            self.queue_wait_ms_total += sum(wait)
            self.queue_wait_ms_max = max(self.queue_wait_ms_max, *wait)
        self._mark("LAUNCH_REQUESTS", n)
        self._mark("LAUNCHES", launches)
        if n > launches:
            self._mark("LAUNCHES_COALESCED", 1)
            self._mark("LAUNCHES_SAVED", n - launches)

    def bind_metrics(self, registry) -> None:
        """Attach a MetricsRegistry (spi/metrics.py ServerMeter.LAUNCH*_).
        Multiple server instances may share one per-mesh scheduler, so
        every bound registry gets the marks."""
        with self._stats_lock:
            if registry not in self._registries:
                self._registries.append(registry)
        registry.gauge("launch_queue_depth", lambda: float(len(self._queue)))
        registry.gauge("launch_max_batch_size",
                       lambda: float(self.max_batch_size))

    def _mark(self, name: str, n: int) -> None:
        if not self._registries or n <= 0:
            return
        from pinot_tpu.spi.metrics import ServerMeter

        metric = getattr(ServerMeter, name, None)
        if metric is None:
            return
        for reg in list(self._registries):
            reg.meter(metric).mark(n)

    def stats_snapshot(self) -> Dict[str, float]:
        """Cumulative counters (bench per-suite deltas diff two of these)."""
        with self._stats_lock:
            return {
                "requests": self.requests,
                "launches": self.launches,
                "coalescedLaunches": self.coalesced_launches,
                "launchesSaved": self.launches_saved,
                "dedupedRequests": self.deduped_requests,
                "batchedRequests": self.batched_requests,
                "failures": self.failures,
                "maxBatchSize": self.max_batch_size,
                "queueWaitMsTotal": round(self.queue_wait_ms_total, 3),
                "queueWaitMsMax": round(self.queue_wait_ms_max, 3),
                "windowWaits": self.window_waits,
                "windowGathered": self.window_gathered,
                "windowLastMs": round(self.window_last_ms, 3),
            }

    def snapshot(self) -> Dict[str, Any]:
        """``/debug/launches`` body: counters + live queue state."""
        out: Dict[str, Any] = self.stats_snapshot()
        out["queued"] = len(self._queue)
        out["dispatcherAlive"] = (self._thread is not None
                                  and self._thread.is_alive())
        out["windowMaxMs"] = self.window_max_ms
        out["windowHotMs"] = self.window_hot_ms
        ewma = self._arrival_ewma_ms
        out["arrivalEwmaMs"] = None if ewma is None else round(ewma, 3)
        return out


# --------------------------------------------------------------------------
# per-mesh registry: every executor over the same device set shares ONE
# dispatcher, so two executors can no longer interleave collective programs
# (the old per-executor _combine_lock never protected against that)
# --------------------------------------------------------------------------

_LAUNCHERS: Dict[Tuple, LaunchScheduler] = {}
_REGISTRY_LOCK = threading.Lock()


def launcher_for_mesh(mesh) -> LaunchScheduler:
    key = tuple(getattr(d, "id", i)
                for i, d in enumerate(mesh.devices.flat))
    with _REGISTRY_LOCK:
        sched = _LAUNCHERS.get(key)
        if sched is None:
            sched = LaunchScheduler(name=f"combine-launch-{len(_LAUNCHERS)}")
            _LAUNCHERS[key] = sched
            # gauge-history rings for the dispatcher: queue depth and the
            # arrival-interval EWMA (the adaptive window's input) at
            # few-second resolution — the history behind /debug/launches'
            # instants. len()/float reads are GIL-atomic, never a sync.
            from pinot_tpu.common.telemetry import TELEMETRY

            TELEMETRY.track_gauge(
                f"{sched._name}.queue_depth",
                lambda s=sched: float(len(s._queue)))
            TELEMETRY.track_gauge(
                f"{sched._name}.arrival_ewma_ms",
                lambda s=sched: float(s._arrival_ewma_ms or 0.0))
        return sched
