"""Plugin loader: runtime discovery of third-party SPI implementations.

Re-design of ``pinot-spi/.../plugin/PluginManager.java:40`` +
``PluginClassLoader``: the reference scans a plugins directory and loads
each plugin in an isolated classloader; here each plugin is a python
module (a ``.py`` file or a package directory) imported from the plugins
dir — importing it is the registration step (plugins call the SPI
registries: ``ingestion.stream.register_stream_type``,
``spi.filesystem.register_fs``, ``ingestion.readers`` format map, scalar
function registries, ...). Isolation is per-module-namespace rather than
per-classloader (python has no classloader hierarchy to mirror).

Directory convention (``plugins.dir`` config key or PINOT_PLUGINS_DIR):
    plugins/
      my_stream.py          <- registers on import
      my_fs/__init__.py     <- package plugin
"""

from __future__ import annotations

import importlib.util
import logging
import os
import sys

from typing import List, Optional

log = logging.getLogger(__name__)

PLUGINS_DIR_ENV = "PINOT_PLUGINS_DIR"


class PluginManager:
    """Ref: PluginManager.java:40 (init/load/get)."""

    def __init__(self, plugins_dir: Optional[str] = None):
        self.plugins_dir = plugins_dir or os.environ.get(PLUGINS_DIR_ENV)
        self.loaded: List[str] = []

    def load_all(self) -> List[str]:
        """Import every plugin module under the plugins dir; returns the
        loaded plugin names (skips, with a log, plugins that fail —
        matching the reference's tolerant startup scan)."""
        d = self.plugins_dir
        if not d or not os.path.isdir(d):
            return []
        for entry in sorted(os.listdir(d)):
            path = os.path.join(d, entry)
            name = None
            if entry.endswith(".py") and not entry.startswith("_"):
                name = entry[:-3]
            elif (os.path.isdir(path)
                  and os.path.isfile(os.path.join(path, "__init__.py"))):
                name = entry
                path = os.path.join(path, "__init__.py")
            if name is None:
                continue
            mod_name = f"pinot_plugin_{name}"
            if mod_name in sys.modules:
                continue  # idempotent: registrations must not re-run
            try:
                self._load_module(mod_name, path)
                self.loaded.append(name)
            except Exception:  # noqa: BLE001 — one bad plugin isn't fatal
                log.exception("failed to load plugin %s", entry)
        return list(self.loaded)

    @staticmethod
    def _load_module(mod_name: str, path: str):
        spec = importlib.util.spec_from_file_location(mod_name, path)
        assert spec is not None and spec.loader is not None, path
        module = importlib.util.module_from_spec(spec)
        sys.modules[mod_name] = module
        try:
            spec.loader.exec_module(module)
        except BaseException:
            # a half-initialized plugin must not stay importable (python's
            # own import machinery removes failed modules the same way)
            sys.modules.pop(mod_name, None)
            raise
        return module
