"""Metrics SPI: meters / gauges / timers + prometheus-text export.

Re-design of the reference's metrics layer
(``pinot-common/.../metrics/AbstractMetrics.java:46`` + per-role
``ServerMeter``/``BrokerMeter``/``ServerTimer``/``ServerQueryPhase`` enums,
exported through a pluggable registry — yammer by JMX there, a
prometheus-text endpoint here): each role process owns a
:class:`MetricsRegistry`; meters and timers take a tiny uncontended lock
per update (python '+=' is not atomic across threads).
"""

from __future__ import annotations

import re
import threading
import time

from typing import Any, Callable, Dict, Tuple, Union

# prometheus metric names admit only [a-zA-Z0-9_:] (label VALUES are free
# text); every exported name is sanitized through this
_NAME_UNSAFE = re.compile(r"[^a-zA-Z0-9_:]+")


def sanitize_metric_name(name: str) -> str:
    return _NAME_UNSAFE.sub("_", name)


class Meter:
    """Monotonic counter (ref: PinotMeter). Locked: '+=' is not atomic
    under the GIL (LOAD/ADD/STORE can interleave across threads)."""

    __slots__ = ("count", "_lock")

    def __init__(self):
        self.count = 0
        self._lock = threading.Lock()

    def mark(self, n: int = 1) -> None:
        with self._lock:
            self.count += n


class Timer:
    """Duration accumulator: count / total / max ms (ref: PinotTimer)."""

    __slots__ = ("count", "total_ms", "max_ms", "_lock")

    def __init__(self):
        self.count = 0
        self.total_ms = 0.0
        self.max_ms = 0.0
        self._lock = threading.Lock()

    def update_ms(self, ms: float) -> None:
        with self._lock:
            self.count += 1
            self.total_ms += ms
            if ms > self.max_ms:
                self.max_ms = ms

    @property
    def mean_ms(self) -> float:
        return self.total_ms / self.count if self.count else 0.0

    def time(self) -> "_TimerContext":
        return _TimerContext(self)


class _TimerContext:
    __slots__ = ("_timer", "_t0")

    def __init__(self, timer: Timer):
        self._timer = timer

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._timer.update_ms((time.perf_counter() - self._t0) * 1e3)


GaugeFn = Union[Callable[[], float], float, int]


class MetricsRegistry:
    """One per role process (ref: PinotMetricsRegistry)."""

    def __init__(self, role: str = ""):
        self.role = role
        self._meters: Dict[str, Meter] = {}  # guarded-by-writes: _lock
        self._timers: Dict[str, Timer] = {}  # guarded-by-writes: _lock
        self._gauges: Dict[str, GaugeFn] = {}
        # family -> {sorted (label, value) tuple -> Meter}: counters that
        # export as ONE prometheus metric family with label dimensions
        # instead of N name-mangled metric names
        self._labeled: Dict[str, Dict[Tuple[Tuple[str, str], ...], Meter]] = {}  # guarded-by-writes: _lock
        self._help: Dict[str, str] = {}
        self._telemetry = None
        self._lock = threading.Lock()

    def meter(self, name: str) -> Meter:
        m = self._meters.get(name)
        if m is None:
            with self._lock:
                m = self._meters.setdefault(name, Meter())
        return m

    def labeled_meter(self, family: str, **labels: str) -> Meter:
        """Counter cell of a labeled family — exported as
        ``family{k="v",...} n`` under one HELP/TYPE header."""
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        cells = self._labeled.get(family)
        if cells is not None:
            m = cells.get(key)
            if m is not None:
                return m
        with self._lock:
            cells = self._labeled.setdefault(family, {})
            return cells.setdefault(key, Meter())

    def timer(self, name: str) -> Timer:
        t = self._timers.get(name)
        if t is None:
            with self._lock:
                t = self._timers.setdefault(name, Timer())
        return t

    def gauge(self, name: str, fn: GaugeFn) -> None:
        """Register a gauge. ``fn`` runs on SCRAPE threads: it must never
        materialize a device value (``np.asarray``/``.item()``/casts on a
        jax array block the scrape on device execution) — the graftlint
        ``sync`` family gates gauge callbacks for exactly this."""
        self._gauges[name] = fn

    def set_help(self, name: str, text: str) -> None:
        """Optional HELP text for one exported family."""
        self._help[name] = text

    def bind_telemetry(self, telemetry) -> None:
        """Attach a :class:`~pinot_tpu.common.telemetry.Telemetry` center:
        its histogram/SLO families ride this registry's exposition."""
        self._telemetry = telemetry

    # -- export --------------------------------------------------------------
    def _prefix(self, name: str) -> str:
        p = f"pinot_{self.role}_" if self.role else "pinot_"
        return sanitize_metric_name(p + name)

    def _header(self, lines, full: str, mtype: str, name: str,
                fallback: str) -> None:
        lines.append(f"# HELP {full} {self._help.get(name, fallback)}")
        lines.append(f"# TYPE {full} {mtype}")

    def export_prometheus(self) -> str:
        """Prometheus text exposition (the /metrics endpoint body):
        HELP/TYPE headers on every family, sanitized names, labeled
        families rendered with label dimensions, and — when a telemetry
        center is bound — the histogram ``_bucket``/``_sum``/``_count``
        series and SLO burn gauges."""
        lines = []
        for name, m in sorted(self._meters.items()):
            full = self._prefix(name)
            self._header(lines, full, "counter", name,
                         f"Cumulative count of {name}.")
            lines.append(f"{full} {m.count}")
        for family, cells in sorted(self._labeled.items()):
            full = self._prefix(family)
            self._header(lines, full, "counter", family,
                         f"Cumulative count of {family} by label.")
            for key in sorted(cells):
                labels = ",".join(
                    f'{sanitize_metric_name(k)}="{v}"' for k, v in key)
                lines.append(f"{full}{{{labels}}} {cells[key].count}")
        for name, g in sorted(self._gauges.items()):
            full = self._prefix(name)
            v = g() if callable(g) else g
            self._header(lines, full, "gauge", name,
                         f"Instantaneous value of {name}.")
            lines.append(f"{full} {float(v)}")
        for name, t in sorted(self._timers.items()):
            full = self._prefix(name)
            self._header(lines, f"{full}_ms", "summary", name,
                         f"Duration of {name} in milliseconds.")
            lines.append(f"{full}_ms_count {t.count}")
            lines.append(f"{full}_ms_sum {round(t.total_ms, 3)}")
            self._header(lines, f"{full}_ms_max", "gauge", name + "_max",
                         f"Maximum observed {name} duration (ms).")
            lines.append(f"{full}_ms_max {round(t.max_ms, 3)}")
        body = "\n".join(lines) + "\n"
        if self._telemetry is not None:
            p = f"pinot_{self.role}_" if self.role else "pinot_"
            body += self._telemetry.export_prometheus(sanitize_metric_name(p))
        return body

    def to_dict(self) -> Dict[str, Any]:
        return {
            "meters": {n: m.count for n, m in self._meters.items()},
            "labeled": {family: {"|".join(f"{k}={v}" for k, v in key):
                                 m.count for key, m in cells.items()}
                        for family, cells in self._labeled.items()},
            "gauges": {n: (g() if callable(g) else g)
                       for n, g in self._gauges.items()},
            "timers": {n: {"count": t.count,
                           "totalMs": round(t.total_ms, 3),
                           "maxMs": round(t.max_ms, 3)}
                       for n, t in self._timers.items()},
        }


# canonical metric names (subset of the reference's per-role enums)
class BrokerMeter:
    QUERIES = "queries_total"
    EXCEPTIONS = "query_exceptions_total"
    NO_SERVING_HOST = "no_serving_host_total"
    # single-flight coalescing (broker/broker.py): followers that shared a
    # leader's in-flight execution instead of running their own
    QUERIES_COALESCED = "queries_coalesced_total"
    # admission gate rejections surfaced as 429s (broker/quota.py +
    # server/admission.py at the broker front door)
    QUERIES_REJECTED = "queries_rejected_total"


class BrokerQueryPhase:
    COMPILATION = "COMPILATION"
    ROUTING = "ROUTING"
    SCATTER_GATHER = "SCATTER_GATHER"
    REDUCE = "REDUCE"


class ServerMeter:
    QUERIES = "queries_total"
    DOCS_SCANNED = "docs_scanned_total"
    SEGMENTS_PRUNED = "segments_pruned_total"
    QUERY_EXCEPTIONS = "query_exceptions_total"
    # HBM residency (engine/residency.py; gauges staging_staged_bytes /
    # staging_peak_bytes / staging_budget_bytes ride the same registry)
    STAGING_HITS = "staging_hits_total"
    STAGING_MISSES = "staging_misses_total"
    STAGING_EVICTIONS = "staging_evictions_total"
    STAGING_PIN_BLOCKED = "staging_pin_blocked_evictions_total"
    STAGING_SPILLS = "staging_spills_total"
    STAGING_BORROWS = "staging_borrows_total"
    # host-RAM spill tier (engine/residency.py; gauges staging_host_bytes /
    # staging_host_peak_bytes / staging_host_budget_bytes ride the same
    # registry): demotions move device arrays to host numpy, promotions
    # re-stage them with a plain H2D, host drops are the tier's own LRU
    # evictions, sliced = over-budget queries served via the budget-sliced
    # sharded combine instead of a host-engine spill
    STAGING_DEMOTIONS = "staging_demotions_total"
    STAGING_PROMOTIONS = "staging_promotions_total"
    STAGING_HOST_DROPS = "staging_host_drops_total"
    STAGING_SLICED = "staging_sliced_queries_total"
    # launch coalescing (parallel/launcher.py; gauges launch_queue_depth /
    # launch_max_batch_size ride the same registry)
    LAUNCH_REQUESTS = "combine_launch_requests_total"
    LAUNCHES = "combine_launches_total"
    LAUNCHES_COALESCED = "combine_launches_coalesced_total"
    LAUNCHES_SAVED = "combine_launches_saved_total"
    # adaptive micro-batch window (parallel/launcher.py): dispatch-loop
    # holds taken and straggler requests gathered during a held window
    LAUNCH_WINDOW_WAITS = "launch_window_waits_total"
    LAUNCH_WINDOW_GATHERED = "launch_window_gathered_total"
    # admission gate (server/admission.py)
    ADMISSION_ADMITTED = "admission_admitted_total"
    ADMISSION_REJECTED = "admission_rejected_total"


class ServerQueryPhase:
    SCHEDULER_WAIT = "SCHEDULER_WAIT"
    SEGMENT_PRUNING = "SEGMENT_PRUNING"
    QUERY_EXECUTION = "QUERY_EXECUTION"


_METRIC_SAFE = None


def decision_meter_name(point: str, reason: str) -> str:
    """Meter name for one path-decision histogram cell (the decision
    ledger's /metrics surface, common/tracing.py DecisionLedger): reason
    codes are already snake_case, but defend against stray characters —
    prometheus names admit only [a-zA-Z0-9_:]."""
    global _METRIC_SAFE
    if _METRIC_SAFE is None:
        import re

        _METRIC_SAFE = re.compile(r"[^a-zA-Z0-9_]+")
    p = _METRIC_SAFE.sub("_", point)
    r = _METRIC_SAFE.sub("_", reason)
    return f"decision_declined_total_{p}_{r}"
