"""Access control SPI + basic-auth implementation.

Re-design of the reference's auth stack
(``pinot-broker/.../broker/AccessControlFactory.java`` and the basic-auth
principals of ``pinot-common/.../auth/BasicAuthPrincipal.java``): an
``AccessControl`` interface authenticates a request's headers to a
principal and authorizes (table, access-type) pairs against it. The
default is allow-all; ``BasicAuthAccessControl`` guards REST surfaces with
HTTP Basic credentials and optional per-principal table/permission scoping.
"""

from __future__ import annotations

import base64
import hmac

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional

READ = "READ"
WRITE = "WRITE"


@dataclass
class Principal:
    """Ref: BasicAuthPrincipal — name + scoped tables/permissions."""

    name: str
    password: str = ""
    tables: List[str] = field(default_factory=list)       # [] = all tables
    permissions: List[str] = field(default_factory=list)  # [] = all perms

    def allows(self, table: Optional[str], access_type: str) -> bool:
        """``table=None`` checks only permissions — callers that could not
        resolve a table must fail closed themselves for scoped principals.
        (The query route never passes None: the broker authorizes the
        PARSED table, Broker.handle_sql; admin routes extract the table
        from the route path/body, rest._Api._dispatch.)"""
        if self.permissions and access_type.upper() not in (
                p.upper() for p in self.permissions):
            return False
        if table and self.tables:
            from pinot_tpu.spi.table import raw_table_name

            return table in self.tables or raw_table_name(table) in self.tables
        return True


class AccessControl:
    """The SPI: override both methods."""

    def authenticate(self, headers: Mapping[str, str]) -> Optional[Principal]:
        raise NotImplementedError

    def has_access(self, principal: Optional[Principal],
                   table: Optional[str], access_type: str = READ) -> bool:
        raise NotImplementedError


class AllowAllAccessControl(AccessControl):
    """Default: open cluster (ref: AllowAllAccessControlFactory)."""

    def authenticate(self, headers):
        return Principal("anonymous")

    def has_access(self, principal, table, access_type=READ):
        return True


class BasicAuthAccessControl(AccessControl):
    """HTTP Basic over a static principal list
    (ref: BasicAuthAccessControlFactory)."""

    def __init__(self, principals: List[Principal]):
        self._by_token: Dict[str, Principal] = {}
        for p in principals:
            token = base64.b64encode(
                f"{p.name}:{p.password}".encode("utf-8")).decode("ascii")
            self._by_token[token] = p

    def authenticate(self, headers):
        auth = None
        for k, v in headers.items():
            if k.lower() == "authorization":
                auth = v
                break
        if not auth or not auth.startswith("Basic "):
            return None
        token = auth[len("Basic "):].strip()
        for known, principal in self._by_token.items():
            # constant-time compare: no early-exit credential probing
            if hmac.compare_digest(known, token):
                return principal
        return None

    def has_access(self, principal, table, access_type=READ):
        return principal is not None and principal.allows(table, access_type)


def access_control_from_config(cfg: Optional[Dict]) -> AccessControl:
    """Factory (ref: AccessControlFactory.fromConfiguration). Config shape:
    ``{"type": "basic", "principals": [{"username", "password",
    "tables": [...], "permissions": [...]}]}``; absent/"allowAll" -> open."""
    if not cfg or str(cfg.get("type", "allowAll")).lower() in (
            "allowall", "none"):
        return AllowAllAccessControl()
    if str(cfg["type"]).lower() == "basic":
        principals = [Principal(d["username"], d.get("password", ""),
                                list(d.get("tables") or []),
                                list(d.get("permissions") or []))
                      for d in cfg.get("principals", [])]
        return BasicAuthAccessControl(principals)
    raise ValueError(f"unknown access control type {cfg.get('type')!r}")
