"""SPI layer: the framework-wide contracts every other layer builds on.

Mirrors the reference's ``pinot-spi`` module (SURVEY.md section 2.1):
table/schema config model, layered configuration, filesystem SPI, stream SPI,
record-reader SPI, and the plugin registry.
"""

from pinot_tpu.spi.data import (
    DataType,
    FieldType,
    FieldSpec,
    Schema,
    TimeGranularity,
)
from pinot_tpu.spi.table import (
    TableType,
    TableConfig,
    IndexingConfig,
    SegmentsValidationConfig,
    StarTreeIndexConfig,
    UpsertConfig,
    UpsertMode,
    SegmentPartitionConfig,
    TenantConfig,
    StreamIngestionConfig,
)
from pinot_tpu.spi.config import PinotConfiguration

__all__ = [
    "DataType",
    "FieldType",
    "FieldSpec",
    "Schema",
    "TimeGranularity",
    "TableType",
    "TableConfig",
    "IndexingConfig",
    "SegmentsValidationConfig",
    "StarTreeIndexConfig",
    "UpsertConfig",
    "UpsertMode",
    "SegmentPartitionConfig",
    "TenantConfig",
    "StreamIngestionConfig",
    "PinotConfiguration",
]
