"""Schema and field-spec data model.

Re-design of the reference's ``pinot-spi/.../data/Schema.java`` and
``FieldSpec.java``: a table schema is a named collection of typed fields, each
either a DIMENSION, METRIC, TIME or DATE_TIME column, single- or multi-valued.

TPU-first notes: every data type carries its *device representation*
(``numpy``/``jnp`` dtype) so the storage and engine layers can make layout
decisions (narrowest-int forward indexes, f32 vs f64 accumulation) directly
from the schema. Strings/bytes/json are always dictionary-encoded on device --
the device only ever sees int32 dictIds for them.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, Iterable, List, Optional

import numpy as np


class DataType(Enum):
    """Column value types (ref: pinot-spi FieldSpec.DataType).

    ``stored_np`` is the dtype used in host/npy storage for raw (no-dictionary)
    columns; dictionary-encoded columns store int dictIds regardless.
    """

    INT = ("INT", np.int32, True)
    LONG = ("LONG", np.int64, True)
    FLOAT = ("FLOAT", np.float32, True)
    DOUBLE = ("DOUBLE", np.float64, True)
    BOOLEAN = ("BOOLEAN", np.int32, True)  # stored as 0/1, like the reference pre-0.8 string, now int
    TIMESTAMP = ("TIMESTAMP", np.int64, True)  # millis since epoch
    STRING = ("STRING", np.object_, False)
    JSON = ("JSON", np.object_, False)
    BYTES = ("BYTES", np.object_, False)

    def __init__(self, label: str, stored_np: Any, numeric: bool):
        self.label = label
        self.stored_np = stored_np
        self.numeric = numeric

    @property
    def is_numeric(self) -> bool:
        return self.numeric

    @property
    def is_integral(self) -> bool:
        return self in (DataType.INT, DataType.LONG, DataType.BOOLEAN, DataType.TIMESTAMP)

    @property
    def is_floating(self) -> bool:
        return self in (DataType.FLOAT, DataType.DOUBLE)

    def convert(self, value: Any) -> Any:
        """Coerce a python value to this type (ingestion-time type coercion,
        ref: pinot-segment-local recordtransformer/DataTypeTransformer)."""
        if value is None:
            return None
        if self is DataType.INT:
            return int(value)
        if self in (DataType.LONG, DataType.TIMESTAMP):
            return int(value)
        if self in (DataType.FLOAT, DataType.DOUBLE):
            return float(value)
        if self is DataType.BOOLEAN:
            if isinstance(value, str):
                return 1 if value.lower() in ("true", "1") else 0
            return 1 if value else 0
        if self in (DataType.STRING, DataType.JSON):
            return value if isinstance(value, str) else (
                json.dumps(value) if self is DataType.JSON else str(value))
        if self is DataType.BYTES:
            if isinstance(value, bytes):
                return value
            if isinstance(value, str):
                return bytes.fromhex(value)
            return bytes(value)
        raise ValueError(f"unsupported type {self}")

    @classmethod
    def from_string(cls, s: str) -> "DataType":
        return cls[s.upper()]


class FieldType(Enum):
    """Role of a column (ref: FieldSpec.FieldType)."""

    DIMENSION = "DIMENSION"
    METRIC = "METRIC"
    TIME = "TIME"
    DATE_TIME = "DATE_TIME"


# Default null placeholder values, mirroring the reference's
# FieldSpec.DEFAULT_* constants (pinot-spi/.../data/FieldSpec.java).
_DEFAULT_DIMENSION_NULL = {
    DataType.INT: np.iinfo(np.int32).min,
    DataType.LONG: np.iinfo(np.int64).min,
    DataType.FLOAT: float("-inf"),
    DataType.DOUBLE: float("-inf"),
    DataType.BOOLEAN: 0,
    DataType.TIMESTAMP: 0,
    DataType.STRING: "null",
    DataType.JSON: "null",
    DataType.BYTES: b"",
}
_DEFAULT_METRIC_NULL = {
    DataType.INT: 0,
    DataType.LONG: 0,
    DataType.FLOAT: 0.0,
    DataType.DOUBLE: 0.0,
    DataType.BOOLEAN: 0,
    DataType.TIMESTAMP: 0,
    DataType.STRING: "null",
    DataType.JSON: "null",
    DataType.BYTES: b"",
}


@dataclass
class TimeGranularity:
    """Time unit + size for TIME / DATE_TIME fields."""

    unit: str = "MILLISECONDS"  # MILLISECONDS | SECONDS | MINUTES | HOURS | DAYS
    size: int = 1

    _MILLIS = {
        "MILLISECONDS": 1,
        "SECONDS": 1000,
        "MINUTES": 60_000,
        "HOURS": 3_600_000,
        "DAYS": 86_400_000,
    }

    def to_millis(self, value: int) -> int:
        return int(value) * self.size * self._MILLIS[self.unit.upper()]

    def to_dict(self) -> Dict[str, Any]:
        return {"unit": self.unit, "size": self.size}

    @classmethod
    def from_dict(cls, d: Any) -> "TimeGranularity":
        # The reference serializes DATE_TIME granularity as "size:UNIT"
        # (e.g. "1:DAYS", DateTimeGranularitySpec); TIME uses a dict.
        if isinstance(d, str):
            parts = d.split(":")
            return cls(unit=parts[1], size=int(parts[0]))
        return cls(unit=d.get("unit", "MILLISECONDS"), size=int(d.get("size", 1)))


@dataclass
class FieldSpec:
    """One column's spec (ref: pinot-spi/.../data/FieldSpec.java)."""

    name: str
    data_type: DataType
    field_type: FieldType = FieldType.DIMENSION
    single_value: bool = True
    default_null_value: Any = None
    max_length: int = 512
    granularity: Optional[TimeGranularity] = None  # TIME / DATE_TIME only
    # DATE_TIME format string, e.g. "1:MILLISECONDS:EPOCH" (kept for config parity)
    format: Optional[str] = None
    # ingestion-time derived-column expression (ref: FieldSpec.transformFunction)
    transform_function: Optional[str] = None

    def __post_init__(self):
        if isinstance(self.data_type, str):
            self.data_type = DataType.from_string(self.data_type)
        if isinstance(self.field_type, str):
            self.field_type = FieldType[self.field_type.upper()]
        if self.default_null_value is None:
            table = (_DEFAULT_METRIC_NULL if self.field_type is FieldType.METRIC
                     else _DEFAULT_DIMENSION_NULL)
            self.default_null_value = table[self.data_type]
        else:
            self.default_null_value = self.data_type.convert(self.default_null_value)

    @property
    def is_dimension(self) -> bool:
        return self.field_type in (FieldType.DIMENSION, FieldType.TIME, FieldType.DATE_TIME)

    @property
    def is_metric(self) -> bool:
        return self.field_type is FieldType.METRIC

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "name": self.name,
            "dataType": self.data_type.label,
            "fieldType": self.field_type.value,
        }
        if not self.single_value:
            d["singleValueField"] = False
        if self.default_null_value is not None:
            v = self.default_null_value
            d["defaultNullValue"] = v.hex() if isinstance(v, bytes) else v
        if self.granularity is not None:
            d["granularity"] = self.granularity.to_dict()
        if self.format is not None:
            d["format"] = self.format
        if self.max_length != 512:
            d["maxLength"] = self.max_length
        if self.transform_function:
            d["transformFunction"] = self.transform_function
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any], field_type: Optional[FieldType] = None) -> "FieldSpec":
        """``field_type`` is the fallback when the dict has no explicit
        ``fieldType`` (an explicit one always wins, so TIME specs serialized
        under ``dateTimeFieldSpecs`` round-trip unchanged)."""
        dt = DataType.from_string(d["dataType"])
        default = d.get("defaultNullValue")
        if default is not None and dt is DataType.BYTES and isinstance(default, str):
            default = bytes.fromhex(default)
        gran = d.get("granularity")
        explicit_ft = d.get("fieldType")
        ft = (FieldType[explicit_ft.upper()] if explicit_ft
              else (field_type or FieldType.DIMENSION))
        return cls(
            name=d["name"],
            data_type=dt,
            field_type=ft,
            single_value=d.get("singleValueField", True),
            default_null_value=default,
            max_length=d.get("maxLength", 512),
            granularity=TimeGranularity.from_dict(gran) if gran else None,
            format=d.get("format"),
            transform_function=d.get("transformFunction"),
        )


class Schema:
    """Table schema: ordered column name -> FieldSpec map.

    Serialization follows the reference's JSON schema layout
    (``dimensionFieldSpecs`` / ``metricFieldSpecs`` / ``dateTimeFieldSpecs`` /
    ``timeFieldSpec``) so reference schema files can be loaded directly.
    """

    def __init__(self, schema_name: str, field_specs: Iterable[FieldSpec],
                 primary_key_columns: Optional[List[str]] = None):
        self.schema_name = schema_name
        self._specs: Dict[str, FieldSpec] = {}
        for fs in field_specs:
            if fs.name in self._specs:
                raise ValueError(f"duplicate column {fs.name!r} in schema {schema_name!r}")
            self._specs[fs.name] = fs
        self.primary_key_columns = list(primary_key_columns or [])
        for pk in self.primary_key_columns:
            if pk not in self._specs:
                raise ValueError(f"primary key column {pk!r} not in schema")

    # -- accessors ---------------------------------------------------------
    @property
    def column_names(self) -> List[str]:
        return list(self._specs.keys())

    @property
    def field_specs(self) -> List[FieldSpec]:
        return list(self._specs.values())

    @property
    def dimension_names(self) -> List[str]:
        return [n for n, fs in self._specs.items() if fs.is_dimension]

    @property
    def metric_names(self) -> List[str]:
        return [n for n, fs in self._specs.items() if fs.is_metric]

    @property
    def time_column(self) -> Optional[str]:
        for n, fs in self._specs.items():
            if fs.field_type in (FieldType.TIME, FieldType.DATE_TIME):
                return n
        return None

    def has_column(self, name: str) -> bool:
        return name in self._specs

    def field_spec(self, name: str) -> FieldSpec:
        try:
            return self._specs[name]
        except KeyError:
            raise KeyError(f"column {name!r} not found in schema {self.schema_name!r}") from None

    def __contains__(self, name: str) -> bool:
        return name in self._specs

    def __eq__(self, other) -> bool:
        return (isinstance(other, Schema) and other.schema_name == self.schema_name
                and other.to_dict() == self.to_dict())

    # -- serde -------------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        dims, mets, dts = [], [], []
        for fs in self._specs.values():
            if fs.field_type is FieldType.METRIC:
                mets.append(fs.to_dict())
            elif fs.field_type in (FieldType.DATE_TIME, FieldType.TIME):
                dts.append(fs.to_dict())
            else:
                dims.append(fs.to_dict())
        d: Dict[str, Any] = {"schemaName": self.schema_name}
        if dims:
            d["dimensionFieldSpecs"] = dims
        if mets:
            d["metricFieldSpecs"] = mets
        if dts:
            d["dateTimeFieldSpecs"] = dts
        if self.primary_key_columns:
            d["primaryKeyColumns"] = self.primary_key_columns
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Schema":
        specs: List[FieldSpec] = []
        for fd in d.get("dimensionFieldSpecs", []):
            specs.append(FieldSpec.from_dict(fd, FieldType.DIMENSION))
        for fd in d.get("metricFieldSpecs", []):
            specs.append(FieldSpec.from_dict(fd, FieldType.METRIC))
        # legacy single timeFieldSpec from reference schemas
        tfs = d.get("timeFieldSpec")
        if tfs:
            inner = tfs.get("incomingGranularitySpec", tfs)
            specs.append(FieldSpec(
                name=inner["name"],
                data_type=DataType.from_string(inner["dataType"]),
                field_type=FieldType.TIME,
                granularity=TimeGranularity(unit=inner.get("timeType", "MILLISECONDS")),
            ))
        for fd in d.get("dateTimeFieldSpecs", []):
            specs.append(FieldSpec.from_dict(fd, FieldType.DATE_TIME))
        return cls(d["schemaName"], specs, d.get("primaryKeyColumns"))

    @classmethod
    def from_json(cls, s: str) -> "Schema":
        return cls.from_dict(json.loads(s))

    @classmethod
    def from_file(cls, path: str) -> "Schema":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def __repr__(self) -> str:
        return f"Schema({self.schema_name!r}, columns={self.column_names})"
