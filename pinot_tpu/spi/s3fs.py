"""S3 PinotFS: the AWS REST protocol with SigV4 request signing.

Re-design of the reference's S3 filesystem plugin
(``pinot-plugins/pinot-file-system/pinot-s3/.../S3PinotFS.java``) WITHOUT
the AWS SDK: this module speaks the S3 REST API itself — ListObjectsV2,
GetObject, PutObject, DeleteObject — signing every request with AWS
Signature Version 4 (HMAC-SHA256 chain over the canonical request), the
same bytes a real S3/minio endpoint verifies.

Credentials/endpoint resolve like the SDK's default chain subset:
``AWS_ACCESS_KEY_ID`` / ``AWS_SECRET_ACCESS_KEY`` / ``AWS_REGION`` env
vars, plus ``PINOT_S3_ENDPOINT`` for custom endpoints (the reference's
``region``/``endpoint`` configs for minio-style stores). Path-style
addressing (``endpoint/bucket/key``) keeps custom endpoints simple.

``MockS3Server`` (tests) verifies the SIGNATURE of every request against
the shared secret before serving it, so the client's SigV4 implementation
is exercised for real, not assumed.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import os
import shutil
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from typing import Dict, List, Optional, Tuple

from pinot_tpu.spi.filesystem import PinotFS, register_fs

_ALGO = "AWS4-HMAC-SHA256"


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode("utf-8"), hashlib.sha256).digest()


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def signing_key(secret: str, date: str, region: str, service: str) -> bytes:
    """The SigV4 key derivation chain."""
    k = _hmac(("AWS4" + secret).encode("utf-8"), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def sign_request(method: str, url: str, headers: Dict[str, str],
                 payload: bytes, access_key: str, secret_key: str,
                 region: str, now: Optional[datetime.datetime] = None
                 ) -> Dict[str, str]:
    """Add x-amz-date / x-amz-content-sha256 / Authorization (SigV4).
    Returns the full header map to send. Pure function of its inputs so
    the mock server can recompute and VERIFY the same signature."""
    u = urllib.parse.urlparse(url)
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date = now.strftime("%Y%m%d")
    payload_hash = _sha256(payload)

    out = dict(headers)
    out["host"] = u.netloc
    out["x-amz-date"] = amz_date
    out["x-amz-content-sha256"] = payload_hash

    signed_names = sorted(k.lower() for k in out)
    canonical_headers = "".join(
        f"{k}:{out[_find(out, k)].strip()}\n" for k in signed_names)
    signed_headers = ";".join(signed_names)
    # query string: sorted by key, values URI-encoded
    q = urllib.parse.parse_qsl(u.query, keep_blank_values=True)
    canonical_query = "&".join(
        f"{urllib.parse.quote(k, safe='')}={urllib.parse.quote(v, safe='')}"
        for k, v in sorted(q))
    # canonical URI: the path AS SENT (already single-encoded by the
    # caller) — S3 explicitly does NOT double-encode, so quoting again
    # here would 403 any key containing a space/':'/unicode
    canonical = "\n".join([
        method, u.path or "/",
        canonical_query, canonical_headers, signed_headers, payload_hash])
    scope = f"{date}/{region}/s3/aws4_request"
    to_sign = "\n".join([_ALGO, amz_date, scope, _sha256(canonical.encode())])
    sig = hmac.new(signing_key(secret_key, date, region, "s3"),
                   to_sign.encode("utf-8"), hashlib.sha256).hexdigest()
    out["Authorization"] = (
        f"{_ALGO} Credential={access_key}/{scope}, "
        f"SignedHeaders={signed_headers}, Signature={sig}")
    return out


def _find(d: Dict[str, str], lower: str) -> str:
    for k in d:
        if k.lower() == lower:
            return k
    raise KeyError(lower)


class S3PinotFS(PinotFS):
    """Ref: S3PinotFS.java — the deep-store SPI over the S3 REST API."""

    scheme = "s3"

    def __init__(self, endpoint: Optional[str] = None,
                 access_key: Optional[str] = None,
                 secret_key: Optional[str] = None,
                 region: Optional[str] = None):
        self.endpoint = (endpoint or os.environ.get("PINOT_S3_ENDPOINT")
                         or "https://s3.amazonaws.com").rstrip("/")
        self.access_key = access_key or os.environ.get(
            "AWS_ACCESS_KEY_ID", "")
        self.secret_key = secret_key or os.environ.get(
            "AWS_SECRET_ACCESS_KEY", "")
        self.region = region or os.environ.get("AWS_REGION", "us-east-1")

    # -- request plumbing ---------------------------------------------------
    def _call(self, method: str, bucket: str, key: str = "",
              query: str = "", payload: bytes = b"") -> bytes:
        path = f"/{bucket}" + (f"/{urllib.parse.quote(key)}" if key else "")
        url = self.endpoint + path + (f"?{query}" if query else "")
        headers = sign_request(method, url, {}, payload,
                               self.access_key, self.secret_key, self.region)
        req = urllib.request.Request(url, data=payload or None,
                                     headers=headers, method=method)
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.read()

    def _list_page(self, bucket: str, prefix: str,
                   token: Optional[str]) -> Tuple[List[str], Optional[str]]:
        q = "list-type=2&prefix=" + urllib.parse.quote(prefix, safe="")
        if token:
            q += "&continuation-token=" + urllib.parse.quote(token, safe="")
        root = ET.fromstring(self._call("GET", bucket, query=q))
        ns = root.tag.split("}")[0] + "}" if root.tag.startswith("{") else ""
        keys = [c.findtext(f"{ns}Key") for c in root.iter(f"{ns}Contents")]
        truncated = (root.findtext(f"{ns}IsTruncated") or "").lower() \
            == "true"
        return keys, (root.findtext(f"{ns}NextContinuationToken")
                      if truncated else None)

    @staticmethod
    def _parse(uri: str) -> Tuple[str, str]:
        u = urllib.parse.urlparse(uri)
        return u.netloc, u.path.lstrip("/")

    # -- PinotFS surface ----------------------------------------------------
    def list_files(self, uri: str) -> List[str]:
        bucket, prefix = self._parse(uri)
        if prefix and not prefix.endswith("/"):
            prefix += "/"
        out: List[str] = []
        token: Optional[str] = None
        while True:  # follow ListObjectsV2 pagination to completion
            keys, token = self._list_page(bucket, prefix, token)
            out.extend(keys)
            if token is None:
                return out

    def exists(self, uri: str) -> bool:
        try:
            return bool(self.list_files(uri)) or self._head(uri)
        except urllib.error.HTTPError:
            return False

    def _head(self, uri: str) -> bool:
        bucket, key = self._parse(uri)
        try:
            self._call("HEAD", bucket, key)  # no body transfer
            return True
        except urllib.error.HTTPError:
            return False

    def delete(self, uri: str) -> None:
        bucket, key = self._parse(uri)
        for obj_key in (self.list_files(uri) or [key]):
            self._call("DELETE", bucket, obj_key)

    def copy_from_local_dir(self, local_dir: str, uri: str) -> None:
        bucket, prefix = self._parse(uri)
        for root, _dirs, files in os.walk(local_dir):
            for f in files:
                full = os.path.join(root, f)
                rel = os.path.relpath(full, local_dir)
                with open(full, "rb") as fh:
                    self._call("PUT", bucket,
                               f"{prefix}/{rel}".replace(os.sep, "/"),
                               payload=fh.read())

    def copy_to_local_dir(self, uri: str, local_dir: str) -> str:
        bucket, prefix = self._parse(uri)
        name = prefix.rstrip("/").rsplit("/", 1)[-1]
        seg_dir = os.path.abspath(os.path.join(local_dir, name))
        base = prefix.rstrip("/") + "/"
        keys = self.list_files(uri)
        if not keys:
            # a typo'd/missing segment must FAIL, not return a path to a
            # directory that was never created
            raise FileNotFoundError(f"no objects under {uri!r}")
        for key in keys:
            rel = key[len(base):]
            if not rel or key.endswith("/"):
                continue  # directory-marker objects (console-created)
            dst = os.path.abspath(os.path.join(seg_dir, rel))
            if not dst.startswith(seg_dir + os.sep):
                raise ValueError(f"s3 listing returned an escaping key "
                                 f"{key!r}")
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            with open(dst, "wb") as f:
                f.write(self._call("GET", bucket, key))
        return seg_dir


register_fs("s3", S3PinotFS)


# --------------------------------------------------------------------------
# in-test server (the minio analogue) — VERIFIES SigV4 before serving
# --------------------------------------------------------------------------

class MockS3Server:
    """Path-style S3 endpoint backed by a dict; every request's SigV4
    signature is recomputed from the shared secret and mismatches get 403,
    so the client-side signing is genuinely exercised."""

    def __init__(self, access_key: str = "test-access",
                 secret_key: str = "test-secret",
                 region: str = "us-east-1", port: int = 0):
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.objects: Dict[str, bytes] = {}   # "bucket/key" -> bytes
        self.access_key, self.secret_key = access_key, secret_key
        self.region = region
        self.page_size = 1000  # tests shrink this to exercise pagination
        srv = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _verify(self, payload: bytes) -> bool:
                auth = self.headers.get("Authorization", "")
                amz_date = self.headers.get("x-amz-date", "")
                if not auth.startswith(_ALGO) or not amz_date:
                    return False
                now = datetime.datetime.strptime(
                    amz_date, "%Y%m%dT%H%M%SZ").replace(
                    tzinfo=datetime.timezone.utc)
                url = f"http://{self.headers['host']}{self.path}"
                want = sign_request(
                    self.command, url, {}, payload, srv.access_key,
                    srv.secret_key, srv.region, now=now)["Authorization"]
                return hmac.compare_digest(auth, want)

            def _respond(self, code: int, body: bytes = b"",
                         ctype: str = "application/xml") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if not self._verify(b""):
                    return self._respond(403, b"<Error>SigMismatch</Error>")
                u = urllib.parse.urlparse(self.path)
                q = dict(urllib.parse.parse_qsl(u.query))
                bucket = u.path.lstrip("/").split("/", 1)[0]
                if "list-type" in q:
                    prefix = q.get("prefix", "")
                    keys = sorted(
                        k.split("/", 1)[1] for k in srv.objects
                        if k.startswith(f"{bucket}/")
                        and k.split("/", 1)[1].startswith(prefix))
                    start = q.get("continuation-token", "")
                    if start:
                        keys = [k for k in keys if k > start]
                    page = keys[:srv.page_size]
                    truncated = len(keys) > len(page)
                    items = "".join(
                        f"<Contents><Key>{k}</Key></Contents>" for k in page)
                    extra = (f"<IsTruncated>true</IsTruncated>"
                             f"<NextContinuationToken>{page[-1]}"
                             f"</NextContinuationToken>" if truncated
                             else "<IsTruncated>false</IsTruncated>")
                    return self._respond(
                        200, (f"<ListBucketResult>{items}{extra}"
                              f"</ListBucketResult>").encode())
                key = urllib.parse.unquote(
                    u.path.lstrip("/").split("/", 1)[1])
                obj = srv.objects.get(f"{bucket}/{key}")
                if obj is None:
                    return self._respond(404, b"<Error>NoSuchKey</Error>")
                return self._respond(200, obj, "binary/octet-stream")

            def do_HEAD(self):
                if not self._verify(b""):
                    return self._respond(403)
                u = urllib.parse.urlparse(self.path)
                bucket, key = u.path.lstrip("/").split("/", 1)
                present = f"{bucket}/{urllib.parse.unquote(key)}" \
                    in srv.objects
                return self._respond(200 if present else 404)

            def do_PUT(self):
                n = int(self.headers.get("Content-Length") or 0)
                payload = self.rfile.read(n)
                if not self._verify(payload):
                    return self._respond(403, b"<Error>SigMismatch</Error>")
                u = urllib.parse.urlparse(self.path)
                bucket, key = u.path.lstrip("/").split("/", 1)
                srv.objects[f"{bucket}/{urllib.parse.unquote(key)}"] = payload
                return self._respond(200)

            def do_DELETE(self):
                if not self._verify(b""):
                    return self._respond(403, b"<Error>SigMismatch</Error>")
                u = urllib.parse.urlparse(self.path)
                bucket, key = u.path.lstrip("/").split("/", 1)
                srv.objects.pop(f"{bucket}/{urllib.parse.unquote(key)}", None)
                return self._respond(204)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_port
        self.endpoint = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="mock-s3")

    def start(self) -> "MockS3Server":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
