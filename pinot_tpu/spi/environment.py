"""Environment provider SPI: fault-domain discovery for placement.

Re-design of ``pinot-plugins/pinot-environment/`` (``PinotEnvironmentProvider``
SPI + ``AzureEnvironmentProvider`` reading the instance-metadata service's
``platformFaultDomain``): a provider surfaces the failure domain the process
runs in, the controller records it on the instance, and segment assignment
spreads replicas across distinct domains so a rack/zone loss cannot take
out every replica.

Cloud metadata services are unreachable in this environment, so the
concrete providers are config/env driven — the SPI boundary is the same.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional


class PinotEnvironmentProvider:
    """The SPI (ref: PinotEnvironmentProvider.getEnvironment)."""

    def get_environment(self) -> Dict[str, str]:
        """Arbitrary key/value environment facts; ``failureDomain`` is the
        one placement consumes."""
        raise NotImplementedError

    def failure_domain(self) -> Optional[str]:
        return self.get_environment().get("failureDomain")


class NoOpEnvironmentProvider(PinotEnvironmentProvider):
    """Default: no environment facts (single-domain clusters)."""

    def get_environment(self) -> Dict[str, str]:
        return {}


class EnvVarEnvironmentProvider(PinotEnvironmentProvider):
    """Reads PINOT_FAILURE_DOMAIN (the operator/scheduler injects it, the
    way cloud deployments template zone labels into pod env)."""

    def get_environment(self) -> Dict[str, str]:
        fd = os.environ.get("PINOT_FAILURE_DOMAIN")
        return {"failureDomain": fd} if fd else {}


_REGISTRY: Dict[str, Callable[[], PinotEnvironmentProvider]] = {
    "noop": NoOpEnvironmentProvider,
    "env": EnvVarEnvironmentProvider,
}


def register_environment_provider(
        name: str, ctor: Callable[[], PinotEnvironmentProvider]) -> None:
    _REGISTRY[name.lower()] = ctor


def get_environment_provider(
        name: str = "env") -> PinotEnvironmentProvider:
    ctor = _REGISTRY.get(name.lower())
    if ctor is None:
        raise ValueError(f"no environment provider {name!r} "
                         f"(registered: {sorted(_REGISTRY)})")
    return ctor()
