"""Retry policies + service lifecycle contracts.

Re-design of ``pinot-spi/.../utils/retry/`` (``RetryPolicy`` +
``RetryPolicies`` factories + ``AttemptsExceededException``) and
``pinot-spi/.../services/ServiceStartable.java`` (the role-process
lifecycle contract ``StartServiceManagerCommand`` drives).
"""

from __future__ import annotations

import random
import time
from typing import Callable, List, Optional, TypeVar

T = TypeVar("T")


class AttemptsExceededError(Exception):
    """Ref: AttemptsExceededException — the operation never succeeded."""

    def __init__(self, attempts: int, last: Optional[BaseException]):
        super().__init__(f"operation failed after {attempts} attempts: "
                         f"{last}")
        self.attempts = attempts
        self.last = last


class RetryPolicy:
    """Ref: RetryPolicy.attempt — run ``op`` until it returns without
    raising a retriable error, sleeping policy-defined delays between
    attempts. ``retriable`` gates which exceptions retry (defaults to
    everything except ``ValueError`` — permanent input errors)."""

    def __init__(self, max_attempts: int):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = max_attempts

    def delay_s(self, attempt: int) -> float:
        raise NotImplementedError

    def attempt(self, op: Callable[[], T],
                retriable: Optional[Callable[[BaseException], bool]] = None
                ) -> T:
        last: Optional[BaseException] = None
        for i in range(self.max_attempts):
            try:
                return op()
            except BaseException as e:  # noqa: BLE001 — gated below
                if isinstance(e, (KeyboardInterrupt, SystemExit)):
                    raise
                if retriable is not None:
                    if not retriable(e):
                        raise
                elif isinstance(e, ValueError):
                    raise
                last = e
                if i + 1 < self.max_attempts:
                    time.sleep(self.delay_s(i))
        raise AttemptsExceededError(self.max_attempts, last) from last


class FixedDelayRetryPolicy(RetryPolicy):
    """Ref: FixedDelayRetryPolicy."""

    def __init__(self, max_attempts: int, delay_ms: float):
        super().__init__(max_attempts)
        self._delay = delay_ms / 1e3

    def delay_s(self, attempt: int) -> float:
        return self._delay


class ExponentialBackoffRetryPolicy(RetryPolicy):
    """Ref: ExponentialBackoffRetryPolicy — the delay before attempt N is
    a uniform draw from [0, initial * scale^N) (the reference randomizes
    to avoid thundering herds)."""

    def __init__(self, max_attempts: int, initial_delay_ms: float,
                 delay_scale: float = 2.0, randomize: bool = True):
        super().__init__(max_attempts)
        self._initial = initial_delay_ms / 1e3
        self._scale = delay_scale
        self._randomize = randomize

    def delay_s(self, attempt: int) -> float:
        cap = self._initial * (self._scale ** attempt)
        return random.uniform(0, cap) if self._randomize else cap


def exponential_backoff(max_attempts: int = 3, initial_delay_ms: float = 100,
                        delay_scale: float = 2.0
                        ) -> ExponentialBackoffRetryPolicy:
    """Ref: RetryPolicies.exponentialBackoffRetryPolicy."""
    return ExponentialBackoffRetryPolicy(max_attempts, initial_delay_ms,
                                         delay_scale)


def fixed_delay(max_attempts: int = 3, delay_ms: float = 100
                ) -> FixedDelayRetryPolicy:
    """Ref: RetryPolicies.fixedDelayRetryPolicy."""
    return FixedDelayRetryPolicy(max_attempts, delay_ms)


# --------------------------------------------------------------------------
# service lifecycle (ref: ServiceStartable.java + StartServiceManagerCommand)
# --------------------------------------------------------------------------

class ServiceStartable:
    """The role-process contract: start/stop + identity."""

    def start(self) -> None:
        raise NotImplementedError

    def stop(self) -> None:
        raise NotImplementedError

    @property
    def service_role(self) -> str:
        raise NotImplementedError


class ServiceManager:
    """Start services in registration order, stop in reverse — and stop
    the already-started prefix if a later start fails (the reference's
    bootstrap ordering: controller before broker before server)."""

    def __init__(self):
        self._services: List[ServiceStartable] = []
        self._started: List[ServiceStartable] = []

    def register(self, svc: ServiceStartable) -> "ServiceManager":
        self._services.append(svc)
        return self

    def start_all(self) -> None:
        for svc in self._services:
            try:
                svc.start()
            except BaseException:
                self.stop_all()
                raise
            self._started.append(svc)

    def stop_all(self) -> None:
        for svc in reversed(self._started):
            try:
                svc.stop()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        self._started.clear()

    @property
    def roles(self) -> List[str]:
        return [s.service_role for s in self._services]
