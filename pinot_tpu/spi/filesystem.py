"""PinotFS SPI: pluggable deep-store filesystem + segment fetchers.

Re-design of ``pinot-spi/.../filesystem/PinotFS.java`` (copy / move /
delete / exists / listFiles over scheme-addressed URIs, with
``LocalPinotFS`` and a scheme registry ``PinotFSFactory``) plus the
download side of ``pinot-common/.../utils/fetcher/SegmentFetcherFactory``
(HTTP fetcher): servers resolve a segment's ``downloadUrl`` through this
layer instead of assuming ``file://`` paths, so S3/GCS-class stores slot
in by registering a scheme.

Segment layout note: a "segment" in the deep store is a DIRECTORY here
(file-per-index, the v1 layout); ``copy_to_local_dir`` materializes it
locally. Remote stores that hold tarballs can override ``fetch_segment``.
"""

from __future__ import annotations

import os
import shutil
import urllib.parse
import urllib.request

from typing import Callable, Dict, List, Optional


class PinotFS:
    """Ref: PinotFS.java — the operative subset."""

    scheme = ""

    def exists(self, uri: str) -> bool:
        raise NotImplementedError

    def list_files(self, uri: str) -> List[str]:
        raise NotImplementedError

    def delete(self, uri: str) -> None:
        raise NotImplementedError

    def copy_to_local_dir(self, uri: str, local_dir: str) -> str:
        """Materialize the segment at ``uri`` under ``local_dir``; returns
        the local segment directory."""
        raise NotImplementedError

    def copy_from_local_dir(self, local_dir: str, uri: str) -> None:
        raise NotImplementedError


class LocalPinotFS(PinotFS):
    """file:// (and bare paths) — ref: LocalPinotFS.java. Local segments
    are served in place: no copy, the mmap loader reads them directly."""

    scheme = "file"

    @staticmethod
    def _path(uri: str) -> str:
        if uri.startswith("file://"):
            return uri[len("file://"):]
        return uri

    def exists(self, uri: str) -> bool:
        return os.path.exists(self._path(uri))

    def list_files(self, uri: str) -> List[str]:
        p = self._path(uri)
        return sorted(os.path.join(p, f) for f in os.listdir(p))

    def delete(self, uri: str) -> None:
        p = self._path(uri)
        if os.path.isdir(p):
            shutil.rmtree(p, ignore_errors=True)
        elif os.path.exists(p):
            os.remove(p)

    def copy_to_local_dir(self, uri: str, local_dir: str) -> str:
        return self._path(uri)  # already local — serve in place

    def copy_from_local_dir(self, local_dir: str, uri: str) -> None:
        dst = self._path(uri)
        if os.path.abspath(local_dir) != os.path.abspath(dst):
            shutil.copytree(local_dir, dst, dirs_exist_ok=True)


class HttpSegmentFetcher(PinotFS):
    """http(s):// download-only fetcher (ref: HttpSegmentFetcher /
    FileUploadDownloadClient): GET ``<url>/<file>`` for each file listed
    at ``<url>/__files__`` (the controller's segment-download endpoint
    shape reduced to static listing)."""

    scheme = "http"

    def exists(self, uri: str) -> bool:
        try:
            urllib.request.urlopen(f"{uri}/__files__", timeout=10).read()
            return True
        except Exception:  # noqa: BLE001 — existence probe
            return False

    def list_files(self, uri: str) -> List[str]:
        import json

        with urllib.request.urlopen(f"{uri}/__files__", timeout=30) as r:
            return json.loads(r.read().decode())

    def delete(self, uri: str) -> None:
        raise NotImplementedError("http deep store is read-only")

    def copy_from_local_dir(self, local_dir: str, uri: str) -> None:
        raise NotImplementedError("http deep store is read-only")

    def copy_to_local_dir(self, uri: str, local_dir: str) -> str:
        name = uri.rstrip("/").rsplit("/", 1)[-1]
        seg_dir = os.path.abspath(os.path.join(local_dir, name))
        os.makedirs(seg_dir, exist_ok=True)
        for rel in self.list_files(uri):
            dst = os.path.abspath(os.path.join(seg_dir, rel))
            # server-supplied names must stay INSIDE the segment dir
            if (os.path.isabs(rel)
                    or not dst.startswith(seg_dir + os.sep)):
                raise ValueError(
                    f"deep store returned an escaping file name {rel!r}")
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            with urllib.request.urlopen(f"{uri}/{rel}", timeout=60) as r, \
                    open(dst, "wb") as f:
                shutil.copyfileobj(r, f)
        return seg_dir


# --------------------------------------------------------------------------
# registry (ref: PinotFSFactory)
# --------------------------------------------------------------------------

_REGISTRY: Dict[str, Callable[[], PinotFS]] = {
    "file": LocalPinotFS,
    "": LocalPinotFS,
    "http": HttpSegmentFetcher,
    "https": HttpSegmentFetcher,
}


def register_fs(scheme: str, ctor: Callable[[], PinotFS]) -> None:
    _REGISTRY[scheme.lower()] = ctor


def get_fs(uri: str) -> PinotFS:
    scheme = urllib.parse.urlparse(uri).scheme.lower()
    if scheme == "s3" and "s3" not in _REGISTRY:
        from pinot_tpu.spi import s3fs  # noqa: F401 — registers scheme s3
    ctor = _REGISTRY.get(scheme)
    if ctor is None:
        raise ValueError(f"no PinotFS registered for scheme {scheme!r} "
                         f"(registered: {sorted(_REGISTRY)})")
    return ctor()


def fetch_segment(download_url: str, local_dir: str,
                  retries: int = 3, backoff_s: float = 0.2,
                  crypter: Optional[str] = None) -> str:
    """Resolve a segment downloadUrl to a local segment directory (the
    server's downloadSegmentFromDeepStore, BaseTableDataManager.java:388).

    Retries with exponential backoff (ref: SegmentFetcherFactory
    fetchSegmentToLocal wrapping fetchers in RetryPolicies) and, when a
    ``crypter`` name is given, decrypts every downloaded file
    (ref: fetchAndDecryptSegmentToLocal + the crypt SPI)."""
    from pinot_tpu.spi.retry import ExponentialBackoffRetryPolicy

    fs = get_fs(download_url)  # unknown scheme fails fast, no retries
    # ValueError (path-escape rejection, bad config) is permanent and
    # never retried — the policy's default retriable gate
    local = ExponentialBackoffRetryPolicy(
        max_attempts=max(retries, 1), initial_delay_ms=backoff_s * 1e3,
        randomize=False,
    ).attempt(lambda: fs.copy_to_local_dir(download_url, local_dir))
    if crypter:
        from pinot_tpu.spi.crypt import get_crypter

        # decrypt a LOCAL copy, never the deep-store original: file://
        # stores serve in place (LocalPinotFS.copy_to_local_dir), and an
        # in-place decrypt would silently de-encrypt the shared store
        dst = os.path.join(local_dir, os.path.basename(local.rstrip("/")))
        if os.path.abspath(local) != os.path.abspath(dst):
            shutil.copytree(local, dst, dirs_exist_ok=True)
            local = dst
        c = get_crypter(crypter)
        for root, _dirs, files in os.walk(local):
            for f in files:
                c.decrypt(os.path.join(root, f))
    return local
