"""Segment encryption SPI: crypters applied around deep-store transfer.

Re-design of ``pinot-common/.../crypt/`` (``PinotCrypter`` SPI +
``PinotCrypterFactory`` + ``NoOpPinotCrypter``): a crypter encrypts a
segment file before it reaches the deep store and decrypts it after
download (``SegmentFetcherFactory.fetchAndDecryptSegmentToLocal``). The
registry is name-keyed like the reference's factory.

The built-in keyed crypter is a SHA-256 CTR keystream XOR — a real
symmetric stream cipher built only from the standard library (the
environment has no cryptography package; the reference likewise treats the
cipher itself as pluggable and ships only NoOp in-tree).
"""

from __future__ import annotations

import hashlib
import os
from typing import Callable, Dict


class PinotCrypter:
    """The SPI: both methods transform a file IN PLACE."""

    def encrypt(self, path: str) -> None:
        raise NotImplementedError

    def decrypt(self, path: str) -> None:
        raise NotImplementedError


class NoOpPinotCrypter(PinotCrypter):
    """Ref: NoOpPinotCrypter — the default when tables opt out."""

    def encrypt(self, path: str) -> None:
        pass

    def decrypt(self, path: str) -> None:
        pass


class KeyedStreamCrypter(PinotCrypter):
    """Symmetric XOR stream cipher with a SHA-256 CTR keystream.

    Layout of an encrypted file: 16-byte random nonce || ciphertext.
    Keystream block i = SHA256(key || nonce || i_le8); XOR is its own
    inverse so decrypt re-derives the stream from the stored nonce.
    """

    _MAGIC = b"PCRY1\x00"

    def __init__(self, key: bytes):
        if not key:
            raise ValueError("empty crypter key")
        self.key = key

    def _stream(self, nonce: bytes, n: int) -> bytes:
        out = bytearray()
        i = 0
        while len(out) < n:
            out += hashlib.sha256(
                self.key + nonce + i.to_bytes(8, "little")).digest()
            i += 1
        return bytes(out[:n])

    def encrypt(self, path: str) -> None:
        with open(path, "rb") as f:
            plain = f.read()
        nonce = os.urandom(16)
        cipher = bytes(a ^ b for a, b in
                       zip(plain, self._stream(nonce, len(plain))))
        with open(path, "wb") as f:
            f.write(self._MAGIC + nonce + cipher)

    def decrypt(self, path: str) -> None:
        with open(path, "rb") as f:
            raw = f.read()
        if not raw.startswith(self._MAGIC):
            raise ValueError(f"{path}: not a {type(self).__name__} file")
        nonce = raw[len(self._MAGIC):len(self._MAGIC) + 16]
        cipher = raw[len(self._MAGIC) + 16:]
        plain = bytes(a ^ b for a, b in
                      zip(cipher, self._stream(nonce, len(cipher))))
        with open(path, "wb") as f:
            f.write(plain)


# -- registry (ref: PinotCrypterFactory.init + getPinotCrypter) -------------

_REGISTRY: Dict[str, Callable[[], PinotCrypter]] = {
    "noop": NoOpPinotCrypter,
    "nooppinotcrypter": NoOpPinotCrypter,
}


def register_crypter(name: str, ctor: Callable[[], PinotCrypter]) -> None:
    _REGISTRY[name.lower()] = ctor


def get_crypter(name: str) -> PinotCrypter:
    ctor = _REGISTRY.get(name.lower())
    if ctor is None:
        raise ValueError(f"no crypter registered under {name!r} "
                         f"(registered: {sorted(_REGISTRY)})")
    return ctor()
