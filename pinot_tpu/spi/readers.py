"""RecordReader SPI: input-format-agnostic row reading for batch ingest.

Re-design of the reference's reader contracts
(``pinot-spi/.../data/readers/RecordReader.java`` — init/hasNext/next/
rewind/close over a data file — and ``GenericRow.java``): a reader yields
:class:`GenericRow` dicts; concrete format readers live in
``pinot_tpu/ingestion/readers.py`` (CSV/JSON/Parquet, the
pinot-input-format plugin family). Readers may implement
``read_columnar()`` returning column arrays directly — the vectorized
fast path the TPU segment builder prefers (row iteration stays the
compatibility path for transforms).
"""

from __future__ import annotations

import abc
from typing import Any, Dict, Iterator, Optional, Sequence


class GenericRow(dict):
    """One ingestion row: column -> value (None = null, list = MV).
    Ref: ``GenericRow.java`` (putValue/getValue are dict ops here)."""

    def put_value(self, column: str, value: Any) -> None:
        self[column] = value

    def get_value(self, column: str) -> Any:
        return self.get(column)


class RecordReaderConfig(dict):
    """Format-specific reader settings (ref: RecordReaderConfig marker
    interface + CSVRecordReaderConfig etc.); plain key/value map."""


class RecordReader(abc.ABC):
    """Ref: ``RecordReader.java:init/hasNext/next/rewind/close``."""

    @abc.abstractmethod
    def init(self, data_file: str,
             fields_to_read: Optional[Sequence[str]] = None,
             config: Optional[RecordReaderConfig] = None) -> None:
        """Open ``data_file``; restrict to ``fields_to_read`` when given."""

    @abc.abstractmethod
    def __iter__(self) -> Iterator[GenericRow]:
        """Iterate rows from the current position (next/hasNext)."""

    @abc.abstractmethod
    def rewind(self) -> None:
        """Reset to the first record (the two-pass build re-reads)."""

    def close(self) -> None:  # noqa: B027 (optional hook)
        pass

    def read_columnar(self) -> Optional[Dict[str, Any]]:
        """Column -> array/list for the whole file, or None when the format
        only supports row iteration. Overridden by columnar formats."""
        return None

    def __enter__(self) -> "RecordReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
