"""Table configuration model.

Re-design of ``pinot-spi/.../config/table/TableConfig.java`` and friends:
JSON-serialized table definitions covering indexing, segment validation
(replication/retention), tenants, stream ingestion, partitioning, star-tree
and upsert config. Field names follow the reference's JSON layout so
reference table-config files load directly.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional


class TableType(Enum):
    OFFLINE = "OFFLINE"
    REALTIME = "REALTIME"

    @property
    def suffix(self) -> str:
        return "_" + self.value


def table_name_with_type(raw_name: str, table_type: TableType) -> str:
    """'myTable' + OFFLINE -> 'myTable_OFFLINE' (ref: TableNameBuilder)."""
    if raw_name.endswith(table_type.suffix):
        return raw_name
    return raw_name + table_type.suffix


def raw_table_name(name: str) -> str:
    for t in TableType:
        if name.endswith(t.suffix):
            return name[: -len(t.suffix)]
    return name


def table_type_from_name(name: str) -> Optional[TableType]:
    for t in TableType:
        if name.endswith(t.suffix):
            return t
    return None


@dataclass
class StarTreeIndexConfig:
    """Ref: pinot-spi/.../config/table/StarTreeIndexConfig.java."""

    dimensions_split_order: List[str] = field(default_factory=list)
    skip_star_node_creation_for_dimensions: List[str] = field(default_factory=list)
    function_column_pairs: List[str] = field(default_factory=list)  # e.g. "SUM__revenue"
    max_leaf_records: int = 10_000

    def to_dict(self) -> Dict[str, Any]:
        return {
            "dimensionsSplitOrder": self.dimensions_split_order,
            "skipStarNodeCreationForDimensions": self.skip_star_node_creation_for_dimensions,
            "functionColumnPairs": self.function_column_pairs,
            "maxLeafRecords": self.max_leaf_records,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "StarTreeIndexConfig":
        return cls(
            dimensions_split_order=d.get("dimensionsSplitOrder", []),
            skip_star_node_creation_for_dimensions=d.get("skipStarNodeCreationForDimensions", []),
            function_column_pairs=d.get("functionColumnPairs", []),
            max_leaf_records=d.get("maxLeafRecords", 10_000),
        )


@dataclass
class SegmentPartitionConfig:
    """column -> {functionName, numPartitions} (ref: SegmentPartitionConfig.java)."""

    column_partition_map: Dict[str, Dict[str, Any]] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {"columnPartitionMap": self.column_partition_map}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SegmentPartitionConfig":
        return cls(column_partition_map=d.get("columnPartitionMap", {}))


@dataclass
class IndexingConfig:
    """Ref: pinot-spi/.../config/table/IndexingConfig.java (reduced to the
    knobs the TPU engine honors)."""

    inverted_index_columns: List[str] = field(default_factory=list)
    range_index_columns: List[str] = field(default_factory=list)
    sorted_column: List[str] = field(default_factory=list)
    bloom_filter_columns: List[str] = field(default_factory=list)
    text_index_columns: List[str] = field(default_factory=list)
    fst_index_columns: List[str] = field(default_factory=list)
    no_dictionary_columns: List[str] = field(default_factory=list)
    json_index_columns: List[str] = field(default_factory=list)
    var_length_dictionary_columns: List[str] = field(default_factory=list)
    star_tree_index_configs: List[StarTreeIndexConfig] = field(default_factory=list)
    enable_default_star_tree: bool = False
    segment_partition_config: Optional[SegmentPartitionConfig] = None
    aggregate_metrics: bool = False  # realtime metric pre-aggregation
    null_handling_enabled: bool = False

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "invertedIndexColumns": self.inverted_index_columns,
            "rangeIndexColumns": self.range_index_columns,
            "sortedColumn": self.sorted_column,
            "bloomFilterColumns": self.bloom_filter_columns,
            "textIndexColumns": self.text_index_columns,
            "fstIndexColumns": self.fst_index_columns,
            "noDictionaryColumns": self.no_dictionary_columns,
            "jsonIndexColumns": self.json_index_columns,
            "varLengthDictionaryColumns": self.var_length_dictionary_columns,
            "enableDefaultStarTree": self.enable_default_star_tree,
            "aggregateMetrics": self.aggregate_metrics,
            "nullHandlingEnabled": self.null_handling_enabled,
        }
        if self.star_tree_index_configs:
            d["starTreeIndexConfigs"] = [c.to_dict() for c in self.star_tree_index_configs]
        if self.segment_partition_config:
            d["segmentPartitionConfig"] = self.segment_partition_config.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "IndexingConfig":
        spc = d.get("segmentPartitionConfig")
        return cls(
            inverted_index_columns=d.get("invertedIndexColumns") or [],
            range_index_columns=d.get("rangeIndexColumns") or [],
            sorted_column=d.get("sortedColumn") or [],
            bloom_filter_columns=d.get("bloomFilterColumns") or [],
            text_index_columns=d.get("textIndexColumns") or [],
            fst_index_columns=d.get("fstIndexColumns") or [],
            no_dictionary_columns=d.get("noDictionaryColumns") or [],
            json_index_columns=d.get("jsonIndexColumns") or [],
            var_length_dictionary_columns=d.get("varLengthDictionaryColumns") or [],
            star_tree_index_configs=[StarTreeIndexConfig.from_dict(c)
                                     for c in d.get("starTreeIndexConfigs") or []],
            enable_default_star_tree=d.get("enableDefaultStarTree", False),
            segment_partition_config=SegmentPartitionConfig.from_dict(spc) if spc else None,
            aggregate_metrics=d.get("aggregateMetrics", False),
            null_handling_enabled=d.get("nullHandlingEnabled", False),
        )


@dataclass
class FieldConfig:
    """Per-column encoding/index directives
    (ref: pinot-spi/.../config/table/FieldConfig.java — fieldConfigList)."""

    name: str
    encoding_type: str = "DICTIONARY"   # DICTIONARY | RAW
    index_type: Optional[str] = None    # TEXT | FST | H3 | ...
    compression_codec: Optional[str] = None  # SNAPPY | LZ4 | ZSTANDARD | ...
    properties: Dict[str, str] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"name": self.name,
                             "encodingType": self.encoding_type}
        if self.index_type:
            d["indexType"] = self.index_type
        if self.compression_codec:
            d["compressionCodec"] = self.compression_codec
        if self.properties:
            d["properties"] = self.properties
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FieldConfig":
        return cls(
            name=d["name"],
            encoding_type=d.get("encodingType", "DICTIONARY"),
            index_type=d.get("indexType"),
            compression_codec=d.get("compressionCodec"),
            properties=d.get("properties") or {},
        )


@dataclass
class SegmentsValidationConfig:
    """Ref: SegmentsValidationAndRetentionConfig.java."""

    time_column_name: Optional[str] = None
    time_type: str = "MILLISECONDS"
    replication: int = 1
    retention_time_unit: Optional[str] = None  # e.g. "DAYS"
    retention_time_value: Optional[int] = None
    segment_push_type: str = "APPEND"  # APPEND | REFRESH

    def to_dict(self) -> Dict[str, Any]:
        return {
            "timeColumnName": self.time_column_name,
            "timeType": self.time_type,
            "replication": str(self.replication),
            "retentionTimeUnit": self.retention_time_unit,
            "retentionTimeValue": (str(self.retention_time_value)
                                   if self.retention_time_value is not None else None),
            "segmentPushType": self.segment_push_type,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SegmentsValidationConfig":
        rtv = d.get("retentionTimeValue")
        return cls(
            time_column_name=d.get("timeColumnName"),
            time_type=d.get("timeType", "MILLISECONDS"),
            replication=int(d.get("replication", 1)),
            retention_time_unit=d.get("retentionTimeUnit"),
            retention_time_value=int(rtv) if rtv not in (None, "") else None,
            segment_push_type=d.get("segmentPushType", "APPEND"),
        )


@dataclass
class TenantConfig:
    broker: str = "DefaultTenant"
    server: str = "DefaultTenant"

    def to_dict(self) -> Dict[str, Any]:
        return {"broker": self.broker, "server": self.server}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TenantConfig":
        return cls(broker=d.get("broker", "DefaultTenant"),
                   server=d.get("server", "DefaultTenant"))


class UpsertMode(Enum):
    NONE = "NONE"
    FULL = "FULL"
    PARTIAL = "PARTIAL"


@dataclass
class UpsertConfig:
    """Ref: pinot-spi/.../config/table/UpsertConfig.java."""

    mode: UpsertMode = UpsertMode.NONE
    comparison_column: Optional[str] = None  # defaults to the time column
    partial_upsert_strategies: Dict[str, str] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "mode": self.mode.value,
            "comparisonColumn": self.comparison_column,
            "partialUpsertStrategies": self.partial_upsert_strategies,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "UpsertConfig":
        return cls(
            mode=UpsertMode[d.get("mode", "NONE").upper()],
            comparison_column=d.get("comparisonColumn"),
            partial_upsert_strategies=d.get("partialUpsertStrategies", {}),
        )


@dataclass
class StreamIngestionConfig:
    """Realtime stream config (ref: stream configs in IndexingConfig.streamConfigs).

    ``stream_type`` selects a registered StreamConsumerFactory; the free-form
    ``properties`` map is passed through to the factory.
    """

    stream_type: str = "fake"
    topic: str = ""
    decoder: str = "json"
    segment_flush_threshold_rows: int = 100_000
    segment_flush_threshold_millis: int = 6 * 3600 * 1000
    properties: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "streamType": self.stream_type,
            "topic": self.topic,
            "decoder": self.decoder,
            "segmentFlushThresholdRows": self.segment_flush_threshold_rows,
            "segmentFlushThresholdMillis": self.segment_flush_threshold_millis,
            "properties": self.properties,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "StreamIngestionConfig":
        return cls(
            stream_type=d.get("streamType", "fake"),
            topic=d.get("topic", ""),
            decoder=d.get("decoder", "json"),
            segment_flush_threshold_rows=int(d.get("segmentFlushThresholdRows", 100_000)),
            segment_flush_threshold_millis=int(d.get("segmentFlushThresholdMillis", 6 * 3600 * 1000)),
            properties=d.get("properties", {}),
        )

    @classmethod
    def from_stream_configs_map(cls, m: Dict[str, Any]) -> "StreamIngestionConfig":
        """Parse the reference's flat ``tableIndexConfig.streamConfigs`` map
        (ref: pinot-spi stream/StreamConfig.java key layout, e.g.
        ``stream.kafka.topic.name``, ``realtime.segment.flush.threshold.size``)."""
        stream_type = m.get("streamType", "fake")
        prefix = f"stream.{stream_type}."
        topic = m.get(prefix + "topic.name", m.get("topic", ""))
        decoder = m.get(prefix + "decoder.class.name", m.get("decoder", "json"))
        rows = int(m.get("realtime.segment.flush.threshold.rows",
                         m.get("realtime.segment.flush.threshold.size", 100_000)))
        millis = _duration_ms(
            m.get("realtime.segment.flush.threshold.time", 6 * 3600 * 1000))
        props = {k: v for k, v in m.items()
                 if k not in ("streamType",)}
        return cls(stream_type=stream_type, topic=topic, decoder=decoder,
                   segment_flush_threshold_rows=rows,
                   segment_flush_threshold_millis=millis, properties=props)


def _duration_ms(v: Any) -> int:
    """Millis from an int, numeric string, or period string ('12h', '6d',
    '30m', '45s', '500ms' — ref: TimeUtils.convertPeriodToMillis used for
    realtime.segment.flush.threshold.time)."""
    s = str(v).strip().lower()
    try:
        return int(s)
    except ValueError:
        pass
    import re as _re

    # compound periods compose ('1d12h', ref: Joda PeriodFormatter chain)
    if not _re.fullmatch(r"(?:\d+\s*(?:ms|s|m|h|d)\s*)+", s):
        raise ValueError(f"bad duration {v!r} (want millis or e.g. '6h')")
    unit_ms = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000,
               "d": 86_400_000}
    return sum(int(n) * unit_ms[u]
               for n, u in _re.findall(r"(\d+)\s*(ms|s|m|h|d)", s))


@dataclass
class TransformConfig:
    """One ingestion-time derived/renamed column
    (ref: pinot-spi ingestion/TransformConfig)."""

    column: str
    transform_function: str  # SQL expression over source fields

    def to_dict(self) -> Dict[str, Any]:
        return {"columnName": self.column,
                "transformFunction": self.transform_function}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TransformConfig":
        return cls(d["columnName"], d["transformFunction"])


@dataclass
class IngestionConfig:
    """Ref: pinot-spi/.../config/table/ingestion/IngestionConfig.java
    (filterConfig + transformConfigs)."""

    filter_function: Optional[str] = None  # rows matching this are DROPPED
    transform_configs: List[TransformConfig] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if self.filter_function:
            d["filterConfig"] = {"filterFunction": self.filter_function}
        if self.transform_configs:
            d["transformConfigs"] = [t.to_dict() for t in self.transform_configs]
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "IngestionConfig":
        return cls(
            filter_function=(d.get("filterConfig") or {}).get("filterFunction"),
            transform_configs=[TransformConfig.from_dict(t)
                               for t in d.get("transformConfigs") or []],
        )


@dataclass
class QuotaConfig:
    """Ref: pinot-spi/.../config/table/QuotaConfig.java."""

    max_queries_per_second: Optional[float] = None
    storage: Optional[str] = None  # e.g. "100G" (recorded, not enforced)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if self.max_queries_per_second is not None:
            d["maxQueriesPerSecond"] = str(self.max_queries_per_second)
        if self.storage:
            d["storage"] = self.storage
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "QuotaConfig":
        qps = d.get("maxQueriesPerSecond")
        return cls(
            max_queries_per_second=float(qps) if qps is not None else None,
            storage=d.get("storage"))


@dataclass
class RoutingConfig:
    """Ref: pinot-spi/.../config/table/RoutingConfig.java — the broker's
    instance-selector + pruner choices."""

    instance_selector_type: str = "balanced"  # balanced | replicaGroup |
    #                                           strictReplicaGroup
    segment_pruner_types: List[str] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {"instanceSelectorType": self.instance_selector_type,
                "segmentPrunerTypes": self.segment_pruner_types}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "RoutingConfig":
        return cls(
            instance_selector_type=d.get("instanceSelectorType", "balanced"),
            segment_pruner_types=d.get("segmentPrunerTypes") or [])


@dataclass
class TableConfig:
    """Ref: pinot-spi/.../config/table/TableConfig.java."""

    table_name: str  # raw name, without type suffix
    table_type: TableType = TableType.OFFLINE
    validation_config: SegmentsValidationConfig = field(default_factory=SegmentsValidationConfig)
    indexing_config: IndexingConfig = field(default_factory=IndexingConfig)
    tenant_config: TenantConfig = field(default_factory=TenantConfig)
    routing_config: RoutingConfig = field(default_factory=RoutingConfig)
    quota_config: QuotaConfig = field(default_factory=QuotaConfig)
    upsert_config: Optional[UpsertConfig] = None
    stream_config: Optional[StreamIngestionConfig] = None
    ingestion_config: Optional[IngestionConfig] = None
    query_config: Dict[str, Any] = field(default_factory=dict)  # e.g. timeoutMs
    custom_config: Dict[str, Any] = field(default_factory=dict)
    # taskType -> config map (ref: TableTaskConfig.java taskTypeConfigsMap)
    task_config: Dict[str, Dict[str, str]] = field(default_factory=dict)
    field_config_list: List[FieldConfig] = field(default_factory=list)
    # tier configs ride as raw dicts (controller/tiers.TierConfig parses)
    tier_configs: List[Dict[str, Any]] = field(default_factory=list)

    def __post_init__(self):
        if isinstance(self.table_type, str):
            self.table_type = TableType[self.table_type.upper()]
        self.table_name = raw_table_name(self.table_name)

    @property
    def table_name_with_type(self) -> str:
        return table_name_with_type(self.table_name, self.table_type)

    @property
    def replication(self) -> int:
        return self.validation_config.replication

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "tableName": self.table_name_with_type,
            "tableType": self.table_type.value,
            "segmentsConfig": self.validation_config.to_dict(),
            "tableIndexConfig": self.indexing_config.to_dict(),
            "tenants": self.tenant_config.to_dict(),
            "metadata": {"customConfigs": self.custom_config},
        }
        if (self.routing_config.instance_selector_type != "balanced"
                or self.routing_config.segment_pruner_types):
            d["routing"] = self.routing_config.to_dict()
        if self.quota_config.to_dict():
            d["quota"] = self.quota_config.to_dict()
        if self.upsert_config:
            d["upsertConfig"] = self.upsert_config.to_dict()
        if self.stream_config:
            d["streamConfig"] = self.stream_config.to_dict()
        if self.ingestion_config:
            d["ingestionConfig"] = self.ingestion_config.to_dict()
        if self.query_config:
            d["query"] = self.query_config
        if self.task_config:
            d["task"] = {"taskTypeConfigsMap": self.task_config}
        if self.field_config_list:
            d["fieldConfigList"] = [c.to_dict()
                                    for c in self.field_config_list]
        if self.tier_configs:
            d["tierConfigs"] = self.tier_configs
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TableConfig":
        uc = d.get("upsertConfig")
        sc = d.get("streamConfig")
        if sc is not None:
            stream_config = StreamIngestionConfig.from_dict(sc)
        else:
            # reference layout: streamConfigs nested inside tableIndexConfig
            # (ref: pinot-spi/.../config/table/IndexingConfig.java:42)
            nested = (d.get("tableIndexConfig") or {}).get("streamConfigs")
            stream_config = (StreamIngestionConfig.from_stream_configs_map(nested)
                             if nested else None)
        return cls(
            table_name=d["tableName"],
            table_type=TableType[d.get("tableType", "OFFLINE").upper()],
            validation_config=SegmentsValidationConfig.from_dict(d.get("segmentsConfig", {})),
            indexing_config=IndexingConfig.from_dict(d.get("tableIndexConfig", {})),
            tenant_config=TenantConfig.from_dict(d.get("tenants", {})),
            routing_config=RoutingConfig.from_dict(d.get("routing", {})),
            quota_config=QuotaConfig.from_dict(d.get("quota", {})),
            upsert_config=UpsertConfig.from_dict(uc) if uc else None,
            stream_config=stream_config,
            ingestion_config=(IngestionConfig.from_dict(d["ingestionConfig"])
                              if d.get("ingestionConfig") else None),
            query_config=d.get("query", {}),
            custom_config=(d.get("metadata") or {}).get("customConfigs", {}),
            task_config=(d.get("task") or {}).get("taskTypeConfigsMap", {}),
            field_config_list=[FieldConfig.from_dict(c)
                               for c in d.get("fieldConfigList") or []],
            tier_configs=d.get("tierConfigs") or [],
        )

    @classmethod
    def from_json(cls, s: str) -> "TableConfig":
        return cls.from_dict(json.loads(s))

    @classmethod
    def from_file(cls, path: str) -> "TableConfig":
        with open(path) as f:
            return cls.from_dict(json.load(f))
