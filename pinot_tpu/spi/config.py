"""Layered key/value configuration.

Re-design of ``pinot-spi/.../env/PinotConfiguration.java:88``: merges (in
priority order) explicit overrides > environment variables (``PINOT_``
prefixed, mapping ``PINOT_SERVER_PORT`` -> ``pinot.server.port``) >
properties files > defaults, with relaxed key matching (case-insensitive,
``-``/``_``/``.``/camelCase-insensitive within a segment).
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, Iterator, List, Mapping, Optional

_SEP = re.compile(r"[-_.]")

# Layer priorities: higher wins, regardless of insertion order.
PRIORITY_DEFAULT = 0
PRIORITY_FILE = 1
PRIORITY_ENV = 2
PRIORITY_OVERRIDE = 3


def _segments(key: str) -> List[str]:
    return [s for s in _SEP.split(key.lower()) if s]


def _relax(key: str) -> str:
    """Relaxed key normalization: case-insensitive, separator-insensitive.

    ``timeoutMs`` == ``timeout.ms`` == ``TIMEOUT_MS`` == ``timeout-ms``.
    """
    return "".join(_segments(key))


class PinotConfiguration:
    def __init__(self, overrides: Optional[Mapping[str, Any]] = None,
                 use_env: bool = True):
        self._store: Dict[str, Any] = {}
        self._priority: Dict[str, int] = {}
        self._raw_keys: Dict[str, str] = {}
        if use_env:
            for k, v in os.environ.items():
                if k.startswith("PINOT_"):
                    # PINOT_SERVER_PORT -> pinot.server.port (prefix retained:
                    # all framework keys are namespaced under pinot.*)
                    self.set(k.lower().replace("_", "."), v, PRIORITY_ENV)
        if overrides:
            for k, v in overrides.items():
                self.set(k, v, PRIORITY_OVERRIDE)

    # -- mutation ----------------------------------------------------------
    def set(self, key: str, value: Any, priority: int = PRIORITY_OVERRIDE) -> None:
        rk = _relax(key)
        if self._priority.get(rk, -1) > priority:
            return  # a higher layer already owns this key
        self._store[rk] = value
        self._priority[rk] = priority
        self._raw_keys[rk] = key

    def set_default(self, key: str, value: Any) -> None:
        self.set(key, value, PRIORITY_DEFAULT)

    def load_properties_file(self, path: str) -> None:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith(("#", "!")):
                    continue
                if "=" in line:
                    k, _, v = line.partition("=")
                    self.set(k.strip(), v.strip(), PRIORITY_FILE)

    # -- access ------------------------------------------------------------
    def get(self, key: str, default: Any = None) -> Any:
        return self._store.get(_relax(key), default)

    def get_int(self, key: str, default: int = 0) -> int:
        v = self.get(key)
        return default if v is None else int(v)

    def get_float(self, key: str, default: float = 0.0) -> float:
        v = self.get(key)
        return default if v is None else float(v)

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get(key)
        if v is None:
            return default
        if isinstance(v, bool):
            return v
        return str(v).strip().lower() in ("true", "1", "yes", "on")

    def get_str(self, key: str, default: str = "") -> str:
        v = self.get(key)
        return default if v is None else str(v)

    def subset(self, prefix: str) -> "PinotConfiguration":
        """All keys under ``prefix``, prefix stripped, matched on whole
        key segments (``subset('server')`` does NOT match ``serverx.port``)."""
        psegs = _segments(prefix)
        out = PinotConfiguration(use_env=False)
        for rk, raw in self._raw_keys.items():
            ksegs = _segments(raw)
            if len(ksegs) > len(psegs) and ksegs[: len(psegs)] == psegs:
                out.set(".".join(ksegs[len(psegs):]), self._store[rk],
                        self._priority[rk])
        return out

    def keys(self) -> Iterator[str]:
        return iter(self._raw_keys.values())

    def to_dict(self) -> Dict[str, Any]:
        return {raw: self._store[rk] for rk, raw in self._raw_keys.items()}

    def __contains__(self, key: str) -> bool:
        return _relax(key) in self._store

    def __repr__(self) -> str:
        return f"PinotConfiguration({self.to_dict()!r})"


class CommonConstants:
    """Centralized config keys + defaults (ref: pinot-spi CommonConstants.java)."""

    DEFAULT_BROKER_QUERY_PORT = 8099
    DEFAULT_SERVER_QUERY_PORT = 8098
    DEFAULT_CONTROLLER_PORT = 9000
    DEFAULT_QUERY_TIMEOUT_MS = 10_000
    DEFAULT_MAX_ROWS_IN_RESPONSE = 10_000
    # Engine defaults (ref: InstancePlanMakerImplV2.java:67-84)
    DEFAULT_NUM_GROUPS_LIMIT = 100_000
    DEFAULT_GROUPBY_TRIM_THRESHOLD = 1_000_000
    DEFAULT_MIN_SEGMENT_GROUP_TRIM_SIZE = -1
    DEFAULT_MIN_SERVER_GROUP_TRIM_SIZE = 5000
    # Device-resident broker reduce (parallel/reduce_device.py): when
    # broker and servers share the process (embedded cluster / bench
    # topology) group-by partials merge ON DEVICE — segment-sum/sort-rung
    # kernels + psum over the broker mesh — instead of the host lexsort.
    # Off by default: cross-process tables already paid D2H + wire, so
    # the host path is the natural fallback frame. Per-query override:
    # OPTION(deviceReduce=true|false).
    BROKER_DEVICE_REDUCE_KEY = "pinot.broker.reduce.device.enabled"
    DEFAULT_BROKER_DEVICE_REDUCE = False
    # Dense-rung slot cap: composite key spaces up to this many slots
    # merge via direct segment-sum scatter; larger spaces ride the sort
    # rung, and spaces whose composite encoding cannot fit i64 decline.
    DEFAULT_DEVICE_REDUCE_DENSE_SLOTS = 1 << 21
    # Row cap on the padded merge input (all servers' groups concatenated,
    # padded to a shared pow2 capacity); above it the device path declines
    # loudly rather than committing unbounded HBM.
    DEFAULT_DEVICE_REDUCE_MAX_ROWS = 1 << 22
    # Block size: the reference drains filters in 10k-doc blocks
    # (DocIdSetPlanNode.java:29). On TPU we tile the doc dimension instead;
    # this is the host-side fallback block size.
    MAX_DOC_PER_CALL = 10_000
    # HBM residency (engine/residency.py): device-staging byte budget.
    # Unset -> auto from the backend's reported device memory times the
    # fraction below (uncapped on backends that report nothing, e.g. CPU);
    # <= 0 -> explicitly uncapped.
    HBM_BUDGET_BYTES_KEY = "pinot.server.query.hbm.budget.bytes"
    DEFAULT_HBM_BUDGET_FRACTION = 0.75
    # Host-RAM spill tier (engine/residency.py): eviction demotes device
    # arrays to pinned host numpy copies instead of dropping them, so a
    # re-stage is one H2D transfer instead of a full column rebuild (the
    # ISCA'23 D2H+H2D vs rebuild cost model — ~10x cheaper). Budget key
    # unset -> auto from psutil available RAM times the fraction below
    # (uncapped when psutil is missing); <= 0 -> explicitly uncapped.
    # The enabled key turns the tier off wholesale (eviction drops, the
    # pre-tier behavior) — the bench uses it for the spill baseline.
    HOSTRAM_BUDGET_BYTES_KEY = "pinot.server.query.hostram.budget.bytes"
    HOSTRAM_ENABLED_KEY = "pinot.server.query.hostram.enabled"
    DEFAULT_HOSTRAM_BUDGET_FRACTION = 0.5
    # Budget-sliced sharded combine (parallel/executor.py): a query whose
    # working set exceeds the HBM budget — but whose largest single
    # segment fits — runs the combine in budget-sized slices (stage k
    # segments, launch, demote-to-host, repeat) instead of spilling to
    # the host engine. Disable to restore spill-on-over-budget.
    HBM_SLICING_ENABLED_KEY = "pinot.server.query.hbm.slicing.enabled"
    # Server pool sizing (ref: the pqr/pqw pools,
    # CommonConstants.Server.*_QUERY_RUNNER_THREADS /
    # QUERY_WORKER_THREADS): runner threads execute whole queries off the
    # scheduler queue; worker threads fan segment plans out inside one
    # query (engine/executor._map_segments). Worker default: min(cpu, 8),
    # the pre-knob hardcoded fan-out width.
    RUNNER_THREADS_KEY = "pinot.server.query.runner.threads"
    DEFAULT_RUNNER_THREADS = 8
    # Pallas LUT eligibility (engine/pallas_kernels.py): max interval runs
    # a boolean dictId LUT (IN / REGEXP / TEXT_MATCH predicates) may
    # decompose into before the fused kernel declines to the jnp
    # LUT-gather path. Small run counts bake into the filter tree; past
    # _MAX_LUT_RUNS and up to this cap they ride the padded interval-set
    # ("ivs") fallback node — each run is one SMEM compare pair per tile.
    PALLAS_LUT_MAX_RUNS_KEY = "pinot.server.query.pallas.lut.max.runs"
    DEFAULT_PALLAS_LUT_MAX_RUNS = 64
    # Per-shape pallas blocklist persistence (engine/pallas_blocklist.py):
    # when set, runtime lowering failures AND preflight-predicted failures
    # (tools/preflight.py) are written through to this JSON file and
    # reloaded at executor start — a chip that fell over mid-round must
    # not forget its lowering failures on restart.
    PALLAS_BLOCKLIST_PATH_KEY = "pinot.server.query.pallas.blocklist.path"
    WORKER_THREADS_KEY = "pinot.server.query.worker.threads"
    # Launch coalescing (parallel/launcher.py): max requests one vmapped
    # combine launch may carry. 1 disables batching (dedup + single-thread
    # dispatch ordering still apply).
    LAUNCH_MAX_BATCH_KEY = "pinot.server.query.launch.max.batch"
    DEFAULT_LAUNCH_MAX_BATCH = 8
    # Adaptive micro-batch window (parallel/launcher.py): when the launch
    # queue is hot (EWMA inter-arrival <= the hot threshold) the dispatcher
    # holds up to this long for stragglers so vmap groups get bigger
    # exactly when it pays; idle traffic pays zero added latency. <= 0
    # disables the hold.
    LAUNCH_WINDOW_MS_KEY = "pinot.server.query.launch.window.ms"
    DEFAULT_LAUNCH_WINDOW_MS = 1.0
    LAUNCH_WINDOW_HOT_MS_KEY = "pinot.server.query.launch.window.hot.ms"
    DEFAULT_LAUNCH_WINDOW_HOT_MS = 2.0
    # Scheduler policy (server/scheduler.py make_scheduler): fcfs |
    # tokenbucket | priority | sewf (shortest-expected-work-first with an
    # age-based anti-starvation boost — the default).
    SCHEDULER_POLICY_KEY = "pinot.server.query.scheduler.policy"
    DEFAULT_SCHEDULER_POLICY = "sewf"
    # Admission gate (server/admission.py): bounded concurrency + bounded
    # queue in front of query execution. 0 = auto-size (concurrent from
    # cpu count, queue from the concurrency bound); max.concurrent < 0
    # disables the gate. Past the queue bound — or past the wait bound —
    # queries are REJECTED with a typed retriable QueryRejectedError, so
    # overload degrades to bounded-latency rejection instead of convoy
    # collapse.
    ADMISSION_MAX_CONCURRENT_KEY = \
        "pinot.server.query.admission.max.concurrent"
    DEFAULT_ADMISSION_MAX_CONCURRENT = 0
    ADMISSION_MAX_QUEUE_KEY = "pinot.server.query.admission.max.queue"
    DEFAULT_ADMISSION_MAX_QUEUE = 0
    ADMISSION_MAX_WAIT_MS_KEY = "pinot.server.query.admission.max.wait.ms"
    DEFAULT_ADMISSION_MAX_WAIT_MS = 10_000.0
    # Query lifecycle tracing (common/tracing.py): span trees are
    # recorded when the request carries OPTION(trace=true) OR this sample
    # rate (0..1) hits — sampled traces ship in the response exactly like
    # requested ones. 0 (the default) keeps the untraced path at its
    # zero-allocation cost.
    TRACE_SAMPLE_KEY = "pinot.server.query.trace.sample"
    DEFAULT_TRACE_SAMPLE = 0.0
    # Slow-query log (/debug/queries): a query over this wall-time
    # threshold retains its FULL span tree in the server's slow log even
    # when trace/sampling missed it — while the threshold is configured,
    # the executor records spans for every query and ships them only for
    # traced ones. 0 (the default) disables the forced recording so the
    # serving path stays span-free.
    SLOW_THRESHOLD_MS_KEY = "pinot.server.query.slow.threshold.ms"
    DEFAULT_SLOW_THRESHOLD_MS = 0.0
    # Continuous telemetry (common/telemetry.py): sampler resolution for
    # the gauge-history rings (staged/host bytes, queue depths, arrival
    # EWMA, rejection counters) and the flight recorder's anomaly checks.
    TELEMETRY_RESOLUTION_S_KEY = "pinot.server.telemetry.resolution.s"
    DEFAULT_TELEMETRY_RESOLUTION_S = 2.0
    # Flight recorder (common/telemetry.py FlightRecorder): post-mortem
    # bundle directory (default <tmp>/pinot_tpu_flightrecorder), the
    # freeze debounce, and the windowed-p99-vs-EWMA spike factor.
    FLIGHT_DIR_KEY = "pinot.server.telemetry.flightrecorder.dir"
    FLIGHT_MIN_INTERVAL_S_KEY = \
        "pinot.server.telemetry.flightrecorder.min.interval.s"
    FLIGHT_P99_FACTOR_KEY = \
        "pinot.server.telemetry.flightrecorder.p99.factor"
    # Per-table SLOs (common/telemetry.py SloTracker): latency and error
    # objectives parsed from the RAW key strings so table names survive
    # relaxed-key normalization —
    #   pinot.broker.slo.<table>.p99.ms   (latency objective, ms)
    #   pinot.broker.slo.<table>.error.pct (error-rate objective, percent)
    # Burn rates (>1 = over-burning the budget) ride /debug/slo and the
    # slo_burn_rate exposition gauges.
    SLO_KEY_PREFIX = "pinot.broker.slo."
