"""HyperLogLog: the approximate distinct-count sketch.

Re-design of the reference's HLL usage (``DistinctCountHLLAggregationFunction``
over com.clearspring HyperLogLog, default log2m = 8): a numpy register array
with vectorized 64-bit hashing, so register updates are bulk ``np.maximum``
operations — the same max-reduce shape the TPU kernels use for dictId
presence, which is what makes the sketch device-friendly (per-dictionary
hash tables are precomputable, and register merge is an elementwise max that
``pmax`` handles across shards).

Serialized form: log2m byte + raw registers (bytes), stable across the wire.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional, Sequence

import numpy as np

DEFAULT_LOG2M = 8  # ref: CommonConstants.Helix.DEFAULT_HYPERLOGLOG_LOG2M


def _hash64(values: np.ndarray) -> np.ndarray:
    """Vectorized 64-bit mix (splitmix64 finalizer) over int64 input."""
    x = values.astype(np.uint64)
    x = (x + np.uint64(0x9E3779B97F4A7C15))
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def hash_values(values: Sequence[Any]) -> np.ndarray:
    """Arbitrary python/numpy values -> uint64 hashes (strings/bytes via
    FNV-1a; numerics via splitmix64)."""
    arr = np.asarray(values)
    if arr.dtype.kind in ("i", "u"):
        return _hash64(arr.astype(np.int64))
    if arr.dtype.kind == "f":
        return _hash64(arr.astype(np.float64).view(np.int64))
    out = np.empty(len(values), dtype=np.uint64)
    for i, v in enumerate(values):
        data = v if isinstance(v, bytes) else str(v).encode("utf-8")
        h = 0xCBF29CE484222325
        for b in data:
            h = (h ^ b) * 0x100000001B3 & 0xFFFFFFFFFFFFFFFF
        out[i] = h
    # FNV-1a avalanches poorly in the high bits (which HLL uses for the
    # register index); finish with the splitmix64 mixer
    return _hash64(out.view(np.int64))


def register_updates(hashes: np.ndarray, log2m: int):
    """(register index, rank) per hash — the HLL update decomposed so the
    TPU path can PRECOMPUTE per-dictId (bucket, rank) lookup tables and
    turn register updates into a masked scatter-max on device (the same
    max-merge shape as dictId presence; see engine/kernels.py)."""
    idx = (hashes >> np.uint64(64 - log2m)).astype(np.int64)
    rest = hashes << np.uint64(log2m)
    # rank = leading zeros of the remaining bits + 1 (capped)
    width = 64 - log2m
    rank = np.full(hashes.shape, width + 1, dtype=np.int32)
    bits = rest
    found = np.zeros(hashes.shape, dtype=bool)
    for r in range(1, width + 1):
        top = (bits >> np.uint64(63)).astype(bool)
        newly = top & ~found
        rank[newly] = r
        found |= top
        bits = bits << np.uint64(1)
        if found.all():
            break
    return idx, rank


def dictionary_register_luts(values, log2m: int = DEFAULT_LOG2M):
    """(bucket [card] i32, rank [card] i32) for a dictionary's values —
    the device HLL's plan-time parameters."""
    idx, rank = register_updates(hash_values(list(values)), log2m)
    return idx.astype(np.int32), rank.astype(np.int32)


class HyperLogLog:
    def __init__(self, log2m: int = DEFAULT_LOG2M,
                 registers: Optional[np.ndarray] = None):
        self.log2m = log2m
        self.m = 1 << log2m
        self.registers = (registers if registers is not None
                          else np.zeros(self.m, dtype=np.uint8))

    # -- updates -------------------------------------------------------------
    def add_hashes(self, hashes: np.ndarray) -> None:
        if hashes.size == 0:
            return
        idx, rank = register_updates(hashes, self.log2m)
        np.maximum.at(self.registers, idx, rank.astype(np.uint8))

    def add_values(self, values: Sequence[Any]) -> None:
        self.add_hashes(hash_values(values))

    def merge(self, other: "HyperLogLog") -> "HyperLogLog":
        if other.log2m != self.log2m:
            raise ValueError("cannot merge HLLs with different log2m")
        return HyperLogLog(self.log2m,
                           np.maximum(self.registers, other.registers))

    # -- estimate (standard HLL with small/large range corrections) ----------
    def cardinality(self) -> int:
        m = self.m
        regs = self.registers.astype(np.float64)
        alpha = {16: 0.673, 32: 0.697, 64: 0.709}.get(
            m, 0.7213 / (1 + 1.079 / m))
        est = alpha * m * m / np.sum(np.exp2(-regs))
        if est <= 2.5 * m:
            zeros = int(np.count_nonzero(self.registers == 0))
            if zeros:
                est = m * np.log(m / zeros)
        elif est > (1 << 32) / 30.0:
            est = -(1 << 32) * np.log(1.0 - est / (1 << 32))
        return int(round(est))

    # -- serde (wire state) --------------------------------------------------
    def serialize(self) -> bytes:
        return bytes([self.log2m]) + self.registers.tobytes()

    @classmethod
    def deserialize(cls, raw: bytes) -> "HyperLogLog":
        if not raw:
            raise ValueError("empty HyperLogLog payload")
        log2m = raw[0]
        if len(raw) != 1 + (1 << log2m):
            raise ValueError(
                f"HyperLogLog payload length {len(raw)} != 1 + 2^{log2m}")
        regs = np.frombuffer(raw[1:], dtype=np.uint8).copy()
        return cls(log2m, regs)

    @classmethod
    def of(cls, values: Sequence[Any],
           log2m: int = DEFAULT_LOG2M) -> "HyperLogLog":
        h = cls(log2m)
        h.add_values(values)
        return h
