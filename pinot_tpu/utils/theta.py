"""Theta sketch: mergeable approximate distinct counting with set algebra.

Re-design of the reference's theta-sketch aggregations
(``DistinctCountThetaSketchAggregationFunction`` over the DataSketches
library): a KMV (k minimum values) theta sketch — keep the k smallest 64-bit
hashes seen; theta is the (k+1)-th smallest (as a fraction of hash space),
retained hashes stay strictly below it, and the distinct estimate is
``retained / theta`` once sampling kicks in.

TPU-shaped on purpose: updates are vectorized numpy (hash -> sort -> trim),
and merge is a concatenate + k-smallest trim — both expressible as on-device
sort/top-k if sketch building ever moves into a kernel. Unlike the
DataSketches binary layout, serialization here is a simple header + the
sorted retained hashes (u64 little-endian); set operations (union /
intersection / a-not-b) follow the standard theta algebra.
"""

from __future__ import annotations

import struct
from typing import Any, Sequence

import numpy as np

from pinot_tpu.utils.hll import hash_values

DEFAULT_NOMINAL_ENTRIES = 4096  # ref: the DataSketches default (2^12)

_MAX_HASH = float(1 << 64)


class ThetaSketch:
    """KMV theta sketch over 64-bit hashes."""

    def __init__(self, nominal_entries: int = DEFAULT_NOMINAL_ENTRIES,
                 hashes: np.ndarray = None, theta: float = 1.0):
        if nominal_entries < 1:
            raise ValueError("nominal_entries must be >= 1")
        self.k = int(nominal_entries)
        # sorted unique uint64 hashes, all strictly below theta * 2^64
        self.hashes = (np.empty(0, dtype=np.uint64) if hashes is None
                       else hashes)
        self.theta = float(theta)

    # -- building ----------------------------------------------------------
    def add_values(self, values: Sequence[Any]) -> "ThetaSketch":
        if len(values):
            self._absorb(hash_values(values))
        return self

    def _absorb(self, new_hashes: np.ndarray) -> None:
        merged = np.unique(np.concatenate([self.hashes, new_hashes]))
        self._trim(merged)

    def _trim(self, sorted_hashes: np.ndarray) -> None:
        limit = np.uint64(int(self.theta * _MAX_HASH)) \
            if self.theta < 1.0 else None
        if limit is not None:
            sorted_hashes = sorted_hashes[sorted_hashes < limit]
        if sorted_hashes.size > self.k:
            # theta drops to the (k+1)-th smallest: retained stay below it
            cut = sorted_hashes[self.k]
            self.theta = float(cut) / _MAX_HASH
            sorted_hashes = sorted_hashes[:self.k]
        self.hashes = sorted_hashes

    # -- set algebra (ref: theta sketch union/intersection/aNotB) ----------
    def merge(self, other: "ThetaSketch") -> "ThetaSketch":
        """Union (in place); theta = min(thetas), retained trimmed to k."""
        self.theta = min(self.theta, other.theta)
        merged = np.unique(np.concatenate([self.hashes, other.hashes]))
        self._trim(merged)
        return self

    def intersect(self, other: "ThetaSketch") -> "ThetaSketch":
        theta = min(self.theta, other.theta)
        limit = np.uint64(int(theta * _MAX_HASH)) if theta < 1.0 else None
        common = np.intersect1d(self.hashes, other.hashes)
        if limit is not None:
            common = common[common < limit]
        return ThetaSketch(self.k, common, theta)

    def a_not_b(self, other: "ThetaSketch") -> "ThetaSketch":
        theta = min(self.theta, other.theta)
        limit = np.uint64(int(theta * _MAX_HASH)) if theta < 1.0 else None
        kept = np.setdiff1d(self.hashes, other.hashes)
        if limit is not None:
            kept = kept[kept < limit]
        return ThetaSketch(self.k, kept, theta)

    # -- estimation ---------------------------------------------------------
    def estimate(self) -> float:
        if self.theta >= 1.0:
            return float(self.hashes.size)  # exact below k
        # standard theta estimator: retained / theta (every retained hash is
        # strictly below theta by construction after _trim, so no -1 term —
        # the (k-1)/theta form applies to theta = k-th smallest, not ours)
        return self.hashes.size / self.theta if self.hashes.size else 0.0

    # -- wire ----------------------------------------------------------------
    def serialize(self) -> bytes:
        return (struct.pack("<IdI", self.k, self.theta, self.hashes.size)
                + self.hashes.astype("<u8").tobytes())

    @classmethod
    def deserialize(cls, raw: bytes) -> "ThetaSketch":
        k, theta, n = struct.unpack_from("<IdI", raw, 0)
        hashes = np.frombuffer(raw, dtype="<u8", count=n, offset=16).copy()
        return cls(k, hashes, theta)

    @classmethod
    def of(cls, values: Sequence[Any],
           nominal_entries: int = DEFAULT_NOMINAL_ENTRIES) -> "ThetaSketch":
        return cls(nominal_entries).add_values(values)
