"""Bloom filter for segment pruning (vectorized numpy build).

Re-design of the reference's guava-backed bloom filters
(``segment/creator/impl/bloom/OnHeapGuavaBloomFilterCreator.java`` +
``BloomFilterReader``): a bit array with k hash probes derived from two
64-bit hashes (Kirsch-Mitzenmacher double hashing), built in one
vectorized pass over a column's distinct values. Used by the server-side
pruner (ref: ``ColumnValueSegmentPruner.java`` bloom branch) to skip
segments that provably lack an EQ/IN literal.
"""

from __future__ import annotations

import math
from typing import Any, Iterable, Sequence

import numpy as np

from pinot_tpu.utils.hll import hash_values

DEFAULT_FPP = 0.05
MAX_BITS = 1 << 23  # 1 MiB cap per column filter (ref default maxSizeInBytes)


class BloomFilter:
    def __init__(self, bits: np.ndarray, num_hashes: int):
        self.bits = bits  # uint64 words
        self.num_hashes = num_hashes
        self.num_bits = bits.shape[0] * 64

    # -- build ---------------------------------------------------------------
    @classmethod
    def from_values(cls, values: Sequence[Any],
                    fpp: float = DEFAULT_FPP) -> "BloomFilter":
        n = max(len(values), 1)
        m = int(-n * math.log(fpp) / (math.log(2) ** 2))
        m = min(max(64, -(-m // 64) * 64), MAX_BITS)
        k = max(1, round(m / n * math.log(2)))
        bits = np.zeros(m // 64, dtype=np.uint64)
        h = hash_values(list(values))
        h1 = h
        h2 = (h >> np.uint64(17)) | (h << np.uint64(47))
        for i in range(k):
            idx = (h1 + np.uint64(i) * h2) % np.uint64(m)
            np.bitwise_or.at(bits, (idx >> np.uint64(6)).astype(np.int64),
                             np.uint64(1) << (idx & np.uint64(63)))
        return cls(bits, k)

    # -- query ---------------------------------------------------------------
    def might_contain(self, value: Any) -> bool:
        # python-int arithmetic: uint64 wraparound without numpy warnings
        h = int(hash_values([value])[0])
        mask64 = (1 << 64) - 1
        h1 = h
        h2 = ((h >> 17) | (h << 47)) & mask64
        for i in range(self.num_hashes):
            idx = ((h1 + i * h2) & mask64) % self.num_bits
            if not (int(self.bits[idx >> 6]) >> (idx & 63)) & 1:
                return False
        return True

    # -- serde (single array: [k, words...]) ----------------------------------
    def to_array(self) -> np.ndarray:
        return np.concatenate([np.asarray([self.num_hashes], dtype=np.uint64),
                               self.bits])

    @classmethod
    def from_array(cls, arr: np.ndarray) -> "BloomFilter":
        arr = np.asarray(arr, dtype=np.uint64)
        return cls(arr[1:].copy(), int(arr[0]))
