"""Partition functions for partition-aware segment pruning.

Re-design of ``pinot-segment-spi/.../partition/PartitionFunction.java`` +
``PartitionFunctionFactory.java``: Murmur / Modulo / HashCode / ByteArray
functions mapping a column value to a partition id. The Murmur implementation
matches Kafka's murmur2 (as the reference's does) so partition pruning agrees
with Kafka-partitioned streams.
"""

from __future__ import annotations

from typing import Any, Callable, Dict


def _murmur2(data: bytes) -> int:
    """Kafka murmur2, 32-bit (signed semantics match the JVM)."""
    length = len(data)
    seed = 0x9747B28C
    m = 0x5BD1E995
    r = 24
    mask = 0xFFFFFFFF
    h = (seed ^ length) & mask
    n_blocks = length // 4
    for i in range(n_blocks):
        k = int.from_bytes(data[i * 4:(i + 1) * 4], "little", signed=False)
        k = (k * m) & mask
        k ^= k >> r
        k = (k * m) & mask
        h = (h * m) & mask
        h ^= k
    tail = length & 3
    base = n_blocks * 4
    if tail == 3:
        h ^= (data[base + 2] & 0xFF) << 16
    if tail >= 2:
        h ^= (data[base + 1] & 0xFF) << 8
    if tail >= 1:
        h ^= data[base] & 0xFF
        h = (h * m) & mask
    h ^= h >> 13
    h = (h * m) & mask
    h ^= h >> 15
    # to signed 32-bit
    return h - (1 << 32) if h >= (1 << 31) else h


def _java_string_hashcode(s: str) -> int:
    h = 0
    for ch in s:
        h = (31 * h + ord(ch)) & 0xFFFFFFFF
    return h - (1 << 32) if h >= (1 << 31) else h


class PartitionFunction:
    def __init__(self, name: str, num_partitions: int, fn: Callable[[Any, int], int]):
        if num_partitions <= 0:
            raise ValueError("numPartitions must be > 0")
        self.name = name
        self.num_partitions = num_partitions
        self._fn = fn

    def partition(self, value: Any) -> int:
        return self._fn(value, self.num_partitions)


def _murmur_partition(value: Any, n: int) -> int:
    return (_murmur2(str(value).encode("utf-8")) & 0x7FFFFFFF) % n


def _modulo_partition(value: Any, n: int) -> int:
    return int(value) % n


def _hashcode_partition(value: Any, n: int) -> int:
    h = _java_string_hashcode(str(value))
    return abs(h) % n


def _bytearray_partition(value: Any, n: int) -> int:
    data = value if isinstance(value, bytes) else str(value).encode("utf-8")
    # JVM Arrays.hashCode(byte[]) over the bytes
    h = 1
    for b in data:
        sb = b - 256 if b >= 128 else b
        h = (31 * h + sb) & 0xFFFFFFFF
    h = h - (1 << 32) if h >= (1 << 31) else h
    return abs(h) % n


_FUNCTIONS: Dict[str, Callable[[Any, int], int]] = {
    "murmur": _murmur_partition,
    "modulo": _modulo_partition,
    "hashcode": _hashcode_partition,
    "bytearray": _bytearray_partition,
}


def get_partition_function(name: str, num_partitions: int) -> PartitionFunction:
    fn = _FUNCTIONS.get(name.lower())
    if fn is None:
        raise ValueError(f"unknown partition function {name!r}; "
                         f"available: {sorted(_FUNCTIONS)}")
    return PartitionFunction(name, num_partitions, fn)
