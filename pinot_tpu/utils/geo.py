"""Geospatial types + operations.

Re-design of the reference's geospatial layer (``pinot-core/.../geospatial/``
— JTS geometry/geography types, ST_* transform functions, H3-cell indexing):
a compact WKT-backed geometry model (POINT / POLYGON / MULTIPOINT) with
vectorized numpy predicates, so point-set operations (distance prefilters,
point-in-polygon over a whole column) run as array ops — the same masked
vector shape the TPU scan kernels consume.

Geometry (planar, euclidean) vs geography (spherical, haversine meters)
follows the reference's split: the serialized form carries a geography bit
(ref: GeometryUtils.GEOGRAPHY_SRID).
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

EARTH_RADIUS_M = 6371008.8  # mean earth radius


@dataclass(frozen=True)
class Geometry:
    """POINT / MULTIPOINT / POLYGON; coords are (x=lng, y=lat) pairs."""

    kind: str                       # POINT | MULTIPOINT | POLYGON
    coords: Tuple[Tuple[float, float], ...]
    geography: bool = False         # spherical semantics when True

    # -- WKT ----------------------------------------------------------------
    def wkt(self) -> str:
        if self.kind == "POINT":
            x, y = self.coords[0]
            return f"POINT ({_fmt(x)} {_fmt(y)})"
        if self.kind == "MULTIPOINT":
            inner = ", ".join(f"{_fmt(x)} {_fmt(y)}" for x, y in self.coords)
            return f"MULTIPOINT ({inner})"
        inner = ", ".join(f"{_fmt(x)} {_fmt(y)}" for x, y in self.coords)
        return f"POLYGON (({inner}))"

    @property
    def x(self) -> float:
        return self.coords[0][0]

    @property
    def y(self) -> float:
        return self.coords[0][1]


def _fmt(v: float) -> str:
    return f"{v:.10g}"


_WKT_POINT = re.compile(
    r"^\s*POINT\s*\(\s*([-\d.eE+]+)\s+([-\d.eE+]+)\s*\)\s*$", re.I)
_WKT_POLY = re.compile(
    r"^\s*POLYGON\s*\(\s*\((.*?)\)\s*\)\s*$", re.I | re.S)
_WKT_MULTIPOINT = re.compile(
    r"^\s*MULTIPOINT\s*\((.*?)\)\s*$", re.I | re.S)


def from_wkt(text: str, geography: bool = False) -> Geometry:
    """Parse POINT/POLYGON/MULTIPOINT WKT (ref: ST_GeomFromText /
    ST_GeogFromText)."""
    m = _WKT_POINT.match(text)
    if m:
        return Geometry("POINT", ((float(m.group(1)), float(m.group(2))),),
                        geography)
    m = _WKT_POLY.match(text)
    if m:
        pts = _parse_coord_list(m.group(1))
        return Geometry("POLYGON", tuple(pts), geography)
    m = _WKT_MULTIPOINT.match(text)
    if m:
        body = m.group(1).replace("(", "").replace(")", "")
        pts = _parse_coord_list(body)
        return Geometry("MULTIPOINT", tuple(pts), geography)
    raise ValueError(f"unsupported WKT: {text[:80]!r}")


def _parse_coord_list(body: str) -> List[Tuple[float, float]]:
    pts = []
    for part in body.split(","):
        xy = part.split()
        if len(xy) != 2:
            raise ValueError(f"bad coordinate {part!r}")
        pts.append((float(xy[0]), float(xy[1])))
    return pts


def point(x: float, y: float, geography: bool = False) -> Geometry:
    return Geometry("POINT", ((float(x), float(y)),), geography)


GEOG_PREFIX = "SRID=4326;"  # EWKT geography tag (ref: GEOGRAPHY_SRID)


def parse_ewkt(text) -> Geometry:
    """WKT or EWKT string -> Geometry; the ``SRID=4326;`` prefix selects
    geography (spherical) semantics. THE single entry every consumer of
    stored/literal geo strings goes through."""
    s = str(text)
    if s.startswith(GEOG_PREFIX):
        return from_wkt(s[len(GEOG_PREFIX):], geography=True)
    return from_wkt(s)


# --------------------------------------------------------------------------
# distance
# --------------------------------------------------------------------------

def haversine_m(lng1, lat1, lng2, lat2):
    """Spherical distance in meters; accepts scalars or numpy arrays."""
    lng1, lat1 = np.radians(lng1), np.radians(lat1)
    lng2, lat2 = np.radians(lng2), np.radians(lat2)
    dlat = lat2 - lat1
    dlng = lng2 - lng1
    a = (np.sin(dlat / 2) ** 2
         + np.cos(lat1) * np.cos(lat2) * np.sin(dlng / 2) ** 2)
    return 2 * EARTH_RADIUS_M * np.arcsin(np.sqrt(a))


def distance(a: Geometry, b: Geometry) -> float:
    """ST_DISTANCE: euclidean for geometry, meters for geography
    (ref: StDistanceFunction)."""
    if a.kind != "POINT" or b.kind != "POINT":
        raise ValueError("ST_DISTANCE supports POINT arguments")
    if a.geography or b.geography:
        return float(haversine_m(a.x, a.y, b.x, b.y))
    return math.hypot(a.x - b.x, a.y - b.y)


# --------------------------------------------------------------------------
# containment (ray casting; vectorized over candidate points)
# --------------------------------------------------------------------------

def points_in_polygon(xs: np.ndarray, ys: np.ndarray,
                      poly: Sequence[Tuple[float, float]]) -> np.ndarray:
    """Boolean mask: which (xs[i], ys[i]) fall inside the polygon ring
    (boundary counts as inside for axis-crossing edges, matching typical
    even-odd ray casting)."""
    xs = np.asarray(xs, dtype=np.float64)
    ys = np.asarray(ys, dtype=np.float64)
    inside = np.zeros(xs.shape, dtype=bool)
    pts = list(poly)
    if pts[0] != pts[-1]:
        pts = pts + [pts[0]]
    for (x1, y1), (x2, y2) in zip(pts[:-1], pts[1:]):
        crosses = ((y1 > ys) != (y2 > ys))
        with np.errstate(divide="ignore", invalid="ignore"):
            xint = (x2 - x1) * (ys - y1) / (y2 - y1) + x1
        inside ^= crosses & (xs < xint)
    return inside


def contains(outer: Geometry, inner: Geometry) -> bool:
    """ST_CONTAINS(polygon, point) (ref: StContainsFunction)."""
    if outer.kind != "POLYGON" or inner.kind != "POINT":
        raise ValueError("ST_CONTAINS supports (POLYGON, POINT)")
    return bool(points_in_polygon(
        np.array([inner.x]), np.array([inner.y]), outer.coords)[0])


def area(g: Geometry) -> float:
    """ST_AREA via the shoelace formula (planar)."""
    if g.kind != "POLYGON":
        return 0.0
    pts = list(g.coords)
    if pts[0] != pts[-1]:
        pts = pts + [pts[0]]
    s = 0.0
    for (x1, y1), (x2, y2) in zip(pts[:-1], pts[1:]):
        s += x1 * y2 - x2 * y1
    return abs(s) / 2.0


def union(geoms: Sequence[Geometry]) -> Geometry:
    """ST_UNION over point sets -> MULTIPOINT (the reference unions
    arbitrary JTS geometries; this build covers point data)."""
    pts = []
    geography = False
    for g in geoms:
        geography = geography or g.geography
        if g.kind in ("POINT", "MULTIPOINT"):
            pts.extend(g.coords)
        else:
            raise ValueError("ST_UNION here supports point geometries")
    uniq = sorted(set(pts))
    return Geometry("MULTIPOINT", tuple(uniq), geography)


# --------------------------------------------------------------------------
# grid cells (the H3-equivalent): lat/lng -> cell id at a resolution
# --------------------------------------------------------------------------
#
# The reference's H3 index buckets points into hexagonal cells so distance
# predicates prefilter by cell disk before exact tests. Hex grids buy ~15%
# fewer candidate cells than squares — irrelevant next to a TPU-vectorized
# exact pass — so this build uses a square lat/lng grid: cell id packs
# (resolution, ix, iy); kRing becomes a (2r+1)^2 block. Resolution r has
# 2^r cells per 360 degrees.

def cell_of(lng: float, lat: float, res: int) -> int:
    n = 1 << res
    ix = int((lng + 180.0) / 360.0 * n) % n
    iy = min(int((lat + 90.0) / 180.0 * n), n - 1)
    return (res << 52) | (ix << 26) | iy


def cells_of(lngs: np.ndarray, lats: np.ndarray, res: int) -> np.ndarray:
    n = 1 << res
    ix = (((np.asarray(lngs) + 180.0) / 360.0 * n).astype(np.int64)) % n
    iy = np.minimum(((np.asarray(lats) + 90.0) / 180.0 * n).astype(np.int64),
                    n - 1)
    return (np.int64(res) << 52) | (ix << 26) | iy


def cell_disk(lng: float, lat: float, radius_m: float, res: int) -> List[int]:
    """Cells whose contents can be within ``radius_m`` of the point — the
    kRing analogue used by the geo index prefilter.

    The longitude reach of a spherical cap is widest at its most poleward
    latitude (arcsin(sin c / cos phi)), not at the center, so the ring width
    uses cos() at the cap's poleward edge; near the poles the cap spans all
    longitudes and the full ring is taken."""
    n = 1 << res
    cell_h_m = 180.0 / n * 111_320.0   # meridian meters per cell
    ry = int(radius_m / cell_h_m) + 2
    reach_deg = math.degrees(radius_m / EARTH_RADIUS_M)
    edge_lat = min(abs(lat) + reach_deg, 90.0)
    lat_cos = math.cos(math.radians(edge_lat))
    if lat_cos <= 1e-3:
        rx = n // 2  # cap touches the pole: every longitude qualifies
    else:
        cell_w_m = 360.0 / n * 111_320.0 * lat_cos
        rx = min(int(radius_m / cell_w_m) + 2, n // 2)
    ix0 = int((lng + 180.0) / 360.0 * n) % n
    iy0 = min(int((lat + 90.0) / 180.0 * n), n - 1)
    out = []
    for dx in range(-rx, rx + 1):
        for dy in range(-ry, ry + 1):
            ix = (ix0 + dx) % n
            iy = iy0 + dy
            if 0 <= iy < n:
                out.append((res << 52) | (ix << 26) | iy)
    return sorted(set(out))
