"""t-digest: the mergeable quantile sketch.

Re-design of the reference's TDigest usage
(``PercentileTDigestAggregationFunction``, com.tdunning t-digest, default
compression 100): the merging-digest variant — centroids kept as parallel
numpy arrays (means, weights), merged by concatenate + sort + k-scale
compression, which is bulk vector math rather than per-point insertion.
"""

from __future__ import annotations

import math

from typing import Sequence, Tuple

import numpy as np

DEFAULT_COMPRESSION = 100.0


class TDigest:
    def __init__(self, compression: float = DEFAULT_COMPRESSION,
                 means: np.ndarray = None, weights: np.ndarray = None):
        self.compression = compression
        self.means = (np.asarray(means, dtype=np.float64)
                      if means is not None else np.empty(0))
        self.weights = (np.asarray(weights, dtype=np.float64)
                        if weights is not None else np.empty(0))

    # -- construction --------------------------------------------------------
    @classmethod
    def of(cls, values: Sequence[float],
           compression: float = DEFAULT_COMPRESSION) -> "TDigest":
        # unit weights: a plain value sort IS the centroid order, so the
        # build pays ONE np.sort instead of compressed()'s argsort+gather
        # (the per-segment sketch hot spot — round-5 profile: 2 full-column
        # argsorts per build)
        v = np.sort(np.asarray(values, dtype=np.float64))
        d = cls(compression, v, np.ones(v.shape[0]))
        return d.compressed(presorted=True)

    def merge(self, other: "TDigest") -> "TDigest":
        d = TDigest(self.compression,
                    np.concatenate([self.means, other.means]),
                    np.concatenate([self.weights, other.weights]))
        return d.compressed()

    def compressed(self, presorted: bool = False) -> "TDigest":
        """Cluster sorted centroids by unit steps of the k1 scale function —
        fully vectorized: each point's quantile midpoint maps to a k value,
        and points sharing ``floor(k)`` merge into one centroid (weighted
        mean via scatter-add). Python work is O(1), not O(N)."""
        n = self.means.shape[0]
        if n == 0:
            return self
        if presorted:
            means, weights = self.means, self.weights
        else:
            order = np.argsort(self.means, kind="stable")
            means, weights = self.means[order], self.weights[order]
        total = weights.sum()
        c = self.compression

        q = (np.cumsum(weights) - weights / 2.0) / total
        q = np.clip(q, 1e-15, 1 - 1e-15)
        k = c / (2 * math.pi) * np.arcsin(2 * q - 1)  # k1 scale, range ±c/4
        cluster = np.floor(k - k[0]).astype(np.int64)
        # monotone guard (numerical noise), then dense renumbering — unit
        # k-steps can skip integers for isolated heavy points. ``cluster``
        # is nondecreasing after the accumulate, so renumbering is a
        # diff/cumsum, NOT np.unique (which would argsort the column again)
        cluster = np.maximum.accumulate(cluster)
        cluster = np.cumsum(np.concatenate(
            [[0], (np.diff(cluster) > 0).astype(np.int64)]))
        n_out = int(cluster[-1]) + 1

        w_out = np.zeros(n_out)
        np.add.at(w_out, cluster, weights)
        m_out = np.zeros(n_out)
        np.add.at(m_out, cluster, means * weights)
        m_out /= w_out
        return TDigest(c, m_out, w_out)

    # -- quantile ------------------------------------------------------------
    @property
    def total_weight(self) -> float:
        return float(self.weights.sum())

    def quantile(self, q: float) -> float:
        """q in [0, 1]; linear interpolation between centroid means
        (matches the reference digest's behavior closely enough for the
        approximate contract)."""
        n = self.means.shape[0]
        if n == 0:
            return float("-inf")
        if n == 1:
            return float(self.means[0])
        total = self.weights.sum()
        target = q * total
        cum = np.cumsum(self.weights) - self.weights / 2.0
        if target <= cum[0]:
            return float(self.means[0])
        if target >= cum[-1]:
            return float(self.means[-1])
        i = int(np.searchsorted(cum, target))
        t = (target - cum[i - 1]) / (cum[i] - cum[i - 1])
        return float(self.means[i - 1] + t * (self.means[i] - self.means[i - 1]))

    # -- serde ---------------------------------------------------------------
    def serialize(self) -> Tuple:
        return (float(self.compression), tuple(float(m) for m in self.means),
                tuple(float(w) for w in self.weights))

    @classmethod
    def deserialize(cls, state: Tuple) -> "TDigest":
        c, means, weights = state
        return cls(c, np.asarray(means), np.asarray(weights))
