"""Per-column sorted dictionaries.

Re-design of ``pinot-segment-local/.../readers/BaseImmutableDictionary.java``
and ``SegmentDictionaryCreator.java:45``: values are sorted ascending so
dictId order == value order, which makes range predicates on dictionary
columns a *dictId interval* — the property the TPU filter kernels exploit
(a RANGE filter compiles to ``lo <= dictId <= hi``, pure vector compares).

Numeric dictionaries are plain sorted numpy arrays (device-stageable
directly). String/bytes dictionaries use an offsets+blob layout (mmap
friendly); the device only ever sees their dictIds.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from pinot_tpu.spi.data import DataType


class Dictionary:
    """Read interface (ref: pinot-segment-spi index/reader/Dictionary.java:33)."""

    data_type: DataType

    def __len__(self) -> int:
        raise NotImplementedError

    @property
    def cardinality(self) -> int:
        return len(self)

    def hll_register_luts(self, log2m: int):
        """Memoized (bucket, rank) register LUTs over this dictionary's
        values — the device HLL's plan-time parameters (string hashing is
        python-loop FNV, so recomputing per query would dominate plan
        time; the LUT depends only on (dictionary, log2m))."""
        cache = getattr(self, "_hll_luts", None)
        if cache is None:
            cache = {}
            self._hll_luts = cache
        luts = cache.get(log2m)
        if luts is None:
            from pinot_tpu.utils.hll import dictionary_register_luts

            luts = dictionary_register_luts(
                self.get_values(range(len(self))), log2m)
            cache[log2m] = luts
        return luts

    def index_of(self, value: Any) -> int:
        """value -> dictId, or -1 if absent (ref: Dictionary.NULL_VALUE_INDEX)."""
        raise NotImplementedError

    def insertion_index_of(self, value: Any) -> int:
        """Like index_of, but returns -(insertion_point+1) when absent
        (binary-search contract used by range predicate evaluation)."""
        raise NotImplementedError

    def get_value(self, dict_id: int) -> Any:
        raise NotImplementedError

    def get_values(self, dict_ids: Sequence[int]) -> List[Any]:
        return [self.get_value(i) for i in dict_ids]

    @property
    def min_value(self) -> Any:
        return self.get_value(0)

    @property
    def max_value(self) -> Any:
        return self.get_value(len(self) - 1)

    def device_values(self) -> Optional[np.ndarray]:
        """Numeric dictionaries expose their sorted value array for HBM
        staging (dictId -> value gather on device); None for var-width."""
        return None

    def range_to_dict_id_interval(self, lo: Any, hi: Any,
                                  lo_inclusive: bool, hi_inclusive: bool) -> Tuple[int, int]:
        """Map a value range to the matching closed dictId interval [a, b]
        (empty iff a > b). Core of dictionary-based range predicate eval
        (ref: RangePredicateEvaluatorFactory dictionary-based path)."""
        n = len(self)
        if lo is None:
            a = 0
        else:
            idx = self.insertion_index_of(lo)
            if idx >= 0:
                a = idx if lo_inclusive else idx + 1
            else:
                a = -idx - 1
        if hi is None:
            b = n - 1
        else:
            idx = self.insertion_index_of(hi)
            if idx >= 0:
                b = idx if hi_inclusive else idx - 1
            else:
                b = -idx - 2
        return a, b


class NumericDictionary(Dictionary):
    def __init__(self, values: np.ndarray, data_type: DataType):
        # values must be sorted ascending and unique
        self._values = values
        self.data_type = data_type

    def __len__(self) -> int:
        return int(self._values.shape[0])

    def index_of(self, value: Any) -> int:
        i = int(np.searchsorted(self._values, value))
        if i < len(self._values) and self._values[i] == value:
            return i
        return -1

    def insertion_index_of(self, value: Any) -> int:
        i = int(np.searchsorted(self._values, value))
        if i < len(self._values) and self._values[i] == value:
            return i
        return -(i + 1)

    def get_value(self, dict_id: int) -> Any:
        v = self._values[dict_id]
        if self.data_type in (DataType.FLOAT, DataType.DOUBLE):
            return float(v)
        return int(v)

    def get_values(self, dict_ids: Sequence[int]) -> List[Any]:
        arr = self._values[np.asarray(dict_ids)]
        return arr.tolist()

    def device_values(self) -> Optional[np.ndarray]:
        return self._values

    @property
    def raw_array(self) -> np.ndarray:
        return self._values


class StringDictionary(Dictionary):
    """Sorted UTF-8 strings as offsets[card+1] + byte blob.

    Bytes dictionaries reuse this with raw bytes (sorted bytewise, which
    matches the reference's ByteArray comparison order).
    """

    def __init__(self, offsets: np.ndarray, blob: np.ndarray, data_type: DataType):
        self._offsets = offsets
        self._blob = blob
        self.data_type = data_type
        self._is_bytes = data_type is DataType.BYTES

    @classmethod
    def from_values(cls, sorted_values: List[Any], data_type: DataType) -> "StringDictionary":
        encoded = [v if isinstance(v, bytes) else str(v).encode("utf-8")
                   for v in sorted_values]
        offsets = np.zeros(len(encoded) + 1, dtype=np.int64)
        for i, e in enumerate(encoded):
            offsets[i + 1] = offsets[i] + len(e)
        blob = np.frombuffer(b"".join(encoded), dtype=np.uint8).copy()
        return cls(offsets, blob, data_type)

    def __len__(self) -> int:
        return int(self._offsets.shape[0]) - 1

    def _raw(self, dict_id: int) -> bytes:
        lo, hi = int(self._offsets[dict_id]), int(self._offsets[dict_id + 1])
        return self._blob[lo:hi].tobytes()

    def get_value(self, dict_id: int) -> Any:
        raw = self._raw(dict_id)
        return raw if self._is_bytes else raw.decode("utf-8")

    def _encode(self, value: Any) -> bytes:
        return value if isinstance(value, bytes) else str(value).encode("utf-8")

    def insertion_index_of(self, value: Any) -> int:
        target = self._encode(value)
        lo, hi = 0, len(self)
        while lo < hi:
            mid = (lo + hi) // 2
            if self._raw(mid) < target:
                lo = mid + 1
            else:
                hi = mid
        if lo < len(self) and self._raw(lo) == target:
            return lo
        return -(lo + 1)

    def index_of(self, value: Any) -> int:
        i = self.insertion_index_of(value)
        return i if i >= 0 else -1

    @property
    def offsets(self) -> np.ndarray:
        return self._offsets

    @property
    def blob(self) -> np.ndarray:
        return self._blob


def build_dictionary(sorted_unique_values: List[Any], data_type: DataType) -> Dictionary:
    """Creator-side entry (ref: SegmentDictionaryCreator.java:45)."""
    if data_type.is_numeric:
        arr = np.asarray(sorted_unique_values, dtype=data_type.stored_np)
        return NumericDictionary(arr, data_type)
    return StringDictionary.from_values(sorted_unique_values, data_type)
