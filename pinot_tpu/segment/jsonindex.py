"""JSON flattening index: JSON_MATCH over path=value posting lists.

Re-design of the reference's JSON index
(``pinot-segment-local/.../segment/index/readers/json/ImmutableJsonIndexReader.java``
+ ``creator/impl/inv/json/``): at segment-create time every document of a
JSON column is flattened into canonical ``path\\0value`` keys (nested
objects become dotted paths, array elements collapse to ``[*]``); each key
owns a sorted doc-id posting list stored in the same delta+varint form as
the inverted index. ``JSON_MATCH(col, '...')`` filters then resolve to
posting-list unions/intersections instead of parsing documents at query
time.

Supported filter subset (the reference accepts full SQL there):
``"$.path" = 'v'`` / ``!=`` / ``<>``, ``"$.path" IS [NOT] NULL``, combined
with AND / OR and parentheses. Exact array indices (``$.arr[0]``) are not
indexed — only ``[*]`` — and raise, keeping results sound.
"""

from __future__ import annotations

import json
import re
from bisect import bisect_left
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

_SEP = "\x00"


# --------------------------------------------------------------------------
# flattening (ref: JsonUtils.flatten)
# --------------------------------------------------------------------------

def _canon(value: Any) -> Optional[str]:
    """Canonical value string (query literals normalize the same way)."""
    if value is None:
        return None
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, float) and value.is_integer():
        return str(int(value))
    return str(value)


def flatten_json(obj: Any, prefix: str = "") -> Iterator[Tuple[str, str]]:
    """(path, canonical value) pairs for every scalar leaf; arrays collapse
    to ``[*]`` path steps."""
    if isinstance(obj, dict):
        for k, v in obj.items():
            yield from flatten_json(v, f"{prefix}.{k}" if prefix else k)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            yield from flatten_json(v, f"{prefix}[*]")
    else:
        c = _canon(obj)
        if c is not None and prefix:
            yield prefix, c


# --------------------------------------------------------------------------
# creator
# --------------------------------------------------------------------------

def build_json_index(json_values: List[Any], num_docs: int, save,
                     col_dir: str, name: str) -> None:
    """Flatten every doc -> sorted key set -> posting lists (same storage
    scheme as the inverted index: key strings as offsets+blob, doc ids as
    delta+varint lists)."""
    import os

    from pinot_tpu import native

    pairs: Dict[str, List[int]] = {}
    for doc_id in range(num_docs):
        raw = json_values[doc_id]
        if raw is None:
            continue
        try:
            obj = json.loads(raw) if isinstance(raw, str) else raw
        except (ValueError, TypeError):
            continue
        seen = set()
        for path, value in flatten_json(obj):
            key = path + _SEP + value
            if key not in seen:
                seen.add(key)
                pairs.setdefault(key, []).append(doc_id)

    keys = sorted(pairs)
    blob = "".join(keys).encode("utf-8")
    offsets = np.zeros(len(keys) + 1, dtype=np.int64)
    for i, k in enumerate(keys):
        offsets[i + 1] = offsets[i] + len(k.encode("utf-8"))
    save("jkeysoff", offsets)
    save("jkeysblob", np.frombuffer(blob, dtype=np.uint8))

    doc_counts = np.zeros(len(keys) + 1, dtype=np.int64)
    all_docs = []
    for i, k in enumerate(keys):
        doc_counts[i + 1] = doc_counts[i] + len(pairs[k])
        all_docs.extend(pairs[k])
    flat = np.asarray(all_docs, dtype=np.int32)
    save("jinvoff", doc_counts)
    posting_blob, byte_offsets = native.varint_encode_lists(flat, doc_counts)
    save("jinvbo", byte_offsets)
    with open(os.path.join(col_dir, f"{name}.jinv.bin"), "wb") as f:
        f.write(posting_blob)


# --------------------------------------------------------------------------
# filter expression AST (the JSON_MATCH mini-dialect)
# --------------------------------------------------------------------------

_TOKEN = re.compile(r"""
    \s*(?:
      (?P<lp>\() | (?P<rp>\)) |
      (?P<and>AND\b) | (?P<or>OR\b) |
      (?P<isnotnull>IS\s+NOT\s+NULL\b) | (?P<isnull>IS\s+NULL\b) |
      (?P<neq><>|!=) | (?P<eq>=) |
      '(?P<sq>(?:[^']|'')*)' | "(?P<dq>(?:[^"]|"")*)" |
      (?P<num>-?\d+(?:\.\d+)?) | (?P<word>[^\s()=<>!]+)
    )""", re.VERBOSE | re.IGNORECASE)


def _tokenize(s: str) -> List[Tuple[str, str]]:
    s = s.strip()
    out, i = [], 0
    while i < len(s):
        m = _TOKEN.match(s, i)
        if m is None or m.end() == i:
            raise ValueError(f"bad JSON_MATCH filter at {s[i:i+20]!r}")
        i = m.end()
        kind = m.lastgroup
        if kind is None:
            continue
        text = m.group(kind)
        if kind == "sq":
            out.append(("str", text.replace("''", "'")))
        elif kind == "dq":
            out.append(("str", text.replace('""', '"')))
        else:
            out.append((kind, text))
    return out


def parse_match_filter(s: str):
    """-> AST: ("eq"|"neq", path, value) | ("exists"|"missing", path)
    | ("and"|"or", [children])."""
    toks = _tokenize(s)
    pos = 0

    def peek():
        return toks[pos] if pos < len(toks) else (None, None)

    def take(kind=None):
        nonlocal pos
        t = toks[pos]
        if kind is not None and t[0] != kind:
            raise ValueError(f"expected {kind}, got {t}")
        pos += 1
        return t

    def norm_path(p: str) -> str:
        if p.startswith("$."):
            p = p[2:]
        elif p.startswith("$"):
            p = p[1:]
        if re.search(r"\[\d+\]", p):
            raise ValueError(
                "exact array indices are not indexed; use [*]")
        return p

    def term():
        kind, text = peek()
        if kind == "lp":
            take("lp")
            node = expr()
            take("rp")
            return node
        kind, text = take()
        if kind not in ("str", "word"):
            raise ValueError(f"expected a path, got {text!r}")
        path = norm_path(text)
        kind2, _ = peek()
        if kind2 in ("eq", "neq"):
            op, _ = take()
            vkind, vtext = take()
            if vkind not in ("str", "num", "word"):
                raise ValueError(f"expected a literal, got {vtext!r}")
            value = _canon(json.loads(vtext) if vkind == "num" else vtext)
            return ("eq" if op == "eq" else "neq", path, value)
        if kind2 == "isnotnull":
            take()
            return ("exists", path)
        if kind2 == "isnull":
            take()
            return ("missing", path)
        raise ValueError(f"expected an operator after {path!r}")

    def and_expr():
        # AND binds tighter than OR (SQL precedence)
        node = term()
        children = [node]
        while peek()[0] == "and":
            take()
            children.append(term())
        return children[0] if len(children) == 1 else ("and", children)

    def expr():
        node = and_expr()
        children = [node]
        while peek()[0] == "or":
            take()
            children.append(and_expr())
        return children[0] if len(children) == 1 else ("or", children)

    node = expr()
    if pos != len(toks):
        raise ValueError(f"trailing tokens in JSON_MATCH filter: {toks[pos:]}")
    return node


def eval_match_ast(ast, doc_pairs: set, doc_paths: set) -> bool:
    """Evaluate the AST against one flattened document (the index-less
    fallback; ``doc_pairs`` = {(path, value)}, ``doc_paths`` = {path})."""
    op = ast[0]
    if op == "eq":
        return (ast[1], ast[2]) in doc_pairs
    if op == "neq":
        return ast[1] in doc_paths and (ast[1], ast[2]) not in doc_pairs
    if op == "exists":
        return ast[1] in doc_paths
    if op == "missing":
        return ast[1] not in doc_paths
    if op == "and":
        return all(eval_match_ast(c, doc_pairs, doc_paths) for c in ast[1])
    return any(eval_match_ast(c, doc_pairs, doc_paths) for c in ast[1])


def match_json_value(raw: Any, ast) -> bool:
    """Index-less evaluation of one JSON value (dictionary-LUT fallback).
    Unparseable/null docs flatten to NOTHING — the same view the index has
    of them (never flattened), so 'missing' is True and 'eq' False on both
    paths."""
    try:
        obj = json.loads(raw) if isinstance(raw, str) else raw
        pairs = set(flatten_json(obj))
    except (ValueError, TypeError):
        pairs = set()
    paths = {p for p, _ in pairs}
    return eval_match_ast(ast, pairs, paths)


# --------------------------------------------------------------------------
# reader
# --------------------------------------------------------------------------

class JsonIndexReader:
    """Posting-list resolution of JSON_MATCH filters
    (ref: ImmutableJsonIndexReader.getMatchingDocIds)."""

    def __init__(self, keys_off: np.ndarray, keys_blob: np.ndarray,
                 inv_off: np.ndarray, inv_byte_off: np.ndarray,
                 inv_blob, num_docs: int):
        blob = bytes(keys_blob.tobytes())
        self._keys = [
            blob[int(keys_off[i]):int(keys_off[i + 1])].decode("utf-8")
            for i in range(len(keys_off) - 1)]
        self._inv_off = inv_off
        self._inv_byte_off = inv_byte_off
        self._inv_blob = inv_blob
        self.num_docs = num_docs

    def _postings(self, idx: int) -> np.ndarray:
        from pinot_tpu import native

        n = int(self._inv_off[idx + 1] - self._inv_off[idx])
        if n == 0:
            return np.empty(0, dtype=np.int32)
        lo = int(self._inv_byte_off[idx])
        hi = int(self._inv_byte_off[idx + 1])
        return native.varint_decode(self._inv_blob[lo:hi], n)

    def _docs_for_key(self, key: str) -> np.ndarray:
        i = bisect_left(self._keys, key)
        if i < len(self._keys) and self._keys[i] == key:
            return self._postings(i)
        return np.empty(0, dtype=np.int32)

    def _docs_for_path(self, path: str) -> np.ndarray:
        """Union of postings for every key of ``path`` (keys are sorted, so
        the path's keys are one contiguous prefix range)."""
        prefix = path + _SEP
        lo = bisect_left(self._keys, prefix)
        # the separator is \x00, so path+"\x01" bounds the prefix range for
        # EVERY value (a "￿" bound would drop astral-plane values)
        hi = bisect_left(self._keys, path + "\x01")
        if lo == hi:
            return np.empty(0, dtype=np.int32)
        parts = [self._postings(i) for i in range(lo, hi)]
        return np.unique(np.concatenate(parts))

    def _mask(self, docs: np.ndarray) -> np.ndarray:
        m = np.zeros(self.num_docs, dtype=bool)
        m[docs] = True
        return m

    def match(self, filter_string: str) -> np.ndarray:
        """[num_docs] bool mask for a JSON_MATCH filter string."""
        return self._eval(parse_match_filter(filter_string))

    def _eval(self, ast) -> np.ndarray:
        op = ast[0]
        if op == "eq":
            return self._mask(self._docs_for_key(ast[1] + _SEP + ast[2]))
        if op == "neq":
            return (self._mask(self._docs_for_path(ast[1]))
                    & ~self._mask(self._docs_for_key(
                        ast[1] + _SEP + ast[2])))
        if op == "exists":
            return self._mask(self._docs_for_path(ast[1]))
        if op == "missing":
            return ~self._mask(self._docs_for_path(ast[1]))
        if op == "and":
            out = self._eval(ast[1][0])
            for c in ast[1][1:]:
                out &= self._eval(c)
            return out
        out = self._eval(ast[1][0])
        for c in ast[1][1:]:
            out |= self._eval(c)
        return out
