"""Immutable segment: mmap loader + per-column DataSource access.

Re-design of ``ImmutableSegmentImpl.java:48`` / ``ImmutableSegmentLoader.java:57``
+ ``datasource/DataSource.java:36``: a loaded segment wires each column's
dictionary, forward index, optional null bitmap and inverted index behind one
access object. All index arrays are ``np.load(mmap_mode="r")`` views — the
host never copies column data until it is staged to the device.
"""

from __future__ import annotations

import os
from functools import cached_property
from typing import Dict, Optional, Tuple

import numpy as np

from pinot_tpu.segment import metadata as meta
from pinot_tpu.segment.creator import COLUMNS_DIR, compute_dir_crc
from pinot_tpu.segment.dictionary import (
    Dictionary,
    NumericDictionary,
    StringDictionary,
)
from pinot_tpu.spi.data import DataType


class DataSource:
    """Single column's read access (ref: DataSource.java:36)."""

    def __init__(self, segment: "ImmutableSegment", name: str):
        self._segment = segment
        self.name = name
        self.metadata = segment.metadata.column(name)

    @cached_property
    def dictionary(self) -> Optional[Dictionary]:
        return self._segment._load_dictionary(self.name)

    @cached_property
    def forward_index(self) -> np.ndarray:
        """SV: [padded_capacity] dictIds or raw values.
        MV: [total_entries] flattened dictIds (use ``mv_offsets``)."""
        cm = self.metadata
        if cm.stored_dtype.startswith("packed:"):
            # fixed-bit packed (native unpack into an int32 staging buffer,
            # ref: FixedBitSVForwardIndexReaderV2.java:32)
            from pinot_tpu import native

            bits = int(cm.stored_dtype.split(":", 1)[1])
            buf = native.MmapBuffer(
                self._segment._path(self.name, "fwdpk", ext="bin"))
            try:
                return native.bitunpack(
                    buf.read(), self._segment.metadata.padded_capacity, bits)
            finally:
                buf.release()
        if cm.compression_codec:
            # chunk-compressed raw column: decompress once (HBM staging
            # consumes the dense array; ref: BaseChunkSVForwardIndexReader)
            from pinot_tpu.segment.compression import read_compressed

            return read_compressed(
                self._segment._path(self.name, "fwdcc", ext="bin"))
        return self._segment._load_array(self.name, "fwd")

    @cached_property
    def mv_offsets(self) -> Optional[np.ndarray]:
        if self.metadata.single_value:
            return None
        return self._segment._load_array(self.name, "mvoff")

    @cached_property
    def null_bitmap(self) -> Optional[np.ndarray]:
        if not self.metadata.has_nulls:
            return None
        return self._segment._load_array(self.name, "null")

    @cached_property
    def bloom_filter(self):
        """BloomFilter over distinct values, or None
        (ref: BloomFilterReader; used by the server-side pruner)."""
        if not self.metadata.has_bloom_filter:
            return None
        from pinot_tpu.utils.bloom import BloomFilter

        return BloomFilter.from_array(
            self._segment._load_array(self.name, "bloom"))

    @cached_property
    def json_index(self):
        """JsonIndexReader, or None (ref: ImmutableJsonIndexReader)."""
        if not self.metadata.has_json_index:
            return None
        from pinot_tpu.segment.jsonindex import JsonIndexReader

        with open(self._segment._path(self.name, "jinv", ext="bin"),
                  "rb") as f:
            blob = f.read()
        return JsonIndexReader(
            self._segment._load_array(self.name, "jkeysoff"),
            self._segment._load_array(self.name, "jkeysblob"),
            self._segment._load_array(self.name, "jinvoff"),
            self._segment._load_array(self.name, "jinvbo"),
            blob, self._segment.num_docs)

    @cached_property
    def text_index(self):
        """TextIndexReader over dictIds, or None (ref: TextIndexReader)."""
        if not self.metadata.has_text_index:
            return None
        from pinot_tpu.segment.textindex import TextIndexReader

        with open(self._segment._path(self.name, "txtinv", ext="bin"),
                  "rb") as f:
            blob = f.read()
        d = self.dictionary
        return TextIndexReader(
            self._segment._load_array(self.name, "txtoff"),
            self._segment._load_array(self.name, "txtblob"),
            self._segment._load_array(self.name, "txtinvoff"),
            self._segment._load_array(self.name, "txtinvbo"),
            blob, self.metadata.cardinality,
            value_of=lambda i: d.get_value(int(i)))

    @cached_property
    def fst_index(self):
        """FstIndexReader for REGEXP prefix narrowing, or None
        (ref: LuceneFSTIndexReader)."""
        if not self.metadata.has_fst_index:
            return None
        from pinot_tpu.segment.fstindex import FstIndexReader

        return FstIndexReader(
            self._segment._load_array(self.name, "fstoff"),
            self._segment._load_array(self.name, "fstlab"),
            self._segment._load_array(self.name, "fsttgt"),
            self._segment._load_array(self.name, "fstrng"),
            self.dictionary)

    @cached_property
    def geo_index(self):
        """GeoIndexReader for distance prefilters, or None
        (ref: ImmutableH3IndexReader)."""
        if not self.metadata.has_geo_index:
            return None
        from pinot_tpu.segment.geoindex import GeoIndexReader

        meta_arr = self._segment._load_array(self.name, "geometa")
        # segments built before coordinate arrays existed fall back to the
        # reader's parse-candidates path
        has_coords = os.path.exists(
            self._segment._path(self.name, "geolng"))
        return GeoIndexReader(
            self._segment._load_array(self.name, "geocells"),
            int(meta_arr[0]), self.dictionary,
            lngs=(self._segment._load_array(self.name, "geolng")
                  if has_coords else None),
            lats=(self._segment._load_array(self.name, "geolat")
                  if has_coords else None))

    @cached_property
    def range_order(self):
        """Sorted-order permutation for RANGE binary search, or None
        (host-path equivalent of BitSlicedRangeIndexReader)."""
        if not self.metadata.has_range_index:
            return None
        return self._segment._load_array(self.name, "rangeord")

    @cached_property
    def range_sorted_values(self):
        """Values in sorted order, gathered ONCE per staged segment so a
        RANGE lookup is O(log n) search + O(k) scatter per query."""
        order = self.range_order
        if order is None:
            return None
        n = self._segment.num_docs
        return np.asarray(self.forward_index[:n])[np.asarray(order)]

    @cached_property
    def inverted_index(self) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """(doc-count offsets[card+1], byte offsets[card+1]) of the varint
        posting lists, or None (ref: BitmapInvertedIndexReader.java:34)."""
        if not self.metadata.has_inverted_index:
            return None
        return (self._segment._load_array(self.name, "invoff"),
                self._segment._load_array(self.name, "invbo"))

    @cached_property
    def _inv_blob(self):
        from pinot_tpu import native

        return native.MmapBuffer(
            self._segment._path(self.name, "inv", ext="bin"))

    def doc_ids_for_dict_id(self, dict_id: int) -> np.ndarray:
        """Inverted lookup: sorted docIds containing dictId (native varint
        posting-list decode)."""
        from pinot_tpu import native

        inv = self.inverted_index
        if inv is None:
            raise ValueError(f"no inverted index on column {self.name!r}")
        offsets, byte_offsets = inv
        n = int(offsets[dict_id + 1] - offsets[dict_id])
        if n == 0:
            return np.empty(0, dtype=np.int32)
        raw = self._inv_blob.as_array(
            np.uint8, count=int(byte_offsets[dict_id + 1] - byte_offsets[dict_id]),
            offset=int(byte_offsets[dict_id]))
        return native.varint_decode(raw.tobytes(), n)

    def dense_mv(self) -> Tuple[np.ndarray, np.ndarray]:
        """Densify the MV column for device staging:
        returns (values [padded_capacity, max_mv] with 0-padding,
                 counts [padded_capacity] int32).

        Fixed-shape layout is the TPU representation of the reference's
        var-length MV forward index (FixedBitMVForwardIndexReader)."""
        cm = self.metadata
        assert not cm.single_value
        capacity = self._segment.metadata.padded_capacity
        num_docs = self._segment.metadata.num_docs
        max_mv = max(cm.max_num_multi_values, 1)
        offsets = self.mv_offsets
        flat = self.forward_index
        row_counts = np.diff(offsets)
        counts = np.zeros(capacity, dtype=np.int32)
        counts[:num_docs] = row_counts.astype(np.int32)
        dense = np.zeros((capacity, max_mv), dtype=np.int32)
        # CSR -> dense: rows are variable length; vectorized fill
        row_idx = np.repeat(np.arange(num_docs), row_counts)
        col_idx = np.arange(offsets[-1]) - np.repeat(offsets[:-1], row_counts)
        dense[row_idx, col_idx] = flat.astype(np.int32)
        return dense, counts


class ImmutableSegment:
    """Ref: ImmutableSegmentImpl.java:48 (read path only; creation lives in
    segment/creator.py, mutation in segment/mutable.py)."""

    def __init__(self, segment_dir: str, metadata: meta.SegmentMetadata):
        self.segment_dir = segment_dir
        self.metadata = metadata
        self._data_sources: Dict[str, DataSource] = {}

    # -- IndexSegment interface (ref: IndexSegment.java:32) ---------------
    @property
    def segment_name(self) -> str:
        return self.metadata.segment_name

    @property
    def num_docs(self) -> int:
        return self.metadata.num_docs

    @property
    def padded_capacity(self) -> int:
        return self.metadata.padded_capacity

    @property
    def column_names(self):
        return list(self.metadata.columns.keys())

    def data_source(self, column: str) -> DataSource:
        ds = self._data_sources.get(column)
        if ds is None:
            self.metadata.column(column)  # raises on unknown column
            ds = DataSource(self, column)
            self._data_sources[column] = ds
        return ds

    @cached_property
    def star_trees(self):
        """Loaded star-trees (ref: ImmutableSegmentImpl star-tree wiring)."""
        from pinot_tpu.segment.startree import StarTree

        trees = []
        for i in range(self.metadata.star_tree_count):
            t = StarTree.load(self.segment_dir, index=i)
            if t is not None:
                trees.append(t)
        return trees

    # -- loading helpers ---------------------------------------------------
    def _path(self, column: str, suffix: str, ext: str = "npy") -> str:
        return os.path.join(self.segment_dir, COLUMNS_DIR,
                            f"{column}.{suffix}.{ext}")

    def _load_array(self, column: str, suffix: str) -> np.ndarray:
        return np.load(self._path(column, suffix), mmap_mode="r")

    def _load_dictionary(self, column: str) -> Optional[Dictionary]:
        cm = self.metadata.column(column)
        if not cm.has_dictionary:
            return None
        if cm.data_type.is_numeric:
            return NumericDictionary(self._load_array(column, "dict"), cm.data_type)
        return StringDictionary(self._load_array(column, "dictoff"),
                                self._load_array(column, "dictblob"),
                                cm.data_type)

    # -- value reads (host-side; used by selection results + tests) -------
    def get_value(self, column: str, doc_id: int):
        ds = self.data_source(column)
        cm = ds.metadata
        if cm.single_value:
            v = ds.forward_index[doc_id]
            if cm.has_dictionary:
                return ds.dictionary.get_value(int(v))
            return cm.data_type.convert(v)
        offsets = ds.mv_offsets
        ids = ds.forward_index[offsets[doc_id]:offsets[doc_id + 1]]
        return [ds.dictionary.get_value(int(i)) for i in ids]

    def __repr__(self) -> str:
        return (f"ImmutableSegment({self.segment_name!r}, docs={self.num_docs}, "
                f"columns={len(self.metadata.columns)})")


def load_segment(segment_dir: str) -> ImmutableSegment:
    """Ref: ImmutableSegmentLoader.load:57 (mmap via PinotDataBuffer in the
    reference; numpy mmap here)."""
    md_path = os.path.join(segment_dir, meta.METADATA_FILE)
    if not os.path.isfile(md_path):
        raise FileNotFoundError(f"not a segment directory (no {meta.METADATA_FILE}): "
                                f"{segment_dir}")
    sm = meta.SegmentMetadata.load(md_path)
    return ImmutableSegment(segment_dir, sm)


def verify_crc(segment_dir: str) -> bool:
    """Recompute the CRC over all index files and compare to metadata
    (refresh detection, ref: creation.meta CRC)."""
    seg = load_segment(segment_dir)
    col_dir = os.path.join(segment_dir, COLUMNS_DIR)
    return compute_dir_crc(col_dir) == seg.metadata.crc
