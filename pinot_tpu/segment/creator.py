"""Segment creation: the two-pass columnar index build.

Re-design of ``SegmentIndexCreationDriverImpl.java:81`` +
``SegmentColumnarIndexCreator.java:78``: pass 1 collects per-column stats
(unique values, min/max, sortedness, MV fan-out), then dictionaries are
built, then pass 2 writes the forward (and optional inverted) indexes.

Output layout (file-per-index, like the reference's v1 format,
``V1Constants.java:25-27``) under ``<segment_dir>/``:

- ``metadata.json``                   segment + column metadata, CRC
- ``columns/<col>.dict.npy``          numeric dictionary (sorted values)
- ``columns/<col>.dictoff.npy`` / ``.dictblob.npy``  string/bytes dictionary
- ``columns/<col>.fwdpk.bin``         SV dict column: fixed-bit packed
  dictIds over [padded_capacity] (native pack/unpack,
  ref: FixedBitSVForwardIndexWriter; stored_dtype records ``packed:<bits>``)
- ``columns/<col>.fwd.npy``           RAW numeric SV values; MV: flattened
  dictIds
- ``columns/<col>.mvoff.npy``         MV row offsets [num_docs + 1]
- ``columns/<col>.null.npy``          optional null bitmap [padded_capacity]
- ``columns/<col>.invoff.npy`` / ``.invbo.npy`` / ``.inv.bin``  optional
  inverted index: per-dictId delta+varint posting lists (the
  RoaringBitmap-equivalent form, ref: BitmapInvertedIndexReader.java:34)

Forward indexes are padded to ``padded_capacity`` (multiple of 1024 docs) so
staged device arrays are tile-aligned; pad rows carry dictId 0 / value 0 and
are masked by ``doc_id >= num_docs`` in kernels.
"""

from __future__ import annotations

import os
import time

from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence, Union

import numpy as np

from pinot_tpu import native
from pinot_tpu.segment import metadata as meta
from pinot_tpu.segment.dictionary import (
    NumericDictionary,
    StringDictionary,
    build_dictionary,
)
from pinot_tpu.spi.data import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import IndexingConfig, TableConfig
from pinot_tpu.utils.partition import get_partition_function

COLUMNS_DIR = "columns"


def _sorted_factorize(arr: np.ndarray):
    """(sorted unique values, int64 dictIds) for a flat value array.

    Hash-based ``pd.factorize`` + a cardinality-sized sort: O(n + k log k)
    vs the O(n log n) full-column sort of ``np.unique(return_inverse=True)``
    — the segment-build hot spot at SSB scale (profiling: ~70% of build
    time was argsorting 375k-row string columns whose cardinality is 25)."""
    import pandas as pd

    codes, uniq = pd.factorize(arr, use_na_sentinel=False)
    uniq = np.asarray(uniq)
    order = np.argsort(uniq, kind="stable")
    rank = np.empty(order.shape[0], dtype=np.int64)
    rank[order] = np.arange(order.shape[0], dtype=np.int64)
    return uniq[order], rank[codes]


def compute_dir_crc(col_dir: str) -> int:
    """CRC over all index files in canonical (sorted-filename) order, for
    refresh detection (ref: creation.meta CRC, V1Constants.java:56).
    Native file CRC when the library is available."""
    crc = 0
    for fname in sorted(os.listdir(col_dir)):
        crc = native.crc32_file(os.path.join(col_dir, fname), crc)
    return crc & 0xFFFFFFFF

RowsInput = Union[Iterable[Mapping[str, Any]], Mapping[str, Sequence[Any]]]


def build_inverted_index(name: str, dict_ids_flat: np.ndarray,
                         mv_counts: Optional[np.ndarray], num_docs: int,
                         cardinality: int, save, col_dir: str) -> None:
    """Inverted index: per dictId, the sorted docIds containing it, stored
    as delta+varint posting lists (the RoaringBitmap-equivalent compressed
    form; ref: creators under segment/creator/impl/inv/). ``invoff`` =
    cumulative doc counts, ``invbo`` = byte offsets into the varint blob.
    Shared by the creator and the reload preprocessor."""
    if mv_counts is None:
        doc_ids = np.arange(num_docs, dtype=np.int64)
        ids = dict_ids_flat[:num_docs]
    else:
        doc_ids = np.repeat(np.arange(num_docs, dtype=np.int64), mv_counts)
        ids = dict_ids_flat
    order = np.lexsort((doc_ids, ids))
    sorted_ids = ids[order]
    sorted_docs = doc_ids[order].astype(np.int32)
    offsets = np.zeros(cardinality + 1, dtype=np.int64)
    np.add.at(offsets, sorted_ids + 1, 1)
    offsets = np.cumsum(offsets)
    save("invoff", offsets)
    blob, byte_offsets = native.varint_encode_lists(sorted_docs, offsets)
    save("invbo", byte_offsets)
    with open(os.path.join(col_dir, f"{name}.inv.bin"), "wb") as f:
        f.write(blob)


class SegmentBuilder:
    """Driver for building one immutable segment directory.

    ``rows`` may be an iterable of row dicts (GenericRow equivalent,
    ref: pinot-spi data/readers/GenericRow.java) or a columnar mapping
    ``column -> sequence`` (fast path for batch ingest).
    """

    def __init__(self, schema: Schema, segment_name: str,
                 table_name: Optional[str] = None,
                 indexing_config: Optional[IndexingConfig] = None,
                 table_config: Optional[TableConfig] = None):
        self.schema = schema
        self.segment_name = segment_name
        if table_config is not None:
            self.table_name = table_config.table_name
            self.indexing = table_config.indexing_config
            self.field_configs = {c.name: c
                                  for c in table_config.field_config_list}
        else:
            self.table_name = table_name or schema.schema_name
            self.indexing = indexing_config or IndexingConfig()
            self.field_configs = {}

    # -- public API --------------------------------------------------------
    def build(self, rows: RowsInput, out_dir: str) -> meta.SegmentMetadata:
        columns = self._to_columnar(rows)
        num_docs = self._num_docs(columns)
        capacity = meta.pad_capacity(num_docs)

        seg_dir = os.path.join(out_dir, self.segment_name)
        col_dir = os.path.join(seg_dir, COLUMNS_DIR)
        os.makedirs(col_dir, exist_ok=True)

        col_metas: Dict[str, meta.ColumnMetadata] = {}
        for fs in self.schema.field_specs:
            values = columns.get(fs.name)
            cm = self._build_column(fs, values, num_docs, capacity, col_dir)
            col_metas[fs.name] = cm
        crc = compute_dir_crc(col_dir)

        time_col = self.schema.time_column
        min_t = max_t = None
        if time_col is not None and col_metas[time_col].min_value is not None:
            # integral time columns store the range as ints; string/float time
            # columns keep the raw values (pruners compare in column order)
            mn, mx = col_metas[time_col].min_value, col_metas[time_col].max_value
            if self.schema.field_spec(time_col).data_type.is_integral:
                min_t, max_t = int(mn), int(mx)
            else:
                min_t, max_t = mn, mx

        sm = meta.SegmentMetadata(
            segment_name=self.segment_name,
            table_name=self.table_name,
            schema=self.schema,
            num_docs=num_docs,
            padded_capacity=capacity,
            creation_time_ms=meta.now_ms(),
            time_column=time_col,
            min_time=min_t,
            max_time=max_t,
            crc=crc,
            columns=col_metas,
        )
        sm.star_tree_count = self._build_star_trees(seg_dir, sm)
        sm.save(os.path.join(seg_dir, meta.METADATA_FILE))
        return sm

    def _build_star_trees(self, seg_dir: str, sm: meta.SegmentMetadata) -> int:
        """Build configured star-trees over the just-written columns
        (ref: MultipleTreesBuilder after SegmentColumnarIndexCreator)."""
        from pinot_tpu.segment.startree import StarTreeBuilder, StarTreeConfig

        configs = [StarTreeConfig.from_spi(c)
                   for c in self.indexing.star_tree_index_configs]
        if self.indexing.enable_default_star_tree and not configs:
            default = self._default_star_tree_config(sm)
            if default is not None:
                configs = [default]
        if not configs:
            return 0

        col_dir = os.path.join(seg_dir, COLUMNS_DIR)

        def load(col: str, suffix: str) -> np.ndarray:
            return np.load(os.path.join(col_dir, f"{col}.{suffix}.npy"))

        def load_fwd(col: str) -> np.ndarray:
            cm = sm.columns[col]
            if cm.stored_dtype.startswith("packed:"):
                bits = int(cm.stored_dtype.split(":", 1)[1])
                with open(os.path.join(col_dir, f"{col}.fwdpk.bin"),
                          "rb") as f:
                    return native.bitunpack(f.read(), sm.padded_capacity,
                                            bits)
            if cm.compression_codec:
                from pinot_tpu.segment.compression import read_compressed

                return read_compressed(
                    os.path.join(col_dir, f"{col}.fwdcc.bin"))
            return np.load(os.path.join(col_dir, f"{col}.fwd.npy"))

        from pinot_tpu.segment.startree import derived_pair_expr

        count = 0
        build_s: List[float] = []
        for cfg in configs:
            try:
                dim_ids = {}
                for d in cfg.dimensions_split_order:
                    cm = sm.columns[d]
                    if not (cm.has_dictionary and cm.single_value):
                        raise ValueError(f"dimension {d} must be a "
                                         "dict-encoded SV column")
                    dim_ids[d] = load_fwd(d).astype(np.int32)
                metric_vals = {}
                for fn, col in cfg.function_column_pairs:
                    if col == "*":
                        continue
                    # derived pair columns ('sum__(a*b)') evaluate in the
                    # builder from their base columns' raw values
                    expr = derived_pair_expr(col)
                    for c in (expr.columns() if expr is not None else [col]):
                        if c in metric_vals:
                            continue
                        cm = sm.columns[c]
                        if not (cm.single_value and cm.data_type.is_numeric):
                            raise ValueError(f"metric {c} must be a numeric "
                                             "SV column")
                        fwd = load_fwd(c)
                        if cm.has_dictionary:
                            metric_vals[c] = load(c, "dict")[fwd]
                        else:
                            metric_vals[c] = fwd
                t0 = time.perf_counter()
                tree = StarTreeBuilder(cfg).build(dim_ids, metric_vals,
                                                  sm.num_docs)
                build_s.append(round(time.perf_counter() - t0, 4))
                tree.save(seg_dir, index=count)
                count += 1
            except (ValueError, KeyError, OSError) as e:
                import logging

                logging.getLogger(__name__).warning(
                    "skipping star-tree for %s: %s", self.segment_name, e)
        sm.star_tree_build_s = build_s
        return count

    def _default_star_tree_config(self, sm: meta.SegmentMetadata):
        """Ref: enableDefaultStarTree — dimensions with bounded cardinality
        (descending), COUNT(*) + SUM per numeric metric."""
        from pinot_tpu.segment.startree import StarTreeConfig

        dims = [(cm.cardinality, name) for name, cm in sm.columns.items()
                if cm.has_dictionary and cm.single_value
                and sm.schema.field_spec(name).is_dimension
                and 1 < cm.cardinality <= 10_000]
        if not dims:
            return None
        split = [n for _, n in sorted(dims, reverse=True)]
        pairs = [("count", "*")]
        for name, cm in sm.columns.items():
            if sm.schema.field_spec(name).is_metric and cm.data_type.is_numeric \
                    and cm.single_value:
                pairs.append(("sum", name))
        return StarTreeConfig(split, pairs, max_leaf_records=10_000)

    # -- internals ---------------------------------------------------------
    def _to_columnar(self, rows: RowsInput) -> Dict[str, List[Any]]:
        if isinstance(rows, Mapping):
            # numpy arrays pass through untouched (vectorized build path)
            return {k: (v if isinstance(v, np.ndarray) else list(v))
                    for k, v in rows.items()}
        columns: Dict[str, List[Any]] = {n: [] for n in self.schema.column_names}
        for row in rows:
            for name in self.schema.column_names:
                columns[name].append(row.get(name))
        return columns

    def _num_docs(self, columns: Dict[str, List[Any]]) -> int:
        sizes = {len(v) for v in columns.values() if v is not None}
        if not sizes:
            raise ValueError("no input rows")
        if len(sizes) != 1:
            raise ValueError(f"ragged column lengths: { {k: len(v) for k, v in columns.items()} }")
        return sizes.pop()

    def _normalize(self, fs: FieldSpec, values: Optional[List[Any]],
                   num_docs: int) -> tuple:
        """Null substitution + type coercion. Returns (values, null_mask).

        Vectorized fast path: an SV column handed a numpy array skips the
        per-element convert loop (the batch-ingest analogue of the
        reference's columnar stats collectors — SSB-scale builds would
        otherwise spend minutes in python object conversion)."""
        if (isinstance(values, np.ndarray) and values.ndim == 1
                and fs.single_value):
            if fs.data_type.is_numeric and values.dtype.kind in "iuf":
                if values.dtype.kind == "f":
                    nulls = np.isnan(values)
                    if nulls.any():
                        out = values.copy()
                        out[nulls] = fs.default_null_value
                        return out.astype(fs.data_type.stored_np), nulls
                return (values.astype(fs.data_type.stored_np),
                        np.zeros(num_docs, dtype=bool))
            if (values.dtype.kind == "U"
                    and fs.data_type in (DataType.STRING, DataType.JSON)):
                # unicode arrays only: BYTES columns (and 'S' arrays) must
                # go through per-element convert or str(v) would store
                # python byte reprs
                return values, np.zeros(num_docs, dtype=bool)
        if values is None:
            values = [None] * num_docs
        null_mask = np.zeros(num_docs, dtype=bool)
        out: List[Any] = []
        default = fs.default_null_value
        if fs.single_value:
            for i, v in enumerate(values):
                if v is None or (isinstance(v, float) and v != v):
                    # None and float NaN are both nulls (real-world readers
                    # surface missing numeric cells as NaN)
                    null_mask[i] = True
                    out.append(default)
                else:
                    out.append(fs.data_type.convert(v))
        else:
            def is_nan(x):
                return isinstance(x, float) and x != x

            for i, v in enumerate(values):
                if v is None or is_nan(v) or (
                        isinstance(v, (list, tuple, np.ndarray)) and len(v) == 0):
                    null_mask[i] = True
                    out.append([default])
                elif isinstance(v, (list, tuple, np.ndarray)):
                    vals = [fs.data_type.convert(x) for x in v
                            if not (x is None or is_nan(x))]
                    if vals:
                        out.append(vals)
                    else:
                        null_mask[i] = True
                        out.append([default])
                else:
                    out.append([fs.data_type.convert(v)])
        return out, null_mask

    def _build_column(self, fs: FieldSpec, raw_values: Optional[List[Any]],
                      num_docs: int, capacity: int,
                      col_dir: str) -> meta.ColumnMetadata:
        values, null_mask = self._normalize(fs, raw_values, num_docs)
        has_nulls = bool(null_mask.any())
        fc = self.field_configs.get(fs.name)
        no_dict = ((fs.name in self.indexing.no_dictionary_columns
                    or (fc is not None and fc.encoding_type.upper() == "RAW"))
                   and fs.data_type.is_numeric and fs.single_value)
        want_inverted = fs.name in self.indexing.inverted_index_columns

        def save(suffix: str, arr: np.ndarray) -> None:
            np.save(os.path.join(col_dir, f"{fs.name}.{suffix}.npy"), arr)

        if has_nulls:
            nb = np.zeros(capacity, dtype=bool)
            nb[:num_docs] = null_mask
            save("null", nb)

        if no_dict:
            # RAW numeric column: fwd index holds values directly
            arr = np.zeros(capacity, dtype=fs.data_type.stored_np)
            arr[:num_docs] = np.asarray(values, dtype=fs.data_type.stored_np)
            codec_used = None
            if fc is not None and fc.compression_codec:
                # chunk-compressed raw index (ref: ChunkCompressorFactory +
                # VarByteChunkSVForwardIndexWriterV4)
                from pinot_tpu.segment.compression import write_compressed

                codec_used = write_compressed(
                    os.path.join(col_dir, f"{fs.name}.fwdcc.bin"),
                    arr, fc.compression_codec)
            else:
                save("fwd", arr)
            data = arr[:num_docs]
            uniq = np.unique(data)
            is_sorted = bool(np.all(data[:-1] <= data[1:])) if num_docs > 1 else True
            has_range = False
            if fs.name in self.indexing.range_index_columns and num_docs:
                # sorted-order permutation: RANGE resolves by binary search
                # + slice instead of a full compare scan (the host-path
                # equivalent of BitSlicedRangeIndexReader; the device path
                # keeps its dense compare — that IS the TPU-shaped plan)
                save("rangeord", np.argsort(data, kind="stable")
                     .astype(np.int32))
                has_range = True
            return meta.ColumnMetadata(
                name=fs.name, data_type=fs.data_type, field_type=fs.field_type,
                single_value=True, encoding=meta.Encoding.RAW,
                cardinality=int(len(uniq)),
                stored_dtype=str(arr.dtype),
                min_value=data.min() if num_docs else None,
                max_value=data.max() if num_docs else None,
                is_sorted=is_sorted, has_dictionary=False, has_nulls=has_nulls,
                has_bloom_filter=self._maybe_build_bloom(fs.name, uniq, save),
                has_range_index=has_range,
                compression_codec=codec_used,
                **self._partition_meta(fs.name, values),
            )

        # -- dictionary encoding ------------------------------------------
        if fs.single_value:
            flat = values
        else:
            flat = [x for row in values for x in row]

        if fs.data_type.is_numeric:
            flat_arr = np.asarray(flat, dtype=fs.data_type.stored_np)
            dict_values, dict_ids_flat = _sorted_factorize(flat_arr)
            dictionary = build_dictionary(dict_values, fs.data_type)
        elif isinstance(flat, np.ndarray):
            # vectorized string dictionary build (numpy sorts ASCII the
            # same way python does)
            uniq_arr, dict_ids_flat = _sorted_factorize(flat)
            dictionary = build_dictionary([str(v) for v in uniq_arr],
                                          fs.data_type)
        else:
            uniq = sorted(set(flat))
            dictionary = build_dictionary(uniq, fs.data_type)
            lookup = {v: i for i, v in enumerate(uniq)}
            dict_ids_flat = np.fromiter((lookup[v] for v in flat),
                                        dtype=np.int64, count=len(flat))

        card = dictionary.cardinality
        dtype = meta.narrowest_int_dtype(card)

        # persist dictionary
        if isinstance(dictionary, NumericDictionary):
            save("dict", dictionary.raw_array)
        else:
            assert isinstance(dictionary, StringDictionary)
            save("dictoff", dictionary.offsets)
            save("dictblob", dictionary.blob)

        if fs.single_value:
            # fixed-bit packed forward index (ref: FixedBitSVForwardIndexWriter
            # — the dominant scan format; unpacked natively at load into
            # int32 HBM-staging buffers)
            bits = native.bits_needed(max(card, 1))
            fwd = np.zeros(capacity, dtype=np.int32)
            fwd[:num_docs] = dict_ids_flat.astype(np.int32)
            with open(os.path.join(col_dir, f"{fs.name}.fwdpk.bin"),
                      "wb") as f:
                f.write(native.bitpack(fwd, bits))
            dtype = f"packed:{bits}"
            sv_ids = dict_ids_flat
            is_sorted = bool(np.all(sv_ids[:-1] <= sv_ids[1:])) if num_docs > 1 else True
            max_mv, total_entries = 0, num_docs
        else:
            offsets = np.zeros(num_docs + 1, dtype=np.int64)
            for i, row in enumerate(values):
                offsets[i + 1] = offsets[i] + len(row)
            save("mvoff", offsets)
            save("fwd", dict_ids_flat.astype(dtype))
            is_sorted = False
            max_mv = int(max((len(r) for r in values), default=0))
            total_entries = int(offsets[-1])

        if want_inverted:
            self._build_inverted(fs.name, dict_ids_flat,
                                 values if not fs.single_value else None,
                                 num_docs, card, save, col_dir=col_dir)

        has_bloom = self._maybe_build_bloom(
            fs.name, lambda: dictionary.get_values(range(card)), save)
        has_json = self._maybe_build_json_index(fs, values, num_docs, save,
                                                col_dir)
        has_text = False
        if (fs.name in self.indexing.text_index_columns
                and fs.single_value and not fs.data_type.is_numeric):
            # text index over the DICTIONARY values: postings hold dictIds,
            # so TEXT_MATCH resolves to the same dictId-LUT shape the
            # device scan consumes (ref: LuceneTextIndexCreator)
            from pinot_tpu.segment.textindex import build_text_index

            build_text_index(dictionary.get_values(range(card)), save,
                             col_dir, fs.name)
            has_text = True

        has_geo = False
        if (fc is not None and (fc.index_type or "").upper() == "H3"
                and fs.single_value and not fs.data_type.is_numeric):
            # grid-cell geo index over the dictionary's WKT points
            # (ref: H3IndexCreator; design note in geoindex.py)
            from pinot_tpu.segment.geoindex import (
                DEFAULT_RESOLUTION,
                build_geo_index,
            )

            res = int(str(fc.properties.get(
                "resolutions", DEFAULT_RESOLUTION)).split(",")[0])
            has_geo = build_geo_index(
                dictionary.get_values(range(card)), res, save)

        has_fst = False
        if ((fs.name in self.indexing.fst_index_columns
             or (fc is not None and (fc.index_type or "").upper() == "FST"))
                and fs.single_value and not fs.data_type.is_numeric):
            # FST index: CSR byte-trie over the sorted dictionary terms
            # (ref: LuceneFSTIndexCreator; design note in fstindex.py)
            from pinot_tpu.segment.fstindex import FstIndexBuilder

            eo, el, et, nr = FstIndexBuilder(
                [str(v) for v in dictionary.get_values(range(card))]).build()
            save("fstoff", eo)
            save("fstlab", el)
            save("fsttgt", et)
            save("fstrng", nr)
            has_fst = True

        return meta.ColumnMetadata(
            name=fs.name, data_type=fs.data_type, field_type=fs.field_type,
            single_value=fs.single_value, encoding=meta.Encoding.DICT,
            cardinality=card, stored_dtype=dtype,
            min_value=dictionary.min_value if card else None,
            max_value=dictionary.max_value if card else None,
            is_sorted=is_sorted, has_dictionary=True,
            has_inverted_index=want_inverted, has_nulls=has_nulls,
            has_bloom_filter=has_bloom, has_json_index=has_json,
            has_text_index=has_text, has_fst_index=has_fst,
            has_geo_index=has_geo,
            max_num_multi_values=max_mv, total_number_of_entries=total_entries,
            **self._partition_meta(fs.name, values),
        )

    def _maybe_build_json_index(self, fs: FieldSpec, values, num_docs: int,
                                save, col_dir: str) -> bool:
        """JSON flattening index when configured (ref: jsonIndexColumns ->
        segment/creator/impl/inv/json/)."""
        if (fs.name not in self.indexing.json_index_columns
                or not fs.single_value or fs.data_type.is_numeric):
            return False
        from pinot_tpu.segment.jsonindex import build_json_index

        build_json_index(list(values), num_docs, save, col_dir, fs.name)
        return True

    def _maybe_build_bloom(self, name: str, distinct_values, save) -> bool:
        """Bloom filter over a column's distinct values when configured
        (ref: bloomFilterColumns -> OnHeapGuavaBloomFilterCreator).
        ``distinct_values`` may be a zero-arg callable so unconfigured
        columns never materialize their dictionary."""
        if name not in self.indexing.bloom_filter_columns:
            return False
        from pinot_tpu.utils.bloom import BloomFilter

        if callable(distinct_values):
            distinct_values = distinct_values()
        bf = BloomFilter.from_values(list(distinct_values))
        save("bloom", bf.to_array())
        return True

    def _build_inverted(self, name: str, dict_ids_flat: np.ndarray,
                        mv_rows: Optional[List[List[Any]]], num_docs: int,
                        cardinality: int, save, col_dir: str) -> None:
        counts = (None if mv_rows is None else
                  np.fromiter((len(r) for r in mv_rows), dtype=np.int64,
                              count=num_docs))
        build_inverted_index(name, dict_ids_flat, counts, num_docs,
                             cardinality, save, col_dir)

    def _partition_meta(self, col: str, values: List[Any]) -> Dict[str, Any]:
        spc = self.indexing.segment_partition_config
        if not spc or col not in spc.column_partition_map:
            return {}
        cfg = spc.column_partition_map[col]
        fn = get_partition_function(cfg.get("functionName", "Murmur"),
                                    int(cfg.get("numPartitions", 1)))
        parts = set()
        for v in values:
            if isinstance(v, list):
                for x in v:
                    parts.add(fn.partition(x))
            else:
                parts.add(fn.partition(v))
        return {
            "partition_function": fn.name,
            "num_partitions": fn.num_partitions,
            "partitions": sorted(parts),
        }
