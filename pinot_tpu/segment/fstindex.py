"""FST index: fast REGEXP_LIKE over a string dictionary.

Re-design of the reference's FST index (``LuceneFSTIndexReader.java`` and
the custom Java FSA under ``segment/local/utils/nativefst/`` — a compiled
automaton mapping dictionary terms to dictIds, queried with a regexp): here
the dictionary is already SORTED, so the automaton's two jobs split cleanly:

1. **Prefix narrowing**: a byte-trie over the terms, each node carrying its
   [lo, hi) dictId range (contiguous because terms are sorted). The literal
   prefix extracted from the regexp walks the trie to a candidate interval —
   the trie is the serialized index artifact (CSR arrays, numpy-mappable).
2. **Verification**: the regexp runs only over the candidate interval's
   terms instead of the whole dictionary.

A regexp with no literal prefix (e.g. ``.*foo``) degrades to scanning all
terms — same worst case as the reference's automaton intersection, without
the constant-factor FST machinery that buys nothing on a TPU host path.
"""

from __future__ import annotations

import re
from typing import List, Optional, Tuple

import numpy as np

MAX_DEPTH = 16  # trie depth cap: deeper prefixes narrow via dictId binsearch


class FstIndexBuilder:
    """Builds the CSR trie over sorted utf-8 terms. Depth is fixed at
    MAX_DEPTH: the reader's walk predicate must agree with the builder's
    expansion rule, so the cap is a module contract, not a parameter."""

    def __init__(self, terms: List[str]):
        self.terms = [t.encode("utf-8") for t in terms]
        self.max_depth = MAX_DEPTH

    def build(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """-> (edge_offsets [n_nodes+1], edge_labels [n_edges] u8,
        edge_targets [n_edges] i32, node_ranges [n_nodes, 2] i32).
        Node 0 is the root; ranges are [lo, hi) dictId intervals."""
        edge_labels: List[int] = []
        edge_targets: List[int] = []
        # per node id (creation order): (depth, lo, hi); children always get
        # larger ids than their parent, so processing ids sequentially keeps
        # edge_offsets[k]..edge_offsets[k+1] = node k's edges
        nodes: List[Tuple[int, int, int]] = [(0, 0, len(self.terms))]
        edge_offsets = [0]
        i = 0
        while i < len(nodes):
            depth, lo, hi = nodes[i]
            if depth < self.max_depth and hi - lo > 1:
                # group terms[lo:hi] by byte at `depth` (terms shorter than
                # depth+1 end here — no edge; byte groups are contiguous
                # because terms are sorted)
                p = lo
                while p < hi:
                    t = self.terms[p]
                    if len(t) <= depth:
                        p += 1
                        continue
                    b = t[depth]
                    q = p
                    while q < hi and len(self.terms[q]) > depth \
                            and self.terms[q][depth] == b:
                        q += 1
                    edge_labels.append(b)
                    edge_targets.append(len(nodes))
                    nodes.append((depth + 1, p, q))
                    p = q
            edge_offsets.append(len(edge_labels))
            i += 1
        node_ranges = [(lo, hi) for _, lo, hi in nodes]
        return (np.asarray(edge_offsets, dtype=np.int64),
                np.asarray(edge_labels, dtype=np.uint8),
                np.asarray(edge_targets, dtype=np.int32),
                np.asarray(node_ranges, dtype=np.int32))


class FstIndexReader:
    """Query-side trie walk + regexp verification."""

    def __init__(self, edge_offsets, edge_labels, edge_targets, node_ranges,
                 dictionary):
        self.edge_offsets = np.asarray(edge_offsets)
        self.edge_labels = np.asarray(edge_labels)
        self.edge_targets = np.asarray(edge_targets)
        self.node_ranges = np.asarray(node_ranges)
        self.dictionary = dictionary  # StringDictionary (get_value / card)

    # -- prefix machinery ---------------------------------------------------
    def prefix_range(self, prefix: str) -> Tuple[int, int]:
        """[lo, hi) dictIds of terms starting with ``prefix``."""
        data = prefix.encode("utf-8")
        node = 0
        for depth, b in enumerate(data):
            lo, hi = self.node_ranges[node]
            expanded = depth < MAX_DEPTH and hi - lo > 1
            if not expanded:
                # single-term subtree or depth cap: finish by direct compare
                return self._narrow_by_scan(int(lo), int(hi), prefix)
            off0, off1 = self.edge_offsets[node], self.edge_offsets[node + 1]
            labels = self.edge_labels[off0:off1]
            pos = np.searchsorted(labels, b)
            if pos == len(labels) or labels[pos] != b:
                return (0, 0)  # byte groups are complete: no term matches
            node = int(self.edge_targets[off0 + pos])
        lo, hi = self.node_ranges[node]
        return int(lo), int(hi)

    def _narrow_by_scan(self, lo: int, hi: int, prefix: str) -> Tuple[int, int]:
        ids = [i for i in range(lo, hi)
               if str(self.dictionary.get_value(i)).startswith(prefix)]
        if not ids:
            return (0, 0)
        return (ids[0], ids[-1] + 1)

    # -- the regexp entry ---------------------------------------------------
    def matching_ids(self, pattern: str) -> np.ndarray:
        """dictIds whose term matches the regexp (search semantics, matching
        the reference's RegexpLikePredicateEvaluator)."""
        rx = re.compile(pattern)
        prefix = literal_prefix(pattern)
        if prefix:
            lo, hi = self.prefix_range(prefix)
        else:
            lo, hi = 0, int(self.node_ranges[0][1])
        out = [i for i in range(lo, hi)
               if rx.search(str(self.dictionary.get_value(i)))]
        return np.asarray(out, dtype=np.int64)


def literal_prefix(pattern: str) -> str:
    """Longest literal prefix implied by an ANCHORED regexp (``^abc.*`` ->
    "abc"); un-anchored patterns have search semantics, so any term position
    can match and no prefix narrowing applies."""
    if not pattern.startswith("^"):
        return ""
    # the anchor binds only to the FIRST alternative ('^abc|xyz' matches
    # 'xyz' anywhere), so any unescaped top-level '|' voids prefix narrowing
    depth = 0
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == "\\":
            i += 2
            continue
        if c == "[":
            j = pattern.find("]", i + 1)
            i = (j if j >= 0 else len(pattern)) + 1
            continue
        if c == "(":
            depth += 1
        elif c == ")":
            depth = max(0, depth - 1)
        elif c == "|" and depth == 0:
            return ""
        i += 1
    out = []
    i = 1
    specials = set(".*+?()[]{}|\\$^")
    while i < len(pattern):
        c = pattern[i]
        if c == "\\" and i + 1 < len(pattern) \
                and pattern[i + 1] in specials:
            # escaped metachar is a literal — but only safe to consume if
            # not followed by a quantifier
            if i + 2 < len(pattern) and pattern[i + 2] in "*+?{":
                break
            out.append(pattern[i + 1])
            i += 2
            continue
        if c in specials:
            break
        if i + 1 < len(pattern) and pattern[i + 1] in "*+?{":
            break  # quantified literal isn't a fixed prefix
        out.append(c)
        i += 1
    return "".join(out)
