"""Columnar segment storage engine (ref: pinot-segment-spi + pinot-segment-local).

- ``metadata``   segment/column metadata model (metadata.json)
- ``dictionary`` sorted per-column dictionaries
- ``creator``    two-pass segment builder
- ``immutable``  mmap loader + DataSource access
- ``mutable``    realtime consuming segment (host-resident, append-only)
"""

from pinot_tpu.segment.metadata import (
    ColumnMetadata,
    Encoding,
    SegmentMetadata,
    DOC_TILE,
    pad_capacity,
)
from pinot_tpu.segment.creator import SegmentBuilder
from pinot_tpu.segment.immutable import (
    DataSource,
    ImmutableSegment,
    load_segment,
    verify_crc,
)
from pinot_tpu.segment.mutable import MutableDictionary, MutableSegment

__all__ = [
    "ColumnMetadata",
    "Encoding",
    "SegmentMetadata",
    "DOC_TILE",
    "pad_capacity",
    "SegmentBuilder",
    "DataSource",
    "ImmutableSegment",
    "load_segment",
    "verify_crc",
    "MutableDictionary",
    "MutableSegment",
]
