"""Mutable (consuming) segment: row-at-a-time ingest, concurrently queryable.

Re-design of ``MutableSegmentImpl.java:101`` + ``realtime/impl/*``: rows are
indexed one at a time into append-only column stores while queries read a
consistent prefix (single-writer / multi-reader, snapshot = ``num_docs`` at
read start). TPU-first stance (SURVEY.md §7 hard parts): consuming segments
stay **host-resident** — row-at-a-time mutation is hostile to device layout —
and are served by the host engine; on seal they convert to the immutable
columnar format (ref: RealtimeSegmentConverter) and flip to HBM staging.

Mutable dictionaries are insertion-ordered hash maps (ref:
``realtime/impl/dictionary/`` — also unsorted there); range predicates scan
the dictionary's value array instead of using the sorted-interval property.
"""

from __future__ import annotations

import threading
import time

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pinot_tpu.segment import metadata as meta
from pinot_tpu.segment.creator import SegmentBuilder
from pinot_tpu.segment.dictionary import Dictionary
from pinot_tpu.spi.table import IndexingConfig
from pinot_tpu.spi.data import DataType, FieldSpec, Schema

_GROW = 2
_INITIAL_CAPACITY = 1024


class MutableDictionary(Dictionary):
    """Insertion-ordered value->dictId map (ref: BaseOffHeapMutableDictionary:
    ids are assigned in arrival order, NOT sorted)."""

    def __init__(self, data_type: DataType):
        self.data_type = data_type
        self._index: Dict[Any, int] = {}
        self._values: List[Any] = []

    def __len__(self) -> int:
        return len(self._values)

    def index(self, value: Any) -> int:
        """Get-or-insert (writer thread only)."""
        i = self._index.get(value)
        if i is None:
            i = len(self._values)
            self._values.append(value)
            self._index[value] = i
        return i

    def index_of(self, value: Any) -> int:
        return self._index.get(value, -1)

    def insertion_index_of(self, value: Any) -> int:
        # no sorted order; only exact membership is meaningful
        i = self.index_of(value)
        return i if i >= 0 else -(len(self._values) + 1)

    def get_value(self, dict_id: int) -> Any:
        return self._values[dict_id]

    def get_values(self, dict_ids: Sequence[int]) -> List[Any]:
        return [self._values[int(i)] for i in dict_ids]

    @property
    def min_value(self) -> Any:
        return min(self._values) if self._values else None

    @property
    def max_value(self) -> Any:
        return max(self._values) if self._values else None

    def device_values(self) -> Optional[np.ndarray]:
        if self.data_type.is_numeric:
            return np.asarray(self._values, dtype=self.data_type.stored_np)
        return None

    def matching_range_ids(self, lo: Any, hi: Any, lo_inclusive: bool,
                           hi_inclusive: bool) -> np.ndarray:
        """Value scan over the (unsorted) dictionary — the mutable analogue
        of the sorted dictId interval (ref: RangePredicateEvaluatorFactory's
        non-sorted mutable-dictionary path)."""
        if self.data_type.is_numeric:
            vals = np.asarray(self._values)
            m = np.ones(len(vals), dtype=bool)
            if lo is not None:
                m &= (vals >= lo) if lo_inclusive else (vals > lo)
            if hi is not None:
                m &= (vals <= hi) if hi_inclusive else (vals < hi)
            return np.nonzero(m)[0].astype(np.int64)
        ids = []
        for i, v in enumerate(self._values):
            if lo is not None and not (v >= lo if lo_inclusive else v > lo):
                continue
            if hi is not None and not (v <= hi if hi_inclusive else v < hi):
                continue
            ids.append(i)
        return np.asarray(ids, dtype=np.int64)

    def range_to_dict_id_interval(self, lo, hi, lo_inclusive, hi_inclusive):
        raise TypeError("mutable dictionaries are unsorted; "
                        "use matching_range_ids")

    def sorted_remap(self) -> Tuple[List[Any], np.ndarray]:
        """(sorted values, remap[oldId] -> sortedId) for seal-time conversion
        to the immutable sorted-dictionary format."""
        order = sorted(range(len(self._values)),
                       key=lambda i: self._values[i])
        remap = np.empty(len(order), dtype=np.int64)
        for new_id, old_id in enumerate(order):
            remap[old_id] = new_id
        return [self._values[i] for i in order], remap


class _GrowArray:
    """Append-only numpy array with capacity doubling (the mutable forward
    index; ref: FixedByteSVMutableForwardIndex — chunked there, amortized
    realloc here)."""

    def __init__(self, dtype):
        self._arr = np.zeros(_INITIAL_CAPACITY, dtype=dtype)
        self._n = 0

    def append(self, v) -> None:
        if self._n == self._arr.shape[0]:
            bigger = np.zeros(self._arr.shape[0] * _GROW, dtype=self._arr.dtype)
            bigger[:self._n] = self._arr
            self._arr = bigger
        self._arr[self._n] = v
        self._n += 1

    def view(self, n: Optional[int] = None) -> np.ndarray:
        return self._arr[:self._n if n is None else n]


class _MutableColumn:
    def __init__(self, fs: FieldSpec):
        self.fs = fs
        self.dictionary = MutableDictionary(fs.data_type)
        # SV: dictIds; MV: flattened dictIds + offsets
        self.fwd = _GrowArray(np.int32)
        self.mv_offsets = _GrowArray(np.int64) if not fs.single_value else None
        if self.mv_offsets is not None:
            self.mv_offsets.append(0)
        self.null = _GrowArray(bool)
        self.has_nulls = False
        self.max_mv = 0
        self.total_entries = 0


class MutableDataSource:
    """Read access over a snapshot prefix (duck-types immutable.DataSource)."""

    def __init__(self, seg: "MutableSegment", col: _MutableColumn, n: int):
        self.name = col.fs.name
        self._col = col
        self._n = n
        self.metadata = seg._column_metadata(col, n)
        self.dictionary: Optional[Dictionary] = col.dictionary

    @property
    def forward_index(self) -> np.ndarray:
        if self._col.mv_offsets is None:
            return self._col.fwd.view(self._n)
        end = int(self._col.mv_offsets.view(self._n + 1)[-1])
        return self._col.fwd.view(end)

    @property
    def mv_offsets(self) -> Optional[np.ndarray]:
        if self._col.mv_offsets is None:
            return None
        return self._col.mv_offsets.view(self._n + 1)

    @property
    def null_bitmap(self) -> Optional[np.ndarray]:
        if not self._col.has_nulls:
            return None
        return self._col.null.view(self._n)

    @property
    def inverted_index(self):
        return None


class MutableSegment:
    """Ref: MutableSegmentImpl.java:101. Writer: one thread calls index();
    readers snapshot num_docs and see a consistent prefix."""

    is_mutable = True

    def __init__(self, schema: Schema, segment_name: str,
                 capacity: int = 1_000_000,
                 indexing_config: Optional[IndexingConfig] = None):
        self.schema = schema
        self.segment_name = segment_name
        self.capacity = capacity
        self.indexing = indexing_config or IndexingConfig()
        self._cols: Dict[str, _MutableColumn] = {
            fs.name: _MutableColumn(fs) for fs in schema.field_specs}
        self._num_docs = 0  # race-ok: single_writer
        self.time_column = schema.time_column
        self.min_time: Optional[int] = None
        self.max_time: Optional[int] = None
        self.start_time_ms = int(time.time() * 1000)
        # freshness SLO inputs: per-row append timestamp (monotonic) plus
        # the watermark up to which ingest-to-queryable latency has been
        # recorded (advanced by mutable_staging.observe_freshness)
        self._append_ts = _GrowArray(np.float64)
        self._fresh_observed = 0
        self._fresh_lock = threading.Lock()

    # -- write path ---------------------------------------------------------
    #: key carrying null-field names from NullValueTransformer (the
    #: transformer substitutes defaults, so nullness must ride along)
    NULL_FIELDS_KEY = "__nulls__"

    def index(self, row: Dict[str, Any]) -> bool:
        """Index one (already transformed) row; returns False when the
        segment is at capacity (ref: MutableSegmentImpl.index:471 canTakeMore)."""
        if self._num_docs >= self.capacity:
            return False
        null_fields = set(row.get(self.NULL_FIELDS_KEY) or ())
        for name, col in self._cols.items():
            v = row.get(name)
            self._index_value(col, v, name in null_fields)
        if self.time_column is not None:
            t = row.get(self.time_column)
            if t is not None:
                t = int(t)
                self.min_time = t if self.min_time is None else min(self.min_time, t)
                self.max_time = t if self.max_time is None else max(self.max_time, t)
        self._append_ts.append(time.monotonic())
        # publish the new doc last (readers snapshot _num_docs)
        self._num_docs += 1
        return True

    def _index_value(self, col: _MutableColumn, v: Any,
                     declared_null: bool = False) -> None:
        fs = col.fs
        is_null = (declared_null or v is None
                   or (isinstance(v, float) and v != v))
        if fs.single_value:
            if is_null:
                col.has_nulls = True
                if v is None or v != v:
                    v = fs.default_null_value
            col.null.append(is_null)
            col.fwd.append(col.dictionary.index(fs.data_type.convert(v)))
            col.total_entries += 1
            return
        if is_null or (isinstance(v, (list, tuple, np.ndarray)) and len(v) == 0):
            is_null = True
            col.has_nulls = True
            vals = ([fs.default_null_value] if v is None
                    or not isinstance(v, (list, tuple, np.ndarray)) or not len(v)
                    else list(v))
        elif isinstance(v, (list, tuple, np.ndarray)):
            vals = list(v)
        else:
            vals = [v]
        col.null.append(is_null)
        for x in vals:
            col.fwd.append(col.dictionary.index(fs.data_type.convert(x)))
        prev = int(col.mv_offsets.view()[-1])
        col.mv_offsets.append(prev + len(vals))
        col.max_mv = max(col.max_mv, len(vals))
        col.total_entries += len(vals)

    # -- read path (segment duck-type) ---------------------------------------
    @property
    def num_docs(self) -> int:
        return self._num_docs

    @property
    def padded_capacity(self) -> int:
        return meta.pad_capacity(self._num_docs)

    @property
    def column_names(self) -> List[str]:
        return list(self._cols.keys())

    @property
    def metadata(self) -> meta.SegmentMetadata:
        n = self._num_docs
        return meta.SegmentMetadata(
            segment_name=self.segment_name,
            table_name=self.schema.schema_name,
            schema=self.schema,
            num_docs=n,
            padded_capacity=meta.pad_capacity(n),
            time_column=self.time_column,
            min_time=self.min_time,
            max_time=self.max_time,
            columns=_SnapshotColumns(self, n),
        )

    def data_source(self, column: str) -> MutableDataSource:
        col = self._cols.get(column)
        if col is None:
            raise KeyError(f"column {column!r} not in segment "
                           f"{self.segment_name!r}")
        return MutableDataSource(self, col, self._num_docs)

    def _column_metadata(self, col: _MutableColumn, n: int) -> meta.ColumnMetadata:
        d = col.dictionary
        return meta.ColumnMetadata(
            name=col.fs.name,
            data_type=col.fs.data_type,
            field_type=col.fs.field_type,
            single_value=col.fs.single_value,
            encoding=meta.Encoding.DICT,
            cardinality=len(d),
            stored_dtype="int32",
            min_value=d.min_value,
            max_value=d.max_value,
            is_sorted=False,
            has_dictionary=True,
            has_inverted_index=False,
            has_nulls=col.has_nulls,
            max_num_multi_values=col.max_mv,
            total_number_of_entries=col.total_entries,
        )

    def get_value(self, column: str, doc_id: int):
        ds = self.data_source(column)
        if ds.metadata.single_value:
            return ds.dictionary.get_value(int(ds.forward_index[doc_id]))
        off = ds.mv_offsets
        ids = ds.forward_index[int(off[doc_id]):int(off[doc_id + 1])]
        return [ds.dictionary.get_value(int(i)) for i in ids]

    # -- seal ----------------------------------------------------------------
    def build_immutable(self, out_dir: str,
                        segment_name: Optional[str] = None,
                        indexing_config: Optional[IndexingConfig] = None,
                        ) -> meta.SegmentMetadata:
        """Convert to the immutable columnar format (two-pass builder over the
        accumulated columns; ref: RealtimeSegmentConverter +
        SegmentIndexCreationDriverImpl.build). ``indexing_config`` overrides
        the consuming-time config at seal (the commit path stamps the
        default star-tree set here)."""
        n = self._num_docs
        columns: Dict[str, List[Any]] = {}
        for name, col in self._cols.items():
            ds = MutableDataSource(self, col, n)
            if col.fs.single_value:
                vals = ds.dictionary.get_values(ds.forward_index)
                if col.has_nulls:
                    nb = ds.null_bitmap
                    vals = [None if nb[i] else v for i, v in enumerate(vals)]
            else:
                off = ds.mv_offsets
                fwd = ds.forward_index
                nb = ds.null_bitmap if col.has_nulls else None
                vals = []
                for i in range(n):
                    if nb is not None and nb[i]:
                        vals.append(None)
                    else:
                        ids = fwd[int(off[i]):int(off[i + 1])]
                        vals.append(ds.dictionary.get_values(ids))
            columns[name] = vals
        builder = SegmentBuilder(self.schema,
                                 segment_name or self.segment_name,
                                 indexing_config=indexing_config
                                 or self.indexing)
        return builder.build(columns, out_dir)


class _SnapshotColumns(dict):
    """Lazy column-metadata map bound to a doc-count snapshot."""

    def __init__(self, seg: MutableSegment, n: int):
        super().__init__()
        self._seg = seg
        self._n = n
        for name in seg._cols:
            dict.__setitem__(self, name, None)

    def __getitem__(self, name: str) -> meta.ColumnMetadata:
        v = dict.__getitem__(self, name)
        if v is None:
            v = self._seg._column_metadata(self._seg._cols[name], self._n)
            dict.__setitem__(self, name, v)
        return v

    def get(self, name: str, default=None):
        try:
            return self[name]
        except KeyError:
            return default

    def items(self):
        return [(k, self[k]) for k in self]

    def values(self):
        return [self[k] for k in self]
