"""Geo index: grid-cell prefiltering for distance predicates.

Re-design of the reference's H3 index
(``segment/index/readers/geospatial/ImmutableH3IndexReader.java`` +
``H3IndexFilterOperator`` — points bucketed into hex cells so
``ST_Distance(col, point) < r`` prefilters by a kRing of cells before the
exact test): here the cells are a square lat/lng grid (utils/geo.cell_of —
design note there), the index maps each DICTIONARY id to its cell (the
dictionary holds WKT points, so the per-dictId cell array is the whole
index), and the filter path does

    cell disk -> candidate dictIds -> exact haversine on candidates -> LUT

which keeps the final doc mask in the same dictId-LUT shape every other
index produces (device scan compatible).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

DEFAULT_RESOLUTION = 9


def build_geo_index(values, resolution: int, save) -> bool:
    """Per-dictId cell ids at ``resolution``; non-point values poison the
    build (returns False) rather than producing a lying index."""
    from pinot_tpu.utils import geo

    lngs, lats = [], []
    for v in values:
        try:
            g = geo.parse_ewkt(v)
        except ValueError:
            return False
        if g.kind != "POINT":
            return False
        lngs.append(g.x)
        lats.append(g.y)
    lng_arr = np.asarray(lngs, dtype=np.float64)
    lat_arr = np.asarray(lats, dtype=np.float64)
    cells = geo.cells_of(lng_arr, lat_arr, resolution)
    save("geocells", cells.astype(np.int64))
    save("geolng", lng_arr)
    save("geolat", lat_arr)
    save("geometa", np.asarray([resolution], dtype=np.int64))
    return True


class GeoIndexReader:
    """Query-side candidate narrowing."""

    def __init__(self, cells: np.ndarray, resolution: int, dictionary,
                 lngs: Optional[np.ndarray] = None,
                 lats: Optional[np.ndarray] = None):
        self.cells = np.asarray(cells)
        self.resolution = int(resolution)
        self.dictionary = dictionary
        self.lngs = None if lngs is None else np.asarray(lngs)
        self.lats = None if lats is None else np.asarray(lats)

    def candidate_dict_ids(self, lng: float, lat: float,
                           radius_m: float) -> np.ndarray:
        from pinot_tpu.utils import geo

        disk = np.asarray(
            geo.cell_disk(lng, lat, radius_m, self.resolution),
            dtype=np.int64)
        return np.nonzero(np.isin(self.cells, disk))[0]

    def ids_within(self, lng: float, lat: float, radius_m: float,
                   inclusive: bool = True) -> np.ndarray:
        """dictIds whose point is within ``radius_m`` meters (haversine —
        matching ST_DISTANCE geography semantics)."""
        from pinot_tpu.utils import geo

        cand = self.candidate_dict_ids(lng, lat, radius_m)
        if cand.size == 0:
            return cand
        if self.lngs is not None:
            # stored coordinate arrays: pure vectorized exact pass
            xs, ys = self.lngs[cand], self.lats[cand]
        else:  # legacy index without coordinate arrays: parse candidates
            xs = np.empty(cand.size)
            ys = np.empty(cand.size)
            for j, i in enumerate(cand):
                g = geo.parse_ewkt(self.dictionary.get_value(int(i)))
                xs[j], ys[j] = g.x, g.y
        d = geo.haversine_m(xs, ys, lng, lat)
        keep = d <= radius_m if inclusive else d < radius_m
        return cand[keep]
