"""Segment processing framework: map -> partition -> reduce over segments.

Re-design of the reference's offline segment-processing pipeline
(``pinot-core/.../segment/processing/framework/SegmentProcessorFramework.java:57``
with its mapper/partitioner/reducer/timehandler stages) used by minion
tasks (MergeRollup, RealtimeToOffline, Purge):

- **map**: read input segments back into columnar rows (dictionary decode),
  apply an optional record filter and time-window clamp;
- **partition**: bucket rows by rounded time (EPOCH time handling) and/or a
  partition column;
- **reduce**: per partition CONCAT (plain merge), ROLLUP (group by all
  dimensions, aggregate metrics), or DEDUP (drop exact duplicate rows);
- **build**: one output segment per partition via SegmentBuilder, split at
  ``max_docs_per_segment``.

Columnar throughout (numpy ops, no per-row python loops on the hot path) —
the host-side analogue of the engine's vectorized design.
"""

from __future__ import annotations

import enum
import time

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.segment.creator import SegmentBuilder
from pinot_tpu.segment.immutable import ImmutableSegment
from pinot_tpu.spi.data import FieldType, Schema
from pinot_tpu.spi.table import TableConfig

TIME_UNIT_MS = {
    "MILLISECONDS": 1, "SECONDS": 1000, "MINUTES": 60_000,
    "HOURS": 3_600_000, "DAYS": 86_400_000,
}


class MergeType(enum.Enum):
    CONCAT = "CONCAT"
    ROLLUP = "ROLLUP"
    DEDUP = "DEDUP"


@dataclass
class SegmentProcessorConfig:
    """Ref: SegmentProcessorConfig + MergeRollupTask configs."""

    schema: Schema
    table_config: TableConfig
    merge_type: MergeType = MergeType.CONCAT
    # metric column -> SUM | MIN | MAX (rollup aggregation;
    # ref: pinot-core/.../processing/aggregator/ValueAggregatorFactory)
    aggregation_types: Dict[str, str] = field(default_factory=dict)
    # EPOCH time handling: round row times into buckets of this many
    # time-column units; one output partition per bucket
    bucket_time_ms: Optional[int] = None
    # half-open [start, end) clamp on the time column (ms); rows outside
    # are dropped (RealtimeToOffline window)
    window_start_ms: Optional[int] = None
    window_end_ms: Optional[int] = None
    # row filter: rows where it returns True are DROPPED (PurgeTask's
    # RecordPurger / the processing framework's RecordFilter)
    record_filter: Optional[Callable[[Dict[str, Any]], bool]] = None
    segment_name_prefix: str = "processed"
    max_docs_per_segment: int = 5_000_000

    @property
    def time_column(self) -> Optional[str]:
        return self.table_config.validation_config.time_column_name

    @property
    def time_unit_ms(self) -> int:
        return TIME_UNIT_MS.get(
            self.table_config.validation_config.time_type.upper(), 1)


def read_columnar(segment: ImmutableSegment,
                  valid_only: bool = True) -> Dict[str, List[Any]]:
    """Segment -> columnar python values (dictionary-decoded; MV as lists;
    nulls as None). ``valid_only`` honors upsert valid-doc bitmaps."""
    n = segment.num_docs
    keep = np.ones(n, dtype=bool)
    valid = getattr(segment, "valid_doc_ids", None)
    if valid_only and valid is not None:
        keep = np.asarray([bool(valid[i]) for i in range(n)])
    out: Dict[str, List[Any]] = {}
    for name in segment.column_names:
        ds = segment.data_source(name)
        cm = ds.metadata
        if cm.single_value:
            fwd = np.asarray(ds.forward_index)[:n][keep]
            if cm.has_dictionary:
                vals = list(ds.dictionary.get_values(fwd))
            else:
                vals = [v.item() for v in fwd]
        else:
            dense, counts = ds.dense_mv()
            d = ds.dictionary
            vals = []
            for i in np.nonzero(keep)[0]:
                c = int(counts[i])
                vals.append(list(d.get_values(dense[i, :c])) if c else None)
        if cm.has_nulls:
            nb = np.asarray(ds.null_bitmap)[:n][keep]
            vals = [None if isnull else v for v, isnull in zip(vals, nb)]
        out[name] = vals
    return out


class SegmentProcessorFramework:
    """Ref: SegmentProcessorFramework.java:57 (map/partition/reduce)."""

    def __init__(self, segments: List[ImmutableSegment],
                 config: SegmentProcessorConfig):
        self.segments = segments
        self.config = config

    # -- public --------------------------------------------------------------
    def process(self, out_dir: str) -> List[str]:
        """Returns the built segment directories."""
        cols = self._map_phase()
        n = len(next(iter(cols.values()))) if cols else 0
        if n == 0:
            return []
        partitions = self._partition_phase(cols, n)
        out_dirs: List[str] = []
        seq = 0
        for part_key in sorted(partitions):
            pcols = partitions[part_key]
            pcols = self._reduce_phase(pcols)
            for chunk in self._split(pcols):
                name = f"{self.config.segment_name_prefix}_{part_key}_{seq}"
                seq += 1
                builder = SegmentBuilder(
                    self.config.schema, name,
                    indexing_config=self.config.table_config.indexing_config)
                builder.build(chunk, out_dir)
                out_dirs.append(f"{out_dir}/{name}")
        return out_dirs

    # -- map -----------------------------------------------------------------
    def _map_phase(self) -> Dict[str, List[Any]]:
        cfg = self.config
        merged: Dict[str, List[Any]] = {}
        for seg in self.segments:
            cols = read_columnar(seg)
            keep = np.ones(len(next(iter(cols.values()), [])), dtype=bool)
            tc = cfg.time_column
            if tc is not None and tc in cols and (
                    cfg.window_start_ms is not None
                    or cfg.window_end_ms is not None):
                t_ms = np.asarray(cols[tc], dtype=np.int64) * cfg.time_unit_ms
                if cfg.window_start_ms is not None:
                    keep &= t_ms >= cfg.window_start_ms
                if cfg.window_end_ms is not None:
                    keep &= t_ms < cfg.window_end_ms
            if cfg.record_filter is not None:
                names = list(cols.keys())
                for i in np.nonzero(keep)[0]:
                    row = {c: cols[c][i] for c in names}
                    if cfg.record_filter(row):
                        keep[i] = False
            for c, vals in cols.items():
                kept = [vals[i] for i in np.nonzero(keep)[0]]
                merged.setdefault(c, []).extend(kept)
        return merged

    # -- partition -----------------------------------------------------------
    def _partition_phase(self, cols: Dict[str, List[Any]],
                         n: int) -> Dict[str, Dict[str, List[Any]]]:
        cfg = self.config
        tc = cfg.time_column
        if cfg.bucket_time_ms is None or tc is None or tc not in cols:
            return {"all": cols}
        t_ms = np.asarray(cols[tc], dtype=np.int64) * cfg.time_unit_ms
        bucket = (t_ms // cfg.bucket_time_ms).astype(np.int64)
        parts: Dict[str, Dict[str, List[Any]]] = {}
        for b in np.unique(bucket):
            idx = np.nonzero(bucket == b)[0]
            parts[str(int(b))] = {c: [v[i] for i in idx]
                                  for c, v in cols.items()}
        return parts

    # -- reduce --------------------------------------------------------------
    def _reduce_phase(self, cols: Dict[str, List[Any]]) -> Dict[str, List[Any]]:
        cfg = self.config
        if cfg.merge_type is MergeType.CONCAT:
            return cols
        if cfg.merge_type is MergeType.DEDUP:
            return self._dedup(cols)
        return self._rollup(cols)

    def _key_columns(self) -> Tuple[List[str], List[str]]:
        """(dimension/time columns, metric columns) from the schema."""
        dims, metrics = [], []
        for fs in self.config.schema.field_specs:
            if fs.field_type is FieldType.METRIC:
                metrics.append(fs.name)
            else:
                dims.append(fs.name)
        return dims, metrics

    def _dedup(self, cols: Dict[str, List[Any]]) -> Dict[str, List[Any]]:
        names = list(cols.keys())
        seen = set()
        keep_idx = []
        for i in range(len(cols[names[0]])):
            key = tuple(_hashable(cols[c][i]) for c in names)
            if key not in seen:
                seen.add(key)
                keep_idx.append(i)
        return {c: [v[i] for i in keep_idx] for c, v in cols.items()}

    def _rollup(self, cols: Dict[str, List[Any]]) -> Dict[str, List[Any]]:
        """Group by every dimension (+ rounded time), aggregate metrics
        (ref: RollupReducer + ValueAggregators; default SUM)."""
        dims, metrics = self._key_columns()
        dims = [d for d in dims if d in cols]
        metrics = [m for m in metrics if m in cols]
        groups: Dict[Tuple, int] = {}
        order: List[Tuple] = []
        idx_of: List[int] = []
        for i in range(len(cols[dims[0]]) if dims else len(next(iter(cols.values())))):
            key = tuple(_hashable(cols[d][i]) for d in dims)
            g = groups.get(key)
            if g is None:
                g = len(order)
                groups[key] = g
                order.append(key)
            idx_of.append(g)
        idx_of_arr = np.asarray(idx_of)

        out: Dict[str, List[Any]] = {}
        first_row = [int(np.nonzero(idx_of_arr == g)[0][0])
                     for g in range(len(order))]
        for d in dims:
            out[d] = [cols[d][i] for i in first_row]
        for m in metrics:
            agg = self.config.aggregation_types.get(m, "SUM").upper()
            dt = self.config.schema.field_spec(m).data_type
            if dt.is_integral:
                # exact Python-int accumulation: LONG sums past the f64
                # exact-integer bound (common/bounds.py
                # F64_EXACT_INT_BOUND) must not round-trip through float64
                res_i = []
                for g in range(len(order)):
                    v = [int(cols[m][i])
                         for i in np.nonzero(idx_of_arr == g)[0]]
                    res_i.append(sum(v) if agg == "SUM" else
                                 min(v) if agg == "MIN" else max(v))
                out[m] = res_i
            else:
                vals = np.asarray(cols[m], dtype=np.float64)
                res = []
                for g in range(len(order)):
                    v = vals[idx_of_arr == g]
                    res.append(float(v.sum()) if agg == "SUM" else
                               float(v.min()) if agg == "MIN" else
                               float(v.max()))
                out[m] = res
        return out

    def _split(self, cols: Dict[str, List[Any]]):
        n = len(next(iter(cols.values()))) if cols else 0
        step = self.config.max_docs_per_segment
        for s in range(0, n, step):
            yield {c: v[s:s + step] for c, v in cols.items()}


def _hashable(v: Any) -> Any:
    return tuple(v) if isinstance(v, list) else v


def default_segment_name(prefix: str, table: str) -> str:
    return f"{prefix}_{table}_{int(time.time() * 1e3)}"
