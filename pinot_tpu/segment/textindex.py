"""Text index: tokenized inverted index behind TEXT_MATCH.

Re-design of the reference's Lucene-backed text index
(``segment/index/readers/text/TextIndexReader`` family +
``creator/impl/text/LuceneTextIndexCreator``): instead of a Lucene
directory, terms map to posting lists over the column's DICTIONARY ids
(raw columns fall back to doc ids) — a TEXT_MATCH then resolves to a
dictId set, which is exactly the boolean-LUT shape the device scan and
the host evaluator already consume for IN/REGEXP. Storage reuses the
inverted-index scheme (sorted term strings as offsets+blob, delta+varint
postings).

Analyzer: lowercase + split on non-alphanumerics (the StandardAnalyzer
subset; no stemming/stop-words). Query dialect (the operative subset of
Lucene's QueryParser, which the reference feeds TEXT_MATCH strings to):
bare terms, ``"quoted phrases"`` (adjacency verified against the source
values), ``prefix*`` wildcards, AND / OR (OR is the default operator,
as in Lucene) and parentheses.
"""

from __future__ import annotations

import re
from bisect import bisect_left
from typing import Any, Callable, List, Sequence, Set, Tuple

import numpy as np

_TOKEN_RE = re.compile(r"[a-z0-9]+")


def tokenize(text: str) -> List[str]:
    return _TOKEN_RE.findall(str(text).lower())


# --------------------------------------------------------------------------
# creator
# --------------------------------------------------------------------------

def build_text_index(values: Sequence[Any], save, col_dir: str,
                     name: str) -> None:
    """``values`` are the UNIT of indexing: dictionary values for dict
    columns (postings hold dictIds), per-doc values for raw columns
    (postings hold docIds)."""
    import os

    from pinot_tpu import native

    postings: dict = {}
    for vid, value in enumerate(values):
        if value is None:
            continue
        for term in set(tokenize(value)):
            postings.setdefault(term, []).append(vid)

    terms = sorted(postings)
    blob = "".join(terms).encode("utf-8")
    offsets = np.zeros(len(terms) + 1, dtype=np.int64)
    for i, t in enumerate(terms):
        offsets[i + 1] = offsets[i] + len(t.encode("utf-8"))
    save("txtoff", offsets)
    save("txtblob", np.frombuffer(blob, dtype=np.uint8))

    counts = np.zeros(len(terms) + 1, dtype=np.int64)
    flat: List[int] = []
    for i, t in enumerate(terms):
        counts[i + 1] = counts[i] + len(postings[t])
        flat.extend(postings[t])
    save("txtinvoff", counts)
    posting_blob, byte_offsets = native.varint_encode_lists(
        np.asarray(flat, dtype=np.int32), counts)
    save("txtinvbo", byte_offsets)
    with open(os.path.join(col_dir, f"{name}.txtinv.bin"), "wb") as f:
        f.write(posting_blob)


# --------------------------------------------------------------------------
# query parsing (Lucene QueryParser subset; OR is the default operator)
# --------------------------------------------------------------------------

_QTOKEN = re.compile(r"""
    \s*(?:
      (?P<lp>\() | (?P<rp>\)) |
      (?P<and>AND\b) | (?P<or>OR\b) |
      "(?P<phrase>[^"]*)" |
      (?P<word>[^\s()"]+)
    )""", re.VERBOSE)


def parse_text_query(q: str):
    """-> AST: ("term", t) | ("prefix", p) | ("phrase", [terms], raw)
    | ("and"|"or", [children])."""
    toks: List[Tuple[str, str]] = []
    i = 0
    q = q.strip()
    while i < len(q):
        m = _QTOKEN.match(q, i)
        if m is None or m.end() == i:
            raise ValueError(f"bad TEXT_MATCH query at {q[i:i+20]!r}")
        i = m.end()
        kind = m.lastgroup
        if kind:
            toks.append((kind, m.group(kind)))
    pos = 0

    def peek():
        return toks[pos][0] if pos < len(toks) else None

    def take():
        nonlocal pos
        if pos >= len(toks):
            raise ValueError(f"unexpected end of TEXT_MATCH query {q!r}")
        t = toks[pos]
        pos += 1
        return t

    def unit():
        kind, text = take()
        if kind == "lp":
            node = expr()
            if peek() != "rp":
                raise ValueError("unbalanced parentheses")
            take()
            return node
        if kind == "phrase":
            terms = tokenize(text)
            if not terms:
                raise ValueError("empty phrase")
            return ("phrase", terms, text)
        if kind == "word":
            if text.endswith("*") and len(text) > 1:
                p = tokenize(text[:-1])
                if len(p) != 1:
                    raise ValueError(f"bad wildcard {text!r}")
                return ("prefix", p[0])
            terms = tokenize(text)
            if not terms:
                # '*', '%%', ... — no analyzable content; rejecting beats
                # an index/decay divergence (empty phrase matched ALL rows
                # on the decay path and crashed the indexed path)
                raise ValueError(f"no searchable terms in {text!r}")
            if len(terms) != 1:
                # 'foo-bar' tokenizes to two terms: treat as a phrase
                return ("phrase", terms, text)
            return ("term", terms[0])
        raise ValueError(f"expected a term, got {text!r}")

    def and_expr():
        node = unit()
        children = [node]
        while peek() == "and":
            take()
            children.append(unit())
        return children[0] if len(children) == 1 else ("and", children)

    def expr():
        node = and_expr()
        children = [node]
        while peek() in ("or", "lp", "phrase", "word"):
            if peek() == "or":
                take()
            children.append(and_expr())  # juxtaposition = OR (Lucene)
        return children[0] if len(children) == 1 else ("or", children)

    node = expr()
    if pos != len(toks):
        raise ValueError(f"trailing tokens in TEXT_MATCH query: {toks[pos:]}")
    return node


def match_text_value(value: Any, ast) -> bool:
    """Index-less evaluation of one value (the fallback oracle)."""
    terms = tokenize(value)
    have = set(terms)

    def ev(node) -> bool:
        op = node[0]
        if op == "term":
            return node[1] in have
        if op == "prefix":
            return any(t.startswith(node[1]) for t in have)
        if op == "phrase":
            want = node[1]
            return any(terms[i:i + len(want)] == want
                       for i in range(len(terms) - len(want) + 1))
        if op == "and":
            return all(ev(c) for c in node[1])
        return any(ev(c) for c in node[1])

    return ev(ast)


# --------------------------------------------------------------------------
# reader
# --------------------------------------------------------------------------

class TextIndexReader:
    """Posting resolution of a TEXT_MATCH query to a value-id set (dictIds
    for dict columns, docIds for raw)."""

    def __init__(self, term_off: np.ndarray, term_blob: np.ndarray,
                 inv_off: np.ndarray, inv_byte_off: np.ndarray,
                 inv_blob: bytes, num_ids: int,
                 value_of: Callable[[int], Any]):
        blob = bytes(term_blob.tobytes())
        self._terms = [
            blob[int(term_off[i]):int(term_off[i + 1])].decode("utf-8")
            for i in range(len(term_off) - 1)]
        self._inv_off = inv_off
        self._inv_byte_off = inv_byte_off
        self._inv_blob = inv_blob
        self.num_ids = num_ids
        self._value_of = value_of  # id -> source text (phrase verification)

    def _postings(self, idx: int) -> np.ndarray:
        from pinot_tpu import native

        n = int(self._inv_off[idx + 1] - self._inv_off[idx])
        if n == 0:
            return np.empty(0, dtype=np.int32)
        lo = int(self._inv_byte_off[idx])
        hi = int(self._inv_byte_off[idx + 1])
        return native.varint_decode(self._inv_blob[lo:hi], n)

    def _ids_for_term(self, term: str) -> Set[int]:
        i = bisect_left(self._terms, term)
        if i < len(self._terms) and self._terms[i] == term:
            return set(int(x) for x in self._postings(i))
        return set()

    def _ids_for_prefix(self, prefix: str) -> Set[int]:
        lo = bisect_left(self._terms, prefix)
        hi = bisect_left(self._terms, prefix + "\U0010ffff")
        out: Set[int] = set()
        for i in range(lo, hi):
            out |= set(int(x) for x in self._postings(i))
        return out

    def matching_ids(self, query: str) -> np.ndarray:
        """Sorted value ids matching the TEXT_MATCH query."""
        ast = parse_text_query(query)

        def ev(node) -> Set[int]:
            op = node[0]
            if op == "term":
                return self._ids_for_term(node[1])
            if op == "prefix":
                return self._ids_for_prefix(node[1])
            if op == "phrase":
                # AND the terms, then verify adjacency against the source
                # values (positions are not stored; candidates are few)
                cand: Set[int] = None  # type: ignore[assignment]
                for t in node[1]:
                    ids = self._ids_for_term(t)
                    cand = ids if cand is None else (cand & ids)
                    if not cand:
                        return set()
                return {i for i in cand
                        if match_text_value(self._value_of(i), node)}
            if op == "and":
                out: Set[int] = None  # type: ignore[assignment]
                for c in node[1]:
                    ids = ev(c)
                    out = ids if out is None else (out & ids)
                    if not out:
                        return set()
                return out
            out = set()
            for c in node[1]:
                out |= ev(c)
            return out

        return np.asarray(sorted(ev(ast)), dtype=np.int64)
