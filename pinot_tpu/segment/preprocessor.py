"""Segment reload preprocessing: add newly-configured indexes in place.

Re-design of ``pinot-segment-local/.../segment/index/loader/
SegmentPreProcessor.java`` + the per-index ``loader/*`` IndexHandlers:
when a table's indexing config gains an index the segment was built
without, a RELOAD rebuilds just the missing index files from the data
already on disk (dictionary + forward index) — no re-ingest, no full
segment rebuild — then rewrites metadata (flags + CRC) so the reloaded
segment serves the new plan strategies immediately.

Handled index families: inverted, bloom, text, json, range.
"""

from __future__ import annotations

import logging
import os

from typing import List

import numpy as np

from pinot_tpu.segment import metadata as meta
from pinot_tpu.segment.creator import (
    COLUMNS_DIR,
    build_inverted_index,
    compute_dir_crc,
)
from pinot_tpu.segment.immutable import ImmutableSegment, load_segment
from pinot_tpu.spi.table import IndexingConfig

log = logging.getLogger(__name__)


def preprocess_segment(segment_dir: str,
                       indexing: IndexingConfig) -> List[str]:
    """Build every configured-but-missing index; returns
    '<column>:<kind>' labels of what was added (empty = up to date)."""
    seg = load_segment(segment_dir)
    sm = seg.metadata
    col_dir = os.path.join(segment_dir, COLUMNS_DIR)
    added: List[str] = []

    for name, cm in list(sm.columns.items()):
        def save(suffix: str, arr: np.ndarray, name=name) -> None:
            np.save(os.path.join(col_dir, f"{name}.{suffix}.npy"), arr)

        ds = seg.data_source(name)
        n = sm.num_docs

        if (name in indexing.inverted_index_columns
                and not cm.has_inverted_index and cm.has_dictionary):
            if cm.single_value:
                ids = np.asarray(ds.forward_index[:n]).astype(np.int64)
                counts = None
            else:
                ids = np.asarray(ds.forward_index).astype(np.int64)
                counts = np.diff(np.asarray(ds.mv_offsets))
            build_inverted_index(name, ids, counts, n, cm.cardinality,
                                 save, col_dir)
            cm.has_inverted_index = True
            added.append(f"{name}:inverted")

        if (name in indexing.bloom_filter_columns
                and not cm.has_bloom_filter):
            from pinot_tpu.utils.bloom import BloomFilter

            if cm.has_dictionary:
                values = ds.dictionary.get_values(range(cm.cardinality))
            else:
                values = np.unique(np.asarray(ds.forward_index[:n]))
            save("bloom", BloomFilter.from_values(list(values)).to_array())
            cm.has_bloom_filter = True
            added.append(f"{name}:bloom")

        if (name in indexing.text_index_columns and not cm.has_text_index
                and cm.single_value and not cm.data_type.is_numeric
                and cm.has_dictionary):
            from pinot_tpu.segment.textindex import build_text_index

            build_text_index(ds.dictionary.get_values(range(cm.cardinality)),
                             save, col_dir, name)
            cm.has_text_index = True
            added.append(f"{name}:text")

        if (name in indexing.json_index_columns and not cm.has_json_index
                and cm.single_value and not cm.data_type.is_numeric
                and cm.has_dictionary):
            from pinot_tpu.segment.jsonindex import build_json_index

            fwd = np.asarray(ds.forward_index[:n])
            values = ds.dictionary.get_values(fwd)
            build_json_index(list(values), n, save, col_dir, name)
            cm.has_json_index = True
            added.append(f"{name}:json")

        if (name in indexing.range_index_columns and not cm.has_range_index
                and not cm.has_dictionary and cm.single_value and n):
            data = np.asarray(ds.forward_index[:n])
            save("rangeord", np.argsort(data, kind="stable")
                 .astype(np.int32))
            cm.has_range_index = True
            added.append(f"{name}:range")

    if added:
        sm.crc = compute_dir_crc(col_dir)
        sm.save(os.path.join(segment_dir, meta.METADATA_FILE))
        log.info("reload of %s added indexes: %s", segment_dir, added)
    return added


def reload_segment(tdm, segment: ImmutableSegment,
                   indexing: IndexingConfig) -> List[str]:
    """Preprocess + swap the served segment (the server-side half of the
    reload message, ref: SegmentMessageHandlerFactory refresh/reload). The
    refcounted add-or-replace keeps in-flight queries on the old image."""
    added = preprocess_segment(segment.segment_dir, indexing)
    if added:
        tdm.add_segment_from_dir(segment.segment_dir)
    return added
