"""Chunked compression codecs for raw (no-dictionary) forward indexes.

Re-design of the reference's chunk compressors
(``pinot-segment-local/.../io/compression/ChunkCompressorFactory.java`` —
Snappy/LZ4/zstd-compressed fixed-size chunks read through
``BaseChunkSVForwardIndexReader``): a raw column is stored as independently
compressed chunks so bounded memory decompresses any doc range. The TPU
read path decompresses the whole column once at staging time (HBM wants the
dense array anyway), so chunk granularity here serves the build side and
host-path point reads, not scan latency.

Codec availability is environment-driven: ZSTANDARD (zstandard), GZIP/ZLIB
and PASS_THROUGH are always available; SNAPPY and LZ4 (JNI libs in the
reference) are accepted as configured names but transparently stored as
ZSTANDARD — the file header records the codec actually used, so readers
never guess.
"""

from __future__ import annotations

import struct
import zlib
from typing import List, Optional

import numpy as np

MAGIC = b"PCC1"
DEFAULT_CHUNK_DOCS = 64 * 1024

_ZLIB = 0
_ZSTD = 1
_PASS = 2

try:
    import zstandard as _zstd_mod
except ImportError:  # pragma: no cover - zstandard is in the base image
    _zstd_mod = None


def _codec_id(name: str) -> int:
    n = (name or "").upper()
    if n in ("PASS_THROUGH", "PASSTHROUGH", "NONE"):
        return _PASS
    if n in ("GZIP", "ZLIB", "DEFLATE"):
        return _ZLIB
    # SNAPPY / LZ4 / ZSTANDARD all land on zstd when present (closest
    # semantics: fast block codec), zlib otherwise
    if n in ("ZSTANDARD", "ZSTD", "SNAPPY", "LZ4", ""):
        return _ZSTD if _zstd_mod is not None else _ZLIB
    raise ValueError(f"unknown compression codec {name!r}")


def _compress(codec: int, raw: bytes) -> bytes:
    if codec == _PASS:
        return raw
    if codec == _ZLIB:
        return zlib.compress(raw, 6)
    return _zstd_mod.ZstdCompressor(level=3).compress(raw)


def _decompress(codec: int, blob: bytes, out_len: int) -> bytes:
    if codec == _PASS:
        return blob
    if codec == _ZLIB:
        return zlib.decompress(blob)
    return _zstd_mod.ZstdDecompressor().decompress(blob, max_output_size=out_len)


def write_compressed(path: str, values: np.ndarray, codec_name: str,
                     chunk_docs: int = DEFAULT_CHUNK_DOCS) -> str:
    """Write ``values`` as compressed chunks; returns the codec label
    actually used (recorded in column metadata)."""
    codec = _codec_id(codec_name)
    values = np.ascontiguousarray(values)
    n = values.shape[0]
    itemsize = values.dtype.itemsize
    chunks: List[bytes] = []
    for start in range(0, max(n, 1), chunk_docs):
        raw = values[start:start + chunk_docs].tobytes()
        chunks.append(_compress(codec, raw))
    with open(path, "wb") as f:
        header = MAGIC + struct.pack(
            "<BIIH", codec, n, chunk_docs, itemsize)
        dtype_label = values.dtype.str.encode("ascii")
        header += struct.pack("<H", len(dtype_label)) + dtype_label
        f.write(header)
        f.write(struct.pack("<I", len(chunks)))
        for c in chunks:
            f.write(struct.pack("<I", len(c)))
        for c in chunks:
            f.write(c)
    return {_ZLIB: "ZLIB", _ZSTD: "ZSTANDARD", _PASS: "PASS_THROUGH"}[codec]


def read_compressed(path: str, doc_range: Optional[tuple] = None) -> np.ndarray:
    """Load the full column (or ``doc_range=(start, stop)``), decompressing
    only the covering chunks."""
    with open(path, "rb") as f:
        blob = f.read()
    if blob[:4] != MAGIC:
        raise ValueError(f"{path}: not a compressed chunk file")
    off = 4
    codec, n, chunk_docs, itemsize = struct.unpack_from("<BIIH", blob, off)
    off += struct.calcsize("<BIIH")
    (dl,) = struct.unpack_from("<H", blob, off)
    off += 2
    dtype = np.dtype(blob[off:off + dl].decode("ascii"))
    off += dl
    (num_chunks,) = struct.unpack_from("<I", blob, off)
    off += 4
    sizes = struct.unpack_from(f"<{num_chunks}I", blob, off)
    off += 4 * num_chunks
    starts = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64) + off

    lo_chunk, hi_chunk = 0, num_chunks
    if doc_range is not None:
        lo, hi = doc_range
        lo_chunk = max(0, lo // chunk_docs)
        hi_chunk = min(num_chunks, -(-hi // chunk_docs))
    parts = []
    for ci in range(lo_chunk, hi_chunk):
        docs_in_chunk = min(chunk_docs, n - ci * chunk_docs)
        raw = _decompress(codec, blob[starts[ci]:starts[ci + 1]],
                          docs_in_chunk * itemsize)
        parts.append(np.frombuffer(raw, dtype=dtype))
    out = (np.concatenate(parts) if parts
           else np.empty(0, dtype=dtype))
    if doc_range is not None:
        lo, hi = doc_range
        base = lo_chunk * chunk_docs
        return out[lo - base:hi - base]
    return out
