"""Upsert engine: primary-key dedup across realtime segments.

Re-design of ``pinot-segment-local/.../upsert/PartitionUpsertMetadataManager.java:67``
+ ``TableUpsertMetadataManager`` + ``upsert/merger/*``: a per-partition
primary-key -> RecordLocation map; when a newer record (by the comparison
column, default the time column) arrives for an existing key, the older
doc is invalidated in its segment's valid-doc bitmap. Queries AND the
valid-doc mask into the filter mask, so every execution path (host, device,
star-tree-free) sees exactly one live doc per key.

The valid-doc bitmap is a plain bool array per segment — the TPU analogue of
the reference's ThreadSafeMutableRoaringBitmap: it stages to the device as
one more mask column.
"""

from __future__ import annotations

import threading

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.spi.table import UpsertMode


@dataclass
class RecordLocation:
    """Ref: upsert RecordLocation — where a key's live doc currently is."""

    segment_name: str
    doc_id: int
    comparison_value: Any


class PartitionUpsertMetadataManager:
    """One per (table, stream partition). Thread-safe: consumers index while
    queries read bitmaps (ref: PartitionUpsertMetadataManager.java:67)."""

    def __init__(self, primary_key_columns: List[str],
                 comparison_column: str,
                 mode: UpsertMode = UpsertMode.FULL):
        self.primary_key_columns = primary_key_columns
        self.comparison_column = comparison_column
        self.mode = mode
        self._locations: Dict[Tuple, RecordLocation] = {}
        # per-segment bitmap mutation counters: device-staged mask caches
        # key on these (staging.StagedSegment.valid_mask)
        self._versions: Dict[str, int] = {}
        self._valid: Dict[str, np.ndarray] = {}
        self._lock = threading.Lock()

    # -- reads ---------------------------------------------------------------
    def valid_docs(self, segment_name: str) -> Optional[np.ndarray]:
        with self._lock:
            v = self._valid.get(segment_name)
            return None if v is None else v.copy()

    def valid_docs_version(self, segment_name: str) -> int:
        """Monotonic bitmap mutation counter (device-mask cache key)."""
        with self._lock:
            return self._versions.get(segment_name, 0)

    def _bump_locked(self, segment_name: str) -> None:
        self._versions[segment_name] = \
            self._versions.get(segment_name, 0) + 1

    @property
    def num_keys(self) -> int:
        with self._lock:
            return len(self._locations)

    # -- segment lifecycle ---------------------------------------------------
    def add_segment(self, segment) -> np.ndarray:
        """Index a sealed segment's keys (ref: addSegment — rebuilt from
        segments on restart, SURVEY.md §5 checkpoint note). Returns the
        segment's valid bitmap (shared; updated in place on invalidation)."""
        n = segment.num_docs
        keys = self._segment_keys(segment)
        cmp_vals = self._read_column(segment, self.comparison_column)
        with self._lock:
            valid = np.ones(n, dtype=bool)
            self._valid[segment.segment_name] = valid
            self._bump_locked(segment.segment_name)
            for doc_id in range(n):
                self._upsert_locked(keys[doc_id], segment.segment_name,
                                    doc_id, cmp_vals[doc_id])
            return valid

    def remove_segment(self, segment_name: str) -> None:
        with self._lock:
            self._valid.pop(segment_name, None)
            dead = [k for k, loc in self._locations.items()
                    if loc.segment_name == segment_name]
            for k in dead:
                del self._locations[k]

    def replace_segment(self, segment) -> np.ndarray:
        """Sealed build replaces the consuming segment under the same name:
        doc ids are unchanged (same rows, same order), so the bitmap carries
        over and locations stay valid."""
        with self._lock:
            old = self._valid.get(segment.segment_name)
            n = segment.num_docs
            valid = np.ones(n, dtype=bool)
            if old is not None:
                m = min(n, old.shape[0])
                valid[:m] = old[:m]
            self._valid[segment.segment_name] = valid
            self._bump_locked(segment.segment_name)
            return valid

    # -- row-level ingest (consuming segments) -------------------------------
    def add_record(self, segment_name: str, doc_id: int, key: Tuple,
                   comparison_value: Any) -> None:
        """Ref: addRecord during consumption — called after
        MutableSegment.index()."""
        with self._lock:
            valid = self._valid.get(segment_name)
            if valid is None or doc_id >= valid.shape[0]:
                grown = np.ones(max(doc_id + 1, 1024), dtype=bool)
                if valid is not None:
                    grown[:valid.shape[0]] = valid
                valid = grown
                self._valid[segment_name] = valid
            self._bump_locked(segment_name)
            self._upsert_locked(key, segment_name, doc_id, comparison_value)

    def _upsert_locked(self, key: Tuple, segment_name: str, doc_id: int,
                       cmp_value: Any) -> None:
        loc = self._locations.get(key)
        if loc is not None:
            # newer-or-equal wins (ref: comparison >= keeps latest arrival);
            # a null comparison value is treated as oldest (the reference
            # requires the comparison column to be non-null, so an incoming
            # null must never displace a record with a real value)
            incoming_older = (
                (cmp_value is None and loc.comparison_value is not None)
                or (cmp_value is not None and loc.comparison_value is not None
                    and cmp_value < loc.comparison_value))
            if incoming_older:
                # incoming is older: invalidate IT instead
                valid = self._valid.get(segment_name)
                if valid is not None and doc_id < valid.shape[0]:
                    valid[doc_id] = False
                    self._bump_locked(segment_name)
                return
            old_valid = self._valid.get(loc.segment_name)
            if old_valid is not None and loc.doc_id < old_valid.shape[0]:
                old_valid[loc.doc_id] = False
                self._bump_locked(loc.segment_name)
        self._locations[key] = RecordLocation(segment_name, doc_id, cmp_value)

    # -- helpers -------------------------------------------------------------
    def key_of_row(self, row: Dict[str, Any]) -> Tuple:
        return tuple(row.get(c) for c in self.primary_key_columns)

    def _segment_keys(self, segment) -> List[Tuple]:
        cols = [self._read_column(segment, c)
                for c in self.primary_key_columns]
        return list(zip(*cols)) if cols else []

    @staticmethod
    def _read_column(segment, column: str) -> List[Any]:
        ds = segment.data_source(column)
        n = segment.num_docs
        fwd = np.asarray(ds.forward_index[:n])
        if ds.dictionary is not None:
            return ds.dictionary.get_values(fwd)
        return fwd.tolist()


class TableUpsertMetadataManager:
    """table -> partition managers (ref: TableUpsertMetadataManager)."""

    def __init__(self, primary_key_columns: List[str],
                 comparison_column: str,
                 mode: UpsertMode = UpsertMode.FULL):
        self.primary_key_columns = primary_key_columns
        self.comparison_column = comparison_column
        self.mode = mode
        self._partitions: Dict[int, PartitionUpsertMetadataManager] = {}
        self._lock = threading.Lock()

    def partition_managers(self) -> List[PartitionUpsertMetadataManager]:
        with self._lock:
            return list(self._partitions.values())

    def partition(self, p: int) -> PartitionUpsertMetadataManager:
        with self._lock:
            m = self._partitions.get(p)
            if m is None:
                m = PartitionUpsertMetadataManager(
                    self.primary_key_columns, self.comparison_column,
                    self.mode)
                self._partitions[p] = m
            return m


def attach_valid_docs(segment, valid: np.ndarray) -> None:
    """Mark a segment as upsert-managed: execution paths AND this bitmap
    into every filter mask (the validDocIds contract,
    ref: IndexSegment.getValidDocIds)."""
    segment.valid_doc_ids = valid
