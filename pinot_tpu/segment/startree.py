"""StarTreeV2: pre-aggregation index — build, store, and query execution.

Re-design of ``pinot-segment-local/.../startree/v2/builder/BaseSingleTreeBuilder.java``
(sort on dimension split order, recursive node split with ``maxLeafRecords``,
star-node records aggregated over the split dimension) plus the query side
(``StarTreeUtils.isFitForStarTree``/``StarTreeFilterOperator.java:87`` tree
walk and ``StarTreeV2.java:29`` read contract).

TPU-first storage: records are flat columnar arrays — ``dims [R, D]`` int32
dictIds with ``STAR = -1`` sentinels and one contiguous float64/int64 column
per aggregation function pair — so the selected record ranges feed the same
masked-reduction kernels as regular columns. The *tree walk* stays host-side:
it is a pruning structure over R pre-aggregated records (R << num_docs),
where pointer chasing is cheap and a dense device scan would waste the
pre-aggregation.
"""

from __future__ import annotations

import json
import os

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

STAR = -1
STARTREE_DIR = "startree{index}"
META_FILE = "startree_metadata.json"


class DictIdRange:
    """Contiguous inclusive dictId interval [lo, hi] — the cap-safe match
    representation for RANGE predicates: sorted dictionaries map a value
    range to one contiguous dictId run, so a predicate matching millions of
    dictIds is a two-compare slice check instead of a materialized set
    (the set-based path caps at ``startree_exec._MAX_RANGE_IDS``)."""

    __slots__ = ("lo", "hi")

    def __init__(self, lo: int, hi: int):
        self.lo = int(lo)
        self.hi = int(hi)

    def __contains__(self, v) -> bool:
        return self.lo <= int(v) <= self.hi

    def __len__(self) -> int:
        return max(0, self.hi - self.lo + 1)

    def __repr__(self) -> str:
        return f"DictIdRange({self.lo}, {self.hi})"


def match_bounds(match) -> Tuple[int, int]:
    """Inclusive (lo, hi) dictId bounds of a match (set or DictIdRange);
    (0, -1) for an empty match."""
    if isinstance(match, DictIdRange):
        return match.lo, match.hi
    if not match:
        return 0, -1
    return min(match), max(match)

# aggregation pairs supported in tree records (ref:
# AggregationFunctionColumnPair; COUNT uses the catch-all '*' column)
_MERGEABLE = {"count", "sum", "min", "max"}

_IDENT_RE = None  # compiled lazily (keeps the numpy-only import surface)


def canonical_pair_column(col: str) -> str:
    """Normalize a function-column pair's column half: bare column names
    pass through; arithmetic EXPRESSIONS (``lo_extendedprice*lo_discount``,
    ref: StarTreeV2 builder configs with derived columns) parse and
    canonicalize into the same key namespace the query side derives from
    aggregation arguments, so ``SUM__a*b`` stores exactly the pair
    ``sum(b * a)`` resolves. Raises ValueError for expressions outside the
    pre-aggregable +/-/* subset."""
    global _IDENT_RE
    if _IDENT_RE is None:
        import re

        _IDENT_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*\Z")
    col = col.strip()
    if col == "*" or _IDENT_RE.match(col):
        return col
    from pinot_tpu.query.expressions import canonical_arith_key
    from pinot_tpu.query.parser import parse_expression

    key = canonical_arith_key(parse_expression(col))
    if key is None:
        raise ValueError(f"function-column pair expression {col!r} is not "
                         "pre-aggregable (+/-/* over columns only)")
    return key


def derived_pair_expr(col: str):
    """The parsed expression behind a DERIVED pair column key (canonical,
    parenthesized), or None for a plain column / '*'."""
    if not col.startswith("("):
        return None
    from pinot_tpu.query.parser import parse_expression

    return parse_expression(col)


def eval_derived_column(expr, columns: Dict[str, np.ndarray],
                        num_docs: int) -> np.ndarray:
    """Vectorized one-shot evaluation of a derived pair column over raw
    forward-column values (the build-time half of expression
    pre-aggregation): integer inputs stay integral so the stored f64
    pre-agg sums are exact."""
    from pinot_tpu.query.expressions import Function, Identifier, Literal

    def ev(e):
        if isinstance(e, Identifier):
            return np.asarray(columns[e.name][:num_docs])
        if isinstance(e, Literal):
            return e.value
        assert isinstance(e, Function) and len(e.args) == 2, e
        a, b = ev(e.args[0]), ev(e.args[1])
        if e.name == "plus":
            return a + b
        if e.name == "minus":
            return a - b
        if e.name == "times":
            return a * b
        raise ValueError(f"derived column op {e.name} unsupported")

    return ev(expr)


@dataclass
class StarTreeConfig:
    """Ref: StarTreeIndexConfig.java + StarTreeV2Metadata."""

    dimensions_split_order: List[str]
    function_column_pairs: List[Tuple[str, str]]  # (agg, column); count -> '*'
    max_leaf_records: int = 10_000
    skip_star_creation: List[str] = field(default_factory=list)

    @classmethod
    def from_spi(cls, spi_config) -> "StarTreeConfig":
        """From spi.table.StarTreeIndexConfig ('SUM__revenue' pair syntax;
        the column half may be a +/-/* expression, 'SUM__a*b')."""
        pairs = []
        for p in spi_config.function_column_pairs:
            fn, _, col = p.partition("__")
            pairs.append((fn.lower(), canonical_pair_column(col or "*")))
        return cls(list(spi_config.dimensions_split_order), pairs,
                   spi_config.max_leaf_records,
                   list(spi_config.skip_star_node_creation_for_dimensions))

    def to_dict(self) -> Dict[str, Any]:
        return {
            "dimensionsSplitOrder": self.dimensions_split_order,
            "functionColumnPairs": [f"{f}__{c}" for f, c in
                                    self.function_column_pairs],
            "maxLeafRecords": self.max_leaf_records,
            "skipStarNodeCreationForDimensions": self.skip_star_creation,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "StarTreeConfig":
        pairs = []
        for p in d["functionColumnPairs"]:
            fn, _, col = p.partition("__")
            pairs.append((fn, canonical_pair_column(col or "*")))
        return cls(d["dimensionsSplitOrder"], pairs, d["maxLeafRecords"],
                   d.get("skipStarNodeCreationForDimensions", []))


# node record dtype: the serialized tree (ref: StarTreeNode on-disk layout)
_NODE_DTYPE = np.dtype([
    ("dim", np.int32),          # split dimension index of the CHILDREN
    ("value", np.int32),        # this node's dictId on parent's dim (STAR ok)
    ("start", np.int64),        # record range [start, end)
    ("end", np.int64),
    ("child_first", np.int64),  # children index range [first, last); -1 leaf
    ("child_last", np.int64),
])


class _BuildNode:
    """Intermediate node for the lexsort construction: a record range
    inside one chunk plus its children (value kids in dictId order, star
    child last), assembled into the serialized DFS layout at the end."""

    __slots__ = ("value", "chunk", "lo", "hi", "dim", "kids", "star", "idx")

    def __init__(self, value: int, chunk: int, lo: int, hi: int):
        self.value = value
        self.chunk = chunk
        self.lo = lo
        self.hi = hi
        self.dim = -1
        self.kids: Optional[List["_BuildNode"]] = None
        self.star: Optional["_BuildNode"] = None
        self.idx = -1


class StarTreeBuilder:
    """On-heap single-tree builder (ref: BaseSingleTreeBuilder, 541 LoC)."""

    def __init__(self, config: StarTreeConfig):
        self.config = config

    def build(self, dim_dict_ids: Dict[str, np.ndarray],
              metric_values: Dict[str, np.ndarray],
              num_docs: int, engine: str = "lexsort") -> "StarTree":
        """``dim_dict_ids``: per split-order dimension, [num_docs] dictIds.
        ``metric_values``: per non-count pair column, [num_docs] raw values
        (derived pair columns evaluate here from their base columns unless
        the caller pre-computed them under the canonical key).

        ``engine``: 'lexsort' (default) runs the level-batched vectorized
        construction; 'recursive' keeps the original per-node recursion —
        both emit byte-identical arrays (pinned by test_startree), the
        recursive path survives as the equality oracle."""
        cfg = self.config
        dims = np.stack([np.asarray(dim_dict_ids[d][:num_docs], dtype=np.int32)
                         for d in cfg.dimensions_split_order], axis=1)

        metrics: Dict[str, np.ndarray] = {}
        for fn, col in cfg.function_column_pairs:
            key = f"{fn}__{col}"
            if fn == "count":
                metrics[key] = np.ones(num_docs, dtype=np.int64)
                continue
            if col not in metric_values:
                expr = derived_pair_expr(col)
                if expr is not None:
                    metric_values[col] = eval_derived_column(
                        expr, metric_values, num_docs)
            metrics[key] = np.asarray(metric_values[col][:num_docs],
                                      dtype=np.float64)

        # pass 1: sort by dims, aggregate duplicate dim tuples
        dims, metrics = self._sort_and_dedup(dims, metrics)
        if engine == "recursive":
            return self._construct_recursive(dims, metrics)
        return self._construct_lexsort(dims, metrics)

    def _construct_recursive(self, dims: np.ndarray,
                             metrics: Dict[str, np.ndarray]) -> "StarTree":
        self._dims_rows: List[np.ndarray] = [dims]
        self._chunk_offsets: List[int] = [0]
        self._metric_rows: Dict[str, List[np.ndarray]] = {
            k: [v] for k, v in metrics.items()}
        self._record_count = dims.shape[0]
        self._nodes: List[Tuple] = []

        # recursive construction from the root
        root_idx = self._new_node(value=STAR, start=0, end=dims.shape[0])
        self._split(root_idx, depth=0)

        all_dims = np.concatenate(self._dims_rows, axis=0)
        all_metrics = {k: np.concatenate(v, axis=0)
                       for k, v in self._metric_rows.items()}
        nodes = np.array([tuple(n) for n in self._nodes], dtype=_NODE_DTYPE)
        return StarTree(self.config, all_dims, all_metrics, nodes)

    # -- vectorized (lexsort) construction -----------------------------------
    def _construct_lexsort(self, dims: np.ndarray,
                           metrics: Dict[str, np.ndarray]) -> "StarTree":
        """Level-batched construction: per depth, ONE boundary scan per
        chunk finds every splitting node's children and ONE ``np.lexsort``
        over all star-candidate records dedups every star child at that
        depth (vs one sort + one ``np.unique`` PER NODE in the recursion —
        the build hot loop at millions of rows). The final assembly replays
        the recursion's DFS so node/record arrays come out byte-identical."""
        cfg = self.config
        D = len(cfg.dimensions_split_order)
        max_leaf = cfg.max_leaf_records
        chunks: List[Tuple[np.ndarray, Dict[str, np.ndarray]]] = [
            (dims, metrics)]
        root = _BuildNode(STAR, 0, 0, dims.shape[0])
        level = [root]
        for depth in range(D):
            splitting = [n for n in level if n.hi - n.lo > max_leaf]
            if not splitting:
                break
            dim_name = cfg.dimensions_split_order[depth]
            make_star = dim_name not in cfg.skip_star_creation
            # one boundary pass per chunk: every position where column
            # ``depth`` changes (records are sorted within node ranges)
            cuts: Dict[int, np.ndarray] = {}
            for ci in {n.chunk for n in splitting}:
                col = chunks[ci][0][:, depth]
                cuts[ci] = np.flatnonzero(col[1:] != col[:-1]) + 1
            next_level: List[_BuildNode] = []
            star_jobs: List[_BuildNode] = []
            for n in splitting:
                n.dim = depth
                b = cuts[n.chunk]
                col = chunks[n.chunk][0][:, depth]
                inner = b[np.searchsorted(b, n.lo, side="right"):
                          np.searchsorted(b, n.hi, side="left")]
                starts = [n.lo] + [int(x) for x in inner]
                ends = starts[1:] + [n.hi]
                n.kids = [_BuildNode(int(col[s]), n.chunk, s, e)
                          for s, e in zip(starts, ends)]
                next_level.extend(n.kids)
                if make_star and len(n.kids) > 1:
                    star_jobs.append(n)
            if star_jobs:
                self._batch_star_children(chunks, star_jobs, depth,
                                          next_level)
            level = next_level
        return self._assemble(self.config, chunks, root)

    def _batch_star_children(self, chunks, star_jobs: List[_BuildNode],
                             depth: int,
                             next_level: List[_BuildNode]) -> None:
        """All star children of one level in ONE lexsort: concatenate the
        splitting nodes' record ranges with the split dim starred, sort by
        (node, dims), aggregate duplicate tuples segment-wise; each node's
        star child is then a contiguous slice of the result, appended as
        its own chunk exactly like the recursion's per-node append."""
        D = chunks[0][0].shape[1]
        keys = list(chunks[0][1].keys())
        d_parts: List[np.ndarray] = []
        id_parts: List[np.ndarray] = []
        m_parts: Dict[str, List[np.ndarray]] = {k: [] for k in keys}
        for j, n in enumerate(star_jobs):
            cd, cm = chunks[n.chunk]
            part = cd[n.lo:n.hi].copy()
            part[:, depth] = STAR
            d_parts.append(part)
            id_parts.append(np.full(n.hi - n.lo, j, dtype=np.int64))
            for k in keys:
                m_parts[k].append(cm[k][n.lo:n.hi])
        bd = np.concatenate(d_parts, axis=0)
        bi = np.concatenate(id_parts)
        bm = {k: np.concatenate(v) for k, v in m_parts.items()}
        # node id is the PRIMARY key (np.lexsort: last key is most
        # significant); within one node this is the recursion's exact
        # _sort_and_dedup permutation (same stable sort, same keys — the
        # starred/constant leading dims tie everywhere)
        order = np.lexsort(tuple(bd[:, i] for i in range(D - 1, -1, -1))
                           + (bi,))
        bd, bi = bd[order], bi[order]
        bm = {k: v[order] for k, v in bm.items()}
        change = (bi[1:] != bi[:-1]) | np.any(bd[1:] != bd[:-1], axis=1)
        starts = np.concatenate([[0], np.flatnonzero(change) + 1])
        gid = np.zeros(bd.shape[0], dtype=np.int64)
        gid[starts[1:]] = 1
        gid = np.cumsum(gid)
        ng = starts.shape[0]
        dd = bd[starts]
        di = bi[starts]
        dm = {k: self._segmented(k, v, gid, ng) for k, v in bm.items()}
        offs = np.searchsorted(di, np.arange(len(star_jobs) + 1))
        for j, n in enumerate(star_jobs):
            lo, hi = int(offs[j]), int(offs[j + 1])
            ci = len(chunks)
            chunks.append((dd[lo:hi],
                           {k: v[lo:hi] for k, v in dm.items()}))
            n.star = _BuildNode(STAR, ci, 0, hi - lo)
            next_level.append(n.star)

    @staticmethod
    def _assemble(cfg: "StarTreeConfig", chunks, root: _BuildNode
                  ) -> "StarTree":
        """Replay the recursion's DFS over the built structure: node
        indices allocate at the parent's split (value kids then star) and
        each star chunk lands in the record stream at exactly the point
        the recursion appended it, so offsets, node order, and child
        ranges match the recursive builder byte for byte."""
        chunk_off = {0: 0}
        chunk_order = [0]
        next_off = chunks[0][0].shape[0]
        nodes: List[List[int]] = []

        def alloc(bn: _BuildNode) -> None:
            bn.idx = len(nodes)
            off = chunk_off[bn.chunk]
            nodes.append([-1, bn.value, off + bn.lo, off + bn.hi, -1, -1])

        alloc(root)
        stack = [root]
        while stack:
            bn = stack.pop()
            if bn.kids is None:
                continue
            rec = nodes[bn.idx]
            rec[0] = bn.dim
            rec[4] = len(nodes)
            for c in bn.kids:
                alloc(c)
            if bn.star is not None:
                ci = bn.star.chunk
                chunk_off[ci] = next_off
                chunk_order.append(ci)
                next_off += chunks[ci][0].shape[0]
                alloc(bn.star)
            rec[5] = len(nodes)
            kids = bn.kids + ([bn.star] if bn.star is not None else [])
            stack.extend(reversed(kids))
        all_dims = np.concatenate([chunks[ci][0] for ci in chunk_order],
                                  axis=0)
        all_metrics = {k: np.concatenate([chunks[ci][1][k]
                                          for ci in chunk_order])
                       for k in chunks[0][1]}
        nodes_arr = np.array([tuple(n) for n in nodes], dtype=_NODE_DTYPE)
        return StarTree(cfg, all_dims, all_metrics, nodes_arr)

    # -- helpers -------------------------------------------------------------
    def _sort_and_dedup(self, dims, metrics):
        order = np.lexsort(tuple(dims[:, i] for i
                                 in range(dims.shape[1] - 1, -1, -1)))
        dims = dims[order]
        metrics = {k: v[order] for k, v in metrics.items()}
        # aggregate equal dim tuples
        if dims.shape[0]:
            change = np.any(np.diff(dims, axis=0) != 0, axis=1)
            starts = np.concatenate([[0], np.nonzero(change)[0] + 1])
            group_id = np.zeros(dims.shape[0], dtype=np.int64)
            group_id[starts[1:]] = 1
            group_id = np.cumsum(group_id)
            n = starts.shape[0]
            dims = dims[starts]
            metrics = {k: self._segmented(k, v, group_id, n)
                       for k, v in metrics.items()}
        return dims, metrics

    @staticmethod
    def _segmented(key: str, v: np.ndarray, gid: np.ndarray, n: int):
        fn = key.split("__", 1)[0]
        if fn in ("count", "sum"):
            out = np.zeros(n, dtype=v.dtype)
            np.add.at(out, gid, v)
            return out
        if fn == "min":
            out = np.full(n, np.inf)
            np.minimum.at(out, gid, v)
            return out
        out = np.full(n, -np.inf)
        np.maximum.at(out, gid, v)
        return out

    def _new_node(self, value: int, start: int, end: int) -> int:
        self._nodes.append([-1, value, start, end, -1, -1])
        return len(self._nodes) - 1

    def _append_records(self, dims: np.ndarray,
                        metrics: Dict[str, np.ndarray]) -> int:
        start = self._record_count
        self._dims_rows.append(dims)
        self._chunk_offsets.append(start)
        for k, v in metrics.items():
            self._metric_rows[k].append(v)
        self._record_count += dims.shape[0]
        return start

    def _range(self, start: int, end: int):
        """Slice one chunk: a node's record range never spans chunks (the
        base chunk holds the sorted input; each star child owns exactly the
        chunk its records were appended as)."""
        import bisect

        ci = bisect.bisect_right(self._chunk_offsets, start) - 1
        off = self._chunk_offsets[ci]
        lo, hi = start - off, end - off
        dims = self._dims_rows[ci][lo:hi]
        metrics = {k: v[ci][lo:hi] for k, v in self._metric_rows.items()}
        return dims, metrics

    def _split(self, node_idx: int, depth: int) -> None:
        """Ref: BaseSingleTreeBuilder.constructStarTree — split the node's
        record range on dimension ``depth``; add a star child aggregating
        the range over that dimension; recurse while above maxLeafRecords."""
        cfg = self.config
        D = len(cfg.dimensions_split_order)
        node = self._nodes[node_idx]
        start, end = node[2], node[3]
        if depth >= D or end - start <= cfg.max_leaf_records:
            return
        self._nodes[node_idx][0] = depth

        dims, metrics = self._range(start, end)
        col = dims[:, depth]
        values, first_idx = np.unique(col, return_index=True)

        children: List[int] = []
        for i, v in enumerate(values):
            c_start = start + first_idx[i]
            c_end = start + (first_idx[i + 1] if i + 1 < len(values)
                             else end - start)
            children.append(self._new_node(int(v), c_start, c_end))

        dim_name = cfg.dimensions_split_order[depth]
        if dim_name not in cfg.skip_star_creation and len(values) > 1:
            # star child: aggregate the range over this dimension
            star_dims = dims.copy()
            star_dims[:, depth] = STAR
            s_dims, s_metrics = self._sort_and_dedup(star_dims, dict(metrics))
            s_start = self._append_records(s_dims, s_metrics)
            children.append(self._new_node(STAR, s_start,
                                           s_start + s_dims.shape[0]))

        self._nodes[node_idx][4] = children[0]
        self._nodes[node_idx][5] = children[-1] + 1
        for c in children:
            self._split(c, depth + 1)


class StarTree:
    """A built (or loaded) star-tree: flat record columns + node array."""

    def __init__(self, config: StarTreeConfig, dims: np.ndarray,
                 metrics: Dict[str, np.ndarray], nodes: np.ndarray):
        self.config = config
        self.dims = dims          # [R, D] int32, STAR = -1
        self.metrics = metrics    # pair key -> [R]
        self.nodes = nodes        # _NODE_DTYPE array; root = 0
        self._dim_index = {d: i for i, d
                           in enumerate(config.dimensions_split_order)}

    @property
    def num_records(self) -> int:
        return int(self.dims.shape[0])

    def has_pair(self, fn: str, col: str) -> bool:
        return f"{fn}__{col}" in self.metrics

    # -- persistence (ref: startree/v2/store single index file) --------------
    def save(self, seg_dir: str, index: int = 0) -> None:
        d = os.path.join(seg_dir, STARTREE_DIR.format(index=index))
        os.makedirs(d, exist_ok=True)
        np.save(os.path.join(d, "dims.npy"), self.dims)
        np.save(os.path.join(d, "nodes.npy"), self.nodes)
        for k, v in self.metrics.items():
            np.save(os.path.join(d, f"metric_{k}.npy"), v)
        with open(os.path.join(d, META_FILE), "w") as f:
            json.dump(self.config.to_dict(), f, indent=1)

    @classmethod
    def load(cls, seg_dir: str, index: int = 0) -> Optional["StarTree"]:
        d = os.path.join(seg_dir, STARTREE_DIR.format(index=index))
        meta_path = os.path.join(d, META_FILE)
        if not os.path.isfile(meta_path):
            return None
        with open(meta_path) as f:
            config = StarTreeConfig.from_dict(json.load(f))
        dims = np.load(os.path.join(d, "dims.npy"), mmap_mode="r")
        nodes = np.load(os.path.join(d, "nodes.npy"), mmap_mode="r")
        metrics = {}
        for fn, col in config.function_column_pairs:
            k = f"{fn}__{col}"
            metrics[k] = np.load(os.path.join(d, f"metric_{k}.npy"),
                                 mmap_mode="r")
        return cls(config, dims, metrics, nodes)

    # -- query-time traversal (ref: StarTreeFilterOperator.java:87) ----------
    def select_records(self,
                       eq_in_per_dim: Dict[str, Any],
                       group_by_dims: List[str]) -> np.ndarray:
        """Record indices answering the query: for each split dimension —
        with a predicate: descend matching children; grouped: descend all
        non-star children; otherwise: descend the star child (fall back to
        scanning all children + post-mask when absent). Predicate matches
        are dictId sets or contiguous :class:`DictIdRange` slices (both
        support ``in``; the post-filter branches on the kind)."""
        grouped = set(self._dim_index[d] for d in group_by_dims)
        predicates = {self._dim_index[d]: ids
                      for d, ids in eq_in_per_dim.items()}

        out: List[np.ndarray] = []
        # stack of (node index, needs_postfilter)
        stack: List[int] = [0]
        nodes = self.nodes
        while stack:
            ni = stack.pop()
            n = nodes[ni]
            if n["child_first"] < 0:  # leaf: emit record range
                out.append(np.arange(n["start"], n["end"], dtype=np.int64))
                continue
            dim = int(n["dim"])
            first, last = int(n["child_first"]), int(n["child_last"])
            kids = range(first, last)
            if dim in predicates:
                match = predicates[dim]
                for c in kids:
                    if int(nodes[c]["value"]) in match:
                        stack.append(c)
            elif dim in grouped:
                for c in kids:
                    if int(nodes[c]["value"]) != STAR:
                        stack.append(c)
            else:
                star = next((c for c in kids
                             if int(nodes[c]["value"]) == STAR), None)
                if star is not None:
                    stack.append(star)
                else:
                    for c in kids:
                        stack.append(c)
        if not out:
            return np.empty(0, dtype=np.int64)
        idx = np.concatenate(out)
        # post-filter: leaves cover un-split tails, so records may still hold
        # concrete values where the query needs specific ones, and STAR rows
        # must never leak into predicate/grouped dims
        mask = np.ones(idx.shape[0], dtype=bool)
        for dim, match in predicates.items():
            col = self.dims[idx, dim]
            if isinstance(match, DictIdRange):
                mask &= (col >= match.lo) & (col <= match.hi)
            else:
                mask &= np.isin(col, np.fromiter(match, dtype=np.int32,
                                                 count=len(match)))
        for dim in grouped:
            mask &= self.dims[idx, dim] != STAR
        # free dims need no post-filter: each emitted leaf range holds either
        # the star-aggregated rows (star child taken) or the full concrete
        # partition (no star child / leaf before that depth) — never both
        return idx[mask]
