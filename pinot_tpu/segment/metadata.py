"""Segment + column metadata.

Re-design of the reference's ``SegmentMetadataImpl`` /
``metadata.properties`` + ``ColumnMetadata`` (pinot-segment-spi): JSON
metadata carrying everything the planner and pruners need without touching
column data — doc counts, per-column cardinality/min/max/sortedness,
encoding, partition info, time range, CRC.

TPU-first: ``padded_capacity`` records the doc-dimension padding (multiple of
the TPU lane*sublane tile, 1024 docs) applied to every forward index so
staged arrays are tile-aligned; kernels mask ``doc_id >= num_docs``.
"""

from __future__ import annotations

import json
import time

from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional

import numpy as np

from pinot_tpu.spi.data import DataType, FieldType, Schema

# Doc-dimension padding: 8 sublanes x 128 lanes (f32/i32 tile).
DOC_TILE = 1024

SEGMENT_FORMAT_VERSION = "tpu-v1"
METADATA_FILE = "metadata.json"


def pad_capacity(num_docs: int) -> int:
    return max(DOC_TILE, ((num_docs + DOC_TILE - 1) // DOC_TILE) * DOC_TILE)


class Encoding(Enum):
    DICT = "DICT"  # forward index holds dictIds into a sorted dictionary
    RAW = "RAW"    # forward index holds raw values (numeric only on device)


def narrowest_int_dtype(cardinality: int) -> str:
    """Smallest signed int dtype that holds dictIds [0, cardinality).

    The storage analogue of the reference's fixed-bit packing
    (``io/util/PinotDataBitSet.java:25``): we trade exact bit-packing for
    byte-aligned narrow ints, which DMA cleanly and upcast to int32 on device.
    """
    if cardinality <= (1 << 7):
        return "int8"
    if cardinality <= (1 << 15):
        return "int16"
    return "int32"


@dataclass
class ColumnMetadata:
    """Ref: pinot-segment-spi ColumnMetadata."""

    name: str
    data_type: DataType
    field_type: FieldType
    single_value: bool
    encoding: Encoding
    cardinality: int
    stored_dtype: str           # numpy dtype name of the fwd index on disk
    min_value: Any = None
    max_value: Any = None
    is_sorted: bool = False
    has_dictionary: bool = True
    has_inverted_index: bool = False
    has_nulls: bool = False
    has_bloom_filter: bool = False
    has_json_index: bool = False
    has_text_index: bool = False
    has_fst_index: bool = False
    has_geo_index: bool = False
    has_range_index: bool = False
    max_num_multi_values: int = 0   # MV only: max values per row
    total_number_of_entries: int = 0  # MV only: total flattened values
    partition_function: Optional[str] = None
    num_partitions: int = 0
    partitions: List[int] = field(default_factory=list)  # partitions present
    # raw columns only: chunk codec of the fwd index file (None = .npy)
    compression_codec: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        d = {
            "name": self.name,
            "dataType": self.data_type.label,
            "fieldType": self.field_type.value,
            "singleValue": self.single_value,
            "encoding": self.encoding.value,
            "cardinality": self.cardinality,
            "storedDtype": self.stored_dtype,
            "minValue": _json_value(self.min_value),
            "maxValue": _json_value(self.max_value),
            "isSorted": self.is_sorted,
            "hasDictionary": self.has_dictionary,
            "hasInvertedIndex": self.has_inverted_index,
            "hasNulls": self.has_nulls,
            "hasBloomFilter": self.has_bloom_filter,
            "hasJsonIndex": self.has_json_index,
            "hasTextIndex": self.has_text_index,
            "hasFstIndex": self.has_fst_index,
            "hasGeoIndex": self.has_geo_index,
            "hasRangeIndex": self.has_range_index,
            "maxNumMultiValues": self.max_num_multi_values,
            "totalNumberOfEntries": self.total_number_of_entries,
        }
        if self.partition_function:
            d["partitionFunction"] = self.partition_function
            d["numPartitions"] = self.num_partitions
            d["partitions"] = self.partitions
        if self.compression_codec:
            d["compressionCodec"] = self.compression_codec
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ColumnMetadata":
        dt = DataType.from_string(d["dataType"])
        return cls(
            name=d["name"],
            data_type=dt,
            field_type=FieldType[d["fieldType"]],
            single_value=d["singleValue"],
            encoding=Encoding[d["encoding"]],
            cardinality=d["cardinality"],
            stored_dtype=d["storedDtype"],
            min_value=_unjson_value(d.get("minValue"), dt),
            max_value=_unjson_value(d.get("maxValue"), dt),
            is_sorted=d.get("isSorted", False),
            has_dictionary=d.get("hasDictionary", True),
            has_inverted_index=d.get("hasInvertedIndex", False),
            has_nulls=d.get("hasNulls", False),
            has_bloom_filter=d.get("hasBloomFilter", False),
            has_json_index=d.get("hasJsonIndex", False),
            has_text_index=d.get("hasTextIndex", False),
            has_fst_index=d.get("hasFstIndex", False),
            has_geo_index=d.get("hasGeoIndex", False),
            has_range_index=d.get("hasRangeIndex", False),
            max_num_multi_values=d.get("maxNumMultiValues", 0),
            total_number_of_entries=d.get("totalNumberOfEntries", 0),
            partition_function=d.get("partitionFunction"),
            num_partitions=d.get("numPartitions", 0),
            partitions=d.get("partitions", []),
            compression_codec=d.get("compressionCodec"),
        )


def _json_value(v: Any) -> Any:
    if v is None:
        return None
    if isinstance(v, bytes):
        return {"__bytes__": v.hex()}
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        f = float(v)
        if f in (float("inf"), float("-inf")) or f != f:
            return {"__float__": repr(f)}
        return f
    if isinstance(v, float) and (v in (float("inf"), float("-inf")) or v != v):
        return {"__float__": repr(v)}
    return v


def _unjson_value(v: Any, dt: DataType) -> Any:
    if v is None:
        return None
    if isinstance(v, dict):
        if "__bytes__" in v:
            return bytes.fromhex(v["__bytes__"])
        if "__float__" in v:
            return float(v["__float__"])
    return v


@dataclass
class SegmentMetadata:
    """Ref: metadata.properties + creation.meta (V1Constants.java:25,56)."""

    segment_name: str
    table_name: str
    schema: Schema
    num_docs: int
    padded_capacity: int
    format_version: str = SEGMENT_FORMAT_VERSION
    creation_time_ms: int = 0
    time_column: Optional[str] = None
    min_time: Optional[int] = None   # in time-column units
    max_time: Optional[int] = None
    crc: int = 0
    columns: Dict[str, ColumnMetadata] = field(default_factory=dict)
    star_tree_count: int = 0
    # per-tree build wall seconds (creator fills; bench records)
    star_tree_build_s: List[float] = field(default_factory=list)
    custom: Dict[str, Any] = field(default_factory=dict)

    @property
    def total_docs(self) -> int:
        return self.num_docs

    def column(self, name: str) -> ColumnMetadata:
        try:
            return self.columns[name]
        except KeyError:
            raise KeyError(
                f"column {name!r} not in segment {self.segment_name!r}") from None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "segmentName": self.segment_name,
            "tableName": self.table_name,
            "schema": self.schema.to_dict(),
            "numDocs": self.num_docs,
            "paddedCapacity": self.padded_capacity,
            "formatVersion": self.format_version,
            "creationTimeMs": self.creation_time_ms,
            "timeColumn": self.time_column,
            "minTime": self.min_time,
            "maxTime": self.max_time,
            "crc": self.crc,
            "starTreeCount": self.star_tree_count,
            "starTreeBuildS": self.star_tree_build_s,
            "columns": {n: c.to_dict() for n, c in self.columns.items()},
            "custom": self.custom,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SegmentMetadata":
        return cls(
            segment_name=d["segmentName"],
            table_name=d["tableName"],
            schema=Schema.from_dict(d["schema"]),
            num_docs=d["numDocs"],
            padded_capacity=d["paddedCapacity"],
            format_version=d.get("formatVersion", SEGMENT_FORMAT_VERSION),
            creation_time_ms=d.get("creationTimeMs", 0),
            time_column=d.get("timeColumn"),
            min_time=d.get("minTime"),
            max_time=d.get("maxTime"),
            crc=d.get("crc", 0),
            star_tree_count=d.get("starTreeCount", 0),
            star_tree_build_s=d.get("starTreeBuildS", []),
            columns={n: ColumnMetadata.from_dict(c)
                     for n, c in d.get("columns", {}).items()},
            custom=d.get("custom", {}),
        )

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=1, sort_keys=True)

    @classmethod
    def load(cls, path: str) -> "SegmentMetadata":
        with open(path) as f:
            return cls.from_dict(json.load(f))


def now_ms() -> int:
    return int(time.time() * 1000)
