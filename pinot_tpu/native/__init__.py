"""Native host runtime bindings (C++ via ctypes).

The runtime around the JAX compute path is native where the reference's is
(SURVEY.md §2 [NATIVE-EQ] items): fixed-bit pack/unpack of dictId arrays,
refcounted mmap buffers, file CRC, and varint posting lists live in
``native/pinot_native.cpp``, compiled once with g++ on first use and bound
through ctypes (no pybind11 in the image). Every entry point has a numpy
fallback so the framework still runs where no compiler exists.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
import zlib

from typing import Optional

import numpy as np

log = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "pinot_native.cpp")
_LIB_DIR = os.path.join(_REPO_ROOT, "native", "build")
_LIB = os.path.join(_LIB_DIR, "libpinot_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _build() -> bool:
    os.makedirs(_LIB_DIR, exist_ok=True)
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           "-o", _LIB, _SRC]
    try:
        r = subprocess.run(cmd, capture_output=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        log.warning("native build failed to run: %s", e)
        return False
    if r.returncode != 0:
        log.warning("native build failed:\n%s", r.stderr.decode()[-2000:])
        return False
    return True


def load() -> Optional[ctypes.CDLL]:
    """The bound library, building it on first use; None -> numpy fallback."""
    global _lib, _load_attempted
    with _lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        have_lib = os.path.isfile(_LIB)
        have_src = os.path.isfile(_SRC)
        stale = (have_lib and have_src
                 and os.path.getmtime(_LIB) < os.path.getmtime(_SRC))
        if not have_lib or stale:
            if not have_src or not _build():
                # a pre-built .so without source is still usable
                if not have_lib:
                    return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError as e:
            log.warning("native library load failed: %s", e)
            return None
        _declare(lib)
        _lib = lib
        return _lib


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    lib.pn_packed_size.restype = c.c_int64
    lib.pn_packed_size.argtypes = [c.c_int64, c.c_int32]
    lib.pn_bitpack_i32.restype = c.c_int64
    lib.pn_bitpack_i32.argtypes = [c.c_void_p, c.c_int64, c.c_int32,
                                   c.c_void_p, c.c_int64]
    lib.pn_bitunpack_i32.restype = c.c_int64
    lib.pn_bitunpack_i32.argtypes = [c.c_void_p, c.c_int64, c.c_int64,
                                     c.c_int32, c.c_void_p]
    lib.pn_mmap_open.restype = c.c_int64
    lib.pn_mmap_open.argtypes = [c.c_char_p]
    lib.pn_mmap_addr.restype = c.c_void_p
    lib.pn_mmap_addr.argtypes = [c.c_int64]
    lib.pn_mmap_size.restype = c.c_int64
    lib.pn_mmap_size.argtypes = [c.c_int64]
    lib.pn_mmap_acquire.restype = c.c_int32
    lib.pn_mmap_acquire.argtypes = [c.c_int64]
    lib.pn_mmap_release.restype = c.c_int32
    lib.pn_mmap_release.argtypes = [c.c_int64]
    lib.pn_mmap_open_count.restype = c.c_int64
    lib.pn_crc32_file.restype = c.c_int64
    lib.pn_crc32_file.argtypes = [c.c_char_p, c.c_uint32]
    lib.pn_varint_encode.restype = c.c_int64
    lib.pn_varint_encode.argtypes = [c.c_void_p, c.c_int64, c.c_void_p,
                                     c.c_int64]
    lib.pn_varint_decode.restype = c.c_int64
    lib.pn_varint_decode.argtypes = [c.c_void_p, c.c_int64, c.c_void_p,
                                     c.c_int64]
    lib.pn_varint_encode_lists.restype = c.c_int64
    lib.pn_varint_encode_lists.argtypes = [c.c_void_p, c.c_void_p, c.c_int64,
                                           c.c_void_p, c.c_int64, c.c_void_p]


def available() -> bool:
    return load() is not None


# --------------------------------------------------------------------------
# fixed-bit packing
# --------------------------------------------------------------------------

def bits_needed(cardinality: int) -> int:
    """Bits per dictId (ref: PinotDataBitSet.getNumBitsPerValue)."""
    return max(1, int(cardinality - 1).bit_length())


def bitpack(values: np.ndarray, bits: int) -> bytes:
    """int32 array -> packed bytes."""
    values = np.ascontiguousarray(values, dtype=np.int32)
    lib = load()
    if lib is not None:
        n = values.shape[0]
        cap = lib.pn_packed_size(n, bits)
        out = np.empty(cap, dtype=np.uint8)
        wrote = lib.pn_bitpack_i32(
            values.ctypes.data, n, bits, out.ctypes.data, cap)
        if wrote < 0:
            raise ValueError(f"bitpack failed (bits={bits})")
        return out[:wrote].tobytes()
    # numpy fallback: expand to a bit matrix, pack into 64-bit words
    n = values.shape[0]
    total_words = (n * bits + 63) // 64
    bit_idx = (np.arange(n, dtype=np.int64)[:, None] * bits
               + np.arange(bits, dtype=np.int64)[None, :]).ravel()
    bit_vals = ((values.astype(np.uint64)[:, None]
                 >> np.arange(bits, dtype=np.uint64)[None, :]) & 1).ravel()
    words = np.zeros(total_words, dtype=np.uint64)
    np.bitwise_or.at(words, bit_idx >> 6,
                     bit_vals.astype(np.uint64) << (bit_idx & 63).astype(np.uint64))
    return words.tobytes()


def bitunpack(buf: bytes, n: int, bits: int) -> np.ndarray:
    """packed bytes -> int32 array of n values."""
    lib = load()
    if lib is not None:
        src = np.frombuffer(buf, dtype=np.uint8)
        out = np.empty(n, dtype=np.int32)
        got = lib.pn_bitunpack_i32(src.ctypes.data, src.shape[0], n, bits,
                                   out.ctypes.data)
        if got != n:
            raise ValueError(f"bitunpack failed (n={n}, bits={bits})")
        return out
    pad = (-len(buf)) % 8
    words = np.frombuffer(buf + b"\x00" * pad, dtype=np.uint64)
    bit_idx = (np.arange(n, dtype=np.int64)[:, None] * bits
               + np.arange(bits, dtype=np.int64)[None, :])
    bit_vals = (words[bit_idx >> 6] >> (bit_idx & 63).astype(np.uint64)) & 1
    weights = (1 << np.arange(bits, dtype=np.uint64))
    return (bit_vals * weights[None, :]).sum(axis=1).astype(np.int32)


# --------------------------------------------------------------------------
# mmap buffers
# --------------------------------------------------------------------------

class MmapBuffer:
    """Refcounted read-only mapping (ref: PinotDataBuffer.mapFile). Use
    ``as_array`` for a zero-copy numpy view; hold the buffer while views
    are alive (release unmaps at refcount zero)."""

    def __init__(self, path: str):
        lib = load()
        self._lib = lib
        self._handle = 0
        self._mm = None
        if lib is not None:
            h = lib.pn_mmap_open(path.encode())
            if h > 0:
                self._handle = h
                self.size = lib.pn_mmap_size(h)
                self._addr = lib.pn_mmap_addr(h)
                return
        # fallback: python mmap
        import mmap as _pymmap

        f = open(path, "rb")
        try:
            self._mm = _pymmap.mmap(f.fileno(), 0, access=_pymmap.ACCESS_READ)
        finally:
            f.close()
        self.size = len(self._mm)

    def as_array(self, dtype, count: int = -1, offset: int = 0) -> np.ndarray:
        if self._handle:
            raw = (ctypes.c_uint8 * (self.size - offset)).from_address(
                self._addr + offset)
            arr = np.frombuffer(raw, dtype=dtype)
        else:
            arr = np.frombuffer(self._mm, dtype=dtype,
                                offset=offset)
        return arr[:count] if count >= 0 else arr

    def read(self) -> bytes:
        return self.as_array(np.uint8).tobytes()

    _local_refs = 1  # references THIS object holds on the mapping

    def acquire(self) -> bool:
        if self._handle:
            if not self._lib.pn_mmap_acquire(self._handle):
                return False
            self._local_refs += 1
        return True

    def release(self) -> None:
        """Give back one of this object's references; never touches other
        holders' refcounts (a double release beyond what was acquired is a
        no-op, so __del__ cannot unmap memory someone else pinned)."""
        if self._handle and self._local_refs > 0:
            self._local_refs -= 1
            rc = self._lib.pn_mmap_release(self._handle)
            if rc == 0 or self._local_refs == 0:
                self._handle = 0

    def __del__(self):
        try:
            while self._handle and self._local_refs > 0:
                self.release()
        except Exception:
            pass


# --------------------------------------------------------------------------
# CRC + varint
# --------------------------------------------------------------------------

def crc32_file(path: str, seed: int = 0) -> int:
    lib = load()
    if lib is not None:
        v = lib.pn_crc32_file(path.encode(), seed & 0xFFFFFFFF)
        if v >= 0:
            return int(v) & 0xFFFFFFFF
    crc = seed
    with open(path, "rb") as f:
        while chunk := f.read(1 << 20):
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def varint_encode(doc_ids: np.ndarray) -> bytes:
    """Sorted int32 doc ids -> delta+varint bytes (posting list storage)."""
    doc_ids = np.ascontiguousarray(doc_ids, dtype=np.int32)
    lib = load()
    if lib is not None:
        cap = doc_ids.shape[0] * 5 + 16
        out = np.empty(cap, dtype=np.uint8)
        wrote = lib.pn_varint_encode(doc_ids.ctypes.data, doc_ids.shape[0],
                                     out.ctypes.data, cap)
        if wrote < 0:
            raise ValueError("varint encode overflow")
        return out[:wrote].tobytes()
    out_b = bytearray()
    prev = 0
    for v in doc_ids.tolist():
        d = v - prev
        prev = v
        while d >= 0x80:
            out_b.append((d & 0x7F) | 0x80)
            d >>= 7
        out_b.append(d)
    return bytes(out_b)


def varint_encode_lists(docs: np.ndarray,
                        offsets: np.ndarray) -> tuple:
    """Encode posting lists docs[offsets[i]:offsets[i+1]] in one pass.
    Returns (blob bytes, byte_offsets int64[num_lists+1])."""
    docs = np.ascontiguousarray(docs, dtype=np.int32)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    num_lists = offsets.shape[0] - 1
    lib = load()
    if lib is not None:
        cap = docs.shape[0] * 5 + 16
        out = np.empty(cap, dtype=np.uint8)
        byte_offsets = np.empty(num_lists + 1, dtype=np.int64)
        wrote = lib.pn_varint_encode_lists(
            docs.ctypes.data, offsets.ctypes.data, num_lists,
            out.ctypes.data, cap, byte_offsets.ctypes.data)
        if wrote < 0:
            raise ValueError("varint encode overflow")
        return out[:wrote].tobytes(), byte_offsets
    blobs = []
    byte_offsets = np.zeros(num_lists + 1, dtype=np.int64)
    for i in range(num_lists):
        enc = varint_encode(docs[offsets[i]:offsets[i + 1]])
        blobs.append(enc)
        byte_offsets[i + 1] = byte_offsets[i] + len(enc)
    return b"".join(blobs), byte_offsets


def varint_decode(buf: bytes, n: int) -> np.ndarray:
    lib = load()
    if lib is not None:
        src = np.frombuffer(buf, dtype=np.uint8)
        out = np.empty(n, dtype=np.int32)
        got = lib.pn_varint_decode(src.ctypes.data, src.shape[0],
                                   out.ctypes.data, n)
        if got != n:
            raise ValueError("varint decode failed")
        return out
    out_l = []
    prev = 0
    i = 0
    for _ in range(n):
        d = 0
        shift = 0
        while True:
            b = buf[i]
            i += 1
            d |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        prev += d
        out_l.append(prev)
    return np.asarray(out_l, dtype=np.int32)


def mmap_buffer_count() -> int:
    """Currently-mapped native buffers (0 with the numpy fallback) —
    the MmapDebugResource accounting hook."""
    lib = load()
    if lib is None:
        return 0
    try:
        return int(lib.pn_mmap_open_count())
    except Exception:
        return 0
