"""HTTP/REST APIs: controller admin + broker query front door.

Re-design of the reference's Jersey resources — controller
(``pinot-controller/.../api/resources/*``: tables, schemas, segments,
rebalance), broker (``pinot-broker/.../api/resources/PinotClientRequest``:
``POST /query/sql``), server health — on the stdlib threading HTTP server
(the control plane is not a throughput surface; the data plane is gRPC).
Endpoint paths and JSON shapes follow the reference so its clients carry
over.
"""

from __future__ import annotations

import json
import logging
import re
import threading

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from pinot_tpu.spi.data import Schema
from pinot_tpu.spi.table import TableConfig

log = logging.getLogger(__name__)

# (method, pattern, handler, table_scope): table_scope=False marks routes
# whose first path capture is NOT a table name (instance ids, task types,
# zk node paths) so authorization runs cluster-scoped (table=None) instead
# of granting/denying against the wrong scope
Route = Tuple[str, re.Pattern, Callable, bool]


class _Api:
    """Tiny method+path router on ThreadingHTTPServer.

    ``access_control`` guards every route (ref: the AccessControlFactory
    hook in BaseBrokerStarter / controller admin app): unauthenticated
    requests get 401, authenticated-but-unauthorized get 403. Health
    endpoints stay open (liveness probes don't carry credentials)."""

    OPEN_PATHS = ("/health",)
    # POSTs that are semantically reads (authorized with READ, not WRITE)
    READ_POSTS = ("/query/sql", "/state/get", "/state/poll")

    def __init__(self, port: int = 0, access_control=None):
        from pinot_tpu.spi.auth import AllowAllAccessControl

        self._routes: List[Route] = []
        self.access_control = access_control or AllowAllAccessControl()
        self._principal_local = threading.local()
        api = self

        class Handler(BaseHTTPRequestHandler):
            # quiet default request logging
            def log_message(self, fmt, *args):
                log.debug("http: " + fmt, *args)

            def _dispatch(self, method: str):
                try:
                    path_only = self.path.split("?", 1)[0]
                    principal = api.access_control.authenticate(self.headers)
                    if principal is None \
                            and path_only not in api.OPEN_PATHS:
                        self.send_response(401)
                        self.send_header("WWW-Authenticate",
                                         'Basic realm="pinot"')
                        self.send_header("Content-Length", "0")
                        self.end_headers()
                        return
                    api._principal_local.value = principal
                    body = None
                    n = int(self.headers.get("Content-Length") or 0)
                    if n:
                        body = json.loads(self.rfile.read(n).decode("utf-8"))
                    for m, pat, fn, table_scope in api._routes:
                        if m != method:
                            continue
                        match = pat.fullmatch(self.path.split("?", 1)[0])
                        if match:
                            if path_only not in api.OPEN_PATHS:
                                # method-level authorization: mutations need
                                # WRITE, scoped to the table the route acts
                                # on — path captures name it for /tables/x,
                                # /segments/x, /schemas/x; body-borne
                                # mutations (POST /tables, /segments,
                                # /schemas) name it in the payload (ref:
                                # per-table auth on the segment/table
                                # controller resources)
                                from pinot_tpu.spi.auth import READ, WRITE

                                access = READ if (method == "GET" or path_only
                                                  in api.READ_POSTS) else WRITE
                                table = (match.group(1)
                                         if pat.groups and table_scope
                                         else None)
                                if table is None and isinstance(body, dict):
                                    # route-aware: the auth scope must be
                                    # the SAME name the handler mutates —
                                    # schemas routes act on schemaName,
                                    # table/segment routes on tableName (a
                                    # mixed body must not authorize one
                                    # name and mutate another)
                                    table = (body.get("schemaName")
                                             if path_only.startswith(
                                                 "/schemas")
                                             else body.get("tableName"))
                                if not api.access_control.has_access(
                                        principal, table, access):
                                    self.send_error(403, "permission denied")
                                    return
                            code, payload = fn(match, body)
                            if isinstance(payload, str):
                                # text endpoints (/metrics prometheus, /ui)
                                raw = payload.encode("utf-8")
                                ctype = ("text/html; charset=utf-8"
                                         if payload.startswith("<!doctype")
                                         else "text/plain; version=0.0.4")
                            else:
                                raw = json.dumps(payload).encode("utf-8")
                                ctype = "application/json"
                            self.send_response(code)
                            self.send_header("Content-Type", ctype)
                            self.send_header("Content-Length", str(len(raw)))
                            self.end_headers()
                            self.wfile.write(raw)
                            return
                    self.send_error(404, "no such endpoint")
                except Exception as e:  # noqa: BLE001 — HTTP boundary
                    log.exception("request failed: %s %s", method, self.path)
                    try:
                        self.send_error(500, str(e)[:200])
                    except Exception:
                        pass

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_DELETE(self):
                self._dispatch("DELETE")

        self._httpd = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        self.port = self._httpd.server_port
        self._thread: Optional[threading.Thread] = None

    def route(self, method: str, pattern: str, fn: Callable,
              table_scope: bool = True) -> None:
        self._routes.append((method, re.compile(pattern), fn, table_scope))

    def current_principal(self):
        """The principal of the request being dispatched on THIS thread."""
        return getattr(self._principal_local, "value", None)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="rest-api")
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


class ControllerApi(_Api):
    """Ref: controller api/resources (45 Jersey resources, reduced to the
    operative set: schemas, tables, segments, state, rebalance, health)."""

    def __init__(self, controller, port: int = 0, access_control=None):
        super().__init__(port, access_control=access_control)
        c = controller
        store = controller.store

        self.route("GET", r"/health",
                   lambda m, b: (200, {"status": "OK"}))
        self.route("GET", r"/metrics",
                   lambda m, b: (200, c.metrics.export_prometheus()))
        # schemas (ref: PinotSchemaRestletResource)
        self.route("POST", r"/schemas",
                   lambda m, b: (200, self._add_schema(c, b)))
        self.route("GET", r"/schemas",
                   lambda m, b: (200, store.schema_names()))
        self.route("GET", r"/schemas/([^/]+)",
                   lambda m, b: self._get_schema(store, m.group(1)))
        # tables (ref: PinotTableRestletResource)
        self.route("POST", r"/tables",
                   lambda m, b: (200, self._add_table(c, b)))
        self.route("PUT", r"/tables/([^/]+)",
                   lambda m, b: self._update_table(c, m.group(1), b))
        self.route("GET", r"/tables",
                   lambda m, b: (200, {"tables": store.table_names()}))
        self.route("DELETE", r"/tables/([^/]+)",
                   lambda m, b: (200, self._delete_table(c, m.group(1))))
        self.route("GET", r"/tables/([^/]+)/idealstate",
                   lambda m, b: (200, store.get_ideal_state(m.group(1))))
        self.route("GET", r"/tables/([^/]+)/externalview",
                   lambda m, b: (200, store.get_external_view(m.group(1))))
        self.route("POST", r"/tables/([^/]+)/rebalance",
                   lambda m, b: (200, {"steps": c.rebalance_table(
                       m.group(1), dry_run=bool((b or {}).get("dryRun")))}))
        # segments (ref: PinotSegmentUploadDownloadRestletResource:102 —
        # local-path upload; multi-host file upload arrives with deep store)
        self.route("POST", r"/segments",
                   lambda m, b: (200, self._add_segment(c, b)))
        # ref: PinotSegmentRestletResource POST /segments/{table}/reload
        self.route("POST", r"/segments/([^/]+)/reload",
                   lambda m, b: (200, self._reload(c, m.group(1))))
        self.route("GET", r"/segments/([^/]+)",
                   lambda m, b: (200, store.segment_names(m.group(1))))
        self.route("GET", r"/instances",
                   lambda m, b: (200, {"instances": [
                       i.to_dict() for i in store.instances()]}))
        # lineage (ref: startReplaceSegments/endReplaceSegments REST);
        # protocol conflicts are 409, unknown entries 404 — a retrying
        # client must distinguish them from server faults
        self.route("POST", r"/segments/([^/]+)/startReplaceSegments",
                   lambda m, b: self._start_replace(c, m, b))
        self.route("POST", r"/segments/([^/]+)/endReplaceSegments/([^/]+)",
                   lambda m, b: self._lineage_flip(
                       c.end_replace_segments, m))
        self.route("POST", r"/segments/([^/]+)/revertReplaceSegments/([^/]+)",
                   lambda m, b: self._lineage_flip(
                       c.revert_replace_segments, m))
        # recommender (ref: RecommenderDriver via PinotTableRestletResource)
        self.route("POST", r"/tables/([^/]+)/recommender",
                   lambda m, b: self._recommend(store, m.group(1), b))
        # tenants (ref: PinotTenantRestletResource): tenants are instance
        # tag groups; SERVER/BROKER membership comes from instance tags
        self.route("GET", r"/tenants",
                   lambda m, b: (200, self._tenants(store)))
        # the capture is a tenant (instance tag group), not a table
        self.route("GET", r"/tenants/([^/]+)",
                   lambda m, b: (200, self._tenant(store, m.group(1))),
                   table_scope=False)
        # the capture is an INSTANCE id, not a table — cluster-scoped auth
        self.route("PUT", r"/instances/([^/]+)/updateTags",
                   lambda m, b: self._update_tags(c, m.group(1), b),
                   table_scope=False)
        # minion tasks (ref: PinotTaskRestletResource); the capture is a
        # task TYPE, not a table — cluster-scoped auth
        self.route("GET", r"/tasks/tasktypes",
                   lambda m, b: (200, self._task_types()))
        self.route("GET", r"/tasks/([^/]+)/state",
                   lambda m, b: (200, {
                       t.task_id: t.status
                       for t in c.task_manager.list_tasks()
                       if t.task_type == m.group(1)}),
                   table_scope=False)
        self.route("POST", r"/tasks/schedule",
                   lambda m, b: (200, {"generated":
                                       c.task_manager.generate_tasks()}))
        # state-store browse (ref: ZookeeperResource /zk/ls + /zk/get; the
        # node path rides IN the URL path after the verb — never a table)
        self.route("GET", r"/zk/ls(?:/(.*))?",
                   lambda m, b: (200, store.children(m.group(1))
                                 if m.group(1)
                                 else sorted(store.snapshot_data()[1])),
                   table_scope=False)
        self.route("GET", r"/zk/get/(.+)",
                   lambda m, b: self._zk_get(store, m.group(1)),
                   table_scope=False)
        # minimal cluster status UI (ref: the controller's bundled web app)
        self.route("GET", r"/ui",
                   lambda m, b: (200, self._render_ui(store)))

    @staticmethod
    def _task_types() -> List[str]:
        """REGISTERED task types (ref: PinotTaskRestletResource
        listTaskTypes reads the registry, not materialized task records)."""
        from pinot_tpu.controller.tasks import _GENERATORS

        return sorted(_GENERATORS)

    @staticmethod
    def _tenants(store) -> Dict[str, Any]:
        """All tags grouped by role (ref: PinotTenantRestletResource
        getAllTenants)."""
        server, broker = set(), set()
        for i in store.instances():
            target = (server if i.instance_type.upper().startswith("SERVER")
                      else broker if
                      i.instance_type.upper().startswith("BROKER") else None)
            if target is not None:
                target.update(i.tags)
        return {"SERVER_TENANTS": sorted(server),
                "BROKER_TENANTS": sorted(broker)}

    @staticmethod
    def _tenant(store, name: str) -> Dict[str, Any]:
        return {"tenantName": name,
                "instances": sorted(i.instance_id for i in store.instances()
                                    if name in i.tags)}

    @staticmethod
    def _update_tags(c, instance_id: str, body):
        tags = (body or {}).get("tags")
        if not isinstance(tags, list) or not all(
                isinstance(t, str) for t in tags):
            return 400, {"error": "body must carry {'tags': [str, ...]}"}
        try:
            c.update_instance_tags(instance_id, tags)
        except KeyError as e:
            return 404, {"error": str(e)}
        return 200, {"status": f"Updated tags of {instance_id}"}

    @staticmethod
    def _zk_get(store, path: str):
        v = store.get(path)
        return (404, {"error": f"no node at {path!r}"}) if v is None \
            else (200, {"path": path, "value": v})

    @staticmethod
    def _start_replace(c, m, b):
        try:
            eid = c.start_replace_segments(
                m.group(1), (b or {}).get("segmentsFrom", []),
                (b or {}).get("segmentsTo", []))
        except ValueError as e:  # overlapping in-progress replacement
            return 409, {"error": str(e)}
        return 200, {"segmentLineageEntryId": eid}

    @staticmethod
    def _lineage_flip(fn, m):
        try:
            fn(m.group(1), m.group(2))
        except KeyError as e:
            return 404, {"error": str(e)}
        except ValueError as e:  # wrong state for the transition
            return 409, {"error": str(e)}
        return 200, {"status": "done"}

    @staticmethod
    def _recommend(store, table: str, body):
        from pinot_tpu.controller.recommender import recommend
        from pinot_tpu.spi.table import raw_table_name

        schema = store.get_schema(raw_table_name(table))
        if schema is None:
            return 404, {"error": f"no schema for table {table}"}
        return 200, recommend(schema, (body or {}).get("queries", []),
                              qps=float((body or {}).get("qps", 0)))

    @staticmethod
    def _render_ui(store) -> str:
        """One self-contained HTML status page (tables / segments /
        instances) — the operational core of the reference's React app."""
        from html import escape

        rows = []
        for t in store.table_names():
            ideal = store.get_ideal_state(t)
            ev = store.get_external_view(t)
            rows.append(f"<tr><td>{escape(t)}</td><td>{len(ideal)}</td>"
                        f"<td>{len(ev)}</td></tr>")
        inst = [f"<tr><td>{escape(i.instance_id)}</td>"
                f"<td>{escape(i.instance_type)}</td>"
                f"<td>{'up' if i.alive else 'DOWN'}</td>"
                f"<td>{escape(', '.join(i.tags))}</td></tr>"
                for i in store.instances()]
        return ("<!doctype html><title>pinot-tpu</title>"
                "<style>body{font-family:sans-serif;margin:2em}"
                "table{border-collapse:collapse;margin:1em 0}"
                "td,th{border:1px solid #ccc;padding:4px 10px}</style>"
                "<h1>pinot-tpu cluster</h1>"
                "<h2>Tables</h2><table><tr><th>table</th><th>segments "
                "(ideal)</th><th>segments (serving)</th></tr>"
                + "".join(rows) + "</table>"
                "<h2>Instances</h2><table><tr><th>id</th><th>type</th>"
                "<th>state</th><th>tags</th></tr>"
                + "".join(inst) + "</table>")

    @staticmethod
    def _add_schema(c, body) -> Dict[str, Any]:
        schema = Schema.from_dict(body)
        c.add_schema(schema)
        return {"status": f"{schema.schema_name} successfully added"}

    @staticmethod
    def _get_schema(store, name):
        s = store.get_schema(name)
        return (404, {"error": f"schema {name} not found"}) if s is None \
            else (200, s.to_dict())

    @staticmethod
    def _add_table(c, body) -> Dict[str, Any]:
        cfg = TableConfig.from_dict(body)
        c.add_table(cfg)
        return {"status": f"Table {cfg.table_name_with_type} successfully "
                          "added"}

    @staticmethod
    def _delete_table(c, name) -> Dict[str, Any]:
        c.delete_table(name)
        return {"status": f"Table deleted {name}"}

    @staticmethod
    def _update_table(c, url_name: str, body):
        cfg = TableConfig.from_dict(body)
        # URL and body must agree (ref: PinotTableRestletResource rejects
        # the mismatch) — a stale body must not overwrite another table
        if url_name not in (cfg.table_name, cfg.table_name_with_type):
            return (400, {"error": f"table name {url_name!r} in the URL "
                                   f"does not match the body "
                                   f"({cfg.table_name_with_type})"})
        c.update_table(cfg)
        return (200, {"status": f"Table config updated for "
                                f"{cfg.table_name_with_type}"})

    @staticmethod
    def _reload(c, table) -> Dict[str, Any]:
        c.reload_table(table)
        return {"status": f"Submitted reload for table: {table}"}

    @staticmethod
    def _add_segment(c, body) -> Dict[str, Any]:
        from pinot_tpu.segment.immutable import load_segment

        table = body["tableName"]
        seg_dir = body["segmentDir"]
        md = load_segment(seg_dir).metadata
        c.add_segment(table, md, f"file://{seg_dir}")
        return {"status": f"Successfully uploaded segment: "
                          f"{md.segment_name} of table: {table}"}


class BrokerApi(_Api):
    """Ref: broker api/resources PinotClientRequest — POST /query/sql."""

    def __init__(self, broker, port: int = 0, access_control=None):
        super().__init__(port, access_control=access_control)

        def query(m, body):
            from pinot_tpu.broker.broker import ACCESS_DENIED_ERROR

            sql = (body or {}).get("sql", "")
            # per-table authorization happens INSIDE the broker on the
            # parsed query (and on every IN_SUBQUERY inner query) — a raw
            # regex over the SQL is spoofable via string literals
            resp = broker.handle_sql(sql,
                                     principal=self.current_principal(),
                                     access_control=self.access_control)
            denied = any(e.get("errorCode") == ACCESS_DENIED_ERROR
                         for e in resp.exceptions)
            return (403 if denied else 200), resp.to_dict()

        self.route("POST", r"/query/sql", query)
        self.route("GET", r"/health", lambda m, b: (200, {"status": "OK"}))
        self._broker = broker
        self.route("GET", r"/metrics",
                   lambda m, b: (200, broker.metrics.export_prometheus()))
        def debug_routing(m, b):
            """The routing snapshot + scatter accounting for one table:
            which servers would be scattered to, what's unavailable, and
            the segment counts behind the prune ratio (the ops view of
            the partition/time metadata pushed into the routing table)."""
            res = broker.routing.route(m.group(1))
            return 200, {
                "routing": dict(res.routing),
                "unavailable": list(res.unavailable),
                "segmentsTotal": res.segments_total,
                "segmentsRouted": res.segments_routed,
                "timePruned": res.time_pruned,
                "partitionPruned": res.partition_pruned,
                "serversRouted": res.servers_routed,
            }

        self.route("GET", r"/debug/routing/([^/]+)", debug_routing)
        # single-flight coalescing + front-door admission counters
        # (broker half of the scheduler-tier ops view)
        self.route("GET", r"/debug/scheduler",
                   lambda m, b: (200, broker.scheduler_snapshot()))
        # continuous telemetry: windowed (table, phase) histograms with
        # sliding p50/p95/p99 + gauge-history rings
        self.route("GET", r"/debug/telemetry",
                   lambda m, b: (200, broker.telemetry_snapshot()))
        # per-table SLO objectives + multi-window burn rates
        self.route("GET", r"/debug/slo",
                   lambda m, b: (200, broker.slo_snapshot()))
        # ingest-to-queryable freshness histograms + objective burn
        self.route("GET", r"/debug/freshness",
                   lambda m, b: (200, broker.freshness_snapshot()))
        # the flight recorder's bundle index + last post-mortem bundle
        self.route("GET", r"/debug/flightrecorder",
                   lambda m, b: (200, broker.flightrecorder_snapshot()))

    def start(self) -> None:
        super().start()
        # advertise this broker in cluster state so dynamic broker
        # selectors can discover it (ref: brokers register their query
        # endpoint in ZK; DynamicBrokerSelector watches that list)
        store = getattr(self._broker, "store", None)
        if store is not None:
            from pinot_tpu.controller.state import InstanceInfo

            self._instance_id = f"Broker_localhost_{self.port}"
            store.register_instance(InstanceInfo(
                self._instance_id, "BROKER",
                host="localhost", port=self.port))

    def stop(self) -> None:
        # deregister LOUDLY: an ephemeral-port restart would otherwise
        # accumulate alive=True ghosts that selectors dial and the query
        # quota divides by (the ZK ephemeral-znode-expiry analogue)
        store = getattr(self._broker, "store", None)
        iid = getattr(self, "_instance_id", None)
        if store is not None and iid is not None:
            store.set_instance_alive(iid, False)
        super().stop()


def serve_cluster(cluster, controller_port: int = 0, broker_port: int = 0,
                  access_control=None):
    """Expose an EmbeddedCluster over REST: controller admin + broker query
    endpoints (ref: QuickstartRunner wiring the role REST apps). Returns
    the started APIs; call ``.stop()`` on each to tear down."""
    apis = [ControllerApi(cluster.controller, port=controller_port,
                          access_control=access_control),
            BrokerApi(cluster.broker, port=broker_port,
                      access_control=access_control)]
    for api in apis:
        api.start()
    return apis


class ServerAdminApi(_Api):
    """Ref: server api/resources TablesResource (health + hosted state)."""

    def __init__(self, server_instance, port: int = 0,
                 access_control=None):
        super().__init__(port, access_control=access_control)
        s = server_instance
        self.route("GET", r"/health", lambda m, b: (200, {"status": "OK"}))
        self.route("GET", r"/metrics",
                   lambda m, b: (200, s.metrics.export_prometheus()))
        self.route("GET", r"/tables",
                   lambda m, b: (200, {"tables": s.hosted_tables()}))
        self.route("GET", r"/tables/([^/]+)/segments",
                   lambda m, b: (200, {m.group(1):
                                       s.hosted_segments(m.group(1))}))
        # ref: TableSizeResource / MmapDebugResource
        self.route("GET", r"/tables/([^/]+)/size",
                   lambda m, b: (200, s.table_size(m.group(1))))
        self.route("GET", r"/debug/memory",
                   lambda m, b: (200, s.memory_debug()))
        # launch-coalescing counters (requests vs device launches, batch
        # sizes, queue waits) — the QPS-scaling ops view
        self.route("GET", r"/debug/launches",
                   lambda m, b: (200, s.launch_debug()))
        # scheduler-tier snapshot: dispatch policy + queue depth, admission
        # bounds/rejections, adaptive launch window, kernel single-flight
        self.route("GET", r"/debug/scheduler",
                   lambda m, b: (200, s.scheduler_debug()))
        # query lifecycle registry: running queries (id/sql/phase/elapsed/
        # pins), completed ring buffer, and the slow-query log with
        # retained span trees (pinot.server.query.slow.threshold.ms)
        self.route("GET", r"/debug/queries",
                   lambda m, b: (200, s.queries_debug()))
        # continuous telemetry: sliding-percentile (table, phase) latency
        # histograms + the gauge-history rings behind the instant gauges
        self.route("GET", r"/debug/telemetry",
                   lambda m, b: (200, s.telemetry_debug()))
        # per-table SLO burn rates (objectives from pinot.broker.slo.*)
        self.route("GET", r"/debug/slo",
                   lambda m, b: (200, s.slo_debug()))
        # per-table ingest-to-queryable freshness (realtime tables)
        self.route("GET", r"/debug/freshness",
                   lambda m, b: (200, s.freshness_debug()))
        # anomaly-triggered flight recorder: post-mortem bundle index +
        # the last frozen bundle (span roots, decision deltas, snapshots)
        self.route("GET", r"/debug/flightrecorder",
                   lambda m, b: (200, s.flightrecorder_debug()))
        # per-shape pallas blocklist (runtime failures + preflight-seeded
        # predictions, each with its decline reason) + the last kernel
        # preflight verdict table (tools/preflight.py)
        self.route("GET", r"/debug/pallas",
                   lambda m, b: (200, s.pallas_debug()))
        # ops hook for the HBM budget knob: force-drop one resident's
        # device arrays (in-flight queries keep theirs via python refs;
        # the next query re-stages)
        self.route("POST", r"/debug/memory/evict/([^/]+)",
                   lambda m, b: (200, s.evict_staged(m.group(1))))
        # tiered-residency sibling: force-demote one resident to the
        # host-RAM tier (next query promotes with a plain H2D instead of
        # a rebuild); /debug/memory reports both tiers' byte accounting
        self.route("POST", r"/debug/memory/demote/([^/]+)",
                   lambda m, b: (200, s.demote_staged(m.group(1))))
