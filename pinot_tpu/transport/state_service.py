"""Remote cluster state store: HTTP service + replicating client.

The multi-host control plane (SURVEY §5 "distributed comm backend"): the
reference coordinates roles through ZooKeeper; here the controller hosts
the authoritative :class:`ClusterStateStore` and exposes its primitives
over HTTP, while remote brokers/servers run a
:class:`RemoteClusterStateStore` — a full local REPLICA synced by a
poller thread (the store is metadata-sized), so every read is local and
watch callbacks fire exactly like the in-process store's (the ZK
spectator-callback property). Writes go to the authority: plain sets
directly, read-modify-writes as CAS retry loops
(``ClusterStateStore.compare_and_set``, the setData-with-version
analogue).

Replica catch-up rides the store's bounded mutation log
(``mutations_since``); a client that falls off the log's tail does one
full resync (``snapshot_data``), mirroring ZK's snapshot+txn-log recovery.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.request

from typing import Any, Callable, List, Optional

from pinot_tpu.controller.state import ClusterStateStore
from pinot_tpu.transport.rest import _Api

log = logging.getLogger(__name__)


class StateStoreApi(_Api):
    """HTTP face of the authoritative store (runs next to the controller)."""

    def __init__(self, store: ClusterStateStore, port: int = 0,
                 access_control=None):
        super().__init__(port, access_control=access_control)
        s = store

        self.route("GET", r"/health", lambda m, b: (200, {"status": "OK"}))
        self.route("POST", r"/state/get",
                   lambda m, b: (200, {"value": s.get(b["path"])}))
        self.route("POST", r"/state/set",
                   lambda m, b: (200, {"version":
                                       s.set(b["path"], b["value"])}))
        self.route("POST", r"/state/cas",
                   lambda m, b: (200, {"ok": s.compare_and_set(
                       b["path"], b.get("expected"), b["value"])}))
        self.route("POST", r"/state/delete",
                   lambda m, b: (200, {"ok": s.delete(b["path"]) or True}))
        self.route("POST", r"/state/poll",
                   lambda m, b: (200, self._poll(s, b)))

    @staticmethod
    def _poll(s: ClusterStateStore, body):
        since = int((body or {}).get("sinceVersion", -1))
        version, muts = s.mutations_since(since)
        if muts is None:  # log doesn't reach back: ship the full snapshot
            version, data = s.snapshot_data()
            return {"version": version, "snapshot": data}
        return {"version": version,
                "mutations": [{"v": v, "path": p, "value": val}
                              for v, p, val in muts]}


class RemoteClusterStateStore(ClusterStateStore):
    """Replica store for remote roles. Same interface as the in-process
    store; reads are local, writes remote, watches fire from the poller."""

    def __init__(self, base_url: str, poll_interval_s: float = 0.05,
                 timeout_s: float = 30.0):
        super().__init__(snapshot_path=None)
        # poller and reconnect race on these; pre-lock snapshot reads are
        # part of the epoch protocol, so only writes must hold the lock
        self._base = base_url.rstrip("/")  # guarded-by-writes: _lock
        self._timeout = timeout_s
        self._poll_interval = poll_interval_s
        self._remote_version = -1  # guarded-by-writes: _lock
        self._epoch = 0  # guarded-by-writes: _lock
        self._stop = threading.Event()
        self._sync_once()  # fail fast if the authority is unreachable
        self._poller = threading.Thread(target=self._poll_loop, daemon=True,
                                        name="state-replica-poller")
        self._poller.start()

    # -- transport ----------------------------------------------------------
    def _call(self, endpoint: str, body: dict) -> dict:
        req = urllib.request.Request(
            f"{self._base}{endpoint}",
            data=json.dumps(body).encode("utf-8"),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self._timeout) as r:
            return json.loads(r.read().decode("utf-8"))

    # -- replica sync --------------------------------------------------------
    def _sync_once(self) -> None:
        epoch = self._epoch
        out = self._call("/state/poll",
                         {"sinceVersion": self._remote_version})
        if "snapshot" in out:
            with self._lock:
                if epoch != self._epoch:
                    return  # reconnect raced: discard the stale reply
                removed = [k for k in self._data if k not in out["snapshot"]]
                self._data = out["snapshot"]
                self._version = max(self._version, int(out["version"]))
                paths = list(self._data.items())
                # full resync can't replay per-path events: fire for every
                # present path AND a deletion event per vanished path, so
                # prefix watchers (lineage caches etc.) never miss a delete
                for p in removed:
                    self._pending.append((p, None))
                for p, v in paths:
                    self._pending.append((p, self._copy(v)))
            self._drain_notifications()
        else:
            muts = out.get("mutations", [])
            if muts:
                with self._lock:
                    if epoch != self._epoch:
                        return
                    for m in muts:
                        if m["value"] is None:
                            self._data.pop(m["path"], None)
                        else:
                            self._data[m["path"]] = m["value"]
                        self._pending.append((m["path"], m["value"]))
                    self._version = max(self._version, int(out["version"]))
                self._drain_notifications()
            else:
                with self._lock:
                    if epoch != self._epoch:
                        return
                    self._version = max(self._version, int(out["version"]))
        with self._lock:
            if epoch != self._epoch:
                return  # reconnect raced: keep the forced -1 resync marker
            self._remote_version = out["version"]

    def _poll_loop(self) -> None:
        while not self._stop.wait(self._poll_interval):
            try:
                self._sync_once()
            except Exception:
                log.warning("state replica poll failed; retrying",
                            exc_info=True)

    def reconnect(self, base_url: str) -> None:
        """Point the replica at a restarted/relocated authority (the ZK
        reconnect analogue) and force a FULL resync: the new authority's
        version counter may be behind ours (restart from an older
        snapshot), and mutations_since would otherwise report 'up to
        date' forever. The epoch guard stops an in-flight poll against
        the old authority from clobbering the reset."""
        with self._lock:
            self._epoch += 1
            self._base = base_url.rstrip("/")
            self._remote_version = -1

    def close(self) -> None:
        self._stop.set()

    # -- write path: remote authority ---------------------------------------
    def set(self, path: str, value: Any) -> int:
        out = self._call("/state/set", {"path": path, "value": value})
        # apply locally right away: the caller's next read must see its own
        # write (the poller would get there, but not synchronously)
        with self._lock:
            self._data[path] = self._copy(value)
            self._version = max(self._version, int(out["version"]))
        return int(out["version"])

    def update(self, path: str, fn: Callable[[Any], Any],
               default: Any = None) -> Any:
        for _ in range(64):
            cur = self._call("/state/get", {"path": path})["value"]
            base = cur if cur is not None else default
            new = fn(self._copy(base))
            if self._call("/state/cas", {"path": path, "expected": cur,
                                         "value": new})["ok"]:
                with self._lock:
                    self._data[path] = self._copy(new)
                return new
        raise RuntimeError(f"CAS contention on {path!r} (64 attempts)")

    def compare_and_set(self, path: str, expected: Any, value: Any) -> bool:
        ok = bool(self._call("/state/cas", {
            "path": path, "expected": expected, "value": value})["ok"])
        if ok:
            with self._lock:
                self._data[path] = self._copy(value)
        return ok

    def delete(self, path: str) -> None:
        self._call("/state/delete", {"path": path})
        with self._lock:
            self._data.pop(path, None)
