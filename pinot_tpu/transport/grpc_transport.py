"""gRPC query transport: broker <-> server over the network.

Re-design of the reference's query RPC layer (Netty + thrift
``InstanceRequest`` at ``transport/QueryServer.java:46`` /
``ServerChannels.java:55``, and the gRPC alternative
``transport/grpc/GrpcQueryServer.java:45`` with
``pinot-common/src/main/proto/server.proto``): a single unary method
carrying a JSON-framed InstanceRequest (compiled QueryContext + table +
segment list) and returning DataTable bytes. Generic bytes-in/bytes-out
method handlers keep the wire layer free of generated stubs (no
grpcio-tools in the image); the payload framing is the versioned contract.

Multi-host note: this is the DCN leg of the design (SURVEY.md §2.12) —
broker scatter/gather rides gRPC across hosts, while the intra-host
multi-chip combine rides ICI collectives inside the sharded executor.
"""

from __future__ import annotations

import json
import logging

from concurrent import futures
from typing import List, Optional

import grpc

from pinot_tpu.common.datatable import DataTable
from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.serde import context_from_dict, context_to_dict

log = logging.getLogger(__name__)

_SERVICE = "pinot_tpu.QueryServer"
_METHOD_EXECUTE = f"/{_SERVICE}/Execute"
_METHOD_EXECUTE_STREAMING = f"/{_SERVICE}/ExecuteStreaming"


def _encode_request(ctx: QueryContext, table: str,
                    segments: Optional[List[str]]) -> bytes:
    return json.dumps({
        "version": 1,
        "context": context_to_dict(ctx),
        "table": table,
        "segments": segments,
    }).encode("utf-8")


def _decode_request(raw: bytes):
    d = json.loads(raw.decode("utf-8"))
    return context_from_dict(d["context"]), d["table"], d.get("segments")


class GrpcQueryServer:
    """Network front of one ServerInstance
    (ref: GrpcQueryServer.java:45 submit:84). ``Execute`` is the unary
    whole-result method; ``ExecuteStreaming`` streams per-segment blocks
    for selection queries (ref: the streaming operators under
    ``operator/streaming/*`` feeding GrpcQueryServer) so the broker can
    short-circuit LIMIT without waiting for every segment."""

    def __init__(self, server_instance, port: int = 0, max_workers: int = 8):
        self._instance = server_instance
        self._grpc = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        handler = grpc.method_handlers_generic_handler(_SERVICE, {
            "Execute": grpc.unary_unary_rpc_method_handler(
                self._execute,
                request_deserializer=None,
                response_serializer=None),
            "ExecuteStreaming": grpc.unary_stream_rpc_method_handler(
                self._execute_streaming,
                request_deserializer=None,
                response_serializer=None),
        })
        self._grpc.add_generic_rpc_handlers((handler,))
        self.port = self._grpc.add_insecure_port(f"[::]:{port}")

    def _execute(self, request: bytes, context) -> bytes:
        try:
            ctx, table, segments = _decode_request(request)
            table_result = self._instance.execute_query(ctx, table, segments)
        except Exception as e:  # errors travel in the DataTable
            log.debug("grpc execute failed", exc_info=True)
            table_result = DataTable.for_exception(repr(e))
        return table_result.to_bytes()

    def _execute_streaming(self, request: bytes, context):
        """Yield one DataTable per block: selection queries stream a block
        PER SEGMENT (each block carries its own stats — unlike the
        reference's trailing-metadata framing, StreamingResponseUtils);
        other query shapes degrade to a single block (their combine is a
        reduction — there is nothing incremental to ship)."""
        try:
            ctx, table, segments = _decode_request(request)
            if not ctx.is_selection:
                yield self._instance.execute_query(
                    ctx, table, segments).to_bytes()
                return
            for block in self._instance.execute_query_streaming(
                    ctx, table, segments):
                yield block.to_bytes()
        except Exception as e:  # noqa: BLE001 — errors travel in-band
            log.debug("grpc streaming execute failed", exc_info=True)
            yield DataTable.for_exception(repr(e)).to_bytes()

    def start(self) -> None:
        self._grpc.start()

    def stop(self, grace: float = 5.0) -> None:
        self._grpc.stop(grace)


class GrpcServerStub:
    """Broker-side remote server handle — same ``execute_query`` surface as
    an in-process ServerInstance, so it registers with
    BrokerRequestHandler.register_server unchanged
    (ref: ServerChannels per-server connection + GrpcQueryClient.java:27)."""

    def __init__(self, address: str, timeout_s: float = 60.0):
        self.address = address
        self._channel = grpc.insecure_channel(address)
        self._call = self._channel.unary_unary(
            _METHOD_EXECUTE, request_serializer=None,
            response_deserializer=None)
        self._call_streaming = self._channel.unary_stream(
            _METHOD_EXECUTE_STREAMING, request_serializer=None,
            response_deserializer=None)
        self.timeout_s = timeout_s

    def execute_query(self, ctx: QueryContext, table: str,
                      segments: Optional[List[str]] = None) -> DataTable:
        try:
            raw = self._call(_encode_request(ctx, table, segments),
                             timeout=self.timeout_s)
            return DataTable.from_bytes(raw)
        except grpc.RpcError as e:
            return DataTable.for_exception(
                f"rpc to {self.address} failed: {e.code().name}")

    def execute_query_streaming(self, ctx: QueryContext, table: str,
                                segments: Optional[List[str]] = None):
        """Yield DataTable blocks as the server produces them
        (ref: GrpcQueryClient.submit returning a response iterator)."""
        try:
            for raw in self._call_streaming(
                    _encode_request(ctx, table, segments),
                    timeout=self.timeout_s):
                yield DataTable.from_bytes(raw)
        except grpc.RpcError as e:
            yield DataTable.for_exception(
                f"rpc to {self.address} failed: {e.code().name}")

    def close(self) -> None:
        self._channel.close()
