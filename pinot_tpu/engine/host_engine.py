"""Host (numpy) execution paths.

Covers what the device kernels don't: selection queries (pure data movement),
DISTINCT, and aggregation shapes outside the device planner's coverage
(exotic aggregations, expression group-bys, MV group-bys). Also the execution
path for host-resident (consuming) segments. Doubles as the oracle the device
kernels are tested against.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.engine.aggregates import AggDef, agg_value_expr, resolve_agg
from pinot_tpu.engine.errors import QueryError, UnsupportedQueryError
from pinot_tpu.engine.host_eval import eval_expr_values, eval_filter, read_values
from pinot_tpu.engine.results import (
    AggResult,
    DataSchema,
    GroupByResult,
    QueryStats,
    ResultTable,
)
from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import Expr, Function, Identifier, Literal
from pinot_tpu.segment.immutable import ImmutableSegment
from pinot_tpu.spi.data import Schema


# --------------------------------------------------------------------------
# column helpers
# --------------------------------------------------------------------------

def _expand_select(ctx: QueryContext, schema: Schema) -> List[Expr]:
    out: List[Expr] = []
    for e in ctx.select_expressions:
        if isinstance(e, Identifier) and e.name == "*":
            out.extend(Identifier(c) for c in schema.column_names)
        else:
            out.append(e)
    return out


def _column_type(segment: ImmutableSegment, e: Expr) -> str:
    if isinstance(e, Identifier) and e.name.startswith("$"):
        from pinot_tpu.engine.host_eval import VIRTUAL_COLUMNS

        return VIRTUAL_COLUMNS.get(e.name, "STRING")
    if isinstance(e, Identifier) and e.name in segment.metadata.columns:
        cm = segment.metadata.column(e.name)
        label = cm.data_type.label
        return label if cm.single_value else label + "_ARRAY"
    if isinstance(e, Literal):
        return "STRING" if isinstance(e.value, str) else "DOUBLE"
    return "DOUBLE"


def _select_values(segment: ImmutableSegment, e: Expr,
                   doc_ids: np.ndarray) -> List[Any]:
    if isinstance(e, Identifier):
        return read_values(segment, e.name, doc_ids)
    vals = eval_expr_values(segment, e, doc_ids)
    return [v.item() if hasattr(v, "item") else v for v in vals]


# --------------------------------------------------------------------------
# selection
# --------------------------------------------------------------------------

def execute_selection(ctx: QueryContext, segments: List[ImmutableSegment],
                      stats: Optional[QueryStats] = None) -> ResultTable:
    """Ref: SelectionOnlyOperator / SelectionOrderByOperator + reducer."""
    if not segments:
        raise QueryError("no segments to query")
    schema = segments[0].metadata.schema
    select = _expand_select(ctx, schema)
    names = _select_names(ctx, select)
    types = [_column_type(segments[0], e) for e in select]
    need = ctx.offset + ctx.limit

    if not ctx.order_by:
        rows: List[List[Any]] = []
        for seg in segments:
            if len(rows) >= need:
                break
            mask = eval_filter(seg, ctx.filter)
            _track(stats, seg, mask)
            doc_ids = np.nonzero(mask)[0][: need - len(rows)]
            if doc_ids.size == 0:
                continue
            cols = [_select_values(seg, e, doc_ids) for e in select]
            rows.extend([list(r) for r in zip(*cols)])
        return ResultTable(DataSchema(names, types),
                           rows[ctx.offset: ctx.offset + ctx.limit])

    # ordered selection: collect order keys from all segments, sort, gather
    candidates: List[Tuple[int, np.ndarray, List[np.ndarray]]] = []
    for si, seg in enumerate(segments):
        mask = eval_filter(seg, ctx.filter)
        _track(stats, seg, mask)
        doc_ids = np.nonzero(mask)[0]
        if doc_ids.size == 0:
            continue
        keys = [_order_key_array(seg, ob.expr, doc_ids) for ob in ctx.order_by]
        candidates.append((si, doc_ids, keys))
    if not candidates:
        return ResultTable(DataSchema(names, types), [])

    seg_idx = np.concatenate([np.full(len(d), si) for si, d, _ in candidates])
    docs = np.concatenate([d for _, d, _ in candidates])
    key_cols = []
    for ki in range(len(ctx.order_by)):
        key_cols.append(np.concatenate([k[ki] for _, _, k in candidates]))
    order = _lexsort(key_cols, [ob.ascending for ob in ctx.order_by])
    order = order[ctx.offset: ctx.offset + ctx.limit]

    rows = [None] * len(order)
    pos_of = {int(o): i for i, o in enumerate(order)}
    for si, seg in enumerate(segments):
        sel = [int(o) for o in order if seg_idx[o] == si]
        if not sel:
            continue
        doc_ids = docs[sel]
        cols = [_select_values(seg, e, doc_ids) for e in select]
        for j, o in enumerate(sel):
            rows[pos_of[o]] = [c[j] for c in cols]
    return ResultTable(DataSchema(names, types), rows)


def _select_names(ctx: QueryContext, select: List[Expr]) -> List[str]:
    # when '*' was expanded the aliases list no longer lines up; rebuild
    if len(select) == len(ctx.select_expressions):
        return [a if a else str(e) for e, a in zip(select, ctx.aliases)]
    return [str(e) for e in select]


def _order_key_array(segment: ImmutableSegment, e: Expr,
                     doc_ids: np.ndarray) -> np.ndarray:
    vals = eval_expr_values(segment, e, doc_ids)
    return np.asarray(vals)


def _lexsort(key_cols: List[np.ndarray], ascending: List[bool]) -> np.ndarray:
    """Stable multi-key sort with per-key direction (strings included —
    object AND unicode dtypes rank-encode so DESC can negate)."""
    processed = []
    for arr, asc in zip(key_cols, ascending):
        if arr.dtype == object or arr.dtype.kind in ("U", "S"):
            _, codes = np.unique(arr, return_inverse=True)
            arr = codes
        processed.append(arr if asc else _negate(arr))
    # np.lexsort sorts by last key first
    return np.lexsort(list(reversed(processed)))


def _negate(arr: np.ndarray) -> np.ndarray:
    if np.issubdtype(arr.dtype, np.integer):
        return -arr.astype(np.int64)
    return -arr.astype(np.float64)


def _track(stats: Optional[QueryStats], seg: ImmutableSegment,
           mask: np.ndarray) -> None:
    if stats is None:
        return
    matched = int(np.count_nonzero(mask))
    stats.num_segments_processed += 1
    stats.num_segments_matched += 1 if matched else 0
    stats.num_docs_scanned += matched
    stats.total_docs += seg.num_docs


# --------------------------------------------------------------------------
# distinct
# --------------------------------------------------------------------------

def execute_distinct(ctx: QueryContext, segments: List[ImmutableSegment],
                     stats: Optional[QueryStats] = None) -> ResultTable:
    """Ref: DistinctOperator + DistinctDataTableReducer."""
    from pinot_tpu.common.tracing import maybe_span

    with maybe_span(stats, "HostDistinct", segments=len(segments)):
        return _execute_distinct(ctx, segments, stats)


def _execute_distinct(ctx: QueryContext, segments: List[ImmutableSegment],
                      stats: Optional[QueryStats] = None) -> ResultTable:
    schema = segments[0].metadata.schema
    select = _expand_select(ctx, schema)
    names = _select_names(ctx, select)
    types = [_column_type(segments[0], e) for e in select]
    seen: Dict[Tuple, List[Any]] = {}
    for seg in segments:
        mask = eval_filter(seg, ctx.filter)
        _track(stats, seg, mask)
        doc_ids = np.nonzero(mask)[0]
        if doc_ids.size == 0:
            continue
        cols = [_select_values(seg, e, doc_ids) for e in select]
        for r in zip(*cols):
            key = tuple(tuple(v) if isinstance(v, list) else v for v in r)
            if key not in seen:
                seen[key] = list(r)
    rows = list(seen.values())
    if ctx.having is not None:
        # GROUP BY without aggregations converts to DISTINCT (context.py);
        # its HAVING filters on the group expressions, evaluated per row
        from pinot_tpu.engine.results import _eval_scalar_filter
        keys = [str(e) for e in select]
        rows = [r for r in rows
                if _eval_scalar_filter(ctx.having, dict(zip(keys, r)))]
    if ctx.order_by:
        idx_of = {str(e): i for i, e in enumerate(select)}
        def sort_key(row):
            parts = []
            for ob in ctx.order_by:
                i = idx_of.get(str(ob.expr))
                if i is None:
                    raise QueryError(f"ORDER BY {ob.expr} not in DISTINCT list")
                from pinot_tpu.engine.results import _Reversible
                parts.append(_Reversible(row[i], ob.ascending))
            return tuple(parts)
        rows.sort(key=sort_key)
    return ResultTable(DataSchema(names, types),
                       rows[ctx.offset: ctx.offset + ctx.limit])


# --------------------------------------------------------------------------
# aggregation fallback (host)
# --------------------------------------------------------------------------

def _agg_input_values(segment: ImmutableSegment, agg: AggDef, fn: Function,
                      mask: np.ndarray):
    vexpr = agg_value_expr(fn)
    if vexpr is None:
        return np.zeros(segment.num_docs)  # COUNT(*): values unused
    if agg.base in ("lastwithtime", "firstwithtime"):
        # (valueColumn, timeColumn, 'dataType'): evaluate both columns
        vals = eval_expr_values(segment, vexpr)
        times = eval_expr_values(segment, fn.args[1])
        return (vals, times)
    if agg.mv:
        if not isinstance(vexpr, Identifier):
            raise UnsupportedQueryError("MV aggregation argument must be a column")
        ds = segment.data_source(vexpr.name)
        offsets = np.asarray(ds.mv_offsets)
        d = ds.dictionary
        dv = np.asarray(d.device_values()) if d and d.device_values() is not None else None
        flat = np.asarray(ds.forward_index)
        out = []
        for i in range(segment.num_docs):
            ids = flat[offsets[i]:offsets[i + 1]]
            if dv is not None:
                out.append(dv[ids])
            else:
                out.append(np.array(d.get_values(ids), dtype=object))
        return out
    vals = eval_expr_values(segment, vexpr)
    return vals


def host_aggregate_segment(ctx: QueryContext, aggs: List[AggDef],
                           segment: ImmutableSegment,
                           stats: Optional[QueryStats] = None) -> AggResult:
    mask = eval_filter(segment, ctx.filter)
    _track(stats, segment, mask)
    states = []
    for agg, fn in zip(aggs, ctx.aggregations):
        vals = _agg_input_values(segment, agg, fn, mask)
        states.append(agg.compute_host(vals, mask))
    return AggResult(states)


def _group_value_array(segment: ImmutableSegment, e: Expr) -> np.ndarray:
    vals = eval_expr_values(segment, e)
    return np.asarray(vals)


def host_group_by_segment(ctx: QueryContext, aggs: List[AggDef],
                          segment: ImmutableSegment,
                          stats: Optional[QueryStats] = None) -> GroupByResult:
    mask = eval_filter(segment, ctx.filter)
    _track(stats, segment, mask)
    filtered = np.nonzero(mask)[0]
    result = GroupByResult()
    if filtered.size == 0:
        return result

    # composed group codes over filtered docs
    from pinot_tpu.engine.groupkeys import compose_group_keys

    key_values: List[np.ndarray] = []
    codes_list: List[np.ndarray] = []
    for e in ctx.group_by:
        arr = _group_value_array(segment, e)[filtered]
        uniq, codes = np.unique(arr, return_inverse=True)
        key_values.append(uniq)
        codes_list.append(codes)
    uniq_keys, gid, decode_codes = compose_group_keys(
        codes_list, [max(len(u), 1) for u in key_values])

    keys = [tuple(_py(u[c]) for u, c in zip(key_values, decode_codes(int(k))))
            for k in uniq_keys]

    order = np.argsort(gid, kind="stable")
    boundaries = np.searchsorted(gid[order], np.arange(len(uniq_keys) + 1))

    for agg, fn in zip(aggs, ctx.aggregations):
        vals = _agg_input_values(segment, agg, fn, mask)
        for g in range(len(uniq_keys)):
            idx = filtered[order[boundaries[g]:boundaries[g + 1]]]
            sub_mask = np.ones(len(idx), dtype=bool)
            if agg.mv:
                sub_vals = [vals[i] for i in idx]
            elif agg.base in ("lastwithtime", "firstwithtime"):
                v, t = vals  # (value array/list, time array) pair
                sub_vals = (np.asarray(v, dtype=object)[idx]
                            if isinstance(v, list) else np.asarray(v)[idx],
                            np.asarray(t)[idx])
            else:
                sub_vals = np.asarray(vals)[idx]
            st = agg.compute_host(sub_vals, sub_mask)
            result.groups.setdefault(keys[g], []).append(st)
    return result


def _py(v: Any) -> Any:
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        return float(v)
    if isinstance(v, np.str_):
        return str(v)
    return v
