"""Index rung: selective conjunctive filters served by a device docId gather.

The re-design of the reference's index-based filter operators
(``BitmapBasedFilterOperator`` over ``BitmapInvertedIndexReader.java:34``,
``SortedIndexBasedFilterOperator`` over the sorted forward index,
``RangeIndexBasedFilterOperator`` over ``BitSlicedRangeIndexReader``) for the
gather-then-kernel shape PR-6 proved out for star-tree node slices:

1. HOST resolves the matching docIds — sorted-postings decode + union for
   EQ/IN over inverted columns, binary search over the sorted forward index
   or the range-index permutation, ``np.intersect1d`` across the AND
   conjuncts, shortest list first. All vectorized numpy; no per-doc Python.
2. The docIds pad to a power-of-two capacity and ride to the device as ONE
   compact int32 array; the SAME jitted gather kernel the star-tree rung
   uses (``startree_device.build_startree_kernel``) gathers the staged
   group/value columns down to the slice and runs ``build_kernel_body``
   over the gathered block — dense/hash/sort rung selection, packed-output
   framing, and group decode all apply unchanged, so results are
   bit-identical to the full scan with ``num_docs_scanned`` = matched rows.
3. Rung selection is cost-based and runs BEFORE any posting list is
   decoded: exact per-predicate match counts come from the inverted
   index's doc-count offsets (``offsets[id+1]-offsets[id]``), from binary
   search over the sorted forward index, or from the range permutation's
   interval width. Estimates over ``SELECTIVITY_THRESHOLD`` of the table
   decline to the scan rungs — a broad filter gathers most of the table
   and the scan kernel wins.

Every outcome lands in the decision ledger under the ``index`` point
(``tracing.INDEX_DECISION_REASONS``); the gathered idx arrays are
residency-accounted and lease-pinned on the segment's resident
(``StagedSegment.index_slice``) so eviction/spill semantics compose
unchanged.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import numpy as np

from pinot_tpu.common.tracing import maybe_span, record_decision
from pinot_tpu.engine.aggregates import AggDef
from pinot_tpu.engine.plan import (
    PlanError,
    SegmentPlan,
    _next_pow2,
    expected_param_count,
)
from pinot_tpu.engine.results import QueryStats
from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import Identifier, Predicate, PredicateType

# fraction of the table above which an estimated match count declines to the
# scan rungs: past this the gather reads most of the table anyway and the
# scan kernel's streaming access pattern wins (the FilterOperatorUtils
# bitmap-vs-scan selection heuristic, recast as a device rung gate)
SELECTIVITY_THRESHOLD = 0.05

# cap on per-dictId python-level iterations (posting-list decodes / interval
# slices). Contiguous dictId runs never hit this — they resolve as one
# interval; only scattered huge id sets bail, and those are broad filters
# the threshold gate should have declined anyway.
_MAX_ID_LISTS = 1024

_MIN_CAPACITY = 128

_EMPTY = np.empty(0, dtype=np.int64)


def build_gather_kernel(spec):
    """Jitted ``fn(cols, idx, params, num_docs) -> packed f64 vector``:
    gathers each staged column's ROW-shaped arrays (fwd/mv/mvcount/null)
    down to the ``idx`` slice and runs the standard kernel body over the
    gathered block. ``dictvals`` stays un-gathered — it is dictId-shaped
    (the body indexes it BY the gathered fwd dictIds), which is exactly why
    the star-tree gather kernel (fwd-only trees) can't serve here."""
    import jax
    import jax.numpy as jnp

    from pinot_tpu.engine.kernels import (
        build_kernel_body,
        pack_outputs,
        sparse_mode,
    )

    body = build_kernel_body(spec, sparse_k=sparse_mode(spec))

    def kernel(cols, idx, params, num_docs):
        gathered = {name: {k: (v if k == "dictvals" else v[idx])
                           for k, v in tree.items()}
                    for name, tree in cols.items()}
        return pack_outputs(body(gathered, params, num_docs, jnp.int32(0)),
                            spec)

    return jax.jit(kernel)


def _decline(stats: Optional[QueryStats], reason: str) -> None:
    record_decision(stats, "index", "scan", "index_gather", reason)


def _chose(stats: Optional[QueryStats], reason: str) -> None:
    record_decision(stats, "index", "index_gather", "scan", reason)


class _Decline(Exception):
    """Internal control flow: predicate routing hit an ineligible shape."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class _Route:
    """One predicate's index path: an exact match-count estimate computed
    WITHOUT decoding postings, and a resolver producing the sorted unique
    int64 docId array when the cost gate passes."""

    __slots__ = ("estimate", "resolve")

    def __init__(self, estimate: int, resolve: Callable[[], np.ndarray]):
        self.estimate = estimate
        self.resolve = resolve


def _postings_route(ds, cm, ids: np.ndarray) -> _Route:
    """EQ/IN/RANGE over an inverted-indexed dictionary column: match count
    from the doc-count offsets, docIds from varint posting decode + union."""
    if ids.size > _MAX_ID_LISTS:
        raise _Decline("index_selectivity_over_threshold")
    offsets = np.asarray(ds.inverted_index[0])
    est = int((offsets[ids + 1] - offsets[ids]).sum()) if ids.size else 0
    multi_value = not cm.single_value

    def resolve() -> np.ndarray:
        if ids.size == 0:
            return _EMPTY
        parts = [ds.doc_ids_for_dict_id(int(i)) for i in ids]
        docs = parts[0] if len(parts) == 1 else np.concatenate(parts)
        docs = docs.astype(np.int64, copy=False)
        if multi_value:
            # an MV doc may repeat a value within a row and postings of
            # different dictIds share docs — union, not concatenation
            return np.unique(docs)
        return docs if len(parts) == 1 else np.sort(docs)

    return _Route(est, resolve)


def _sorted_route(ds, ids: np.ndarray, num_docs: int) -> _Route:
    """Sorted dictionary column: dictIds map to contiguous docId runs, so
    matches are binary searches over the forward index — the sorted-column
    analogue of SortedIndexReader's docId ranges."""
    if ids.size == 0:
        return _Route(0, lambda: _EMPTY)
    fwd = np.asarray(ds.forward_index[:num_docs])
    if int(ids[-1] - ids[0]) + 1 == ids.size:  # contiguous dictId interval
        lo = int(np.searchsorted(fwd, ids[0], side="left"))
        hi = int(np.searchsorted(fwd, ids[-1], side="right"))
        return _Route(hi - lo, lambda: np.arange(lo, hi, dtype=np.int64))
    if ids.size > _MAX_ID_LISTS:
        raise _Decline("index_selectivity_over_threshold")
    los = np.searchsorted(fwd, ids, side="left")
    his = np.searchsorted(fwd, ids, side="right")
    est = int((his - los).sum())

    def resolve() -> np.ndarray:
        parts = [np.arange(lo, hi, dtype=np.int64)
                 for lo, hi in zip(los.tolist(), his.tolist()) if hi > lo]
        if not parts:
            return _EMPTY
        return parts[0] if len(parts) == 1 else np.concatenate(parts)

    return _Route(est, resolve)


def _range_route(ds, cm, pred: Predicate, num_docs: int) -> _Route:
    """RANGE (or EQ, as a degenerate [v, v] range) over a range-indexed RAW
    column: binary search on the values-in-sorted-order array, slice of the
    sorted-order permutation (the host mask path's ``_range_index_mask``,
    producing docIds instead of a mask)."""
    sorted_vals = ds.range_sorted_values
    dt = cm.data_type
    lo_i, hi_i = 0, num_docs
    if pred.type is PredicateType.EQ:
        v = dt.convert(pred.value)
        lo_i = int(np.searchsorted(sorted_vals, v, side="left"))
        hi_i = int(np.searchsorted(sorted_vals, v, side="right"))
    else:
        if pred.lower is not None:
            v = dt.convert(pred.lower)
            side = "left" if pred.lower_inclusive else "right"
            lo_i = int(np.searchsorted(sorted_vals, v, side=side))
        if pred.upper is not None:
            v = dt.convert(pred.upper)
            side = "right" if pred.upper_inclusive else "left"
            hi_i = int(np.searchsorted(sorted_vals, v, side=side))
    est = max(0, hi_i - lo_i)
    order = ds.range_order

    def resolve() -> np.ndarray:
        if hi_i <= lo_i:
            return _EMPTY
        return np.sort(np.asarray(order[lo_i:hi_i]).astype(np.int64))

    return _Route(est, resolve)


def _pred_route(segment, pred: Predicate, num_docs: int) -> _Route:
    """Predicate -> index route, or raise _Decline with the ledger code."""
    lhs = pred.lhs
    if not isinstance(lhs, Identifier) or lhs.name.startswith("$"):
        raise _Decline("index_filter_shape")
    if pred.type not in (PredicateType.EQ, PredicateType.IN,
                         PredicateType.RANGE):
        raise _Decline("index_pred_type_unsupported")
    ds = segment.data_source(lhs.name)
    cm = ds.metadata
    if cm.has_dictionary:
        from pinot_tpu.engine.host_eval import _matching_dict_ids

        ids = _matching_dict_ids(ds, pred)
        if cm.single_value and cm.is_sorted:
            return _sorted_route(ds, ids, num_docs)
        if cm.has_inverted_index:
            return _postings_route(ds, cm, ids)
        raise _Decline("index_missing_index")
    if (cm.single_value
            and pred.type in (PredicateType.EQ, PredicateType.RANGE)
            and getattr(ds, "range_order", None) is not None):
        return _range_route(ds, cm, pred, num_docs)
    raise _Decline("index_missing_index")


def resolve_doc_ids(segment, preds: List[Predicate], num_docs: int,
                    threshold: int) -> Optional[np.ndarray]:
    """Conjunction -> sorted unique int64 docIds, or None past the cost
    gate (raises _Decline for ineligible shapes). The gate runs on exact
    per-predicate counts BEFORE any posting list decodes; resolution then
    intersects shortest-first so the working set never exceeds the most
    selective predicate's match count."""
    routes = [_pred_route(segment, p, num_docs) for p in preds]
    if min(r.estimate for r in routes) > threshold:
        return None
    routes.sort(key=lambda r: r.estimate)
    idx = routes[0].resolve()
    for r in routes[1:]:
        if idx.size == 0:
            break
        idx = np.intersect1d(idx, r.resolve(), assume_unique=True)
    return idx


def gather_plan(full: SegmentPlan, n: int) -> SegmentPlan:
    """The gathered-block plan derived from the scan plan: the filter spec
    collapses to ``("true",)`` (every gathered row satisfied it on the
    host), capacity re-sizes to the idx array's power-of-two pad, and the
    filter's leading params drop — ``plan_segment`` packs params in filter
    -> group -> agg order, so the tail is exactly the group strides/bases
    (KEEPING any filter-narrowed dictId bases: gathered rows satisfy the
    very conjuncts the narrowing came from) plus the agg params."""
    spec = full.spec
    stripped = (("true",), spec[1], spec[2], spec[3],
                max(_MIN_CAPACITY, _next_pow2(max(1, n))))
    n_filter = expected_param_count(spec) \
        - expected_param_count((("true",),) + spec[1:])
    return SegmentPlan(
        spec=stripped,
        params=list(full.params[n_filter:]),
        columns=_spec_columns(stripped, full.columns),
        group_defs=full.group_defs,
        group_cards=full.group_cards,
        group_strides=full.group_strides,
        num_groups=full.num_groups,
        agg_defs=full.agg_defs,
        group_bases=full.group_bases)


def _spec_columns(spec, candidates: List[str]) -> List[str]:
    """Columns the stripped spec still references (filter-only columns must
    not stage: the gather kernel never reads them)."""
    names = set()

    def walk(node):
        if isinstance(node, tuple):
            for x in node:
                walk(x)
        elif isinstance(node, str):
            names.add(node)

    walk((spec[1], spec[2]))
    return [c for c in candidates if c in names]


def batch_index_eligible(executor, ctx: QueryContext, segments) -> bool:
    """Should a multi-segment query leave the sharded combine for the
    per-segment ladder so the index rung can serve it? True when the
    conjunctive filter routes through indexes AND the selectivity estimate
    is under threshold on EVERY segment — estimates only (postings offsets
    arithmetic, searchsorted bounds), no postings decode, so the check
    costs microseconds per segment. ``all`` (not ``any``, unlike the
    star-tree fit check): a segment over threshold would pay a full
    per-segment scan that the sharded combine amortizes across the mesh,
    so one ineligible segment keeps the batch on the combine."""
    if str(ctx.options.get("useIndexRung", "true")).lower() == "false":
        return False
    if ctx.filter is None:
        return False
    from pinot_tpu.engine.startree_exec import _flatten_and

    preds = _flatten_and(ctx.filter)
    if not preds:
        return False
    for segment in segments:
        if getattr(segment, "valid_doc_ids", None) is not None:
            return False
        num_docs = segment.num_docs
        threshold = max(1, int(num_docs * SELECTIVITY_THRESHOLD))
        try:
            routes = [_pred_route(segment, p, num_docs) for p in preds]
        except _Decline:
            return False
        if min(r.estimate for r in routes) > threshold:
            return False
    return True


def try_index_rung(executor, ctx: QueryContext, aggs: List[AggDef],
                   segment, stats: QueryStats,
                   grouped: bool) -> Optional[Any]:
    """AggResult / GroupByResult served by the docId-gather rung, or None
    (scan rungs serve; the reason is in the ledger for every decline on an
    index-candidate shape)."""
    if ctx.options.get("useIndexRung", "true").lower() == "false":
        return None  # operator opt-out, not a decline
    if ctx.filter is None:
        return None  # no filter: nothing selective to index — not a decline
    from pinot_tpu.engine.startree_exec import _flatten_and

    preds = _flatten_and(ctx.filter)
    if not preds:
        if preds is None:  # OR/NOT shape: indexes don't compose here (yet)
            _decline(stats, "index_filter_shape")
        return None  # constant-true filter ([]): nothing selective to
        #              index — not a decline
    if getattr(segment, "valid_doc_ids", None) is not None:
        # upsert: the valid-doc bitmap ANDs every filter and postings don't
        # see it — the scan kernel's validdocs param path serves
        _decline(stats, "index_upsert_valid_docs")
        return None

    num_docs = segment.num_docs
    threshold = max(1, int(num_docs * SELECTIVITY_THRESHOLD))
    try:
        idx = resolve_doc_ids(segment, preds, num_docs, threshold)
    except _Decline as d:
        _decline(stats, d.reason)
        return None
    if idx is None:
        _decline(stats, "index_selectivity_over_threshold")
        return None
    n = int(idx.size)

    try:
        plan = gather_plan(executor._plan_for(ctx, segment), n)
    except PlanError:
        # the scan branch re-plans, re-raises, and ledgers the specific
        # plan-decline code; here only the rung outcome is recorded
        _decline(stats, "index_plan_error")
        return None

    from pinot_tpu.engine.executor import filter_fingerprint

    lease = executor._lease_of(stats)
    staged = executor.residency.stage(segment, lease=lease)
    capacity = plan.spec[4]

    def build_idx() -> np.ndarray:
        padded = np.zeros(capacity, dtype=np.int32)
        padded[:n] = idx.astype(np.int32, copy=False)
        return padded

    try:
        idx_dev = staged.index_slice((filter_fingerprint(ctx), capacity),
                                     build_idx)
        executor.residency.account(segment.segment_name, lease)

        def launch():
            from pinot_tpu.engine.kernels import unpack_outputs

            cols = {name: staged.column(name).tree()
                    for name in plan.columns}
            kernel = executor._index_kernel(plan.spec)
            packed = kernel(cols, idx_dev, tuple(plan.params), np.int32(n))
            return unpack_outputs(packed, plan.spec)  # may raise PlanError

        # per-segment coalescing: concurrent identical dashboard queries —
        # the SAME compiled ctx over the same resident — share one gather
        # launch + D2H (host docId resolution above stays per-caller)
        with maybe_span(stats, "Kernel", kernel="index_gather",
                        segment=segment.segment_name, records=n):
            out, _ = executor._kernel_flight.do(
                ("index", id(ctx), segment.segment_name, id(staged)),
                launch)
    except PlanError:
        _decline(stats, "index_plan_error")
        return None
    except Exception:
        # staging/launch failure must not fail the query: the scan rungs
        # still serve it — mirror the mutable tier's containment
        _decline(stats, "index_exec_failed")
        return None

    stats.num_segments_processed += 1
    stats.total_docs += num_docs
    stats.num_docs_scanned += n
    if n:
        stats.num_segments_matched += 1
    _chose(stats, "index_served")

    from pinot_tpu.engine.executor import (
        decode_grouped_result,
        decode_scalar_result,
    )

    if grouped:
        return decode_grouped_result(plan, segment, out)
    return decode_scalar_result(plan, segment, out)
