"""Server-side segment pruning: skip segments a filter provably excludes.

Re-design of the reference's ``SegmentPrunerService.java`` +
``ColumnValueSegmentPruner.java``: before planning/staging, each acquired
segment's column metadata is tested against the query's filter tree —
min/max bounds for EQ/RANGE/IN, partition membership for EQ, bloom filters
for EQ/IN. A segment prunes only when the filter is PROVABLY empty on it:
AND prunes if any conjunct proves empty, OR only if all branches do, NOT
and unhandled predicates are conservatively kept.

On the TPU serving path pruning is worth more than on the reference: a
pruned segment never joins the device batch, never pays dictionary
unification, and never burns HBM bandwidth in the dense scan.
"""

from __future__ import annotations

from typing import Any, List, Optional

from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import (
    FilterNode,
    FilterOp,
    Identifier,
    Predicate,
    PredicateType,
)
from pinot_tpu.utils.partition import get_partition_function


def prune_segments(ctx: QueryContext, segments: List,
                   stats=None) -> List:
    """Segments the query may still match (ref:
    SegmentPrunerService.prune called at ServerQueryExecutorV1Impl:277)."""
    if ctx.filter is None:
        return segments
    kept = [s for s in segments if _may_match(ctx.filter, s)]
    if stats is not None:
        stats.num_segments_pruned += len(segments) - len(kept)
    return kept


def _may_match(node: FilterNode, seg) -> bool:
    if node.op is FilterOp.AND:
        return all(_may_match(c, seg) for c in node.children)
    if node.op is FilterOp.OR:
        return any(_may_match(c, seg) for c in node.children)
    if node.op is FilterOp.NOT:
        return True  # negations are not provable from min/max
    return _predicate_may_match(node.predicate, seg)


def _predicate_may_match(pred: Predicate, seg) -> bool:
    if not isinstance(pred.lhs, Identifier):
        return True
    cm = seg.metadata.columns.get(pred.lhs.name)
    if cm is None or not cm.single_value:
        return True
    t = pred.type

    def conv(v) -> Optional[Any]:
        from pinot_tpu.spi.data import DataType

        try:
            v = cm.data_type.convert(v)
        except (TypeError, ValueError):
            return None
        if cm.data_type is DataType.FLOAT:
            # stored values are float32: the probe must see the same
            # precision or bounds/bloom checks compare f64 0.1 against
            # f64(f32(0.1)) and false-prune
            import numpy as np

            v = float(np.float32(v))
        return v

    if t is PredicateType.EQ:
        v = conv(pred.value)
        if v is None:
            return True
        return (_within_bounds(cm, v)
                and _partition_may_contain(cm, v)
                and _bloom_may_contain(seg, cm, v))
    if t is PredicateType.IN:
        vals = [conv(x) for x in pred.values]
        vals = [v for v in vals if v is not None]
        if not vals:
            return True
        return any(_within_bounds(cm, v)
                   and _partition_may_contain(cm, v)
                   and _bloom_may_contain(seg, cm, v) for v in vals)
    if t is PredicateType.RANGE:
        return _range_overlaps(cm, pred, conv)
    return True


def _within_bounds(cm, v) -> bool:
    if cm.min_value is None or cm.max_value is None or cm.has_nulls:
        return True
    try:
        return cm.min_value <= v <= cm.max_value
    except TypeError:
        return True


def _partition_may_contain(cm, v) -> bool:
    """Ref: the partition branch of ColumnValueSegmentPruner (and the
    broker's PartitionSegmentPruner — same metadata)."""
    if not cm.partition_function or not cm.partitions:
        return True
    fn = get_partition_function(cm.partition_function, cm.num_partitions)
    return fn.partition(v) in cm.partitions


def _bloom_may_contain(seg, cm, v) -> bool:
    if not cm.has_bloom_filter:
        return True
    bf = seg.data_source(cm.name).bloom_filter
    # v already round-tripped through the stored precision (see conv);
    # the build side hashed the f64 widening of the stored f32 values
    return bf is None or bf.might_contain(v)


def _range_overlaps(cm, pred: Predicate, conv) -> bool:
    if cm.min_value is None or cm.max_value is None or cm.has_nulls:
        return True
    lo = conv(pred.lower) if pred.lower is not None else None
    hi = conv(pred.upper) if pred.upper is not None else None
    try:
        if lo is not None:
            if pred.lower_inclusive:
                if cm.max_value < lo:
                    return False
            elif cm.max_value <= lo:
                return False
        if hi is not None:
            if pred.upper_inclusive:
                if cm.min_value > hi:
                    return False
            elif cm.min_value >= hi:
                return False
    except TypeError:
        return True
    return True
