"""Result model + reduce-side finalization.

Re-design of the reference's ``DataSchema`` / ``ResultTable`` response model
(``pinot-common/.../utils/DataSchema.java:46``,
``response/broker/ResultTable.java``) and the reduce machinery
(``IndexedTable.java:38``, ``HavingFilterHandler``,
``PostAggregationHandler``): merged group states -> HAVING -> order-by ->
offset/limit -> select-row materialization.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.engine.aggregates import AggDef
from pinot_tpu.engine.errors import QueryError, UnsupportedQueryError
from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import (
    Expr,
    FilterNode,
    FilterOp,
    Function,
    Identifier,
    Literal,
    Predicate,
    PredicateType,
)

_ARITH = {
    "plus": lambda a, b: a + b,
    "minus": lambda a, b: a - b,
    "times": lambda a, b: a * b,
    "divide": lambda a, b: (a / b) if b else float("nan"),
    "mod": lambda a, b: a % b,
}


@dataclass
class DataSchema:
    column_names: List[str]
    column_types: List[str]

    def to_dict(self) -> Dict[str, Any]:
        return {"columnNames": self.column_names,
                "columnDataTypes": self.column_types}


@dataclass
class ResultTable:
    schema: DataSchema
    rows: List[List[Any]]

    def to_dict(self) -> Dict[str, Any]:
        return {"dataSchema": self.schema.to_dict(), "rows": self.rows}


@dataclass
class QueryStats:
    """Per-query execution stats surfaced in the response metadata
    (ref: MetadataKey numDocsScanned etc., ServerQueryExecutorV1Impl:232-256)."""

    num_segments_queried: int = 0
    num_segments_processed: int = 0
    num_segments_matched: int = 0
    num_segments_pruned: int = 0
    num_docs_scanned: int = 0
    total_docs: int = 0
    num_groups_limit_reached: bool = False
    # scatter accounting, set by the BROKER after gather (servers leave
    # them 0): responded counts only servers that returned a usable
    # DataTable, so responded < queried IS the partial-result flag —
    # on the wire, not just on the top-level BrokerResponse
    num_servers_queried: int = 0
    num_servers_responded: int = 0
    # group-by ladder rung that served ('dense'|'compact'|'hash'|'sort'|
    # 'startree_device'|'startree'|'index'|'host'; 'mixed' when segments
    # split across rungs) — the bench gates SSB Q2.x/Q3.x on this, and
    # the userfacing suite gates selective point filters on 'index'
    group_by_rung: Optional[str] = None
    # index of the star-tree that served (segment.star_trees order; the
    # bench records it per query), or None off the star-tree rungs. A
    # table's segments share one tree config, so merge keeps any value
    startree_tree_index: Optional[int] = None
    # broker reduce path that produced the final table ('device' |
    # 'vectorized' | 'oracle'); set ONCE by the broker at finish, so
    # merge keeps any incoming value (servers leave it None)
    reduce_path: Optional[str] = None
    # HBM residency counters for this query (engine/residency.py):
    # hits/misses/evictions/pinBlockedEvictions/spills — and the tiered
    # keys promotions/demotions/slices (budget-slice boundaries the query
    # crossed) — sum across segments/shards/servers at merge; *Bytes keys
    # (stagedBytes, hostBytes) take the max (each server reports its own
    # staged total — summing would double-count)
    staging: Dict[str, int] = field(default_factory=dict)
    # launch-coalescing counters for this query (parallel/launcher.py):
    # launches/coalesced/launchesSaved sum across shards/servers at merge;
    # batchSize (the coalesced batch this query rode) and queueWaitMs
    # (dispatcher queue wait) take the max — each server reports its own
    # worst case, summing would misstate both
    launch: Dict[str, float] = field(default_factory=dict)
    # phase -> ms (ref: TimerContext/ServerQueryPhase —
    # ServerQueryExecutorV1Impl.java:122,276,297,303); summed across
    # servers at reduce
    phase_ms: Dict[str, float] = field(default_factory=dict)
    # request-scoped trace entries, populated only when the query is
    # traced (trace=true / sample / slow-log force) — the legacy FLAT
    # view, emitted from the span tree at each span close
    # (ref: TraceContext.java:46 — operator-level timings attached to
    # the response metadata)
    trace: List[Dict[str, Any]] = field(default_factory=list)
    # hierarchical span trees (common/tracing.py SpanRecorder): completed
    # root spans land here directly (the recorder's sink IS this list).
    # Serialized on the DataTable wire; the broker re-parents each
    # server's roots under its own root at reduce. Concat at merge —
    # unless the merging stats has an OPEN span, in which case the merged
    # trees nest under it (segment fan-out workers -> caller's combine)
    spans: List[Dict[str, Any]] = field(default_factory=list)
    # path-decision ledger (common/tracing.py record_decision): every
    # decline of a faster rung, keyed "point:declined->chosen:reason",
    # counts summed across segments/shards/servers at merge. Always on —
    # declines are off the resident fast path, so the cost is nil
    decisions: Dict[str, int] = field(default_factory=dict)

    def add_phase_ms(self, phase: str, ms: float) -> None:
        self.phase_ms[phase] = self.phase_ms.get(phase, 0.0) + ms

    def add_trace(self, operator: str, ms: float, **detail: Any) -> None:
        self.trace.append({"operator": operator, "ms": round(ms, 3),
                           **detail})

    def merge(self, other: "QueryStats") -> None:
        self.num_segments_queried += other.num_segments_queried
        self.num_segments_processed += other.num_segments_processed
        self.num_segments_matched += other.num_segments_matched
        self.num_segments_pruned += other.num_segments_pruned
        self.num_docs_scanned += other.num_docs_scanned
        self.total_docs += other.total_docs
        self.num_groups_limit_reached |= other.num_groups_limit_reached
        # broker-only counters: exactly one side of any merge is nonzero
        # (servers ship 0), so sum keeps the broker's gather accounting
        self.num_servers_queried += other.num_servers_queried
        self.num_servers_responded += other.num_servers_responded
        if other.group_by_rung is not None:
            self.group_by_rung = (
                other.group_by_rung
                if self.group_by_rung in (None, other.group_by_rung)
                else "mixed")
        if other.startree_tree_index is not None:
            self.startree_tree_index = other.startree_tree_index
        if other.reduce_path is not None:
            self.reduce_path = other.reduce_path
        for k, v in other.staging.items():
            if k.endswith("Bytes"):
                self.staging[k] = max(self.staging.get(k, 0), v)
            else:
                self.staging[k] = self.staging.get(k, 0) + v
        for k, v in other.launch.items():
            if k in ("batchSize", "queueWaitMs"):
                self.launch[k] = max(self.launch.get(k, 0), v)
            else:
                self.launch[k] = self.launch.get(k, 0) + v
        for phase, ms in other.phase_ms.items():
            self.add_phase_ms(phase, ms)
        self.trace.extend(other.trace)
        if other.spans:
            rec = getattr(self, "_recorder", None)
            if rec is not None:
                # a live recorder with an open span adopts the merged
                # trees as children (worker-thread partials nest under
                # the caller's combine); otherwise they concat top-level
                rec.adopt(other.spans)
            else:
                self.spans.extend(other.spans)
        for k, v in other.decisions.items():
            self.decisions[k] = self.decisions.get(k, 0) + v

    def to_dict(self) -> Dict[str, Any]:
        return {
            "numSegmentsQueried": self.num_segments_queried,
            "numSegmentsProcessed": self.num_segments_processed,
            "numSegmentsMatched": self.num_segments_matched,
            "numSegmentsPrunedByServer": self.num_segments_pruned,
            "numDocsScanned": self.num_docs_scanned,
            "totalDocs": self.total_docs,
            "numGroupsLimitReached": self.num_groups_limit_reached,
            **({"numServersQueried": self.num_servers_queried,
                "numServersResponded": self.num_servers_responded}
               if self.num_servers_queried else {}),
            "phaseTimesMs": {k: round(v, 3)
                             for k, v in self.phase_ms.items()},
            **({"groupByRung": self.group_by_rung}
               if self.group_by_rung else {}),
            **({"startreeTreeIndex": self.startree_tree_index}
               if self.startree_tree_index is not None else {}),
            **({"reducePath": self.reduce_path}
               if self.reduce_path else {}),
            **({"staging": self.staging} if self.staging else {}),
            **({"launch": self.launch} if self.launch else {}),
            **({"trace": self.trace} if self.trace else {}),
            **({"spans": self.spans} if self.spans else {}),
            **({"decisions": self.decisions} if self.decisions else {}),
        }


# --------------------------------------------------------------------------
# intermediate (mergeable) results — the DataTable payload equivalent
# --------------------------------------------------------------------------

@dataclass
class AggResult:
    """Aggregation without group-by: one state per aggregation."""

    states: List[Any]

    def merge(self, other: "AggResult", aggs: List[AggDef]) -> None:
        self.states = [a.merge(s, o) for a, s, o in
                       zip(aggs, self.states, other.states)]


@dataclass
class GroupByResult:
    """group key (tuple of python values) -> [state per agg]
    (ref: IndexedTable)."""

    groups: Dict[Tuple, List[Any]] = field(default_factory=dict)

    def merge(self, other: "GroupByResult", aggs: List[AggDef]) -> None:
        for key, states in other.groups.items():
            mine = self.groups.get(key)
            if mine is None:
                self.groups[key] = list(states)
            else:
                self.groups[key] = [a.merge(m, s) for a, m, s in
                                    zip(aggs, mine, states)]

    def trim(self, max_size: int) -> bool:
        """Cap group count (ref: numGroupsLimit). Returns True if trimmed."""
        if len(self.groups) <= max_size:
            return False
        self.groups = dict(list(self.groups.items())[:max_size])
        return True


@dataclass
class SelectionResult:
    """Selection rows (+ order-by keys when ordered, for streaming merge)."""

    rows: List[List[Any]]
    order_keys: Optional[List[Tuple]] = None


# --------------------------------------------------------------------------
# vectorized IndexedTable merge (the array-native half of the broker reduce)
# --------------------------------------------------------------------------

# numeric aggregation states whose cross-server merge is an elementwise
# ufunc fold — everything else (tuples, sketches, decimal strings) merges
# through AggDef.merge per key
_VEC_STATE_FOLDS: Dict[str, Any] = {
    "count": np.add,
    "sum": np.add,
    "min": np.minimum,
    "max": np.maximum,
}


def lexsort_runs(sort_keys: List[np.ndarray]
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """ONE stable ``np.lexsort`` over the concatenated key columns ->
    ``(order, starts)``: ``order`` permutes rows so equal keys are
    adjacent (ties keep input order — the dict-insertion semantics of the
    row-path oracle), ``starts`` marks each run's first sorted position.
    NaN keys never equal anything (ElementWise ``!=``), so every NaN row
    is its own run — exactly the oracle's dict behavior."""
    n = int(len(sort_keys[0])) if sort_keys else 0
    if n == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    order = np.lexsort(tuple(reversed(sort_keys)))
    if n == 1:
        return order, np.zeros(1, np.int64)
    diff = np.zeros(n - 1, dtype=bool)
    for k in sort_keys:
        ks = k[order]
        diff |= ks[1:] != ks[:-1]
    starts = np.concatenate(
        (np.zeros(1, np.int64), np.flatnonzero(diff) + 1))
    return order, starts


def fold_grouped_runs(order: np.ndarray, starts: np.ndarray, n: int,
                      agg_entries: List[Tuple[str, Any]],
                      aggs: List[AggDef]) -> List[Any]:
    """Fold per-run aggregation states: -> one folded-state sequence per
    aggregation, in RUN (sorted) order.

    ``agg_entries[i]`` is ``("vec", concat_array)`` for numeric array
    states (``aggs[i].base`` must be in ``_VEC_STATE_FOLDS`` — one
    boundary ``reduceat`` folds every group at once) or ``("obj",
    boxed_list)`` for object states, merged per run through the existing
    per-key ``AggDef.merge`` in ascending input order (the oracle's
    arrival order — merge-order-sensitive sketches stay bit-identical)."""
    out: List[Any] = []
    ends = np.concatenate((starts[1:], np.asarray([n], dtype=np.int64)))
    for (tag, data), agg in zip(agg_entries, aggs):
        if tag == "vec":
            out.append(_VEC_STATE_FOLDS[agg.base].reduceat(data[order],
                                                           starts))
        else:
            states = []
            for s, e in zip(starts, ends):
                run = order[s:e]
                st = data[int(run[0])]
                for i in run[1:]:
                    st = agg.merge(st, data[int(i)])
                states.append(st)
            out.append(states)
    return out


# --------------------------------------------------------------------------
# reduce: merged results -> final ResultTable
# --------------------------------------------------------------------------

def _env_lookup(env: Dict[str, Any], expr: Expr) -> Any:
    key = str(expr)
    if key in env:
        return env[key]
    if isinstance(expr, Literal):
        return expr.value
    if isinstance(expr, Function) and expr.name in _ARITH:
        a = _env_lookup(env, expr.args[0])
        b = _env_lookup(env, expr.args[1])
        try:
            return _ARITH[expr.name](float(a), float(b))
        except (TypeError, ValueError) as e:
            raise QueryError(f"post-aggregation arithmetic failed: {e}")
    raise QueryError(f"expression {expr} is not in GROUP BY or an aggregation")


def _eval_scalar_filter(node: FilterNode, env: Dict[str, Any]) -> bool:
    """HAVING evaluation over a single group's env
    (ref: HavingFilterHandler)."""
    if node.op is FilterOp.AND:
        return all(_eval_scalar_filter(c, env) for c in node.children)
    if node.op is FilterOp.OR:
        return any(_eval_scalar_filter(c, env) for c in node.children)
    if node.op is FilterOp.NOT:
        return not _eval_scalar_filter(node.children[0], env)
    p = node.predicate
    v = _env_lookup(env, p.lhs)
    t = p.type
    if t is PredicateType.EQ:
        return v == p.value
    if t is PredicateType.NOT_EQ:
        return v != p.value
    if t is PredicateType.IN:
        return v in p.values
    if t is PredicateType.NOT_IN:
        return v not in p.values
    if t is PredicateType.RANGE:
        if p.lower is not None:
            if p.lower_inclusive:
                if v < p.lower:
                    return False
            elif v <= p.lower:
                return False
        if p.upper is not None:
            if p.upper_inclusive:
                if v > p.upper:
                    return False
            elif v >= p.upper:
                return False
        return True
    raise UnsupportedQueryError(f"HAVING predicate {t} not supported")


def _group_env(ctx: QueryContext, aggs: List[AggDef], key: Tuple,
               states: List[Any]) -> Dict[str, Any]:
    env: Dict[str, Any] = {}
    for e, v in zip(ctx.group_by, key):
        env[str(e)] = v
    for fn, agg, st in zip(ctx.aggregations, aggs, states):
        env[str(fn)] = agg.finalize(st)
    return env


def reduce_group_by(ctx: QueryContext, aggs: List[AggDef],
                    merged: GroupByResult,
                    schema_types: Dict[str, str]) -> ResultTable:
    """Ref: GroupByDataTableReducer.java:66."""
    envs = [ _group_env(ctx, aggs, key, states)
             for key, states in merged.groups.items() ]
    if ctx.having is not None:
        envs = [e for e in envs if _eval_scalar_filter(ctx.having, e)]

    if ctx.order_by:
        def sort_key(env):
            parts = []
            for ob in ctx.order_by:
                v = _env_lookup(env, ob.expr)
                parts.append(_Reversible(v, ob.ascending))
            return tuple(parts)
        envs.sort(key=sort_key)
    rows_env = envs[ctx.offset: ctx.offset + ctx.limit]

    names, types = _result_schema(ctx, aggs, schema_types)
    rows = [[_finalize_cell(_env_lookup(env, e)) for e in ctx.select_expressions]
            for env in rows_env]
    return ResultTable(DataSchema(names, types), rows)


def reduce_aggregation(ctx: QueryContext, aggs: List[AggDef],
                       merged: AggResult) -> ResultTable:
    """Ref: AggregationDataTableReducer."""
    env: Dict[str, Any] = {}
    for fn, agg, st in zip(ctx.aggregations, aggs, merged.states):
        env[str(fn)] = agg.finalize(st)
    names, types = _result_schema(ctx, aggs, {})
    row = [_finalize_cell(_env_lookup(env, e)) for e in ctx.select_expressions]
    return ResultTable(DataSchema(names, types), [row])


def _finalize_cell(v: Any) -> Any:
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.bool_):
        return bool(v)
    return v


def _result_schema(ctx: QueryContext, aggs: List[AggDef],
                   schema_types: Dict[str, str]) -> Tuple[List[str], List[str]]:
    agg_types = {str(fn): a.result_type
                 for fn, a in zip(ctx.aggregations, aggs)}
    names: List[str] = []
    types: List[str] = []
    for e, alias in zip(ctx.select_expressions, ctx.aliases):
        names.append(alias if alias else str(e))
        k = str(e)
        if k in agg_types:
            types.append(agg_types[k])
        elif k in schema_types:
            types.append(schema_types[k])
        elif isinstance(e, Literal):
            types.append("STRING" if isinstance(e.value, str) else "DOUBLE")
        else:
            types.append("DOUBLE")  # post-aggregation arithmetic
    return names, types


class _Reversible:
    """Sort-key wrapper supporting DESC for arbitrary comparable values."""

    __slots__ = ("v", "asc")

    def __init__(self, v, asc: bool):
        self.v = v
        self.asc = asc

    def __lt__(self, other: "_Reversible") -> bool:
        if self.v == other.v:
            return False
        lt = self.v < other.v
        return lt if self.asc else not lt

    def __eq__(self, other) -> bool:
        return self.v == other.v
