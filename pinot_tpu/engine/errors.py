"""Engine exceptions (ref: BadQueryRequestException / QueryException codes)."""


class QueryError(Exception):
    """User-facing query error (bad request, type mismatch, unsupported)."""

    def __init__(self, message: str, code: int = 700):
        super().__init__(message)
        self.code = code


class UnsupportedQueryError(QueryError):
    def __init__(self, message: str):
        super().__init__(message, code=150)
