"""Engine exceptions (ref: BadQueryRequestException / QueryException codes)."""


class QueryError(Exception):
    """User-facing query error (bad request, type mismatch, unsupported)."""

    def __init__(self, message: str, code: int = 700):
        super().__init__(message)
        self.code = code


class UnsupportedQueryError(QueryError):
    def __init__(self, message: str):
        super().__init__(message, code=150)


class QueryRejectedError(QueryError):
    """Admission control rejected the query: the bounded scheduler queue is
    full, the queue-wait bound expired, or a per-table QPS quota tripped
    (ref: QueryScheduler returning 503-shaped errors + the queryquota 429).
    Retriable — the caller saw a load signal, not a broken query — and
    carries the queue depth observed at rejection so clients can back off
    proportionally."""

    retriable = True

    def __init__(self, message: str, queue_depth: int = 0,
                 reason: str = "overload"):
        super().__init__(message, code=429)
        self.queue_depth = int(queue_depth)
        self.reason = reason
