"""Device staging: segment columns -> HBM arrays.

The TPU analogue of the reference's mmap-into-PinotDataBuffer read path
(``ImmutableSegmentLoader`` + ``DataFetcher.java:44`` bulk reads): a column is
staged once into device memory as tile-aligned arrays and reused across
queries. Staging is lazy per (segment, column) and cached; the cache is the
HBM residency manager (eviction hooks come with the server layer).

Staged layout per column:
- SV dict column:  ``fwd``  [capacity] int32 dictIds (upcast from narrow)
- SV raw column:   ``fwd``  [capacity] value dtype
- numeric dict:    ``dictvals`` [cardinality] values (dictId -> value gather)
- MV dict column:  ``mv`` [capacity, max_mv] int32 + ``mvcount`` [capacity]
- null bitmap:     ``null`` [capacity] bool
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from pinot_tpu.segment.immutable import ImmutableSegment
from pinot_tpu.spi.data import DataType


# accumulation dtypes (x64 enabled in engine __init__; on TPU f64/i64 are
# emulated — metadata-driven narrowing to f32/i32 is a planned optimization)
VALUE_DTYPE = jnp.float64
INT_VALUE_DTYPE = jnp.int64


class StagedColumn:
    """One column's device-resident arrays."""

    def __init__(self, fwd=None, dictvals=None, mv=None, mvcount=None,
                 null=None, data_type: Optional[DataType] = None,
                 has_dictionary: bool = True):
        self.fwd = fwd
        self.dictvals = dictvals
        self.mv = mv
        self.mvcount = mvcount
        self.null = null
        self.data_type = data_type
        self.has_dictionary = has_dictionary

    def tree(self) -> Dict[str, jnp.ndarray]:
        """The pytree handed to jitted kernels (only present arrays)."""
        out = {}
        for k in ("fwd", "dictvals", "mv", "mvcount", "null"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        return out


class StagedSegment:
    """Device image of one segment (subset of columns, staged on demand)."""

    def __init__(self, segment: ImmutableSegment):
        self.segment = segment
        self.num_docs = segment.num_docs
        self.capacity = segment.padded_capacity
        self._columns: Dict[str, StagedColumn] = {}

    def column(self, name: str) -> StagedColumn:
        col = self._columns.get(name)
        if col is None:
            col = self._stage(name)
            self._columns[name] = col
        return col

    def _stage(self, name: str) -> StagedColumn:
        ds = self.segment.data_source(name)
        cm = ds.metadata
        sc = StagedColumn(data_type=cm.data_type, has_dictionary=cm.has_dictionary)

        if cm.single_value:
            fwd = np.asarray(ds.forward_index)
            if cm.has_dictionary:
                sc.fwd = jnp.asarray(fwd.astype(np.int32))
            else:
                # RAW numeric values: keep integral as int64, floats as f64
                if cm.data_type.is_integral:
                    sc.fwd = jnp.asarray(fwd.astype(np.int64))
                else:
                    sc.fwd = jnp.asarray(fwd.astype(np.float64))
        else:
            dense, counts = ds.dense_mv()
            sc.mv = jnp.asarray(dense)
            sc.mvcount = jnp.asarray(counts)

        if cm.has_dictionary and cm.data_type.is_numeric:
            vals = np.asarray(ds.dictionary.device_values())
            if cm.data_type.is_integral:
                sc.dictvals = jnp.asarray(vals.astype(np.int64))
            else:
                sc.dictvals = jnp.asarray(vals.astype(np.float64))

        if cm.has_nulls:
            sc.null = jnp.asarray(np.asarray(ds.null_bitmap))
        return sc

    def release(self) -> None:
        """Drop device references (HBM freed when XLA GCs the buffers)."""
        self._columns.clear()


class StagingCache:
    """(segment_name -> StagedSegment) cache; the HBM residency manager
    (ref: the acquire/release protocol of BaseTableDataManager and the
    FetchContext prefetch path, InstancePlanMakerImplV2.java:155-170)."""

    def __init__(self):
        self._staged: Dict[str, StagedSegment] = {}

    def stage(self, segment: ImmutableSegment) -> StagedSegment:
        st = self._staged.get(segment.segment_name)
        if st is None or st.segment is not segment:
            st = StagedSegment(segment)
            self._staged[segment.segment_name] = st
        return st

    def evict(self, segment_name: str) -> None:
        st = self._staged.pop(segment_name, None)
        if st is not None:
            st.release()

    def clear(self) -> None:
        self._staged.clear()
