"""Device staging: segment columns -> HBM arrays.

The TPU analogue of the reference's mmap-into-PinotDataBuffer read path
(``ImmutableSegmentLoader`` + ``DataFetcher.java:44`` bulk reads): a column is
staged once into device memory as tile-aligned arrays and reused across
queries. Staging is lazy per (segment, column) and cached; the cache is the
HBM residency manager (eviction hooks come with the server layer).

Staged layout per column:
- SV dict column:  ``fwd``  [capacity] int32 dictIds (upcast from narrow)
- SV raw column:   ``fwd``  [capacity] value dtype
- numeric dict:    ``dictvals`` [cardinality] values (dictId -> value gather)
- MV dict column:  ``mv`` [capacity, max_mv] int32 + ``mvcount`` [capacity]
- null bitmap:     ``null`` [capacity] bool
"""

from __future__ import annotations

import threading

from collections import OrderedDict
from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np

from pinot_tpu.segment.immutable import ImmutableSegment
from pinot_tpu.spi.data import DataType


# Metadata-driven narrowing: v5e has no native f64/i64 units (XLA emulates
# them as f32/i32 pairs), so capacity-sized device arrays are narrowed
# whenever column min/max bounds allow. Raw FLOAT/DOUBLE forward arrays stay
# f64: filter literals compare against exact stored values and rounding to
# f32 could flip boundary rows (dictionary columns filter on dictIds, so
# their value tables narrow safely to f32).
_I32_MIN, _I32_MAX = np.iinfo(np.int32).min, np.iinfo(np.int32).max


def staged_int_dtype(cm) -> np.dtype:
    """Device dtype for an integral column's values, from stats min/max."""
    if (cm.min_value is not None and cm.max_value is not None
            and _I32_MIN <= int(cm.min_value)
            and int(cm.max_value) <= _I32_MAX):
        return np.dtype(np.int32)
    return np.dtype(np.int64)


class StagedColumn:
    """One column's device-resident arrays."""

    def __init__(self, fwd=None, dictvals=None, mv=None, mvcount=None,
                 null=None, data_type: Optional[DataType] = None,
                 has_dictionary: bool = True):
        self.fwd = fwd
        self.dictvals = dictvals
        self.mv = mv
        self.mvcount = mvcount
        self.null = null
        self.data_type = data_type
        self.has_dictionary = has_dictionary

    def tree(self) -> Dict[str, jnp.ndarray]:
        """The pytree handed to jitted kernels (only present arrays)."""
        out = {}
        for k in ("fwd", "dictvals", "mv", "mvcount", "null"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        return out


# Pallas tile: docs per grid step of the fused scan kernel. Packed columns
# are laid out planar per tile (value j of a tile lives in word j%W at bit
# slot (j//W)*B) so the in-kernel unpack is K static shift+mask ops over
# contiguous words — no gathers, no cross-lane interleave
# (TPU-side re-design of the reference's unaligned bit extraction,
# io/util/PinotDataBitSet.java:25).
PALLAS_TILE = 4096

# resident idx arrays per segment (index rung): LRU working-set bound —
# each is at most ~SELECTIVITY_THRESHOLD * capacity int32s, so the cap
# bounds idx residency to a small multiple of one staged column
_INDEX_SLICE_CAP = 64

# 12-bit value limbs for the fused kernel's exact integer accumulation
# (pallas_kernels._LIMB_BITS aliases this): i64-staged value columns ship
# as pre-split limb PLANES so the kernel never touches i64 math
LIMB_BITS = 12


def pack_bits(bits_needed: int) -> int:
    """Device bit width: power-of-two so values never straddle words."""
    for b in (1, 2, 4, 8, 16):
        if bits_needed <= b:
            return b
    return 32


class PackedColumn:
    """Planar bit-packed dictIds: ``words`` [num_tiles, W] uint32."""

    def __init__(self, words, bits: int):
        self.words = words
        self.bits = bits
        self.vals_per_word = 32 // bits


class SegmentHostImage:
    """Host-RAM tier image of one demoted :class:`StagedSegment`: numpy
    copies of every device array, byte-accounted against the residency
    manager's host budget (``pinot.server.query.hostram.budget.bytes``).
    Promotion hands the image back to a fresh StagedSegment, which
    restores each array with a plain H2D ``jnp.asarray`` — no decode, no
    dictionary build, no bit-packing (the cheap half of the ISCA'23
    D2H+H2D vs rebuild tradeoff, ~10x cheaper than a cold column build).
    Containers mirror the StagedSegment caches: ``columns`` holds
    :class:`StagedColumn` objects whose fields are numpy arrays."""

    __slots__ = ("columns", "packed", "values", "startree",
                 "segment_names", "_segment_ref", "_nbytes")

    def __init__(self, segment):
        import weakref

        # weakref: a host image must not keep an unloaded segment (and its
        # mmapped buffers) alive; identity is re-validated at promotion
        self._segment_ref = weakref.ref(segment)
        self.segment_names = (segment.segment_name,)
        self.columns: Dict[str, StagedColumn] = {}  # race-ok: quiesced_by_refcount
        self.packed: Dict[str, tuple] = {}  # race-ok: quiesced_by_refcount
        self.values: Dict[str, np.ndarray] = {}  # race-ok: quiesced_by_refcount
        self.startree: Dict[int, Dict[str, np.ndarray]] = {}  # race-ok: quiesced_by_refcount
        self._nbytes = 0

    def seal(self) -> "SegmentHostImage":
        """Freeze the byte count after the demoting thread filled the
        containers (the residency manager accounts this number once, at
        host-tier admission)."""
        total = 0
        for col in self.columns.values():
            for arr in col.tree().values():
                total += int(getattr(arr, "nbytes", 0))
        for words, _bits in self.packed.values():
            total += int(getattr(words, "nbytes", 0))
        for v in self.values.values():
            total += int(getattr(v, "nbytes", 0))
        for tree in self.startree.values():
            for arr in tree.values():
                total += int(getattr(arr, "nbytes", 0))
        self._nbytes = total
        return self

    def empty(self) -> bool:
        return not (self.columns or self.packed or self.values
                    or self.startree)

    def matches(self, segment) -> bool:
        """Identity check at promotion: a reloaded segment (same name, new
        object) must never be served stale host copies."""
        return segment is not None and self._segment_ref() is segment

    def nbytes(self) -> int:
        return self._nbytes

    def release(self) -> None:
        """Drop the host arrays eagerly (big numpy buffers should not wait
        for GC of stray references)."""
        self.columns.clear()
        self.packed.clear()
        self.values.clear()
        self.startree.clear()
        self._nbytes = 0


class StagedSegment:
    """Device image of one segment (subset of columns, staged on demand).

    Column builds serialize on a per-segment lock: two query threads
    staging the same column must share ONE set of device arrays — a
    duplicate build leaks its losing copy until GC (the round-2 residency
    hazard). Reads stay lock-free (dict get is atomic under the GIL).

    Conservation contract (machine-enforced by the lint ``conservation``
    family's cache-parity rule): every field this class populates outside
    ``__init__`` must be counted in ``nbytes()`` AND cleared in
    ``release()`` — staged bytes invisible to the HBM budget, or device
    arrays that outlive eviction, are exactly the drift the gate blocks."""

    def __init__(self, segment: ImmutableSegment, borrower=None,
                 host_image: Optional[SegmentHostImage] = None):
        self.segment = segment
        self.num_docs = segment.num_docs
        self.capacity = segment.padded_capacity
        # host-tier promotion source (residency demote/promote protocol):
        # per-array numpy copies consumed on first access — a restored
        # array is one H2D jnp.asarray, skipping decode/dictionary/pack
        # work entirely. Host RAM, so never counted in nbytes(); arrays
        # leave the image as they promote, and release() drops leftovers.
        self._host_image = host_image
        # writes-only guard: double-checked locking — reads are deliberate
        # lock-free dict gets (atomic under the GIL), builds serialize
        self._columns: Dict[str, StagedColumn] = {}  # guarded-by-writes: _lock
        self._packed: Dict[str, PackedColumn] = {}  # guarded-by-writes: _lock
        self._values: Dict[str, jnp.ndarray] = {}  # guarded-by-writes: _lock
        # star-tree node arrays: tree index -> {pseudo-column key -> array}
        # (engine/plan.py startree_dim_key/startree_metric_key namespace) —
        # resident like any column: counted in nbytes(), dropped in release()
        self._startree: Dict[int, Dict[str, jnp.ndarray]] = {}  # guarded-by-writes: _lock
        # index-rung idx arrays: filter fingerprint -> padded int32 docIds
        # (LRU-capped; tiny next to columns but resident all the same —
        # counted in nbytes(), dropped in release())
        self._index_slices: "OrderedDict[Any, jnp.ndarray]" = OrderedDict()  # guarded-by-writes: _lock
        self._valid_cache = None  # guarded-by-writes: _lock
        self._lock = threading.Lock()
        # cross-query dedup hook: ``borrower(segment, name)`` may return a
        # StagedColumn built from a resident sharded batch's device copy of
        # the same column (no second H2D, dictvals buffer shared) — wired
        # by the sharded executor through the residency manager
        self._borrower = borrower

    def column(self, name: str) -> StagedColumn:
        col = self._columns.get(name)
        if col is None:
            with self._lock:
                col = self._columns.get(name)
                if col is None:
                    if self._borrower is not None:
                        col = self._borrower(self.segment, name)
                    if col is None:
                        col = self._promote_column(name)
                    if col is None:
                        col = self._stage(name)
                    self._columns[name] = col
        return col

    def _promote_column(self, name: str) -> Optional[StagedColumn]:
        """Host-tier restore: plain H2D of the demoted numpy arrays (no
        decode/dictionary/pack work). Consumes the image's copy — promoted
        bytes are device-owned from here on."""
        img = self._host_image
        if img is None:
            return None
        hc = img.columns.pop(name, None)
        if hc is None:
            return None
        sc = StagedColumn(data_type=hc.data_type,
                          has_dictionary=hc.has_dictionary)
        for k in ("fwd", "dictvals", "mv", "mvcount", "null"):
            v = getattr(hc, k)
            if v is not None:
                setattr(sc, k, jnp.asarray(v))
        return sc

    def _stage(self, name: str) -> StagedColumn:
        ds = self.segment.data_source(name)
        cm = ds.metadata
        sc = StagedColumn(data_type=cm.data_type, has_dictionary=cm.has_dictionary)

        if cm.single_value:
            fwd = np.asarray(ds.forward_index)
            if cm.has_dictionary:
                sc.fwd = jnp.asarray(fwd.astype(np.int32))
            else:
                # RAW values: integral narrowed by stats bounds; floats stay
                # f64 for exact filter-literal comparison (see module note)
                if cm.data_type.is_integral:
                    sc.fwd = jnp.asarray(fwd.astype(staged_int_dtype(cm)))
                else:
                    sc.fwd = jnp.asarray(fwd.astype(np.float64))
        else:
            dense, counts = ds.dense_mv()
            sc.mv = jnp.asarray(dense)
            sc.mvcount = jnp.asarray(counts)

        if cm.has_dictionary and cm.data_type.is_numeric:
            vals = np.asarray(ds.dictionary.device_values())
            if cm.data_type.is_integral:
                sc.dictvals = jnp.asarray(vals.astype(staged_int_dtype(cm)))
            else:
                sc.dictvals = jnp.asarray(vals.astype(np.float32))

        if cm.has_nulls:
            sc.null = jnp.asarray(np.asarray(ds.null_bitmap))
        return sc

    def packed_column(self, name: str) -> Optional[PackedColumn]:
        """Planar bit-packed dictIds for the Pallas scan kernel, or None if
        the column/segment shape doesn't fit the packed layout."""
        pc = self._packed.get(name)
        if pc is None:
            with self._lock:
                pc = self._packed.get(name)
                if pc is None:
                    pc = self._promote_packed(name)
                    if pc is None:
                        pc = self._pack(name)
                    if pc is None:
                        return None
                    self._packed[name] = pc
        return pc

    def _promote_packed(self, name: str) -> Optional["PackedColumn"]:
        img = self._host_image
        if img is None:
            return None
        hp = img.packed.pop(name, None)
        if hp is None:
            return None
        words, bits = hp
        return PackedColumn(jnp.asarray(words), bits)

    def pallas_capacity(self) -> int:
        """Doc capacity padded up to a whole number of Pallas tiles (the
        kernel's validity mask drops the zero-padded tail)."""
        return -(-self.capacity // PALLAS_TILE) * PALLAS_TILE

    def _pack(self, name: str) -> Optional["PackedColumn"]:
        ds = self.segment.data_source(name)
        cm = ds.metadata
        if not (cm.has_dictionary and cm.single_value):
            return None
        bits = pack_bits(max(1, (max(cm.cardinality - 1, 1)).bit_length()))
        K = 32 // bits
        W = PALLAS_TILE // K
        cap = self.pallas_capacity()
        ids = np.zeros(cap, dtype=np.uint32)
        fwd = np.asarray(ds.forward_index)
        ids[:fwd.shape[0]] = fwd.astype(np.uint32)
        tiles = cap // PALLAS_TILE
        planes = ids.reshape(tiles, K, W)
        words = np.zeros((tiles, W), dtype=np.uint32)
        for k in range(K):
            words |= planes[:, k, :] << np.uint32(k * bits)
        return PackedColumn(jnp.asarray(words), bits)

    def value_column(self, name: str) -> Optional[jnp.ndarray]:
        """Decoded per-doc numeric values [capacity] (f32 / i32) for kernels
        that read values without a dictionary gather; one-time decode, cached
        in HBM (the metric-column analogue of raw chunk indexes)."""
        v = self._values.get(name)
        if v is None:
            img = self._host_image
            if img is not None:
                with self._lock:
                    v = self._values.get(name)
                    if v is None:
                        hv = img.values.pop(name, None)
                        if hv is not None:
                            v = jnp.asarray(hv)
                            self._values[name] = v
                if v is not None:
                    return v
            ds = self.segment.data_source(name)
            cm = ds.metadata
            if not (cm.single_value and cm.data_type.is_numeric):
                return None
            col = self.column(name)
            with self._lock:
                v = self._values.get(name)
                if v is None:
                    if cm.has_dictionary:
                        v = col.dictvals[col.fwd]
                    else:
                        v = col.fwd
                    if cm.data_type.is_integral:
                        v = v.astype(staged_int_dtype(cm))
                    else:
                        v = v.astype(jnp.float32)
                    pad = self.pallas_capacity() - v.shape[0]
                    if pad:
                        v = jnp.pad(v, (0, pad))
                    self._values[name] = v
        return v

    @staticmethod
    def _limb_key(name: str, k: int) -> str:
        # '#' can't appear in a column name, so limb-plane cache entries
        # never collide with value_column entries in _values
        return f"{name}#limb{k}"

    def value_limb_planes(self, name: str,
                          limbs: int) -> Optional[List[jnp.ndarray]]:
        """i64-staged value column as ``limbs`` pre-split 12-bit limb
        PLANES [pallas_capacity] i32 (plane ``k`` = ``(v >> 12k) & 0xFFF``;
        the top plane keeps the sign via arithmetic shift — bit-for-bit
        the fused kernel's own in-kernel split, applied host-side at the
        value-load layer). Cached in ``_values`` under reserved keys, so
        the residency conservation contract (nbytes/release/demote/
        promote) covers the planes like any staged value array."""
        keys = [self._limb_key(name, k) for k in range(limbs)]
        got = [self._values.get(k) for k in keys]
        if all(v is not None for v in got):
            return got
        ds = self.segment.data_source(name)
        cm = ds.metadata
        if not (cm.single_value and cm.data_type.is_numeric
                and cm.data_type.is_integral):
            return None
        with self._lock:
            got = [self._values.get(k) for k in keys]
            if all(v is not None for v in got):
                return got
            img = self._host_image
            if img is not None:
                hv = [img.values.pop(k, None) for k in keys]
                if all(v is not None for v in hv):
                    planes = [jnp.asarray(v) for v in hv]
                    for k, p in zip(keys, planes):
                        self._values[k] = p
                    return planes
                for k, v in zip(keys, hv):   # partial image: rebuild cold
                    if v is not None:
                        img.values[k] = v
            fwd = np.asarray(ds.forward_index)
            if cm.has_dictionary:
                vals = np.asarray(ds.dictionary.device_values()
                                  ).astype(np.int64)
                v = vals[fwd]
            else:
                v = fwd.astype(np.int64)
            pad = self.pallas_capacity() - v.shape[0]
            if pad:
                v = np.pad(v, (0, pad))
            mask = np.int64((1 << LIMB_BITS) - 1)
            planes = []
            for k in range(limbs):
                if k < limbs - 1:
                    p = ((v >> (k * LIMB_BITS)) & mask).astype(np.int32)
                else:
                    p = (v >> (k * LIMB_BITS)).astype(np.int32)
                planes.append(jnp.asarray(p))
            for k, p in zip(keys, planes):
                self._values[k] = p
        return planes

    def startree_nodes(self, tree_index: int) -> Dict[str, jnp.ndarray]:
        """Device image of star-tree ``tree_index``'s node record columns:
        one int32 [R] array per split dimension (dictIds, STAR = -1) and
        one value array per pre-agg pair (i64 counts, f64 values). Staged
        once per resident — the star-tree rung gathers query-selected node
        slices out of these, so repeat queries pay zero H2D for the tree."""
        key = int(tree_index)
        t = self._startree.get(key)
        if t is None:
            with self._lock:
                t = self._startree.get(key)
                if t is None:
                    t = self._promote_startree(key)
                    if t is None:
                        t = self._stage_startree(key)
                    self._startree[key] = t
        return t

    def release_startree(self, tree_index: int) -> int:
        """Drop ONE star-tree's device arrays, leaving sibling trees (and
        every staged column) resident — the per-tree eviction grain.
        Returns the device bytes released. Host-image leftovers for the
        tree are kept on purpose: a later ``startree_nodes`` call then
        restages with one H2D promotion instead of a cold rebuild.
        In-flight launches holding the popped dict keep their arrays alive
        by reference; only the residency accounting lets go here."""
        with self._lock:
            t = self._startree.pop(int(tree_index), None)
        if t is None:
            return 0
        return sum(int(getattr(a, "nbytes", 0)) for a in t.values())

    def startree_nbytes(self) -> Dict[int, int]:
        """Device bytes per resident tree index (each tree accounted
        independently — /debug/memory's per-tree view)."""
        return {ti: sum(int(getattr(a, "nbytes", 0)) for a in t.values())
                for ti, t in list(self._startree.items())}

    def _promote_startree(self, key: int):
        img = self._host_image
        if img is None:
            return None
        ht = img.startree.pop(key, None)
        if ht is None:
            return None
        return {k: jnp.asarray(v) for k, v in ht.items()}

    def _stage_startree(self, tree_index: int) -> Dict[str, jnp.ndarray]:
        from pinot_tpu.engine.plan import (
            startree_dim_key,
            startree_metric_key,
        )

        tree = self.segment.star_trees[tree_index]
        cols: Dict[str, jnp.ndarray] = {}
        dims = np.asarray(tree.dims)
        for i, name in enumerate(tree.config.dimensions_split_order):
            cols[startree_dim_key(name)] = jnp.asarray(
                np.ascontiguousarray(dims[:, i]).astype(np.int32))
        for pair, vals in tree.metrics.items():
            fn, _, col = pair.partition("__")
            dt = np.int64 if fn == "count" else np.float64
            cols[startree_metric_key(fn, col)] = jnp.asarray(
                np.asarray(vals).astype(dt))
        return cols

    def index_slice(self, key, build) -> jnp.ndarray:
        """Device idx array for one resolved filter (index rung): the padded
        int32 docId slice, H2D'd once per (filter, capacity) and reused by
        repeat queries — the point-lookup analogue of the star-tree node
        cache. ``build()`` returns the padded host array on miss. LRU-capped:
        a dashboard's rotating literal set must not grow the resident
        unboundedly (the residency manager re-measures via ``account`` after
        every install, so the cap is a working-set bound, not the budget)."""
        arr = self._index_slices.get(key)
        if arr is not None:
            with self._lock:
                if key in self._index_slices:
                    self._index_slices.move_to_end(key)
            return arr
        with self._lock:
            arr = self._index_slices.get(key)
            if arr is None:
                arr = jnp.asarray(build())
                self._index_slices[key] = arr
                while len(self._index_slices) > _INDEX_SLICE_CAP:
                    self._index_slices.popitem(last=False)
        return arr

    def release_index_slices(self) -> int:
        """Drop every resident idx array (columns stay resident) — the
        index rung's eviction grain. Returns the device bytes released;
        in-flight launches keep their array alive by reference."""
        with self._lock:
            slices = list(self._index_slices.values())
            self._index_slices.clear()
        return sum(int(getattr(a, "nbytes", 0)) for a in slices)

    def index_nbytes(self) -> int:
        """Device bytes held by resident idx arrays (/debug/memory view)."""
        return sum(int(getattr(a, "nbytes", 0))
                   for a in list(self._index_slices.values()))

    def valid_mask(self):
        """Upsert valid-doc snapshot [capacity] for the validdocs kernel
        param, or None when the segment isn't upsert-managed. Versioned
        bitmaps (_LiveValidDocs) get a DEVICE-committed snapshot cached on
        the mutation version, so repeat queries skip the H2D upload (the
        round-3 tunnel-latency lesson); unversioned raw-array attaches get
        a fresh host snapshot per call (per-query snapshot semantics
        either way). The single implementation of the snapshot build."""
        v = getattr(self.segment, "valid_doc_ids", None)
        if v is None:
            return None
        ver = getattr(v, "version", None)
        if ver is not None:
            cached = getattr(self, "_valid_cache", None)
            if cached is not None and cached[0] == ver:
                return cached[1]
        n = self.segment.num_docs
        snap = np.zeros(self.capacity, dtype=bool)
        snap[:n] = np.asarray(v[:n])
        if ver is None:
            return snap
        arr = jnp.asarray(snap)
        with self._lock:
            self._valid_cache = (ver, arr)
        return arr

    def nbytes(self) -> int:
        """Device bytes this segment holds resident (HBM accounting for the
        residency manager). Walks the staged arrays — list() snapshots the
        dicts against concurrent stagers."""
        total = 0
        for col in list(self._columns.values()):
            for arr in col.tree().values():
                total += int(getattr(arr, "nbytes", 0))
        for pc in list(self._packed.values()):
            total += int(pc.words.nbytes)
        for v in list(self._values.values()):
            total += int(v.nbytes)
        for t in list(self._startree.values()):
            for arr in t.values():
                total += int(getattr(arr, "nbytes", 0))
        for a in list(self._index_slices.values()):
            total += int(getattr(a, "nbytes", 0))
        vc = self._valid_cache
        if vc is not None:
            total += int(getattr(vc[1], "nbytes", 0))
        return total

    def demote(self) -> Optional[SegmentHostImage]:
        """D2H snapshot for the residency host-RAM tier, then release the
        device arrays. Returns the host image (or None when nothing was
        staged — nothing worth keeping). The device syncs run OUTSIDE the
        segment lock (the snapshot under the lock is just dict copies):
        a column build landing after the snapshot is simply not captured
        and rebuilds cold on the next stage. Unconsumed leftovers of this
        resident's OWN promotion image are still-valid host copies and
        carry over, so demote(promote(demote(x))) never decays."""
        with self._lock:
            cols = dict(self._columns)
            packed = dict(self._packed)
            values = dict(self._values)
            trees = dict(self._startree)
            src = self._host_image
        img = SegmentHostImage(self.segment)
        for name, col in cols.items():
            hc = StagedColumn(data_type=col.data_type,
                              has_dictionary=col.has_dictionary)
            for k in ("fwd", "dictvals", "mv", "mvcount", "null"):
                v = getattr(col, k)
                if v is not None:
                    setattr(hc, k, np.asarray(v))
            img.columns[name] = hc
        for name, pc in packed.items():
            img.packed[name] = (np.asarray(pc.words), pc.bits)
        for name, v in values.items():
            img.values[name] = np.asarray(v)
        for ti, tree in trees.items():
            img.startree[ti] = {k: np.asarray(v) for k, v in tree.items()}
        if src is not None:
            for name, hc in src.columns.items():
                img.columns.setdefault(name, hc)
            for name, hp in src.packed.items():
                img.packed.setdefault(name, hp)
            for name, hv in src.values.items():
                img.values.setdefault(name, hv)
            for ti, ht in src.startree.items():
                img.startree.setdefault(ti, ht)
        self.release()
        if img.empty():
            return None
        return img.seal()

    def release(self) -> None:
        """Drop device references (HBM freed when XLA GCs the buffers).
        Locked against in-flight column builds: a build completing after
        the clear would re-insert into a released segment (its arrays are
        then invisible to the residency accounting until GC)."""
        with self._lock:
            self._columns.clear()
            self._packed.clear()
            self._values.clear()
            self._startree.clear()
            # idx arrays rebuild from the host-resolved docIds in one H2D —
            # cheaper than any column restage, so they never demote to the
            # host image; release drops them outright
            self._index_slices.clear()
            self._valid_cache = None
            img = self._host_image
            if img is not None:
                # demote() re-homed anything worth keeping before calling
                # release(); leftover numpy buffers free eagerly
                img.release()


# The HBM residency manager subsumed the old unbounded StagingCache
# (budget + pins + LRU + spill admission live in engine/residency.py);
# the name stays importable from here for existing callers. Lazy (PEP 562)
# because residency imports this module for StagedSegment.
def __getattr__(name: str):
    if name in ("StagingCache", "ResidencyManager"):
        from pinot_tpu.engine import residency

        return getattr(residency, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
