"""Host (numpy) evaluation of filters and expressions over a segment.

This is the CPU execution path, used for (a) selection queries (data movement,
not compute — the device adds nothing), (b) consuming/mutable segments that
are not yet device-staged (mirroring the reference, where the realtime tail
is served from the mutable segment), and (c) as the oracle the device kernels
are tested against.

Predicate semantics follow the reference's filter operators
(``operator/filter/*``): on multi-value columns a predicate matches a doc if
ANY value matches (ref: MV doc-id iterators).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

import numpy as np

from pinot_tpu.engine.errors import QueryError, UnsupportedQueryError
from pinot_tpu.query.expressions import (
    Expr,
    FilterNode,
    FilterOp,
    Function,
    Identifier,
    Literal,
    Predicate,
    PredicateType,
)
from pinot_tpu.segment.immutable import DataSource, ImmutableSegment
from pinot_tpu.spi.data import DataType


# --------------------------------------------------------------------------
# Filter evaluation -> boolean doc mask
# --------------------------------------------------------------------------

def eval_filter(segment: ImmutableSegment, node: Optional[FilterNode]) -> np.ndarray:
    n = segment.num_docs
    if node is None:
        mask = np.ones(n, dtype=bool)
    else:
        mask = _eval_node(segment, node)
    valid = getattr(segment, "valid_doc_ids", None)
    if valid is not None:
        # upsert: only the live doc per primary key is visible
        # (ref: IndexSegment.getValidDocIds AND-ed into every filter)
        mask = mask & np.asarray(valid[:n])
    return mask


def _eval_node(segment: ImmutableSegment, node: FilterNode) -> np.ndarray:
    if node.op is FilterOp.AND:
        out = _eval_node(segment, node.children[0])
        for c in node.children[1:]:
            out = out & _eval_node(segment, c)
        return out
    if node.op is FilterOp.OR:
        out = _eval_node(segment, node.children[0])
        for c in node.children[1:]:
            out = out | _eval_node(segment, c)
        return out
    if node.op is FilterOp.NOT:
        return ~_eval_node(segment, node.children[0])
    return eval_predicate(segment, node.predicate)


def _matching_dict_ids(ds: DataSource, pred: Predicate) -> np.ndarray:
    """Predicate -> sorted array of matching dictIds (the host analogue of
    the reference's dictionary-based predicate evaluators,
    ``operator/filter/predicate/*``)."""
    d = ds.dictionary
    card = d.cardinality
    t = pred.type
    dt = ds.metadata.data_type

    def conv(v):
        try:
            return dt.convert(v)
        except (ValueError, TypeError) as e:
            raise QueryError(f"cannot convert {v!r} for column "
                             f"{ds.name!r} ({dt.label}): {e}")

    if t is PredicateType.EQ:
        i = d.index_of(conv(pred.value))
        return np.array([i] if i >= 0 else [], dtype=np.int64)
    if t is PredicateType.NOT_EQ:
        i = d.index_of(conv(pred.value))
        ids = np.arange(card, dtype=np.int64)
        return ids[ids != i] if i >= 0 else ids
    if t is PredicateType.IN:
        ids = sorted({d.index_of(conv(v)) for v in pred.values} - {-1})
        return np.array(ids, dtype=np.int64)
    if t is PredicateType.NOT_IN:
        hit = {d.index_of(conv(v)) for v in pred.values} - {-1}
        return np.array([i for i in range(card) if i not in hit], dtype=np.int64)
    if t is PredicateType.RANGE:
        lo = conv(pred.lower) if pred.lower is not None else None
        hi = conv(pred.upper) if pred.upper is not None else None
        if hasattr(d, "matching_range_ids"):
            # unsorted (mutable) dictionary: value scan, not dictId interval
            return d.matching_range_ids(lo, hi, pred.lower_inclusive,
                                        pred.upper_inclusive)
        a, b = d.range_to_dict_id_interval(lo, hi, pred.lower_inclusive,
                                           pred.upper_inclusive)
        return np.arange(max(a, 0), min(b, card - 1) + 1, dtype=np.int64)
    if t is PredicateType.REGEXP_LIKE:
        try:
            rx = re.compile(str(pred.value))
        except re.error as e:
            raise QueryError(f"bad regex {pred.value!r}: {e}")
        reader = getattr(ds, "fst_index", None)
        if reader is not None:
            return reader.matching_ids(str(pred.value))
        return np.array([i for i in range(card)
                         if rx.search(str(d.get_value(i)))], dtype=np.int64)
    if t is PredicateType.TEXT_MATCH:
        from pinot_tpu.segment.textindex import (
            match_text_value,
            parse_text_query,
        )

        try:
            reader = getattr(ds, "text_index", None)
            if reader is not None:
                # tokenized inverted index -> dictId postings
                # (ref: TextMatchFilterOperator over TextIndexReader)
                return reader.matching_ids(str(pred.value))
            # index-less decay: SAME query dialect, evaluated per distinct
            # value (results must not depend on whether the index exists)
            ast = parse_text_query(str(pred.value))
        except ValueError as e:
            raise QueryError(f"bad TEXT_MATCH query: {e}")
        return np.array([i for i in range(card)
                         if match_text_value(d.get_value(i), ast)],
                        dtype=np.int64)
    raise UnsupportedQueryError(f"predicate {t} not supported on "
                                f"dictionary column {ds.name!r}")


def eval_predicate(segment: ImmutableSegment, pred: Predicate) -> np.ndarray:
    n = segment.num_docs
    # IS_NULL / IS_NOT_NULL read the null bitmap regardless of encoding
    if pred.type in (PredicateType.IS_NULL, PredicateType.IS_NOT_NULL):
        col = _predicate_column(pred)
        ds = segment.data_source(col)
        nb = ds.null_bitmap
        isnull = (np.asarray(nb[:n]) if nb is not None
                  else np.zeros(n, dtype=bool))
        return isnull if pred.type is PredicateType.IS_NULL else ~isnull

    if not isinstance(pred.lhs, Identifier):
        # expression predicate: evaluate values then compare
        return _eval_expr_predicate(segment, pred)

    if pred.lhs.name.startswith("$"):
        vals = _virtual_column_values(segment, pred.lhs.name, n)
        dt = (DataType.LONG if vals.dtype.kind == "i" else DataType.STRING)
        return _compare_values(vals, pred, dt)

    ds = segment.data_source(pred.lhs.name)
    cm = ds.metadata

    if pred.type is PredicateType.JSON_MATCH:
        return _eval_json_match(ds, pred, n)

    # RANGE over a range-indexed RAW column: binary search + slice instead
    # of a full compare scan (ref: RangeIndexBasedFilterOperator)
    if (pred.type is PredicateType.RANGE and not cm.has_dictionary
            and cm.single_value
            and getattr(ds, "range_order", None) is not None):
        return _range_index_mask(ds, pred, n)

    # Exclusive predicates on MV columns: ALL values must satisfy
    # (ref: BaseDictionaryBasedPredicateEvaluator.applyMV isExclusive) —
    # evaluate the inclusive form and negate.
    if not cm.single_value and pred.type in (PredicateType.NOT_EQ,
                                             PredicateType.NOT_IN):
        from dataclasses import replace
        inner_t = (PredicateType.EQ if pred.type is PredicateType.NOT_EQ
                   else PredicateType.IN)
        return ~eval_predicate(segment, replace(pred, type=inner_t))

    if cm.has_dictionary:
        ids = _matching_dict_ids(ds, pred)
        if cm.single_value:
            if len(ids) == 0:
                return np.zeros(n, dtype=bool)
            if cm.has_inverted_index and len(ids) <= max(4, cm.cardinality // 8):
                # posting lists beat a full scan for selective predicates
                # (ref: BitmapBasedFilterOperator vs ScanBasedFilterOperator
                # selection in FilterOperatorUtils)
                mask = np.zeros(n, dtype=bool)
                for i in ids:
                    mask[ds.doc_ids_for_dict_id(int(i))] = True
                return mask
            fwd = np.asarray(ds.forward_index[:n])
            if len(ids) == int(ids[-1] - ids[0]) + 1:  # contiguous interval
                return (fwd >= ids[0]) & (fwd <= ids[-1])
            return np.isin(fwd, ids)
        offsets = np.asarray(ds.mv_offsets)
        flat = np.asarray(ds.forward_index)
        if len(ids) == 0:
            return np.zeros(n, dtype=bool)
        hit = np.isin(flat, ids)
        # any per row: reduceat over CSR offsets (empty rows -> False)
        return _any_per_row(hit, offsets, n)

    # RAW column: compare values directly
    vals = np.asarray(ds.forward_index[:n])
    return _compare_values(vals, pred, cm.data_type)


def _eval_json_match(ds: DataSource, pred: Predicate, n: int) -> np.ndarray:
    """JSON_MATCH: posting lists when the column carries a JSON index,
    else parse-per-distinct-value over the dictionary (or per doc on raw)
    (ref: JsonMatchFilterOperator vs the index-less decay)."""
    from pinot_tpu.segment.jsonindex import match_json_value, parse_match_filter

    cm = ds.metadata
    if not cm.single_value:
        raise UnsupportedQueryError(
            f"JSON_MATCH on multi-value column {ds.name!r}")
    try:
        reader = getattr(ds, "json_index", None)
        if reader is not None:
            return np.asarray(reader.match(str(pred.value))[:n])
        ast = parse_match_filter(str(pred.value))
    except ValueError as e:
        raise QueryError(f"bad JSON_MATCH filter: {e}")
    if cm.has_dictionary:
        d = ds.dictionary
        lut = np.fromiter(
            (match_json_value(d.get_value(i), ast)
             for i in range(cm.cardinality)), dtype=bool,
            count=cm.cardinality)
        return lut[np.asarray(ds.forward_index[:n])]
    vals = ds.forward_index[:n]
    return np.fromiter((match_json_value(v, ast) for v in vals),
                       dtype=bool, count=n)


def _range_index_mask(ds: DataSource, pred: Predicate, n: int) -> np.ndarray:
    order = np.asarray(ds.range_order)
    sorted_vals = ds.range_sorted_values  # gathered once, cached
    dt = ds.metadata.data_type
    lo_i = 0
    hi_i = n
    if pred.lower is not None:
        v = dt.convert(pred.lower)
        side = "left" if pred.lower_inclusive else "right"
        lo_i = int(np.searchsorted(sorted_vals, v, side=side))
    if pred.upper is not None:
        v = dt.convert(pred.upper)
        side = "right" if pred.upper_inclusive else "left"
        hi_i = int(np.searchsorted(sorted_vals, v, side=side))
    mask = np.zeros(n, dtype=bool)
    if hi_i > lo_i:
        mask[order[lo_i:hi_i]] = True
    return mask


def _any_per_row(flat_hits: np.ndarray, offsets: np.ndarray, n: int) -> np.ndarray:
    counts = np.diff(offsets)
    rows = np.repeat(np.arange(n), counts)  # row index of each flat entry
    out = np.zeros(n, dtype=bool)
    out[rows[flat_hits]] = True
    return out


def _compare_values(vals: np.ndarray, pred: Predicate, dt: DataType) -> np.ndarray:
    t = pred.type

    def conv(v):
        try:
            return dt.convert(v)
        except (ValueError, TypeError) as e:
            raise QueryError(f"cannot convert {v!r} to {dt.label}: {e}")

    if t is PredicateType.EQ:
        return vals == conv(pred.value)
    if t is PredicateType.NOT_EQ:
        return vals != conv(pred.value)
    if t is PredicateType.IN:
        return np.isin(vals, [conv(v) for v in pred.values])
    if t is PredicateType.NOT_IN:
        return ~np.isin(vals, [conv(v) for v in pred.values])
    if t is PredicateType.RANGE:
        mask = np.ones(vals.shape, dtype=bool)
        if pred.lower is not None:
            lo = conv(pred.lower)
            mask &= (vals >= lo) if pred.lower_inclusive else (vals > lo)
        if pred.upper is not None:
            hi = conv(pred.upper)
            mask &= (vals <= hi) if pred.upper_inclusive else (vals < hi)
        return mask
    raise UnsupportedQueryError(f"predicate {t} not supported on raw column")


def _virtual_column_values(segment: ImmutableSegment, name: str,
                           n: int) -> np.ndarray:
    """Auto-columns every segment serves (ref: segment/virtualcolumn/* —
    DocIdVirtualColumnProvider etc.)."""
    if name == "$docId":
        return np.arange(n, dtype=np.int64)
    if name == "$segmentName":
        return np.full(n, segment.segment_name, dtype=object)
    if name == "$hostName":
        import socket

        return np.full(n, socket.gethostname(), dtype=object)
    raise UnsupportedQueryError(f"unknown virtual column {name!r}")


VIRTUAL_COLUMNS = {"$docId": "LONG", "$segmentName": "STRING",
                   "$hostName": "STRING"}


def _eval_expr_predicate(segment: ImmutableSegment, pred: Predicate) -> np.ndarray:
    geo_mask = _try_geo_index(segment, pred)
    if geo_mask is not None:
        return geo_mask
    vals = eval_expr_values(segment, pred.lhs)
    dt = (DataType.DOUBLE if np.issubdtype(np.asarray(vals).dtype, np.floating)
          else DataType.LONG)
    if np.asarray(vals).dtype == object:
        dt = DataType.STRING
    return _compare_values(np.asarray(vals), pred, dt)


def _try_geo_index(segment: ImmutableSegment,
                   pred: Predicate) -> Optional[np.ndarray]:
    """``stdistance(geoCol, 'POINT...') < r`` with a geo-indexed column:
    cell-disk prefilter + exact haversine on candidates only
    (ref: H3IndexFilterOperator). Returns None when the shape doesn't fit."""
    lhs = pred.lhs
    if not (isinstance(lhs, Function) and lhs.name in ("stdistance", "st_distance")
            and pred.type is PredicateType.RANGE
            and pred.upper is not None and pred.lower is None
            and len(lhs.args) == 2):
        return None
    col_arg, lit_arg = lhs.args
    if isinstance(col_arg, Literal) and isinstance(lit_arg, Identifier):
        col_arg, lit_arg = lit_arg, col_arg
    if not (isinstance(col_arg, Identifier) and isinstance(lit_arg, Literal)):
        return None
    if col_arg.name.startswith("$") \
            or col_arg.name not in segment.metadata.columns:
        return None
    ds = segment.data_source(col_arg.name)
    reader = getattr(ds, "geo_index", None)
    if reader is None:
        return None
    from pinot_tpu.utils import geo

    try:
        center = geo.parse_ewkt(lit_arg.value)
    except ValueError:
        return None
    if not center.geography:
        # planar (euclidean) distance: the index's haversine candidates
        # would disagree with the scalar semantics — decline
        return None
    if center.kind != "POINT":
        return None
    n = segment.num_docs
    ids = reader.ids_within(center.x, center.y, float(pred.upper),
                            inclusive=pred.upper_inclusive)
    if ids.size == 0:
        return np.zeros(n, dtype=bool)
    fwd = np.asarray(ds.forward_index[:n])
    return np.isin(fwd, ids)


def _predicate_column(pred: Predicate) -> str:
    cols = pred.lhs.columns()
    if not cols:
        raise QueryError(f"predicate references no column: {pred}")
    return cols[0]


# --------------------------------------------------------------------------
# Expression evaluation -> value arrays
# --------------------------------------------------------------------------

_ARITH = {
    "plus": np.add,
    "minus": np.subtract,
    "times": np.multiply,
    "divide": np.true_divide,
    "mod": np.mod,
}

# scalar transform functions usable host-side (subset of the reference's 42
# transform functions, operator/transform/function/*)
_UNARY = {
    "abs": np.abs,
    "ceil": np.ceil,
    "floor": np.floor,
    "exp": np.exp,
    "ln": np.log,
    "sqrt": np.sqrt,
}


def eval_expr_values(segment: ImmutableSegment, expr: Expr,
                     doc_ids: Optional[np.ndarray] = None) -> np.ndarray:
    """Evaluate an expression to per-doc values (numeric -> float/int arrays,
    strings -> object arrays). SV only; MV columns are handled by the MV
    aggregation functions."""
    n = segment.num_docs

    if isinstance(expr, Literal):
        return np.full(n if doc_ids is None else len(doc_ids), expr.value)

    if isinstance(expr, Identifier):
        if expr.name.startswith("$"):
            vals = _virtual_column_values(segment, expr.name, n)
            return vals if doc_ids is None else vals[doc_ids]
        ds = segment.data_source(expr.name)
        cm = ds.metadata
        if not cm.single_value:
            raise UnsupportedQueryError(
                f"multi-value column {expr.name!r} in expression position")
        fwd = np.asarray(ds.forward_index[:n])
        if doc_ids is not None:
            fwd = fwd[doc_ids]
        if not cm.has_dictionary:
            return fwd
        if cm.data_type.is_numeric:
            return np.asarray(ds.dictionary.device_values())[fwd]
        return np.array(ds.dictionary.get_values(fwd), dtype=object)

    if isinstance(expr, Function):
        name = expr.name
        if name in _ARITH:
            a = _to_float(eval_expr_values(segment, expr.args[0], doc_ids))
            b = _to_float(eval_expr_values(segment, expr.args[1], doc_ids))
            return _ARITH[name](a, b)
        if name in _UNARY:
            a = _to_float(eval_expr_values(segment, expr.args[0], doc_ids))
            return _UNARY[name](a)
        # scalar-registry fallback: any registered function evaluates
        # row-wise over the argument arrays (ref: the TransformFunction ->
        # ScalarFunction reflection bridge, FunctionInvoker)
        from pinot_tpu.query import functions as fnreg

        fn = fnreg.lookup(name)
        if fn is not None:
            arg_arrays = [eval_expr_values(segment, a, doc_ids)
                          for a in expr.args]
            n_rows = (len(arg_arrays[0]) if arg_arrays
                      else (n if doc_ids is None else len(doc_ids)))
            out = [fn(*(arr[i] for arr in arg_arrays))
                   for i in range(n_rows)]
            arr = np.asarray(out)
            return arr if arr.dtype != object or not out \
                else np.asarray(out, dtype=object)
        raise UnsupportedQueryError(f"transform function {name!r} not supported")

    raise UnsupportedQueryError(f"cannot evaluate expression {expr}")


def _to_float(a: np.ndarray) -> np.ndarray:
    if a.dtype == object:
        raise QueryError("arithmetic on non-numeric column")
    return a.astype(np.float64) if not np.issubdtype(a.dtype, np.floating) else a


def read_values(segment: ImmutableSegment, column: str,
                doc_ids: np.ndarray) -> List[Any]:
    """Gather output values for selection results (host path)."""
    if column.startswith("$"):
        vals = _virtual_column_values(segment, column, segment.num_docs)
        return [v.item() if hasattr(v, "item") else v
                for v in vals[doc_ids]]
    ds = segment.data_source(column)
    cm = ds.metadata
    if cm.single_value:
        fwd = np.asarray(ds.forward_index)[doc_ids]
        if not cm.has_dictionary:
            return [cm.data_type.convert(v) for v in fwd]
        return ds.dictionary.get_values(fwd)
    offsets = np.asarray(ds.mv_offsets)
    flat = np.asarray(ds.forward_index)
    d = ds.dictionary
    out = []
    for i in doc_ids:
        ids = flat[offsets[i]:offsets[i + 1]]
        out.append(d.get_values(ids))
    return out
