"""Composite group-key encode/decode shared by the host group-by and the
star-tree executor (the single source of truth for key packing — ref:
DictionaryBasedGroupKeyGenerator key composition)."""

from __future__ import annotations

from typing import Callable, List, Sequence, Tuple

import numpy as np


def compose_group_keys(code_arrays: Sequence[np.ndarray],
                       cardinalities: Sequence[int]
                       ) -> Tuple[np.ndarray, np.ndarray,
                                  Callable[[int], Tuple[int, ...]]]:
    """Pack per-column integer codes into one int64 key per row.

    Returns (unique_keys, group_id_per_row, decode) where ``decode`` maps a
    packed key back to the per-column code tuple. Cardinalities are the
    per-column key-space sizes (the packing strides).

    When the product of cardinalities would overflow int64, falls back to
    tuple keys via lexicographic np.unique over the stacked code columns
    (the reference's map/array-based generator past the long-key limit,
    DictionaryBasedGroupKeyGenerator cardinality ladder).
    """
    cards = [int(c) for c in cardinalities]

    key_space = 1
    for card in cards:
        key_space *= max(card, 1)
    if key_space >= 2 ** 63:
        stacked = np.stack([np.asarray(c, dtype=np.int64)
                            for c in code_arrays], axis=1)
        uniq_rows, gid = np.unique(stacked, axis=0, return_inverse=True)
        uniq = np.arange(len(uniq_rows), dtype=np.int64)

        def decode(key: int) -> Tuple[int, ...]:
            return tuple(int(p) for p in uniq_rows[int(key)])

        return uniq, gid.ravel(), decode

    combined = np.asarray(code_arrays[0], dtype=np.int64)
    for codes, card in zip(code_arrays[1:], cardinalities[1:]):
        combined = combined * int(card) + np.asarray(codes, dtype=np.int64)
    uniq, gid = np.unique(combined, return_inverse=True)

    def decode(key: int) -> Tuple[int, ...]:
        parts = []
        for card in reversed(cards[1:]):
            parts.append(key % card)
            key //= card
        parts.append(key)
        return tuple(int(p) for p in reversed(parts))

    return uniq, gid, decode
