"""Device-resident staging for mutable (consuming) segments.

The realtime serving tier's device half (ref: the consuming-segment query
path of ``MutableSegmentImpl`` + ``RealtimeSegmentDataManager``): a
:class:`StagedMutableSegment` keeps chunked append-only device columns for
one consuming segment and re-serves them through the SAME fused jnp kernel
path immutable segments use — the host engine remains the fallback for
shapes the planner declines.

Design points (SURVEY.md §7 said "host-resident forever"; this module is
the revision that makes the device tier incremental instead):

- **Chunked append-only columns, delta-only H2D.** Device buffers grow by
  power-of-two *row capacity*; growth copies history device-side
  (``zeros.at[:old].set(chunk)``) so the PCIe/ICI wire only ever carries
  rows past the last staged watermark (the TPU v4 HBM cost model in
  PAPERS.md: incremental H2D beats restage by the ratio of delta to
  history). Dictionary value tables ride the same scheme in dictId space —
  ``MutableDictionary`` assigns ids in arrival order, so a staged prefix
  is never invalidated by later inserts (dictId-stable growth).
- **Per-query watermark snapshot.** ``snapshot()`` captures, under the
  resident lock, the row watermark ``wm = segment.num_docs``, the chunk
  capacity, the column trees, and the upsert valid-doc mask as ONE frozen
  view; the kernel then runs over exactly ``num_docs = wm`` rows (rows
  past the watermark sit masked behind the kernel's ``arange(capacity) <
  num_docs`` guard, so garbage in not-yet-overwritten chunk tails is
  unreachable). Reading ``wm`` *before* any dictionary cardinality means
  every dictId referenced by a row below the watermark is covered by the
  staged value tables (the writer inserts dictionary values before
  publishing ``_num_docs``).
- **Residency-managed.** The resident registers with
  :class:`~pinot_tpu.engine.residency.ResidencyManager` under
  ``mutable::<segment>`` (leases, pins, byte accounting); eviction demotes
  the segment back to the host engine — the next device query simply
  restages from the host-side mutable columns.
- **Declines are ledger records.** Ineligible shapes (HLL register LUTs
  go stale as the dictionary grows; empty watermark; kernel failure) fall
  back to the host engine through ``_decline`` — every reason code below
  is registered in ``tracing.reason_registry()['mutable']`` and scanned
  by the conformance harness.

Conservation contract (machine-enforced by the lint ``conservation``
family's cache-parity AND chunk-accounting rules): every field this class
populates outside ``__init__`` must be counted in ``nbytes()`` and
cleared in ``release()``, and every chunk store must reach the running
byte counter on all paths — chunk installs route through
``_install_locked()``, which recounts immediately.
"""

from __future__ import annotations

import logging
import threading
import time

from typing import Any, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from pinot_tpu.common.telemetry import TELEMETRY, observe_ms
from pinot_tpu.common.tracing import maybe_span, record_decision
from pinot_tpu.engine.plan import PlanError, plan_segment
from pinot_tpu.segment import metadata as meta
from pinot_tpu.segment.mutable import (
    MutableDataSource,
    MutableSegment,
    _SnapshotColumns,
)

log = logging.getLogger(__name__)

#: residency-manager key prefix for consuming-segment residents (the seal
#: swap evicts ``resident_name(segment)`` when the immutable build lands)
MUTABLE_RESIDENT_PREFIX = "mutable::"

_MIN_CHUNK_ROWS = 1024


def resident_name(segment_name: str) -> str:
    return MUTABLE_RESIDENT_PREFIX + segment_name


def _chunk_capacity(n: int, floor: int = _MIN_CHUNK_ROWS) -> int:
    """Power-of-two chunk capacity covering ``n`` (kernel retraces are
    bounded: the spec's capacity only moves on pow2 boundaries)."""
    cap = max(1, floor)
    while cap < n:
        cap *= 2
    return cap


def _dictvals_dtype(data_type) -> np.dtype:
    """Schema-stable device dtype for a growing dictionary's value table.

    Unlike the immutable path's stats-narrowed :func:`staged_int_dtype`,
    the dtype here must never change as values arrive (a dtype flip would
    force a full restage + kernel retrace mid-consume), so it derives from
    the declared type alone: INT stays i32, LONG stays i64, floats ride
    f32 like the immutable dictvals tables."""
    if data_type.is_integral:
        return np.dtype(np.int32) if np.dtype(data_type.stored_np).itemsize <= 4 \
            else np.dtype(np.int64)
    return np.dtype(np.float32)


class MutableSnapshot:
    """Frozen per-query view: the column trees + watermark captured under
    the resident lock (jnp arrays are immutable; later refreshes replace
    dict entries with NEW arrays, so holding these references is safe)."""

    __slots__ = ("wm", "capacity", "cols", "valid_host", "valid_device")

    def __init__(self, wm: int, capacity: int,
                 cols: Dict[str, Dict[str, jnp.ndarray]],
                 valid_host: Optional[np.ndarray],
                 valid_device: Optional[jnp.ndarray]):
        self.wm = wm
        self.capacity = capacity
        self.cols = cols
        self.valid_host = valid_host
        self.valid_device = valid_device

    def tree(self, name: str) -> Dict[str, jnp.ndarray]:
        return self.cols[name]


class WatermarkView:
    """Segment duck-type pinned to one snapshot: ``num_docs`` is the
    watermark and ``padded_capacity`` the chunk capacity, so
    ``plan_segment`` builds a spec that matches the staged arrays exactly.
    Deliberately does NOT carry ``is_mutable`` — the planner's mutable
    gate is the host-only legacy path this module supersedes. Dictionary
    reads go to the LIVE mutable dictionary: ids at-or-past the snapshot
    cardinality are unreferenced by rows below the watermark, so an EQ
    hit on an in-flight value simply matches zero rows (correct), and
    over-sized LUT/key spaces only cost empty groups the presence vector
    drops at decode."""

    def __init__(self, segment: MutableSegment, snap: MutableSnapshot):
        self._seg = segment
        self._wm = snap.wm
        self.segment_name = segment.segment_name
        self.num_docs = snap.wm
        self.padded_capacity = snap.capacity
        self.valid_doc_ids = snap.valid_host
        self.schema = segment.schema
        self.metadata = meta.SegmentMetadata(
            segment_name=segment.segment_name,
            table_name=segment.schema.schema_name,
            schema=segment.schema,
            num_docs=snap.wm,
            padded_capacity=snap.capacity,
            time_column=segment.time_column,
            min_time=segment.min_time,
            max_time=segment.max_time,
            columns=_SnapshotColumns(segment, snap.wm),
        )

    def data_source(self, column: str) -> MutableDataSource:
        col = self._seg._cols.get(column)
        if col is None:
            raise KeyError(f"column {column!r} not in segment "
                           f"{self.segment_name!r}")
        return MutableDataSource(self._seg, col, self._wm)


class StagedMutableSegment:
    """Chunked device image of one consuming segment (see module doc)."""

    def __init__(self, segment: MutableSegment):
        self.segment = segment
        self._lock = threading.Lock()
        # chunk key ("fwd:<col>" | "dictvals:<col>" | "mv:<col>" |
        # "mvcount:<col>" | "null:<col>") -> device array
        self._chunks: Dict[str, jnp.ndarray] = {}  # guarded-by: _lock
        # staging cursors: "cap" (row chunk capacity), "wm" (last refreshed
        # watermark), "rows:<col>" (rows staged), "dict:<col>" (dictionary
        # values staged), "mvw:<col>" (dense MV width) — host ints only
        self._cursor: Dict[str, int] = {}  # guarded-by: _lock
        # running device-byte total, recounted by _install_locked on every chunk
        # store (the lint chunk-accounting obligation)
        self._staged_bytes = 0  # guarded-by: _lock
        # (version, wm, cap)-keyed device snapshot of the upsert mask
        self._valid_cache = None  # guarded-by: _lock
        # consuming-segment inverted index (the index rung's mutable half):
        # column -> {"upto": rows indexed, "lists": {dictId: [docId blocks]}}
        # — host numpy, resident-owned: counted in nbytes(), dropped in
        # release() like any staged state
        self._postings: Dict[str, Any] = {}  # guarded-by: _lock

    # -- accounting (conservation contract) ---------------------------------
    def _recount_bytes_locked(self) -> None:
        total = 0
        for arr in self._chunks.values():
            total += int(getattr(arr, "nbytes", 0))
        self._staged_bytes = total

    def _install_locked(self, key: str, arr: jnp.ndarray) -> None:
        """The ONLY chunk store: every append reaches the byte counter."""
        self._chunks[key] = arr
        self._recount_bytes_locked()

    def nbytes(self) -> int:
        with self._lock:
            total = 0
            for arr in self._chunks.values():
                total += int(getattr(arr, "nbytes", 0))
            vc = self._valid_cache
            if vc is not None:
                total += int(getattr(vc[1], "nbytes", 0))
            for st in self._postings.values():
                for blocks in st["lists"].values():
                    for b in blocks:
                        total += int(b.nbytes)
            if self._cursor:
                # the cursors hold host ints (no device bytes); the chunk
                # walk and the running counter agree under the lock —
                # max() is belt-and-braces for a torn future reader
                total = max(total, int(self._staged_bytes))
            return total

    def release(self) -> None:
        with self._lock:
            self._chunks.clear()
            self._cursor.clear()
            self._staged_bytes = 0
            self._valid_cache = None
            self._postings.clear()

    # -- staging ------------------------------------------------------------
    def snapshot(self) -> MutableSnapshot:
        """Refresh the chunks up to the current watermark and return the
        frozen per-query view (one lock hold: refresh + capture together,
        so a concurrent refresh can never mix capacities in one view)."""
        seg = self.segment
        with self._lock:
            # watermark FIRST: every dictId referenced by rows < wm is
            # already inserted (the writer publishes _num_docs last), so
            # the per-column cardinality reads below are >= what the
            # staged rows need
            wm = int(seg._num_docs)
            cap = int(self._cursor.get("cap", 0))
            if wm > cap or cap == 0:
                new_cap = _chunk_capacity(wm)
                if cap:
                    self._regrow_rows_locked(new_cap)
                cap = new_cap
                self._cursor["cap"] = cap
            for name, col in seg._cols.items():
                self._refresh_column_locked(name, col, wm, cap)
            self._cursor["wm"] = wm
            cols = {name: self._tree_locked(name, col)
                    for name, col in seg._cols.items()}
            valid_host, valid_device = self._valid_locked(wm, cap)
        return MutableSnapshot(wm, cap, cols, valid_host, valid_device)

    def _regrow_rows_locked(self, cap: int) -> None:
        """Double the row-capacity of every row-shaped chunk with a
        device-side history copy (no H2D re-upload)."""
        for key, arr in list(self._chunks.items()):
            if key.startswith("dictvals:"):
                continue  # dictId-shaped, grows on its own cursor
            if arr.ndim == 1:
                grown = jnp.zeros((cap,), dtype=arr.dtype)
                grown = grown.at[:arr.shape[0]].set(arr)
            else:
                grown = jnp.zeros((cap, arr.shape[1]), dtype=arr.dtype)
                grown = grown.at[:arr.shape[0], :].set(arr)
            self._install_locked(key, grown)

    def _refresh_column_locked(self, name: str, col, wm: int,
                               cap: int) -> None:
        staged = int(self._cursor.get(f"rows:{name}", 0))
        sv = col.mv_offsets is None

        if sv:
            key = f"fwd:{name}"
            chunk = self._chunks.get(key)
            if chunk is None:
                chunk = jnp.zeros((cap,), dtype=jnp.int32)
            if wm > staged:
                delta = np.ascontiguousarray(
                    col.fwd.view(wm)[staged:wm]).astype(np.int32)
                chunk = chunk.at[staged:wm].set(jnp.asarray(delta))
            self._install_locked(key, chunk)
        else:
            self._refresh_mv_locked(name, col, staged, wm, cap)

        if col.fs.data_type.is_numeric:
            self._refresh_dictvals_locked(name, col, wm)

        if col.has_nulls:
            key = f"null:{name}"
            chunk = self._chunks.get(key)
            lo = staged
            if chunk is None:
                # has_nulls can flip mid-consume: the null store recorded
                # every row from doc 0, so the first staging backfills the
                # whole prefix
                chunk = jnp.zeros((cap,), dtype=bool)
                lo = 0
            if wm > lo:
                delta = np.ascontiguousarray(col.null.view(wm)[lo:wm])
                chunk = chunk.at[lo:wm].set(jnp.asarray(delta))
            self._install_locked(key, chunk)

        self._cursor[f"rows:{name}"] = wm

    def _refresh_mv_locked(self, name: str, col, staged: int, wm: int,
                           cap: int) -> None:
        width = int(self._cursor.get(f"mvw:{name}", 0))
        need = _chunk_capacity(max(col.max_mv, 1), floor=1)
        mv = self._chunks.get(f"mv:{name}")
        cnt = self._chunks.get(f"mvcount:{name}")
        if mv is None:
            mv = jnp.zeros((cap, need), dtype=jnp.int32)
            cnt = jnp.zeros((cap,), dtype=jnp.int32)
            width = need
            self._cursor[f"mvw:{name}"] = width
        elif need > width:
            # width growth pads device-side (history stays on device)
            mv = jnp.pad(mv, ((0, 0), (0, need - width)))
            width = need
            self._cursor[f"mvw:{name}"] = width
        if wm > staged:
            off = np.asarray(col.mv_offsets.view(wm + 1), dtype=np.int64)
            fwd = col.fwd.view(int(off[-1]))
            block = np.zeros((wm - staged, width), dtype=np.int32)
            counts = np.diff(off[staged:wm + 1]).astype(np.int32)
            for i in range(staged, wm):
                a, b = int(off[i]), int(off[i + 1])
                block[i - staged, :b - a] = fwd[a:b]
            mv = mv.at[staged:wm, :].set(jnp.asarray(block))
            cnt = cnt.at[staged:wm].set(jnp.asarray(counts))
        self._install_locked(f"mv:{name}", mv)
        self._install_locked(f"mvcount:{name}", cnt)

    def _refresh_dictvals_locked(self, name: str, col, wm: int) -> None:
        card = len(col.dictionary)
        if card == 0:
            return
        staged = int(self._cursor.get(f"dict:{name}", 0))
        dt = _dictvals_dtype(col.fs.data_type)
        key = f"dictvals:{name}"
        chunk = self._chunks.get(key)
        dcap = int(chunk.shape[0]) if chunk is not None else 0
        if card > dcap:
            new_dcap = _chunk_capacity(card, floor=_MIN_CHUNK_ROWS)
            grown = jnp.zeros((new_dcap,), dtype=dt)
            if chunk is not None:
                grown = grown.at[:dcap].set(chunk)
            chunk = grown
        if card > staged:
            # dictId-stable growth: ids are arrival-ordered, so the staged
            # prefix never changes — only values [staged, card) cross H2D
            vals = np.asarray(
                col.dictionary.get_values(range(staged, card)), dtype=dt)
            chunk = chunk.at[staged:card].set(jnp.asarray(vals))
            self._cursor[f"dict:{name}"] = card
        if chunk is not None:
            self._install_locked(key, chunk)

    def _tree_locked(self, name: str, col) -> Dict[str, jnp.ndarray]:
        out: Dict[str, jnp.ndarray] = {}
        if col.mv_offsets is None:
            out["fwd"] = self._chunks[f"fwd:{name}"]
        else:
            out["mv"] = self._chunks[f"mv:{name}"]
            out["mvcount"] = self._chunks[f"mvcount:{name}"]
        dv = self._chunks.get(f"dictvals:{name}")
        if dv is not None:
            out["dictvals"] = dv
        nc = self._chunks.get(f"null:{name}")
        if nc is not None:
            out["null"] = nc
        return out

    def postings_doc_ids(self, name: str, col, dict_ids, wm: int
                         ) -> np.ndarray:
        """Sorted unique docIds below ``wm`` whose SV column ``name`` holds
        a dictId in ``dict_ids`` — the consuming-segment analogue of the
        immutable inverted index, grown incrementally: one stable argsort
        over the DELTA rows per refresh groups them by dictId, so repeat
        point queries pay O(delta log delta), never O(wm). Per-dictId block
        lists stay ascending by construction (blocks arrive in watermark
        order; within a block the stable sort preserves row order)."""
        with self._lock:
            st = self._postings.get(name)
            if st is None:
                st = {"upto": 0, "lists": {}}
                self._postings[name] = st
            upto = int(st["upto"])
            if wm > upto:
                fwd = np.asarray(col.fwd.view(wm)[upto:wm])
                order = np.argsort(fwd, kind="stable").astype(np.int64)
                sv = fwd[order]
                uniq, starts = np.unique(sv, return_index=True)
                bounds = np.append(starts, sv.size)
                lists = st["lists"]
                for i, d in enumerate(uniq.tolist()):
                    docs = order[bounds[i]:bounds[i + 1]] + upto
                    lists.setdefault(int(d), []).append(docs)
                st["upto"] = wm
            parts = [block for d in dict_ids
                     for block in st["lists"].get(int(d), ())]
        if not parts:
            return np.empty(0, dtype=np.int64)
        docs = parts[0] if len(parts) == 1 else \
            np.sort(np.concatenate(parts))
        # a concurrent query may have refreshed the map past this query's
        # snapshot: clip to the snapshot watermark
        return docs[:int(np.searchsorted(docs, wm))]

    def _valid_locked(self, wm: int, cap: int):
        """(host numpy snapshot, device snapshot) of the upsert valid-doc
        bitmap at this watermark, or (None, None). Cached on (bitmap
        version, wm, cap) — repeat queries at the same watermark skip the
        O(capacity) copy and the H2D (the staging.valid_mask idiom)."""
        v = getattr(self.segment, "valid_doc_ids", None)
        if v is None:
            return None, None
        ver = getattr(v, "version", None)
        cache_key = (ver, wm, cap)
        cached = self._valid_cache
        if ver is not None and cached is not None and cached[0] == cache_key:
            return cached[2], cached[1]
        snap = np.zeros(cap, dtype=bool)
        snap[:wm] = np.asarray(v[:wm])
        arr = jnp.asarray(snap)
        if ver is not None:
            self._valid_cache = (cache_key, arr, snap)
        return snap, arr


# --------------------------------------------------------------------------
# freshness: event append -> first watermark covering it
# --------------------------------------------------------------------------

def observe_freshness(segment: Any, upto: int, table: str) -> None:
    """Record ingest-to-queryable latency for every row first covered by
    watermark ``upto`` into the ``(table, "freshness")`` windowed
    histogram (the ``pinot.broker.slo.<table>.freshness.ms`` objective
    burns against it). A per-segment cursor (``_fresh_observed``) makes
    each row count exactly once — whichever of the serve path (watermark
    snapshot) or the seal path (final flush) sees it first."""
    lock = getattr(segment, "_fresh_lock", None)
    ts = getattr(segment, "_append_ts", None)
    if lock is None or ts is None or upto <= 0:
        return
    with lock:
        start = int(segment._fresh_observed)
        if upto <= start:
            return
        segment._fresh_observed = upto
    now = time.monotonic()
    h = TELEMETRY.histo(table or "", "freshness")
    for t in np.asarray(ts.view(upto)[start:upto]):
        h.record(max(0.0, (now - float(t)) * 1e3))


# --------------------------------------------------------------------------
# serve path (called from the executor's device branch for mutable segments)
# --------------------------------------------------------------------------

def _decline(stats, reason: str) -> None:
    """Host fallback with a ledger record (scanned by the 'mutable'
    ReasonNamespace — the first string literal is the reason code)."""
    record_decision(stats, "mutable", "host_engine", "mutable_device",
                    reason)


def _decline_rung(stats, reason: str) -> None:
    """Index-assisted gather declined to the FULL mutable chunk scan (not
    to host) — the consuming-segment half of the index rung's ledger."""
    record_decision(stats, "index", "mutable_device", "index_gather",
                    reason)


def _chose_rung(stats, reason: str) -> None:
    record_decision(stats, "index", "index_gather", "mutable_device",
                    reason)


def serve_group_by(executor, ctx, aggs: List[Any], seg: MutableSegment,
                   stats) -> Optional[Any]:
    return _serve(executor, ctx, aggs, seg, stats, grouped=True)


def serve_aggregation(executor, ctx, aggs: List[Any], seg: MutableSegment,
                      stats) -> Optional[Any]:
    return _serve(executor, ctx, aggs, seg, stats, grouped=False)


def _serve(executor, ctx, aggs, seg, stats, grouped: bool):
    """Run one query over the consuming segment through the fused device
    kernel path, or return None for the host-engine fallback (every None
    is preceded by a ledger record)."""
    from pinot_tpu.engine.executor import (
        decode_grouped_result,
        decode_scalar_result,
    )
    from pinot_tpu.engine.kernels import unpack_outputs

    table = getattr(stats, "_tel_table", "") \
        or getattr(seg.schema, "schema_name", "")

    if any(a.base == "distinctcounthll" for a in aggs):
        # the dictionary's HLL register LUTs are memoized per log2m and go
        # stale as the dictionary grows — gathers past the LUT length land
        # in the wrong bucket. Host engine computes HLL exactly.
        _decline(stats, "mutable_hll_lut_unstable")
        return None
    if int(seg.num_docs) == 0:
        _decline(stats, "mutable_empty_watermark")
        return None

    lease = executor._lease_of(stats)
    name = resident_name(seg.segment_name)
    with maybe_span(stats, "Stage", segment=seg.segment_name):
        resident = executor.residency.register(
            name, lambda: StagedMutableSegment(seg),
            same=lambda r: getattr(r, "segment", None) is seg,
            lease=lease)
        try:
            snap = resident.snapshot()
        except Exception:
            log.exception("mutable staging failed for %s; host fallback",
                          seg.segment_name)
            _decline(stats, "mutable_exec_failed")
            return None
        # chunks may have grown: re-measure + enforce the HBM budget
        executor.residency.account(name, lease)
    if snap.wm == 0:
        _decline(stats, "mutable_empty_watermark")
        return None

    view = WatermarkView(seg, snap)
    try:
        plan = plan_segment(ctx, view)
    except PlanError as e:
        record_decision(stats, "plan", "host_engine", "mutable_device",
                        e.reason_code)
        return None

    res = _try_index_gather(executor, ctx, seg, resident, view, snap, plan,
                            stats, table, grouped)
    if res is not None:
        return res

    t0 = time.perf_counter()
    try:
        with maybe_span(stats, "Kernel", kernel="jnp",
                        segment=seg.segment_name):
            cols = {n: snap.tree(n) for n in plan.columns}
            kernel = executor.kernels.get(plan.spec)
            params = tuple(plan.params)
            if plan.spec[0][:1] == ("and",) \
                    and plan.spec[0][1][0] == ("validdocs",):
                # fill the planner's placeholder with the snapshot's
                # device mask (same watermark as the staged rows — the
                # upsert filter and the data agree on one point in time)
                params = (snap.valid_device,) + params[1:]
            packed = kernel(cols, params, np.int32(snap.wm))
            out = unpack_outputs(packed, plan.spec)
    except Exception:
        log.exception("mutable kernel failed for %s; host fallback",
                      seg.segment_name)
        _decline(stats, "mutable_exec_failed")
        return None
    observe_ms(table, "kernel", (time.perf_counter() - t0) * 1e3)
    executor._track_kernel_stats(out, view, stats)
    observe_freshness(seg, snap.wm, table)
    if grouped:
        return decode_grouped_result(plan, view, out)
    return decode_scalar_result(plan, view, out)


def _try_index_gather(executor, ctx, seg, resident, view, snap, plan,
                      stats, table: str, grouped: bool):
    """The consuming-segment half of the index rung: selective conjunctive
    EQ/IN/RANGE filters over SV dict columns resolve docIds from the
    resident's growing dictId->docIds map and run the SAME gather kernel
    the immutable rung uses over the snapshot's chunk trees. Returns the
    decoded result, or None — every None on an index-candidate shape is a
    ``_decline_rung`` record, and the full chunk scan (not host) serves."""
    from pinot_tpu.engine import index_exec
    from pinot_tpu.engine.executor import (
        decode_grouped_result,
        decode_scalar_result,
    )
    from pinot_tpu.engine.host_eval import _matching_dict_ids
    from pinot_tpu.engine.kernels import unpack_outputs
    from pinot_tpu.engine.startree_exec import _flatten_and
    from pinot_tpu.query.expressions import Identifier, PredicateType

    if ctx.options.get("useIndexRung", "true").lower() == "false":
        return None  # operator opt-out, not a decline
    if ctx.filter is None:
        return None  # nothing selective to index: not a decline
    preds = _flatten_and(ctx.filter)
    if not preds:
        if preds is None:  # OR/NOT shape
            _decline_rung(stats, "mutable_index_unsupported_shape")
        return None  # constant-true filter ([]): nothing selective to
        #              index — not a decline
    if snap.valid_host is not None:
        # upsert: validity must AND the filter and the map doesn't see it
        _decline_rung(stats, "mutable_index_unsupported_shape")
        return None

    wm = snap.wm
    threshold = max(1, int(wm * index_exec.SELECTIVITY_THRESHOLD))
    per_pred = []
    for pred in preds:
        lhs = pred.lhs
        if not isinstance(lhs, Identifier) or lhs.name.startswith("$") \
                or pred.type not in (PredicateType.EQ, PredicateType.IN,
                                     PredicateType.RANGE):
            _decline_rung(stats, "mutable_index_unsupported_shape")
            return None
        col = seg._cols.get(lhs.name)
        if col is None or col.mv_offsets is not None \
                or getattr(col, "dictionary", None) is None:
            # MV / missing / dictionary-less column: the chunk scan serves
            _decline_rung(stats, "mutable_index_unsupported_shape")
            return None
        ids = _matching_dict_ids(view.data_source(lhs.name), pred)
        if ids.size > 256:  # broad dictId set: the scan wins outright
            _decline_rung(stats, "mutable_index_over_threshold")
            return None
        per_pred.append((lhs.name, col, ids))

    routes = [resident.postings_doc_ids(name, col, ids, wm)
              for name, col, ids in per_pred]
    if min(d.size for d in routes) > threshold:
        _decline_rung(stats, "mutable_index_over_threshold")
        return None
    routes.sort(key=lambda d: d.size)
    idx = routes[0]
    for d in routes[1:]:
        if idx.size == 0:
            break
        idx = np.intersect1d(idx, d, assume_unique=True)
    n = int(idx.size)

    stripped = index_exec.gather_plan(plan, n)
    capacity = stripped.spec[4]
    padded = np.zeros(capacity, dtype=np.int32)
    padded[:n] = idx.astype(np.int32, copy=False)
    t0 = time.perf_counter()
    try:
        with maybe_span(stats, "Kernel", kernel="index_gather",
                        segment=seg.segment_name, records=n):
            cols = {c: snap.tree(c) for c in stripped.columns}
            kernel = executor._index_kernel(stripped.spec)
            packed = kernel(cols, jnp.asarray(padded),
                            tuple(stripped.params), np.int32(n))
            out = unpack_outputs(packed, stripped.spec)
    except Exception:
        log.exception("mutable index gather failed for %s; chunk scan",
                      seg.segment_name)
        _decline_rung(stats, "mutable_index_exec_failed")
        return None
    observe_ms(table, "kernel", (time.perf_counter() - t0) * 1e3)

    stats.num_segments_processed += 1
    stats.total_docs += wm
    stats.num_docs_scanned += n
    if n:
        stats.num_segments_matched += 1
    _chose_rung(stats, "mutable_index_served")
    observe_freshness(seg, wm, table)
    if grouped:
        return decode_grouped_result(stripped, view, out)
    return decode_scalar_result(stripped, view, out)
